"""Versioned model registry + self-healing canary rollout.

PR 4 gave the serving tier an on-disk IR (`InferenceModel.
export_compiled` → a zip with the serialized executable and the
batch-polymorphic ``export_poly.bin``), PR 7 gave it a fleet that can
drain and restart replicas without dropping acked work, and PR 6
gave it SLOs that notice when a cohort misbehaves. This module is
the control loop that connects them (ROADMAP item 3):

- :class:`ModelRegistry` — ``name → version → artifact + metadata +
  warm-bucket manifest``, persisted as a directory tree a whole
  serving fleet can share (or held in memory for tests);
- :class:`ModelVersion` — one immutable entry; :meth:`~ModelVersion.
  load_into` warm-swaps it into a live :class:`InferenceModel`
  (bumping ``generation`` so every replica batcher drops its stale
  bucket executables on the next dispatch);
- :class:`RolloutController` — the state machine behind
  ``FleetRouter.rollout(version, canary_pct=)``::

      rolling ──► canary ──► promoting ──► promoted
                    │
                    └──(cohort SLO breach / error burst)──►
                        rolling_back ──► rolled_back

  Roll-forward drains ONE replica at a time behind the router (the
  drain flushes its queue, so zero acked requests drop), re-points it
  at the new version, and restarts it. The canary phase then routes
  ``canary_pct``% of traffic to the new version through the router's
  cohort split (consistent-hash traffic stays sticky per key) while
  a cohort-scoped error-ratio SLO — installed by the controller,
  removed when the rollout ends — watches
  ``zoo_tpu_rollout_errors_total{version}`` against
  ``zoo_tpu_rollout_requests_total{version}``. An ``slo_breach``
  anomaly on that objective, or a raw error burst past
  ``max_canary_errors``, triggers automatic rollback through the
  same drain path; a clean bake of ``bake_s`` seconds promotes the
  version to the rest of the fleet.

Observability: every transition appends a ``rollout/state`` event
and bumps ``zoo_tpu_rollout_transitions_total{state}``; the whole
lifecycle is spanned (``rollout/swap_replica`` etc.) and exposed at
``GET /debug/rollout`` on both HTTP front-ends. The chaos harness
(`scripts/chaos_smoke.py`) drives exactly this loop with an injected
canary error burst. Failure-mode catalog: docs/robustness.md.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable, Dict, List, Optional

from analytics_zoo_tpu.common import diagnostics
from analytics_zoo_tpu.common import observability as obs
from analytics_zoo_tpu.common import slo as slo_mod
from analytics_zoo_tpu.common.nncontext import logger

__all__ = [
    "ModelVersion",
    "ModelRegistry",
    "RolloutController",
]

# rollout lifecycle states (GET /debug/rollout)
ROLLING = "rolling"
CANARY = "canary"
PROMOTING = "promoting"
PROMOTED = "promoted"
ROLLING_BACK = "rolling_back"
ROLLED_BACK = "rolled_back"

_META_FILE = "meta.json"
_ARTIFACT_FILE = "artifact.zip"


def _c_transitions(state: str):
    return obs.counter("zoo_tpu_rollout_transitions_total",
                       help="rollout state-machine transitions, "
                            "by entered state",
                       labels={"state": state})


def _g_active():
    return obs.gauge("zoo_tpu_rollout_active",
                     help="1 while a rollout is in progress")


class ModelVersion:
    """One immutable registry entry: a named version of a model,
    backed by an on-disk ``export_compiled`` artifact OR an
    in-memory ``loader(model)`` callable (tests, smokes, and
    processes that build params in place)."""

    def __init__(self, model_name: str, name: str,
                 artifact: Optional[str] = None,
                 loader: Optional[Callable] = None,
                 metadata: Optional[dict] = None,
                 warm_buckets: Optional[List[int]] = None,
                 created_at: Optional[float] = None,
                 registry: "Optional[ModelRegistry]" = None):
        if (artifact is None) == (loader is None):
            raise ValueError(
                "a ModelVersion needs exactly one of artifact= "
                "(export_compiled path) or loader= (callable)")
        self.model_name = str(model_name)
        self.name = str(name)
        self.artifact = artifact
        self.loader = loader
        self.metadata = dict(metadata or {})
        self.warm_buckets = (list(warm_buckets)
                             if warm_buckets else None)
        self.created_at = (time.time() if created_at is None
                           else float(created_at))
        self.registry = registry

    def load_into(self, model) -> None:
        """Warm-swap this version into a live
        :class:`~analytics_zoo_tpu.pipeline.inference.inference_model.
        InferenceModel`: artifact versions go through
        ``load_compiled`` (serialized executable, or the portable
        ``export_poly.bin`` blob compiled once), loader versions call
        their callable. Either path bumps ``model.generation``, so
        batchers serving it drop stale bucket executables."""
        with obs.span("rollout/swap", model=self.model_name,
                      version=self.name):
            if self.loader is not None:
                self.loader(model)
            else:
                model.load_compiled(self.artifact)
        obs.event("rollout/version_loaded", model=self.model_name,
                  version=self.name)

    def to_dict(self) -> dict:
        return {
            "model": self.model_name,
            "version": self.name,
            "artifact": self.artifact,
            "in_memory": self.loader is not None,
            "metadata": self.metadata,
            "warm_buckets": self.warm_buckets,
            "created_at": self.created_at,
        }

    def __repr__(self):
        src = "loader" if self.loader is not None else self.artifact
        return (f"ModelVersion({self.model_name}:{self.name}, "
                f"{src})")


class ModelRegistry:
    """``name → version → ModelVersion``, optionally persisted under
    ``root`` as ``<root>/<model>/<version>/{meta.json,
    artifact.zip}`` (``ZOO_TPU_MODEL_REGISTRY`` names a default
    root). Version order is registration order (on disk:
    ``created_at``); :meth:`latest` returns the newest. In-memory
    (loader-backed) versions never persist — they exist for the
    lifetime of the process that registered them."""

    def __init__(self, root: Optional[str] = None):
        if root is None:
            root = os.environ.get("ZOO_TPU_MODEL_REGISTRY") or None
        self.root = root
        self._lock = threading.Lock()
        self._models: "Dict[str, Dict[str, ModelVersion]]" = {}
        if self.root:
            os.makedirs(self.root, exist_ok=True)
            self._scan()

    # -- persistence ---------------------------------------------------------
    def _scan(self):
        """Rebuild the index from the on-disk tree (crash-safe: a
        version directory without ``meta.json`` is an unfinished
        registration and is skipped)."""
        for model in sorted(os.listdir(self.root)):
            mdir = os.path.join(self.root, model)
            if not os.path.isdir(mdir):
                continue
            for version in sorted(os.listdir(mdir)):
                vdir = os.path.join(mdir, version)
                meta_path = os.path.join(vdir, _META_FILE)
                if not os.path.isfile(meta_path):
                    continue
                try:
                    with open(meta_path) as f:
                        meta = json.load(f)
                except (OSError, ValueError) as e:
                    logger.warning(
                        "registry: skipping unreadable %s (%s)",
                        meta_path, e)
                    continue
                artifact = os.path.join(
                    vdir, meta.get("artifact_file", _ARTIFACT_FILE))
                mv = ModelVersion(
                    model, version, artifact=artifact,
                    metadata=meta.get("metadata"),
                    warm_buckets=meta.get("warm_buckets"),
                    created_at=meta.get("created_at"),
                    registry=self)
                self._models.setdefault(model, {})[version] = mv

    def _persist(self, mv: ModelVersion, src_artifact: str):
        """Write ``<root>/<model>/<version>/`` atomically enough for
        :meth:`_scan`: the artifact lands first, ``meta.json`` last
        (tmp + ``os.replace``) — a half-registered version is
        invisible."""
        vdir = os.path.join(self.root, mv.model_name, mv.name)
        os.makedirs(vdir, exist_ok=True)
        dst = os.path.join(vdir, _ARTIFACT_FILE)
        if os.path.abspath(src_artifact) != os.path.abspath(dst):
            tmp = dst + ".tmp"
            with open(src_artifact, "rb") as fin, \
                    open(tmp, "wb") as fout:
                fout.write(fin.read())
            os.replace(tmp, dst)
        mv.artifact = dst
        meta = {"artifact_file": _ARTIFACT_FILE,
                "metadata": mv.metadata,
                "warm_buckets": mv.warm_buckets,
                "created_at": mv.created_at}
        tmp = os.path.join(vdir, _META_FILE + ".tmp")
        with open(tmp, "w") as f:
            json.dump(meta, f, indent=2, sort_keys=True)
        os.replace(tmp, os.path.join(vdir, _META_FILE))

    # -- registration --------------------------------------------------------
    def register(self, model_name: str, version: str,
                 artifact: Optional[str] = None,
                 loader: Optional[Callable] = None,
                 metadata: Optional[dict] = None,
                 warm_buckets: Optional[List[int]] = None
                 ) -> ModelVersion:
        """Register one version. ``artifact`` is an
        ``export_compiled`` zip (copied under the registry root when
        one is configured); ``loader`` is an in-memory alternative
        (``loader(model)`` must leave ``model`` serving the new
        version). Re-registering an existing version is an error —
        versions are immutable (publish a new name instead)."""
        mv = ModelVersion(model_name, version, artifact=artifact,
                          loader=loader, metadata=metadata,
                          warm_buckets=warm_buckets, registry=self)
        with self._lock:
            versions = self._models.setdefault(str(model_name), {})
            if str(version) in versions:
                raise ValueError(
                    f"version {model_name}:{version} already "
                    f"registered (versions are immutable)")
            if self.root and artifact is not None:
                self._persist(mv, artifact)
            versions[str(version)] = mv
        obs.event("rollout/version_registered", model=model_name,
                  version=version,
                  in_memory=loader is not None)
        return mv

    def register_export(self, model_name: str, version: str,
                        model, metadata: Optional[dict] = None,
                        warm_buckets: Optional[List[int]] = None
                        ) -> ModelVersion:
        """Export a live :class:`InferenceModel`'s compiled serving
        program straight into the registry (requires a ``root``).
        The warm-bucket manifest defaults to the serving bucket
        ladder a replica would warm for this model."""
        if not self.root:
            raise ValueError(
                "register_export needs a registry root directory")
        if warm_buckets is None:
            from analytics_zoo_tpu.pipeline.inference.batching \
                import bucket_ladder
            cap = int(os.environ.get(
                "ZOO_TPU_SERVING_MAX_BATCH", 32))
            warm_buckets = list(bucket_ladder(cap))
        vdir = os.path.join(self.root, str(model_name),
                            str(version))
        os.makedirs(vdir, exist_ok=True)
        artifact = os.path.join(vdir, _ARTIFACT_FILE)
        model.export_compiled(artifact)
        return self.register(model_name, version,
                             artifact=artifact, metadata=metadata,
                             warm_buckets=warm_buckets)

    # -- lookup --------------------------------------------------------------
    def get(self, model_name: str, version: str) -> ModelVersion:
        with self._lock:
            try:
                return self._models[str(model_name)][str(version)]
            except KeyError:
                raise KeyError(
                    f"no version {model_name}:{version} in the "
                    f"registry") from None

    def latest(self, model_name: str) -> ModelVersion:
        with self._lock:
            versions = self._models.get(str(model_name))
            if not versions:
                raise KeyError(
                    f"no model {model_name!r} in the registry")
            return max(versions.values(),
                       key=lambda v: v.created_at)

    def versions(self, model_name: str) -> "List[str]":
        with self._lock:
            vs = self._models.get(str(model_name), {})
            return [v.name for v in sorted(
                vs.values(), key=lambda v: v.created_at)]

    def models(self) -> "List[str]":
        with self._lock:
            return sorted(self._models)

    def status(self) -> dict:
        """JSON-able index dump (debug surfaces)."""
        with self._lock:
            return {
                "root": self.root,
                "models": {
                    m: [v.to_dict() for v in sorted(
                        vs.values(), key=lambda v: v.created_at)]
                    for m, vs in self._models.items()},
            }

    def __repr__(self):
        with self._lock:
            counts = {m: len(vs)
                      for m, vs in self._models.items()}
        return f"ModelRegistry(root={self.root!r}, {counts})"


class RolloutController:
    """Drives one rollout of ``version`` across a
    :class:`~analytics_zoo_tpu.pipeline.inference.fleet.FleetRouter`'s
    fleet (state machine in the module docstring). Constructed by
    ``FleetRouter.rollout``; the router's prober thread (or a manual
    ``router.tick()``) drives :meth:`tick`.

    ``canary_pct`` picks both the replica share swapped first and
    the traffic share routed to them; ``<= 0`` means a plain rolling
    update (every replica swapped, no canary watch), ``>= 100``
    swaps everything but still bakes before declaring ``promoted``.
    ``bake_s`` is the clean-canary dwell before promotion,
    ``max_canary_errors`` the raw cohort error burst that rolls back
    without waiting for the SLO engine (the SLO — objective
    ``slo_objective``, windows ``slo_windows`` — needs traffic
    deltas between engine ticks; the burst check catches a
    fault-storm between them)."""

    def __init__(self, router, version, canary_pct: int = 25,
                 baseline=None, bake_s: float = 30.0,
                 max_canary_errors: Optional[int] = 10,
                 slo_objective: float = 0.95,
                 slo_burn_rate: float = 1.0,
                 slo_windows=(30.0, 120.0),
                 slo_min_events: int = 5,
                 drain_timeout: float = 30.0,
                 engine: "Optional[slo_mod.SLOEngine]" = None):
        self.router = router
        self.version = version
        self.version_name = str(getattr(version, "name", version))
        self.canary_pct = int(canary_pct)
        self.bake_s = float(bake_s)
        self.max_canary_errors = max_canary_errors
        self.slo_objective = float(slo_objective)
        self.slo_burn_rate = float(slo_burn_rate)
        self.slo_windows = tuple(slo_windows)
        self.slo_min_events = int(slo_min_events)
        self.drain_timeout = float(drain_timeout)
        self._engine = engine
        self._explicit_baseline = baseline
        self.baseline = None  # ModelVersion, resolved at begin()
        self.baseline_name: Optional[str] = None
        self.state = "idle"
        self.reason: Optional[str] = None
        self.transitions: "List[dict]" = []
        self.swaps: "List[dict]" = []
        self.canary_replicas: "List[str]" = []
        self.canary_since: Optional[float] = None
        self._err_base = 0.0
        self._breach_reason: Optional[str] = None
        self._clock = router.pool.clock
        self._lock = threading.RLock()
        self._slo_id = "rollout_canary"
        self._listener_installed = False

    # -- state machine -------------------------------------------------------
    @property
    def in_progress(self) -> bool:
        return self.state in (ROLLING, CANARY, PROMOTING,
                              ROLLING_BACK)

    def _transition(self, state: str, **fields):
        self.state = state
        rec = {"state": state, "at": self._clock()}
        rec.update(fields)
        self.transitions.append(rec)
        _c_transitions(state).inc()
        _g_active().set(1 if self.in_progress else 0)
        obs.event("rollout/state", version=self.version_name,
                  state=state, **fields)
        logger.info("rollout %s -> %s %s", self.version_name,
                    state, fields or "")

    def begin(self):
        """Resolve the baseline, swap the canary share of replicas
        (one drained at a time), and either enter the canary watch
        or — for a plain rolling update — run straight through to
        ``promoted``."""
        with self._lock:
            if self.state != "idle":
                raise RuntimeError(
                    f"rollout already began (state={self.state})")
            replicas = [r for r in self.router.pool.replicas
                        if r.state != "down"]
            if not replicas:
                raise RuntimeError("no live replica to roll")
            swappable = [r for r in replicas
                         if getattr(r, "model", None) is not None]
            if len(swappable) != len(replicas):
                bad = [r.name for r in replicas
                       if r not in swappable]
                raise ValueError(
                    f"replicas {bad} are not in-process; warm-swap "
                    f"rollout needs replicas owning their model")
            self.baseline_name = swappable[0].version
            self._resolve_baseline()
            pct = self.canary_pct
            if pct <= 0 or pct >= 100:
                targets = list(swappable)
            else:
                k = max(1, round(len(swappable) * pct / 100.0))
                k = min(k, len(swappable) - 1) or 1
                targets = swappable[:k]
            self._transition(
                ROLLING, canary_pct=pct,
                targets=[r.name for r in targets],
                baseline=self.baseline_name)
            with obs.span("rollout/roll", version=self.version_name,
                          n=len(targets)):
                for r in targets:
                    self._swap(r, self.version)
            self.canary_replicas = [r.name for r in targets]
            if len(targets) == len(swappable):
                # plain rolling update: nothing left to compare the
                # canary against — declare it promoted
                self._finish(PROMOTED)
                return self
            self.router.set_canary(self.version_name,
                                   self.baseline_name, pct)
            self._err_base = self._cohort_errors()
            self.canary_since = self._clock()
            self._install_slo()
            self._transition(
                CANARY, pct=pct,
                canary_replicas=self.canary_replicas,
                bake_s=self.bake_s)
            return self

    def _resolve_baseline(self):
        """The version object rollback restores: explicit
        ``baseline=``, else looked up by the replicas' current
        version name in the registry the new version came from.
        Resolved BEFORE any replica is touched — a rollout that
        could not roll back must not start."""
        if self._explicit_baseline is not None:
            self.baseline = self._explicit_baseline
            self.baseline_name = str(getattr(
                self.baseline, "name", self.baseline))
            return
        reg = getattr(self.version, "registry", None)
        model_name = getattr(self.version, "model_name", None)
        if reg is not None and model_name is not None:
            try:
                self.baseline = reg.get(model_name,
                                        self.baseline_name)
                return
            except KeyError:
                pass
        raise ValueError(
            f"cannot resolve baseline version "
            f"{self.baseline_name!r} for rollback; register it or "
            f"pass baseline= to rollout()")

    def _swap(self, r, version):
        """One replica's warm swap: drain behind the router (queue
        flushed — zero dropped acked requests), load the version
        (generation bump), restart (re-warm, resume admitting)."""
        with obs.span("rollout/swap_replica", replica=r.name,
                      version=str(getattr(version, "name",
                                          version))):
            flushed = self.router.drain(
                r.name, timeout=self.drain_timeout)
            version.load_into(r.model)
            r.version = str(getattr(version, "name", version))
            self.router.restart_replica(r.name)
        self.swaps.append({"replica": r.name,
                           "version": r.version,
                           "flushed": bool(flushed),
                           "at": self._clock()})

    # -- canary watch --------------------------------------------------------
    def _cohort_errors(self) -> float:
        from analytics_zoo_tpu.pipeline.inference.fleet import \
            _c_cohort_errors
        return float(_c_cohort_errors(self.version_name).value)

    def _install_slo(self):
        if self._engine is None:
            if not slo_mod.enabled():
                return
            self._engine = slo_mod.get_engine()
        rule = slo_mod.SLO(
            id=self._slo_id,
            description=(
                f"canary cohort {self.version_name} error ratio "
                f"stays within its {self.slo_objective:.0%} "
                f"objective"),
            signal={
                "type": "ratio",
                "numerator": {
                    "metric": "zoo_tpu_rollout_errors_total",
                    "labels": {"version": self.version_name}},
                "denominator": {
                    "metric": "zoo_tpu_rollout_requests_total",
                    "labels": {"version": self.version_name}},
            },
            objective=self.slo_objective,
            burn_rate=self.slo_burn_rate,
            windows=self.slo_windows,
            min_events=self.slo_min_events)
        self._engine.add(rule, replace=True)
        diagnostics.add_anomaly_listener(self._on_anomaly)
        self._listener_installed = True

    def _remove_slo(self):
        if self._listener_installed:
            diagnostics.remove_anomaly_listener(self._on_anomaly)
            self._listener_installed = False
        if self._engine is not None:
            self._engine.remove(self._slo_id)

    def _on_anomaly(self, kind: str, fields: dict):
        """Anomaly-pipeline hook: an ``slo_breach`` on the canary
        objective marks the rollout for rollback; the next
        :meth:`tick` (prober thread or manual) executes it — the
        listener itself must stay cheap, it runs inside whoever
        called ``engine.tick``."""
        if kind != "slo_breach":
            return
        if fields.get("slo") != self._slo_id:
            return
        self._breach_reason = (
            f"slo_breach on {self._slo_id}: "
            f"value={fields.get('value')}")

    def tick(self, now: Optional[float] = None) -> dict:
        """One canary-watch pass: roll back on a recorded SLO breach
        or a raw cohort error burst; promote after a clean
        ``bake_s``. No-op outside the canary phase."""
        now = self._clock() if now is None else now
        with self._lock:
            if self.state != CANARY:
                return self.status()
            errs = self._cohort_errors() - self._err_base
            if (self.max_canary_errors is not None
                    and errs >= self.max_canary_errors):
                self._rollback_locked(
                    f"canary error burst: {errs:.0f} errors on "
                    f"cohort {self.version_name} (threshold "
                    f"{self.max_canary_errors})")
            elif self._breach_reason is not None:
                self._rollback_locked(self._breach_reason)
            elif now - self.canary_since >= self.bake_s:
                self._promote_locked()
            return self.status()

    def promote(self):
        """Manually promote a baking canary (operators who have seen
        enough; tests)."""
        with self._lock:
            if self.state != CANARY:
                raise RuntimeError(
                    f"nothing to promote (state={self.state})")
            self._promote_locked()
        return self

    def rollback(self, reason: str = "manual"):
        """Manually roll back a baking canary."""
        with self._lock:
            if self.state != CANARY:
                raise RuntimeError(
                    f"nothing to roll back (state={self.state})")
            self._rollback_locked(reason)
        return self

    def _promote_locked(self):
        self._transition(PROMOTING)
        rest = [r for r in self.router.pool.replicas
                if r.name not in self.canary_replicas
                and r.state != "down"]
        with obs.span("rollout/promote", version=self.version_name,
                      n=len(rest)):
            for r in rest:
                self._swap(r, self.version)
        self.router.clear_canary()
        self._finish(PROMOTED)

    def _rollback_locked(self, reason: str):
        self.reason = reason
        self._transition(ROLLING_BACK, reason=reason)
        # stop feeding the sick cohort FIRST, then unwind its
        # replicas through the same zero-drop drain path
        self.router.clear_canary()
        with obs.span("rollout/rollback",
                      version=self.version_name,
                      n=len(self.canary_replicas)):
            for name in self.canary_replicas:
                r = self.router._replica(name)
                self._swap(r, self.baseline)
        diagnostics.anomaly("rollout_rolled_back",
                            version=self.version_name,
                            reason=reason)
        self._finish(ROLLED_BACK, reason=reason)

    def _finish(self, state: str, **fields):
        self._remove_slo()
        self.canary_since = None
        self._transition(state, **fields)

    # -- introspection -------------------------------------------------------
    def status(self) -> dict:
        """JSON-able lifecycle dump — the live half of
        ``GET /debug/rollout``."""
        with self._lock:
            st = {
                "state": self.state,
                "version": self.version_name,
                "baseline": self.baseline_name,
                "canary_pct": self.canary_pct,
                "canary_replicas": list(self.canary_replicas),
                "bake_s": self.bake_s,
                "max_canary_errors": self.max_canary_errors,
                "slo_id": self._slo_id,
                "replica_versions": {
                    r.name: r.version
                    for r in self.router.pool.replicas},
                "swaps": list(self.swaps),
                "transitions": list(self.transitions),
            }
            if self.reason:
                st["reason"] = self.reason
            if self.canary_since is not None:
                st["canary_age_s"] = round(
                    self._clock() - self.canary_since, 3)
            return st

    def __repr__(self):
        return (f"RolloutController({self.version_name}, "
                f"state={self.state}, pct={self.canary_pct})")
