"""Training runtime (L7): `Estimator` — the TPU-native replacement for the
reference's `InternalDistriOptimizer` → BigDL `DistriOptimizer` stack
(reference `Topology.scala:902-1145`, `pipeline/estimator/Estimator.scala`).

Where the reference runs two Spark jobs per iteration (replica
forward/backward, then shuffle-based gradient aggregation + block-manager
weight broadcast — `docs/docs/wp-bigdl.md:146-160`), here one jit'd
train-step runs SPMD over the device mesh: the batch is sharded on the
data axes, parameters are replicated (or FSDP-sharded), and XLA inserts
the gradient all-reduce over ICI. There is no parameter server and no
host round-trip in the hot loop; the host only feeds the next sharded
batch and reads back scalar metrics.

Checkpointing, TensorBoard scalars (Throughput/Loss/LearningRate — the
same scalars BigDL's TrainSummary records), trigger-based validation, and
gradient clipping mirror the reference's training features (SURVEY.md §5).
"""

from __future__ import annotations

import logging
import os
import pickle
import time
from dataclasses import dataclass
from typing import Any, Callable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from analytics_zoo_tpu.common import diagnostics
from analytics_zoo_tpu.common import faults
from analytics_zoo_tpu.common import observability as obs
from analytics_zoo_tpu.common import slo as slo_lib
from analytics_zoo_tpu.common import tracing
from analytics_zoo_tpu.perf import goodput as goodput_lib
from analytics_zoo_tpu.common.nncontext import NNContext, get_nncontext, \
    logger
from analytics_zoo_tpu.ops import losses as losses_lib
from analytics_zoo_tpu.ops import metrics as metrics_lib
from analytics_zoo_tpu.ops import optimizers as optim_lib
from analytics_zoo_tpu.parallel.mesh import shard_batch, shard_params

logger = logging.getLogger("analytics_zoo_tpu")

# fires after the pickle lands in the tmp file but before any
# durability/rename work — a kill here must leave only an unpromoted
# tmp, never a torn ckpt_*.pkl (tests/test_faults.py proves resume
# skips it)
_CKPT_FAULT = faults.point("estimator/checkpoint_write")


def _fsync_dir(path: str) -> None:
    """fsync a directory so a rename inside it is durable; tolerated
    to fail on filesystems (or platforms) that refuse O_RDONLY dir
    fds — atomicity does not depend on it, only crash durability."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


# ---------------------------------------------------------------------------
# Triggers (BigDL Trigger analog: EveryEpoch / SeveralIteration / MaxEpoch /
# MaxIteration — used for validation, checkpoint and stop conditions)
# ---------------------------------------------------------------------------

class Trigger:
    """Training-control predicate (reference: BigDL `Trigger` algebra —
    everyEpoch/severalIteration/maxEpoch/maxIteration/minLoss/maxScore
    plus and/or composition). ``**state`` carries the current epoch
    loss and validation metrics at epoch-end evaluations."""

    def __call__(self, epoch: int, iteration: int,
                 epoch_end: bool, **state) -> bool:
        raise NotImplementedError

    @staticmethod
    def every_epoch() -> "Trigger":
        return EveryEpoch()

    @staticmethod
    def several_iteration(n: int) -> "Trigger":
        return SeveralIteration(n)

    @staticmethod
    def max_epoch(n: int) -> "Trigger":
        return MaxEpoch(n)

    @staticmethod
    def max_iteration(n: int) -> "Trigger":
        return MaxIteration(n)

    @staticmethod
    def min_loss(v: float) -> "Trigger":
        return MinLoss(v)

    @staticmethod
    def max_score(v: float, metric: "Optional[str]" = None) -> "Trigger":
        return MaxScore(v, metric)

    @staticmethod
    def and_(*triggers: "Trigger") -> "Trigger":
        return TriggerAnd(*triggers)

    @staticmethod
    def or_(*triggers: "Trigger") -> "Trigger":
        return TriggerOr(*triggers)


class EveryEpoch(Trigger):
    def __call__(self, epoch, iteration, epoch_end, **state):
        return epoch_end


class SeveralIteration(Trigger):
    def __init__(self, n: int):
        self.n = int(n)

    def __call__(self, epoch, iteration, epoch_end, **state):
        return iteration > 0 and iteration % self.n == 0


class MaxEpoch(Trigger):
    def __init__(self, n: int):
        self.n = int(n)

    def __call__(self, epoch, iteration, epoch_end, **state):
        return epoch >= self.n


class MaxIteration(Trigger):
    def __init__(self, n: int):
        self.n = int(n)

    def __call__(self, epoch, iteration, epoch_end, **state):
        return iteration >= self.n


class MinLoss(Trigger):
    """Stop once the epoch training loss drops to ``v`` (BigDL
    `Trigger.minLoss`); evaluated at epoch end."""

    def __init__(self, v: float):
        self.v = float(v)

    def __call__(self, epoch, iteration, epoch_end, **state):
        loss = state.get("loss")
        return epoch_end and loss is not None and loss <= self.v


class MaxScore(Trigger):
    """Stop once a validation metric reaches ``v`` (BigDL
    `Trigger.maxScore`); uses ``metric`` or the first validation
    metric reported."""

    def __init__(self, v: float, metric: "Optional[str]" = None):
        self.v = float(v)
        self.metric = metric

    def __call__(self, epoch, iteration, epoch_end, **state):
        metrics = state.get("val_metrics") or {}
        if not (epoch_end and metrics):
            return False
        if self.metric is not None:
            score = metrics.get(self.metric)
        else:
            score = next(iter(metrics.values()), None)
        return score is not None and score >= self.v


class TriggerAnd(Trigger):
    def __init__(self, *triggers: Trigger):
        self.triggers = triggers

    def __call__(self, *a, **state):
        return all(t(*a, **state) for t in self.triggers)


class TriggerOr(Trigger):
    def __init__(self, *triggers: Trigger):
        self.triggers = triggers

    def __call__(self, *a, **state):
        return any(t(*a, **state) for t in self.triggers)


# ---------------------------------------------------------------------------
# In-memory dataset (the FeatureSet protocol's simplest implementation;
# feature.FeatureSet provides the cached/sharded/tiered version)
# ---------------------------------------------------------------------------

class ArrayDataset:
    """Numpy (x, y) pairs with per-epoch shuffling and fixed-size batches.

    Implements the data protocol the Estimator consumes:
    ``num_samples`` and ``iter_batches(batch_size, shuffle, seed)``.
    Incomplete trailing batches are dropped during training (static shapes
    keep XLA from recompiling; the reference similarly requires
    batch % cores == 0, `P/pipeline/api/net.py:741-749`).
    """

    def __init__(self, x, y=None):
        from analytics_zoo_tpu.feature.feature_set import \
            normalize_labels
        self.x = x if isinstance(x, (list, tuple)) else [x]
        self.x = [np.asarray(a) for a in self.x]
        # normalize_labels is the one decision point for single-array
        # vs multi-output label lists (scalar lists stay one array)
        y_cols, self._multi_y = normalize_labels(y)
        self.y = (y_cols if self._multi_y
                  else y_cols[0] if y_cols else None)
        n = self.x[0].shape[0]
        for a in self.x:
            if a.shape[0] != n:
                raise ValueError("inconsistent sample counts in x")
        for a in y_cols:
            if a.shape[0] != n:
                raise ValueError("x and y sample counts differ")
        self._n = n

    @property
    def num_samples(self) -> int:
        return self._n

    def iter_batches(self, batch_size: int, shuffle: bool = True,
                     seed: int = 0, drop_last: bool = True):
        idx = np.arange(self._n)
        if shuffle:
            np.random.RandomState(seed).shuffle(idx)
        end = (self._n - self._n % batch_size) if drop_last else self._n
        for start in range(0, end, batch_size):
            sel = idx[start:start + batch_size]
            xb = [a[sel] for a in self.x]
            xb = xb[0] if len(xb) == 1 else xb
            if self.y is None:
                yb = None
            elif self._multi_y:
                yb = [a[sel] for a in self.y]
            else:
                yb = self.y[sel]
            yield xb, yb


def to_dataset(data, y=None):
    if hasattr(data, "iter_batches"):
        return data
    if hasattr(data, "to_arrays"):
        # TextSet / ImageSet passed straight to fit/evaluate/predict
        # (reference `model.fit(train_set, ...)` over TextSet,
        # `qa_ranker.py`; ImageSet via `ImageSet.toDataSet`)
        xs, ys = data.to_arrays()
        return ArrayDataset(xs, ys if y is None else y)
    from analytics_zoo_tpu.feature.rdd import is_rdd_like, \
        is_spark_dataframe
    if is_rdd_like(data) or is_spark_dataframe(data):
        # RDD[Sample] / Spark-DataFrame ingest (reference
        # `KerasNet.fit(RDD[Sample])`, Topology.scala:411): this host
        # collects its partition share into a cached FeatureSet
        from analytics_zoo_tpu.feature.feature_set import FeatureSet
        return FeatureSet.from_rdd(data)
    return ArrayDataset(data, y)


def _prefetch_iter(it, place, depth: int):
    """Pipeline host batch prep + device placement `depth` batches
    ahead of compute on a background thread (flax
    ``prefetch_to_device`` pattern; role of the reference's
    executor-side Sample→MiniBatch pipelining, SURVEY.md §3.2).

    ``place`` runs IN the worker thread (numpy prep + ``device_put``
    are thread-safe and async); exceptions re-raise at the consumer's
    next pull. ``depth<=0`` = synchronous (debugging / profiling the
    unpipelined path)."""
    if depth <= 0:
        for item in it:
            yield place(item)
        return
    import queue
    import threading

    q: "queue.Queue" = queue.Queue(maxsize=depth)
    stop = threading.Event()
    sentinel = object()

    def _put(obj) -> bool:
        while not stop.is_set():
            try:
                q.put(obj, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def worker():
        try:
            for item in it:
                if stop.is_set() or not _put(place(item)):
                    return
            _put(sentinel)
        except BaseException as e:  # noqa: BLE001 — re-raised below
            _put(e)

    t = threading.Thread(target=worker, daemon=True,
                         name="zoo-tpu-prefetch")
    t.start()
    try:
        while True:
            item = q.get()
            if item is sentinel:
                return
            if isinstance(item, BaseException):
                raise item
            yield item
    finally:
        stop.set()


def _timed_iter(it):
    """Wrap an iterator, yielding ``(wait_s, item)`` — how long the
    consumer blocked waiting for each item. With the prefetch worker
    ahead of compute this is ~0; a sustained positive wait means the
    input pipeline, not the device, is the bottleneck."""
    it = iter(it)
    while True:
        t0 = time.perf_counter()
        try:
            item = next(it)
        except StopIteration:
            return
        yield time.perf_counter() - t0, item


def _prefetch_depth() -> int:
    raw = os.environ.get("ZOO_TPU_PREFETCH", "2")
    try:
        return int(raw)
    except ValueError:
        logger.warning("ZOO_TPU_PREFETCH=%r is not an integer; "
                       "using default depth 2", raw)
        return 2


def _apply_loss(loss_fn, y, out):
    """Keras multi-output semantics: a list/tuple of model outputs
    against a list/tuple of label columns sums per-output losses
    (``loss`` may itself be a list, one fn per output — the
    reference's nested-TensorMeta TFPark contract)."""
    if isinstance(out, (list, tuple)) and isinstance(y, (list, tuple)):
        fns = (list(loss_fn) if isinstance(loss_fn, (list, tuple))
               else [loss_fn] * len(out))
        if not (len(fns) == len(out) == len(y)):
            raise ValueError(
                f"multi-output mismatch: {len(out)} outputs, "
                f"{len(y)} label columns, {len(fns)} losses")
        total = fns[0](y[0], out[0])
        for f, t, o in zip(fns[1:], y[1:], out[1:]):
            total = total + f(t, o)
        return total
    if isinstance(loss_fn, (list, tuple)):
        raise ValueError(
            f"a list of {len(loss_fn)} losses needs a multi-output "
            f"model AND a list of label columns (outputs are "
            f"{type(out).__name__}, labels {type(y).__name__})")
    # mixed structures (list outputs + one packed label array, or the
    # reverse) pass through to the single loss fn: custom joint losses
    # legitimately unpack them (e.g. tfpark IntentEntity)
    return loss_fn(y, out)


def _cast_floats(x, dtype):
    """Cast floating leaves of an input (array or list of arrays);
    ints (ids/labels) pass through."""
    def c(a):
        a = jnp.asarray(a)
        return a.astype(dtype) if jnp.issubdtype(a.dtype, jnp.floating) \
            else a
    if isinstance(x, (list, tuple)):
        return [c(a) for a in x]
    return c(x)


# ---------------------------------------------------------------------------
# Estimator
# ---------------------------------------------------------------------------

@dataclass
class TrainResult:
    history: "list[dict]"
    params: Any
    opt_state: Any
    step: int


class Estimator:
    """`Estimator.train/evaluate` (reference
    `pipeline/estimator/Estimator.scala:31-56`) over a pjit'd step."""

    def __init__(self, model, optimizer="adam", loss="mse",
                 metrics: Optional[List] = None,
                 ctx: Optional[NNContext] = None,
                 parallel_mode: str = "dp",
                 dtype_policy: Optional[str] = None,
                 augment: Optional[Callable] = None):
        if parallel_mode not in ("dp", "fsdp", "tp", "ep"):
            raise ValueError("parallel_mode must be dp|fsdp|tp|ep")
        # default: bf16 activations on TPU (the MXU-native dtype,
        # PERF.md), exact f32 elsewhere (golden tests, CPU parity);
        # explicit arg > env > backend default
        announce_bf16_default = False
        if dtype_policy is None and not os.environ.get(
                "ZOO_TPU_DTYPE_POLICY"):
            dtype_policy = ("mixed_bfloat16"
                            if jax.default_backend() in ("tpu", "axon")
                            else "float32")
            announce_bf16_default = dtype_policy == "mixed_bfloat16"
        else:
            dtype_policy = dtype_policy or os.environ.get(
                "ZOO_TPU_DTYPE_POLICY")
        if dtype_policy not in ("float32", "mixed_bfloat16"):
            raise ValueError(
                "dtype_policy must be float32|mixed_bfloat16")
        # mixed_bfloat16: activations/compute in bf16 (the MXU-native
        # dtype), params + loss in f32 — the framework-wide policy the
        # round-1 bench applied ad hoc (VERDICT "What's weak" #8)
        self.dtype_policy = dtype_policy
        self.augment = augment  # train-only on-device augmentation
        self.model = model
        self.ctx = ctx or get_nncontext()
        if announce_bf16_default and not getattr(
                Estimator, "_warned_bf16_default", False):
            # one-time signal: callers who never chose a policy get
            # changed numerics on TPU — make that traceable. Emitted
            # AFTER ctx resolution: get_nncontext() configures the
            # package logger, so an INFO fired earlier in a fresh
            # process would be dropped at the root WARNING level.
            Estimator._warned_bf16_default = True
            logger.info(
                "Estimator defaulting to mixed_bfloat16 on "
                "%s backend (pass dtype_policy='float32' or "
                "set ZOO_TPU_DTYPE_POLICY to override)",
                jax.default_backend())
        self.parallel_mode = parallel_mode
        # a list of losses = one per model output (multi-output
        # training; _apply_loss sums them)
        if isinstance(loss, (list, tuple)):
            self.loss_fn = [losses_lib.get(l) for l in loss]
            for f in self.loss_fn:
                base = getattr(f, "func", f)
                if base is losses_lib.rank_hinge or getattr(
                        base, "__name__", "") == "rank_hinge":
                    # pairwise losses need the whole-batch eval path,
                    # which the per-output vmap decomposition bypasses
                    raise ValueError(
                        "rank_hinge is pairwise and not supported "
                        "inside a multi-output loss list")
        else:
            self.loss_fn = losses_lib.get(loss)
        self.metrics = [metrics_lib.get(m) for m in (metrics or [])]
        self._base_tx = optim_lib.get(optimizer)
        self._clip: Optional[optax.GradientTransformation] = None
        self._lr_fn = self._extract_lr_fn(optimizer)

        self.params = None
        self.opt_state = None
        self.step = 0
        self._train_step = None
        self._eval_step = None
        self._predict_fn = None

        # training features
        self.checkpoint_path: Optional[str] = None
        self.checkpoint_trigger: Trigger = EveryEpoch()
        self.tensorboard_dir: Optional[str] = None
        self.tensorboard_app: str = "zoo_tpu"
        self._tb_writer = None
        # True only for writers _tb() opened itself — train() must not
        # close a caller-injected writer (duck-typed fakes/adapters)
        self._tb_owns_writer = False
        self._summary_triggers: "Dict[str, Trigger]" = {}
        # jax.profiler trace capture (SURVEY §5: the TPU analog of the
        # reference's TrainSummary observability)
        self._profile_dir: Optional[str] = None
        self._profile_start = 0
        self._profile_end = 0
        self._profiling = False

    # -- knobs (reference `Topology.scala:197-284`) -------------------------
    @staticmethod
    def _extract_lr_fn(optimizer):
        if isinstance(optimizer, optim_lib.ZooOptimizer):
            lr = optimizer.lr
            return lr if callable(lr) else (lambda step: lr)
        return lambda step: float("nan")

    def set_gradient_clipping_by_l2_norm(self, clip_norm: float):
        self._clip = optax.clip_by_global_norm(clip_norm)
        self._train_step = None
        return self

    def set_constant_gradient_clipping(self, min_value: float,
                                       max_value: float):
        # optax.clip is symmetric; emulate [min, max] clamping
        lo, hi = float(min_value), float(max_value)

        def clamp(updates):
            return jax.tree_util.tree_map(
                lambda g: jnp.clip(g, lo, hi), updates)
        self._clip = optax.stateless(lambda u, p=None: clamp(u))
        self._train_step = None
        return self

    def set_checkpoint(self, path: str,
                       trigger: Optional[Trigger] = None):
        self.checkpoint_path = path
        if trigger is not None:
            self.checkpoint_trigger = trigger
        return self

    def set_tensorboard(self, log_dir: str, app_name: str = "zoo_tpu"):
        self.tensorboard_dir = log_dir
        self.tensorboard_app = app_name
        return self

    def set_summary_trigger(self, name: str, trigger: Trigger):
        """Enable extra summaries on a trigger (BigDL
        `TrainSummary.setSummaryTrigger`). Supported: "Parameters" —
        per-layer weight histograms (device fetch per firing; keep the
        trigger sparse on remote transports) — and "LearningRate" —
        the current schedule value, written to TensorBoard at firing
        time and mirrored to the ``zoo_tpu_learning_rate`` gauge."""
        if name not in ("Parameters", "LearningRate"):
            raise ValueError(
                f"unsupported summary {name!r}; supported: "
                f"Parameters, LearningRate")
        self._summary_triggers[name] = trigger
        return self

    def _record_lr(self, tb, step: int) -> float:
        """Schedule value at ``step`` → the ``zoo_tpu_learning_rate``
        gauge, plus the TensorBoard ``LearningRate`` scalar when a
        writer is passed (the "LearningRate" summary-trigger path)."""
        lr = float(self._lr_fn(step))
        if lr == lr:  # not NaN (a ZooOptimizer schedule is attached)
            obs.gauge("zoo_tpu_learning_rate",
                      help="current learning-rate schedule value"
                      ).set(lr)
            if tb is not None:
                tb.add_scalar("LearningRate", lr, step)
        return lr

    def _write_param_histograms(self, tb, step: int):
        # ONE whole-tree fetch (per-leaf device_get would be a
        # round-trip storm on remote transports)
        flat, _ = jax.tree_util.tree_flatten_with_path(
            jax.device_get(self.params))
        for path, leaf in flat:
            tag = jax.tree_util.keystr(path).strip("'[]").replace(
                "']['", "/")
            tb.add_histogram(f"Parameters/{tag}", np.asarray(leaf),
                             step)

    def set_dtype_policy(self, policy: str):
        """"float32" or "mixed_bfloat16" (bf16 activations, f32
        params/loss — the TPU mixed-precision recipe)."""
        if policy not in ("float32", "mixed_bfloat16"):
            raise ValueError(
                "dtype_policy must be float32|mixed_bfloat16")
        self.dtype_policy = policy
        self._train_step = None
        self._eval_step = None
        self._predict_fn = None
        return self

    def set_profile(self, log_dir: str, start_step: int = 3,
                    n_steps: int = 3):
        """Capture a ``jax.profiler`` trace of training steps
        [start_step, start_step + n_steps) into ``log_dir`` —
        TensorBoard-viewable (reference observability analog,
        Topology.scala:197-229 / SURVEY §5). Default skips the compile
        step so the trace shows steady-state device time."""
        self._profile_dir = log_dir
        self._profile_start = int(start_step)
        self._profile_end = int(start_step) + int(n_steps)
        return self

    def _tb(self):
        if self.tensorboard_dir is None:
            return None
        if self._tb_writer is None:
            from torch.utils.tensorboard import SummaryWriter
            self._tb_writer = SummaryWriter(
                os.path.join(self.tensorboard_dir, self.tensorboard_app))
            self._tb_owns_writer = True
        return self._tb_writer

    def _place_params(self, params):
        """DP: replicate (the reference's broadcast-weights semantics);
        FSDP: ZeRO-shard over the 'fsdp' mesh axis; TP: Megatron-style
        output-dim kernel sharding over 'model' (GSPMD propagates the
        activation shardings and inserts the collectives); EP: shard
        layer-declared expert-stacked params over 'expert', replicate
        the rest."""
        if self.parallel_mode == "fsdp":
            from analytics_zoo_tpu.parallel.mesh import shard_params_fsdp
            return shard_params_fsdp(params, self.ctx.mesh)
        if self.parallel_mode == "tp":
            from analytics_zoo_tpu.parallel.mesh import shard_params_tp
            return shard_params_tp(params, self.ctx.mesh)
        if self.parallel_mode == "ep":
            from analytics_zoo_tpu.parallel.mesh import (
                collect_ep_paths, shard_params_ep)
            return shard_params_ep(
                params, self.ctx.mesh,
                ep_paths=collect_ep_paths(self.model))
        return shard_params(params, self.ctx.mesh)

    # -- compiled steps -----------------------------------------------------
    def _tx(self) -> optax.GradientTransformation:
        mask = self.model.trainable_mask(self.params)
        labels = jax.tree_util.tree_map(
            lambda t: "train" if t else "freeze", mask)
        parts = []
        if self._clip is not None:
            parts.append(self._clip)
        parts.append(self._base_tx)
        return optax.multi_transform(
            {"train": optax.chain(*parts), "freeze": optax.set_to_zero()},
            labels)

    @staticmethod
    def _merge_updates(params, updates):
        """Recursively fold BatchNorm-style state updates into params.
        Lists merge element-wise with ``None`` meaning "unchanged"
        (the tfpark bridge's sparse weight-list updates)."""
        if updates is None:
            return params
        if isinstance(updates, (list, tuple)) and \
                isinstance(params, (list, tuple)):
            return type(params)(
                Estimator._merge_updates(p, u)
                for p, u in zip(params, updates))
        if not isinstance(updates, dict) or not isinstance(params, dict):
            return updates
        out = dict(params)
        for k, v in updates.items():
            out[k] = Estimator._merge_updates(params.get(k), v)
        return out

    def _build_train_step(self, tx):
        model = self.model
        loss_fn = self.loss_fn
        mixed = self.dtype_policy == "mixed_bfloat16"
        augment = self.augment

        def train_step(params, opt_state, rng, x, y):
            if augment is not None:
                # train-only, traced into the step (on-device; see
                # feature/image/device_transforms) — eval/predict
                # never augment, like the reference's train-phase
                # transformer chains
                r_aug, rng = jax.random.split(rng)
                x = augment(r_aug, x)
            if mixed:
                x = _cast_floats(x, jnp.bfloat16)

            def compute_loss(p):
                out, state_upd = model.apply(p, x, training=True, rng=rng)
                if mixed:  # loss in f32 for numeric stability
                    out = _cast_floats(out, jnp.float32)
                loss = _apply_loss(loss_fn, y, out)
                loss = loss + model.regularization_loss(p)
                return loss, state_upd

            (loss, state_upd), grads = jax.value_and_grad(
                compute_loss, has_aux=True)(params)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            if state_upd:
                params = Estimator._merge_updates(params, state_upd)
            return params, opt_state, loss

        return jax.jit(train_step, donate_argnums=(0, 1))

    def _build_eval_step(self):
        model = self.model
        metrics = self.metrics
        loss_fn = self.loss_fn

        # pairwise losses can't be decomposed per-sample (vmapping one
        # would see an empty negative set → NaN); detect rank_hinge
        # through functools.partial wrapping too
        base_loss = getattr(loss_fn, "func", loss_fn)
        pairwise = base_loss is losses_lib.rank_hinge or \
            getattr(base_loss, "__name__", "") == "rank_hinge"
        margin = float(getattr(loss_fn, "keywords", {})
                       .get("margin", 1.0)) if pairwise else 1.0

        mixed = self.dtype_policy == "mixed_bfloat16"

        def eval_step(params, x, y, w):
            if mixed:
                x = _cast_floats(x, jnp.bfloat16)
            out = model.forward(params, x, training=False)
            if mixed:
                out = _cast_floats(out, jnp.float32)
            if pairwise:
                # pairwise over adjacent (pos, neg) rows — mask pairs,
                # not samples
                scores = out.reshape(-1)
                wp = w[0::2] * w[1::2]
                per_pair = jnp.maximum(
                    margin - scores[0::2] + scores[1::2], 0.0)
                loss_sum, count = jnp.sum(per_pair * wp), jnp.sum(wp)
            else:
                # per-sample losses so padding samples (w=0) drop out;
                # each sample is evaluated as a batch of 1 so loss fns
                # keep their batch-mean semantics (tree_map: y/out may
                # be multi-output lists)
                _b1 = lambda tree: jax.tree_util.tree_map(
                    lambda a: a[None], tree)
                per = jax.vmap(
                    lambda t, p: _apply_loss(
                        loss_fn, _b1(t), _b1(p)))(y, out)
                loss_sum, count = jnp.sum(per * w), jnp.sum(w)
            stats = {"loss": {"loss_sum": loss_sum, "count": count}}
            if metrics and isinstance(out, (list, tuple)):
                # built-in metrics assume single arrays; fail at trace
                # time with the real reason, not a TypeError deep in
                # the arithmetic
                raise ValueError(
                    "metrics are not supported with multi-output "
                    "models yet — evaluate with metrics=[] (the "
                    "summed multi-output loss is still reported)")
            for m in metrics:
                if _accepts_mask(m):
                    stats[m.name] = m.batch_stats(y, out, mask=w)
                else:  # user Metric subclass on the pre-mask signature
                    stats[m.name] = m.batch_stats(y, out)
            return stats

        for m in metrics:
            if not _accepts_mask(m):
                logger.warning(
                    "metric %s has a batch_stats(y_true, y_pred) without "
                    "a mask parameter: padded tail samples may bias it; "
                    "add mask=None support for exact results", m.name)
        return jax.jit(eval_step)

    def _build_predict_fn(self):
        model = self.model
        mixed = self.dtype_policy == "mixed_bfloat16"

        def predict_fn(params, x):
            if mixed:
                x = _cast_floats(x, jnp.bfloat16)
            out = model.forward(params, x, training=False)
            return _cast_floats(out, jnp.float32) if mixed else out

        return jax.jit(predict_fn)

    def _ensure_initialized(self, sample_batch=None):
        if self.params is None:
            # host init, then ONE sharded placement — device-0 never
            # holds a transient full replica under FSDP/TP
            self.params = self._place_params(self.model.init_params(
                self.ctx.next_rng_key(), device="host"))
        if self.opt_state is None:
            tx = self._tx()
            # one compiled program, one dispatch — eager tx.init is a
            # per-leaf op storm over a remote-device transport, and jit
            # inherits the params' shardings for the momentum/adam
            # buffers (the state lands pre-sharded under FSDP/TP/EP)
            self.opt_state = jax.jit(tx.init)(self.params)
            self._train_step = self._build_train_step(tx)
        elif self._train_step is None:
            self._train_step = self._build_train_step(self._tx())

    def _measure_step_flops(self, rng, xb, yb):
        """Executed-semantics FLOPs of one compiled train step
        (:mod:`analytics_zoo_tpu.perf.flops` — dilation zeros counted
        the way the MXU executes them), via a one-off AOT retrace.
        None when the graph cannot be lowered or parsed; the goodput
        ledger then reports MFU as 0 but keeps the wall-time
        decomposition live."""
        try:
            from analytics_zoo_tpu.perf import flops as flops_lib
            lowered = self._train_step.lower(
                self.params, self.opt_state, rng, xb, yb)
            return flops_lib.executed_flops(
                flops_lib.hlo_text(lowered))
        except Exception:
            return None

    # -- API ---------------------------------------------------------------
    def train(self, data, y=None, batch_size: int = 32,
              nb_epoch: int = 1,
              validation_data=None,
              validation_trigger: Optional[Trigger] = None,
              end_trigger: Optional[Trigger] = None) -> TrainResult:
        ds = to_dataset(data, y)
        self.ctx.check_batch_size(batch_size)
        self._ensure_initialized()
        tb = self._tb()
        validation_trigger = validation_trigger or EveryEpoch()
        base_rng = self.ctx.next_rng_key()
        history: "list[dict]" = []
        stop = False
        # profile window is relative to THIS run (self.step may already
        # be far along from a previous train() call)
        p_start = self.step + self._profile_start
        p_end = self.step + self._profile_end
        # telemetry (docs/observability.md): per-step host wall time is
        # dispatch-to-dispatch — under queue backpressure it converges
        # to device step time without forcing a per-step sync
        step_hist = obs.histogram(
            "zoo_tpu_train_step_seconds",
            help="host wall time per training step "
                 "(dispatch-to-dispatch)")
        steps_total = obs.counter("zoo_tpu_train_steps_total",
                                  help="training steps dispatched")
        examples_total = obs.counter(
            "zoo_tpu_train_examples_total",
            help="training examples consumed")
        first_step = True
        # diagnostics (docs/observability.md anomaly catalog):
        # straggler steps + recompile storms fire structured events
        watcher = diagnostics.StepTimeWatcher()
        diagnostics.install_recompile_monitor()
        # judgement layer: shipped training objectives (docs/slo.md)
        # + the live goodput/MFU ledger (docs/observability.md) —
        # each env-gated (ZOO_TPU_SLO / ZOO_TPU_GOODPUT)
        slo_lib.ensure_default_slos("training")
        ledger = goodput_lib.ledger_for_backend()
        # ZOO_TPU_TRACE_SYNC=1 adds a block_until_ready per step so
        # step traces carry true device time — a per-step sync, so
        # opt-in (it caps dispatch pipelining)
        trace_sync = os.environ.get(
            "ZOO_TPU_TRACE_SYNC", "0") == "1"

        try:
            for epoch in range(1, nb_epoch + 1):
                n_records = 0
                # keep losses on-device during the epoch: fetching per
                # step would stall the dispatch pipeline (expensive
                # over remote device transports)
                pending: "list[tuple[int, Any]]" = []
                mesh = self.ctx.mesh

                def _place(batch, mesh=mesh):
                    xb, yb = batch
                    return (shard_batch(xb, mesh),
                            shard_batch(yb, mesh))

                # closing(): break/exception must stop the worker
                # thread NOW, not at GC — a retained traceback would
                # otherwise pin depth+1 device-resident batches
                # (notebook OOM-retry trap)
                batches = _prefetch_iter(
                    ds.iter_batches(batch_size, shuffle=True,
                                    seed=epoch),
                    _place, _prefetch_depth())
                ep_span = obs.span("train/epoch", epoch=epoch,
                                   step=self.step)
                with ep_span:
                    try:
                        t_prev = time.perf_counter()
                        t_led_prev = t_prev
                        for wait_s, (xb, yb) in _timed_iter(batches):
                            with tracing.trace(
                                      "train/step", step=self.step + 1,
                                      epoch=epoch) as tr:
                                rng = jax.random.fold_in(base_rng,
                                                         self.step)
                                if self._profile_dir and \
                                        not self._profiling and \
                                        self.step + 1 >= p_start:
                                    jax.profiler.start_trace(
                                        self._profile_dir)
                                    self._profiling = True
                                # step markers line up with our spans in
                                # on-demand XLA profiles (/debug/profile)
                                t_disp = time.perf_counter()
                                with jax.profiler.StepTraceAnnotation(
                                        "train", step_num=self.step):
                                    self.params, self.opt_state, loss = \
                                        self._train_step(
                                            self.params, self.opt_state,
                                            rng, xb, yb)
                                dispatch_s = (time.perf_counter()
                                              - t_disp)
                                self.step += 1
                                device_s = None
                                if trace_sync:
                                    t_dev = time.perf_counter()
                                    jax.block_until_ready(loss)
                                    device_s = (time.perf_counter()
                                                - t_dev)
                                if first_step:
                                    # includes XLA compile when this call
                                    # traced a fresh step fn; the one-time
                                    # sync is noise next to the compile
                                    jax.block_until_ready(loss)
                                    obs.gauge(
                                        "zoo_tpu_train_first_step_seconds",
                                        help="first-step wall time of the "
                                             "latest run (incl. compile)"
                                    ).set(time.perf_counter() - t_prev)
                                    first_step = False
                                    if ledger is not None and \
                                            goodput_lib.flops_enabled():
                                        ledger.set_flops_per_step(
                                            self._measure_step_flops(
                                                rng, xb, yb))
                                if self._profiling and self.step >= p_end:
                                    jax.block_until_ready(loss)
                                    jax.profiler.stop_trace()
                                    self._profiling = False
                                    self._profile_dir = None
                                now = time.perf_counter()
                                step_hist.observe(now - t_prev)
                                watcher.observe(now - t_prev,
                                                step=self.step)
                                t_prev = now
                                steps_total.inc()
                                examples_total.inc(batch_size)
                                n_records += batch_size
                                pending.append((self.step, loss))
                                if self._summary_triggers:
                                    trig = self._summary_triggers.get(
                                        "Parameters")
                                    if tb is not None and trig is not None \
                                            and trig(epoch, self.step,
                                                     False):
                                        self._write_param_histograms(
                                            tb, self.step)
                                    trig = self._summary_triggers.get(
                                        "LearningRate")
                                    if trig is not None and trig(
                                            epoch, self.step, False):
                                        self._record_lr(tb, self.step)
                                ckpt_s = None
                                if self.checkpoint_path and \
                                        self.checkpoint_trigger(
                                            epoch, self.step, False):
                                    t_ck = time.perf_counter()
                                    self.save_checkpoint()
                                    ckpt_s = (time.perf_counter()
                                              - t_ck)
                                tr.annotate(
                                    data_wait_s=round(wait_s, 6),
                                    dispatch_s=round(dispatch_s, 6),
                                    device_s=device_s,
                                    checkpoint_s=ckpt_s)
                                if ledger is not None:
                                    # ledger wall is iteration-to-
                                    # iteration (incl. checkpoint) so
                                    # the decomposition sums to 1
                                    t_led = time.perf_counter()
                                    ledger.note_step(
                                        t_led - t_led_prev,
                                        data_wait_s=wait_s,
                                        dispatch_s=dispatch_s,
                                        checkpoint_s=ckpt_s or 0.0)
                                    t_led_prev = t_led
                                if end_trigger is not None and end_trigger(
                                        epoch - 1, self.step, False):
                                    stop = True
                                    break
                    finally:
                        # break/exception must stop the worker thread
                        # NOW, not at GC — a retained traceback would
                        # otherwise pin depth+1 device-resident
                        # batches (notebook OOM-retry trap)
                        batches.close()

                    losses_np = ([float(v) for v in
                                  jax.device_get(
                                      [v for _, v in pending])]
                                 if pending else [])
                dt = max(ep_span.elapsed, 1e-9)
                if tb is not None:
                    for (s, _), lf in zip(pending, losses_np):
                        tb.add_scalar("Loss", lf, s)
                        lr = self._lr_fn(s)
                        if lr == lr:  # not NaN
                            tb.add_scalar("LearningRate", lr, s)
                epoch_batches = len(pending)
                epoch_loss = float(np.sum(losses_np))
                throughput = n_records / dt
                obs.gauge(
                    "zoo_tpu_train_throughput_examples_per_sec",
                    help="epoch training throughput").set(throughput)
                self._record_lr(None, self.step)  # gauge refresh
                diagnostics.update_device_memory_gauges()
                entry = {"epoch": epoch,
                         "loss": epoch_loss / max(epoch_batches, 1),
                         "throughput": throughput, "step": self.step}
                if ledger is not None:
                    gp = ledger.epoch_summary(epoch=epoch)
                    if gp is not None:
                        entry["goodput"] = gp
                if tb is not None:
                    tb.add_scalar("Throughput", throughput, self.step)
                if validation_data is not None and validation_trigger(
                        epoch, self.step, True):
                    # keras-style (x_val, y_val) tuples are
                    # (data, labels), not a two-input feature list
                    if isinstance(validation_data, tuple) and \
                            len(validation_data) == 2 and not hasattr(
                                validation_data, "iter_batches"):
                        val = self.evaluate(validation_data[0],
                                            validation_data[1],
                                            batch_size=batch_size)
                    else:
                        val = self.evaluate(validation_data,
                                            batch_size=batch_size)
                    entry.update(
                        {f"val_{k}": v for k, v in val.items()})
                    if tb is not None:
                        for k, v in val.items():
                            tb.add_scalar(f"Validation/{k}", v,
                                          self.step)
                if self.checkpoint_path and self.checkpoint_trigger(
                        epoch, self.step, True):
                    self.save_checkpoint()
                if self._summary_triggers:
                    trig = self._summary_triggers.get("Parameters")
                    if tb is not None and trig is not None and trig(
                            epoch, self.step, True):
                        # epoch-end firing (EveryEpoch-style triggers)
                        self._write_param_histograms(tb, self.step)
                    trig = self._summary_triggers.get("LearningRate")
                    if trig is not None and trig(
                            epoch, self.step, True):
                        self._record_lr(tb, self.step)
                history.append(entry)
                logger.info("epoch %d: %s", epoch, entry)
                if stop or (end_trigger is not None and end_trigger(
                        epoch, self.step, True,
                        loss=entry.get("loss"),
                        val_metrics={k[4:]: v for k, v in entry.items()
                                     if k.startswith("val_")})):
                    break
        finally:
            if self._profiling:  # run ended inside the trace window
                jax.profiler.stop_trace()
                self._profiling = False
                self._profile_dir = None
            if self._tb_writer is not None:
                self._tb_writer.flush()
                if self._tb_owns_writer:
                    # per-fit lifecycle for writers _tb() opened:
                    # close on every exit path (incl. exceptions) — a
                    # writer leaked across runs keeps its event file
                    # growing and holds the fd until GC. Injected
                    # writers stay attached: the caller owns them.
                    self._tb_writer.close()
                    self._tb_writer = None
                    self._tb_owns_writer = False
        # durable on return: join any in-flight async checkpoint write
        self.wait_for_checkpoint()
        return TrainResult(history, self.params, self.opt_state, self.step)

    def evaluate(self, data, y=None, batch_size: int = 32
                 ) -> "dict[str, float]":
        ds = to_dataset(data, y)
        self._ensure_initialized()
        if self._eval_step is None:
            self._eval_step = self._build_eval_step()
        totals: "dict[str, dict[str, np.ndarray]]" = {}
        # every batch (incl. the tail) is padded to ONE static shape
        # divisible by the data-parallel size and evaluated with a
        # per-sample {0,1} weight vector: no tail samples are dropped
        # (round-1 dropped them, biasing metrics — VERDICT.md weak #3)
        # and the eval step compiles exactly once
        dp = self.ctx.data_parallel_size
        padded = -(-batch_size // dp) * dp
        mesh = self.ctx.mesh

        def _place(batch, mesh=mesh):
            xb, yb = batch
            bsize = _batch_dim(xb)
            w = np.zeros((padded,), np.float32)
            w[:bsize] = 1.0
            if bsize < padded:
                xb = _pad_batch(xb, padded)
                yb = _pad_batch(yb, padded) if yb is not None else None
            return (shard_batch(xb, mesh), shard_batch(yb, mesh),
                    shard_batch(w, mesh))

        batches = _prefetch_iter(
            ds.iter_batches(batch_size, shuffle=False,
                            drop_last=False),
            _place, _prefetch_depth())
        try:
            # each evaluate() call is one trace: the eval span (and
            # any nested spans) lands in /debug/traces & the exporter
            with tracing.trace("train/eval_run", step=self.step), \
                    obs.span("train/eval", step=self.step,
                             n=ds.num_samples):
                for xb, yb, wb in batches:
                    with jax.profiler.StepTraceAnnotation(
                            "eval", step_num=self.step):
                        stats = jax.device_get(
                            self._eval_step(self.params, xb, yb, wb))
                    for mname, mstats in stats.items():
                        acc = totals.setdefault(mname, {})
                        for k, v in mstats.items():
                            acc[k] = acc.get(k, 0) + np.asarray(v)
        finally:
            batches.close()  # deterministic worker shutdown
        out = {}
        if "loss" in totals:
            out["loss"] = float(totals["loss"]["loss_sum"] /
                                np.maximum(totals["loss"]["count"], 1.0))
        for m in self.metrics:
            if m.name in totals:
                out[m.name] = m.aggregate(totals[m.name])
        return out

    def predict(self, data, batch_size: int = 32) -> np.ndarray:
        ds = to_dataset(data)
        self._ensure_initialized()
        if self._predict_fn is None:
            self._predict_fn = self._build_predict_fn()
        outs = []
        n = ds.num_samples
        # compiled batch must divide over the data-parallel size; pad
        # every chunk (incl. full ones when batch_size itself doesn't
        # divide) and trim after
        dp = self.ctx.data_parallel_size
        padded = -(-batch_size // dp) * dp
        mesh = self.ctx.mesh

        def _place(batch, mesh=mesh):
            xb, _ = batch
            bsize = _batch_dim(xb)
            if bsize < padded:  # pad to keep the compiled shape
                xb = _pad_batch(xb, padded)
            return shard_batch(xb, mesh), bsize

        batches = _prefetch_iter(
            ds.iter_batches(batch_size, shuffle=False,
                            drop_last=False),
            _place, _prefetch_depth())
        try:
            for xb, bsize in batches:
                y = jax.device_get(self._predict_fn(self.params, xb))
                outs.append(_trim_batch(y, bsize))
        finally:
            batches.close()  # deterministic worker shutdown
        if not outs:
            return np.empty((0,))
        return _concat_pytree(outs)[:n] if not isinstance(outs[0], (list,
            tuple)) else _concat_pytree(outs)

    # -- checkpoint / resume (reference `Topology.scala:238-248,996-1004`,
    #    resume via Module.load, SURVEY.md §5 "Checkpoint / resume") -------
    def save_checkpoint(self, path: Optional[str] = None,
                        block: Optional[bool] = None):
        """Snapshot params/opt_state/step to ``path``.

        The device→host fetch is always synchronous (donated step
        buffers make a background fetch unsafe); with ``block=False``
        (or ``ZOO_TPU_ASYNC_CKPT=1``) the pickle + atomic write happen
        on a background thread so the train loop resumes immediately.
        Writes are serialized; a failed background write re-raises at
        the next save (or at :meth:`wait_for_checkpoint`)."""
        path = path or self.checkpoint_path
        if path is None:
            raise ValueError("no checkpoint path set")
        if block is None:
            block = os.environ.get("ZOO_TPU_ASYNC_CKPT", "0") != "1"
        self.wait_for_checkpoint()  # serialize + surface prior errors
        os.makedirs(path, exist_ok=True)
        state = {
            "params": jax.device_get(self.params),
            "opt_state": jax.device_get(self.opt_state),
            "step": self.step,
        }
        step = self.step

        def write():
            with obs.span("train/checkpoint", step=step):
                tmp = os.path.join(path, f".tmp_ckpt_{step}")
                with open(tmp, "wb") as f:
                    pickle.dump(state, f)
                    # fault point sits between "bytes written" and
                    # "made durable/visible": a kill/error here leaves
                    # only the .tmp_* file, which load_checkpoint
                    # never considers
                    _CKPT_FAULT.fire(step=step)
                    f.flush()
                    os.fsync(f.fileno())
                final = os.path.join(path, f"ckpt_{step}.pkl")
                os.replace(tmp, final)
                # LATEST is promoted atomically too: a reader (or a
                # crash) can never observe a half-written pointer
                latest = os.path.join(path, "LATEST")
                ltmp = latest + ".tmp"
                with open(ltmp, "w") as f:
                    f.write(os.path.basename(final))
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(ltmp, latest)
                _fsync_dir(path)
            return final

        if block:
            return write()

        def worker():
            try:
                write()
            except BaseException as e:  # noqa: BLE001 — re-raised
                self._ckpt_error = e

        import threading
        # non-daemon: if training dies mid-write, interpreter shutdown
        # still joins the writer, so the newest checkpoint survives —
        # the exact crash-recovery scenario async writes exist for
        t = threading.Thread(target=worker, daemon=False,
                             name="zoo-tpu-ckpt-write")
        t.start()
        self._ckpt_thread = t
        return os.path.join(path, f"ckpt_{step}.pkl")

    def save_checkpoint_sharded(self, path: Optional[str] = None):
        """Orbax-backed checkpoint: each host writes only its own
        param/opt-state shards (no full-tree gather through one host —
        the scalable path for FSDP/TP models too big for a single
        host's RAM; the pickle path stays the default for small
        models and whole-file portability). Layout:
        ``<path>/sharded/<step>`` + the same ``LATEST`` pointer file
        with a ``sharded:`` prefix, so :meth:`load_checkpoint`
        dispatches transparently."""
        import orbax.checkpoint as ocp

        path = path or self.checkpoint_path
        if path is None:
            raise ValueError("no checkpoint path set")
        self.wait_for_checkpoint()
        root = os.path.join(os.path.abspath(path), "sharded")
        os.makedirs(root, exist_ok=True)
        step_dir = os.path.join(root, str(self.step))
        with ocp.StandardCheckpointer() as ckptr:
            # force=True: orbax writes to a tmp dir and renames, so an
            # existing same-step checkpoint stays intact until the new
            # one is complete (the pickle path's tmp+os.replace
            # atomicity)
            ckptr.save(step_dir,
                       {"params": self.params,
                        "opt_state": self.opt_state},
                       force=True)
        with open(os.path.join(path, "LATEST"), "w") as f:
            f.write(f"sharded:{self.step}")
        return step_dir

    def _load_checkpoint_sharded(self, path: str, step: int):
        import orbax.checkpoint as ocp

        self._ensure_initialized()  # abstract tree + shardings
        step_dir = os.path.join(os.path.abspath(path), "sharded",
                                str(step))
        tx = self._tx()
        # ONE opt-state materialization serves both the restore target
        # and the placement template (a second one would transiently
        # double the Adam-state footprint on large FSDP models)
        template = jax.jit(tx.init)(self.params)

        def absify(tree):  # aval + SHARDING per leaf (scalars too)
            return jax.tree_util.tree_map(
                lambda a: jax.ShapeDtypeStruct(
                    a.shape, a.dtype, sharding=a.sharding), tree)

        target = {
            "params": absify(self.params),
            "opt_state": absify(template),
        }
        with ocp.StandardCheckpointer() as ckptr:
            state = ckptr.restore(step_dir, target)
        # explicit re-placement: orbax (and jit's own output layout
        # for fresh scalars like optimizer step counts) can leave 0-d
        # leaves on a single device; mesh-replicate anything without a
        # mesh sharding so the train step sees one device set
        from jax.sharding import NamedSharding, PartitionSpec
        mesh = self.ctx.mesh

        def place(tmpl, restored):
            def put(t, r):
                sh = t.sharding
                if not isinstance(sh, NamedSharding):
                    sh = NamedSharding(mesh, PartitionSpec())
                return jax.device_put(jnp.asarray(r), sh)
            return jax.tree_util.tree_map(put, tmpl, restored)

        self.params = place(self.params, state["params"])
        self.opt_state = place(template, state["opt_state"])
        self.step = step
        self._train_step = self._build_train_step(tx)
        return self

    def _join_ckpt_write(self):
        """Join any in-flight async checkpoint write without raising
        (safe inside ``finally`` — must not mask an active
        exception)."""
        t = getattr(self, "_ckpt_thread", None)
        if t is not None:
            t.join()
            self._ckpt_thread = None

    def wait_for_checkpoint(self):
        """Join any in-flight async checkpoint write; re-raise its
        error if it failed."""
        self._join_ckpt_write()
        err = getattr(self, "_ckpt_error", None)
        if err is not None:
            self._ckpt_error = None
            raise err

    def load_checkpoint(self, path: Optional[str] = None,
                        step: Optional[int] = None):
        # join only (no raise): LATEST may be mid-rewrite, and a
        # failed-save error must not abort the load — but the caller
        # must know LATEST may be older than they think, and the error
        # stays pending so the next save/wait still raises it
        self._join_ckpt_write()
        err = getattr(self, "_ckpt_error", None)
        if err is not None:
            logger.warning(
                "an async checkpoint write failed (%s); LATEST may "
                "point at an older step. The error will re-raise at "
                "the next save_checkpoint/wait_for_checkpoint.", err)
        path = path or self.checkpoint_path
        if step is not None:
            if os.path.isdir(os.path.join(path, "sharded", str(step))):
                return self._load_checkpoint_sharded(path, step)
            fname = os.path.join(path, f"ckpt_{step}.pkl")
        else:
            with open(os.path.join(path, "LATEST")) as f:
                latest = f.read().strip()
            if latest.startswith("sharded:"):
                return self._load_checkpoint_sharded(
                    path, int(latest.split(":", 1)[1]))
            fname = os.path.join(path, latest)
        from analytics_zoo_tpu.common.safe_pickle import checked_load
        state = checked_load(fname)  # class-whitelist deserialization
        params = state["params"]
        _check_params_compatible(self.model, params)
        self.params = self._place_params(params)
        # opt_state leaves are keyed by the saving process's layer names;
        # rebuild the state tree for THIS model and pour the leaves in
        tx = self._tx()
        # structure only — eval_shape runs zero device ops
        template = jax.eval_shape(tx.init, self.params)
        saved_leaves = jax.tree_util.tree_leaves(state["opt_state"])
        template_def = jax.tree_util.tree_structure(template)
        if len(saved_leaves) != template_def.num_leaves:
            raise ValueError(
                "optimizer state in checkpoint does not match this "
                f"model/optimizer ({len(saved_leaves)} vs "
                f"{template_def.num_leaves} leaves)")
        self.opt_state = jax.device_put(
            jax.tree_util.tree_unflatten(template_def, saved_leaves))
        self.step = state["step"]
        self._train_step = self._build_train_step(tx)
        return self


def _check_params_compatible(model, saved: dict) -> None:
    """Layer names are deterministic per architecture
    (`KerasNet._canonicalize_names`), so a checkpoint's keys must match
    this model's layer names exactly; mismatch means a different
    architecture (or user-renamed layers)."""
    expected = {lyr.name for lyr in model.layers}
    got = set(saved)
    if expected != got:
        raise ValueError(
            "checkpoint does not match model architecture; missing "
            f"layers {sorted(expected - got)}, unexpected "
            f"{sorted(got - expected)}")


def _accepts_mask(metric) -> bool:
    import inspect
    try:
        return "mask" in inspect.signature(metric.batch_stats).parameters
    except (TypeError, ValueError):
        return False


def _batch_dim(x) -> int:
    leaf = x[0] if isinstance(x, (list, tuple)) else x
    return int(leaf.shape[0])


def _pad_batch(x, target: int):
    def pad(a):
        missing = target - a.shape[0]
        return np.concatenate(
            [a, np.repeat(a[-1:], missing, axis=0)], axis=0)
    if isinstance(x, (list, tuple)):
        return [pad(np.asarray(a)) for a in x]
    return pad(np.asarray(x))


def _trim_batch(y, n: int):
    if isinstance(y, (list, tuple)):
        return [np.asarray(a)[:n] for a in y]
    return np.asarray(y)[:n]


def _concat_pytree(chunks):
    if isinstance(chunks[0], (list, tuple)):
        n_out = len(chunks[0])
        return [np.concatenate([c[i] for c in chunks], axis=0)
                for i in range(n_out)]
    return np.concatenate(chunks, axis=0)
