"""Sharding rules & helpers — the GSPMD replacement for the reference's
parameter-manager all-reduce (SURVEY.md §2.10).

The reference's only parallelism is synchronous data parallel, implemented as
Spark-shuffle gradient aggregation + block-manager weight broadcast
(reference `docs/docs/wp-bigdl.md:146-160`, subclassed at
`Topology.scala:952`). On TPU that whole mechanism is replaced by compiler-
inserted collectives: we annotate array shardings over a `Mesh` and XLA emits
the all-reduces over ICI. This module holds the annotation vocabulary.

Design (scaling-book recipe): parameters carry *logical axis names*
("embed", "mlp", "heads", "kv", "vocab", ...); a `ShardingRules` table maps
logical names to mesh axes. Swapping DP → FSDP → TP is a table swap, not a
model change.
"""

from __future__ import annotations

from typing import Any, Mapping, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


class ShardingRules:
    """Maps logical array-axis names to mesh axis names (or None)."""

    def __init__(self, rules: Mapping[str, "str | tuple | None"]):
        self.rules = dict(rules)

    def spec(self, logical_axes: Sequence["str | None"]) -> P:
        return P(*[self.rules.get(a) if a is not None else None
                   for a in logical_axes])

    def with_overrides(self, **over) -> "ShardingRules":
        merged = dict(self.rules)
        merged.update(over)
        return ShardingRules(merged)


# Pure data parallel: params replicated, batch over "data".
DP_RULES = ShardingRules({
    "batch": "data",
})

# ZeRO-3 style: params and optimizer state sharded over the fsdp axis on
# their largest dim; batch over (data, fsdp).
FSDP_RULES = ShardingRules({
    "batch": ("data", "fsdp"),
    "embed": "fsdp",
    "vocab": "fsdp",
})

# Megatron-style tensor parallel on the "model" axis.
TP_RULES = ShardingRules({
    "batch": "data",
    "mlp": "model",
    "heads": "model",
    "vocab": "model",
})


def _filter_spec_for_mesh(spec: P, mesh: Mesh) -> P:
    """Drop mesh axes that don't exist in `mesh` from a PartitionSpec, so
    rules written for a big mesh degrade gracefully on a small one."""
    out = []
    for entry in spec:
        if entry is None:
            out.append(None)
        elif isinstance(entry, (tuple, list)):
            kept = tuple(a for a in entry if a in mesh.axis_names)
            out.append(kept if kept else None)
        else:
            out.append(entry if entry in mesh.axis_names else None)
    return P(*out)


def logical_sharding(mesh: Mesh, rules: ShardingRules,
                     logical_axes: Sequence["str | None"]) -> NamedSharding:
    spec = _filter_spec_for_mesh(rules.spec(logical_axes), mesh)
    return NamedSharding(mesh, spec)


def shard_batch(batch: Any, mesh: Mesh,
                data_axes: "tuple[str, ...]" = ("data", "fsdp")) -> Any:
    """Device-put a host batch pytree with dim0 sharded over the data axes."""
    axes = tuple(a for a in data_axes if a in mesh.axis_names)

    def _put(x):
        x = np.asarray(x)
        spec = [None] * x.ndim
        if x.ndim > 0:
            spec[0] = axes or None
        return jax.device_put(x, NamedSharding(mesh, P(*spec)))

    return jax.tree_util.tree_map(_put, batch)


def auto_fsdp_sharding(mesh: Mesh, x, axis: str = "fsdp",
                       min_elems: int = 2 ** 12) -> NamedSharding:
    """Pick a ZeRO-style sharding for one param leaf: shard the largest
    dim divisible by the axis size; replicate small/indivisible leaves.
    XLA all-gathers shards just-in-time inside the jit'd step (GSPMD),
    which is the compiler-native form of ZeRO-3."""
    if axis not in mesh.axis_names:
        return NamedSharding(mesh, P())
    n = mesh.shape[axis]
    if n == 1 or x.size < min_elems:
        return NamedSharding(mesh, P())
    dims = sorted(range(x.ndim), key=lambda d: -x.shape[d])
    for d in dims:
        if x.shape[d] % n == 0:
            spec = [None] * x.ndim
            spec[d] = axis
            return NamedSharding(mesh, P(*spec))
    return NamedSharding(mesh, P())


def shard_params_with(params: Any, mesh: Mesh, chooser, axis: str) -> Any:
    """Place every leaf per a (mesh, leaf, axis) -> NamedSharding
    chooser — the shared body of the parallel-mode placements."""
    return jax.tree_util.tree_map(
        lambda x: jax.device_put(x, chooser(mesh, x, axis)), params)


def shard_params_fsdp(params: Any, mesh: Mesh, axis: str = "fsdp") -> Any:
    return shard_params_with(params, mesh, auto_fsdp_sharding, axis)


def shard_params(params: Any, mesh: Mesh,
                 rules: Optional[ShardingRules] = None,
                 logical_axes: Any = None) -> Any:
    """Device-put a parameter pytree.

    If `logical_axes` (a matching pytree of axis-name tuples) is given, each
    leaf is placed per the rules table; otherwise params are replicated
    (plain DP — the reference's broadcast-weights semantics).
    """
    if logical_axes is None or rules is None:
        repl = NamedSharding(mesh, P())
        return jax.tree_util.tree_map(
            lambda x: jax.device_put(x, repl), params)
    return jax.tree_util.tree_map(
        lambda x, ax: jax.device_put(
            x, logical_sharding(mesh, rules, ax)),
        params, logical_axes)


def auto_tp_sharding(mesh: Mesh, x, axis: str = "model",
                     min_elems: int = 2 ** 8) -> NamedSharding:
    """Pick a tensor-parallel sharding for one param leaf: shard the
    LAST dim (output features of Dense/conv kernels — the Megatron
    column split) over the model axis when divisible; replicate biases
    and small leaves. GSPMD's sharding propagation then derives the
    activation shardings and inserts the all-reduces — the
    compiler-native form of Megatron TP (scaling-book recipe)."""
    if axis not in mesh.axis_names:
        return NamedSharding(mesh, P())
    n = mesh.shape[axis]
    if n == 1 or x.ndim < 2 or x.size < min_elems or \
            x.shape[-1] % n != 0:
        return NamedSharding(mesh, P())
    spec = [None] * x.ndim
    spec[-1] = axis
    return NamedSharding(mesh, P(*spec))


def shard_params_tp(params: Any, mesh: Mesh, axis: str = "model") -> Any:
    return shard_params_with(params, mesh, auto_tp_sharding, axis)


def auto_ep_sharding(mesh: Mesh, x, axis: str = "expert") -> \
        NamedSharding:
    """Expert-parallel placement for one expert-stacked leaf: shard
    the LEADING (expert) dim over the expert mesh axis when
    divisible."""
    if axis not in mesh.axis_names:
        return NamedSharding(mesh, P())
    n = mesh.shape[axis]
    if n == 1 or x.ndim < 1 or x.shape[0] % n != 0:
        return NamedSharding(mesh, P())
    spec = [None] * x.ndim
    spec[0] = axis
    return NamedSharding(mesh, P(*spec))


def shard_params_ep(params: Any, mesh: Mesh, axis: str = "expert",
                    ep_paths: "Optional[set]" = None) -> Any:
    """EP placement: only leaves named in ``ep_paths`` — a set of
    (layer_name, param_key) pairs collected from layers that declare
    ``expert_stacked_params`` — are expert-sharded; everything else
    (routers, embeddings, heads) replicates."""
    repl = NamedSharding(mesh, P())
    ep_paths = ep_paths or set()

    def place(path, x):
        keys = tuple(getattr(e, "key", None) for e in path)
        if len(keys) >= 2 and (keys[-2], keys[-1]) in ep_paths:
            return jax.device_put(x, auto_ep_sharding(mesh, x, axis))
        return jax.device_put(x, repl)

    return jax.tree_util.tree_map_with_path(place, params)


def replica_device_slices(n_replicas: int,
                          devices_per_replica: int = 1,
                          devices: Optional[Sequence] = None) -> list:
    """Partition the host's devices into disjoint per-replica slices
    for the serving fleet (`pipeline/inference/fleet.py`): replica i
    owns ``devices[i*k : (i+1)*k]``. Raises when the host cannot seat
    the fleet — a fleet silently time-slicing one chip would report
    N× capacity it does not have."""
    devs = list(devices) if devices is not None else jax.devices()
    k = int(devices_per_replica)
    need = int(n_replicas) * k
    if k < 1 or n_replicas < 1:
        raise ValueError("n_replicas and devices_per_replica must "
                         "be >= 1")
    if need > len(devs):
        raise ValueError(
            f"fleet needs {need} devices ({n_replicas} replicas x "
            f"{k}) but the host has {len(devs)}")
    return [tuple(devs[i * k:(i + 1) * k]) for i in range(n_replicas)]


def place_inference_params(params: Any, devices: Sequence,
                           mode: str = "auto",
                           axis: str = "model") -> Any:
    """Commit one inference replica's params to its device slice —
    the mesh.py inference path used by ``ReplicaPool``.

    A single device gets a committed single-device placement; a
    multi-device slice gets a 1-D mesh over ``axis`` with the
    Megatron column split (`auto_tp_sharding`) under ``mode="auto"``
    / ``"tp"``, or full replication under ``mode="replicate"``.
    Because the placement is *committed*, `InferenceModel.lower_for`
    AOT-compiles the predict program onto exactly this slice and
    GSPMD inserts the TP all-reduces — uncommitted (numpy) request
    rows follow the params."""
    devs = tuple(devices)
    if not devs:
        raise ValueError("empty device slice")
    if len(devs) == 1:
        return jax.tree_util.tree_map(
            lambda x: jax.device_put(x, devs[0]), params)
    mesh = Mesh(np.asarray(devs), (axis,))
    if mode == "replicate":
        return shard_params(params, mesh)
    if mode in ("auto", "tp"):
        return shard_params_tp(params, mesh, axis=axis)
    raise ValueError(f"unknown inference placement mode {mode!r} "
                     f"(auto|tp|replicate)")


def collect_ep_paths(model) -> set:
    """(layer_name, param_key) pairs of expert-stacked params, from
    each layer's ``expert_stacked_params`` declaration. Recurses into
    nested nets (a Sequential inside a Model etc.) — the params tree
    nests by layer name, so a leaf's path still ends with
    (layer_name, param_key) at any depth (`models.py:93-97`)."""
    out = set()
    for lyr in getattr(model, "layers", []):
        for k in getattr(lyr, "expert_stacked_params", ()):
            out.add((lyr.name, k))
        out |= collect_ep_paths(lyr)
    return out
