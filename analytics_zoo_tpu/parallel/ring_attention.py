"""Ring attention — sequence/context parallelism over a mesh axis.

Absent from the reference (max context 512, SURVEY.md §5); first-class
here because it shapes the core design for long-context training. The
sequence axis of Q/K/V is sharded over the mesh's ``seq`` axis; each
device holds one Q block and rotates K/V blocks around the ring with
`lax.ppermute` (ICI neighbor exchange), accumulating flash-style
blockwise softmax statistics — attention over sequence length S costs
O(S/n) memory per device and overlaps compute with the K/V rotation.

Causal masking uses global block indices: ring step t on device i
processes the K/V block originally resident on device (i - t) mod n.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from analytics_zoo_tpu.ops.attention import _flash_block_update


def _ring_attention_local(q, k, v, axis_name: str, causal: bool,
                          scale: Optional[float],
                          use_flash: bool = False):
    """Inside-shard_map body. q,k,v: (B, T_loc, H, D) local blocks.

    ``use_flash``: compute each ring step's block with the Pallas
    partial-softmax kernel (`ops.flash_attention.flash_block_partial`)
    — the O(T_loc²) logits stay in VMEM — and merge the returned
    (acc, m, l) partials into the running statistics. Numerically the
    same blockwise-softmax recursion as the jnp path.
    """
    n = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    b, t_loc, h, d = q.shape
    scale = scale if scale is not None else 1.0 / (d ** 0.5)

    q_pos = my_idx * t_loc + jnp.arange(t_loc)          # global q rows
    local_pos = jnp.arange(t_loc)

    def step(t, carry):
        o_acc, m, l, k_blk, v_blk = carry
        src = (my_idx - t) % n                           # block origin
        if use_flash:
            from analytics_zoo_tpu.ops.flash_attention import \
                flash_block_partial
            acc_b, m_b, l_b = flash_block_partial(
                q, k_blk, v_blk, (my_idx - src) * t_loc,
                causal=causal, scale=scale)
            m_new = jnp.maximum(m, m_b)
            a1 = jnp.exp(m - m_new)
            a2 = jnp.exp(m_b - m_new)
            l = l * a1 + l_b * a2
            o_acc = o_acc * a1.transpose(0, 2, 1)[..., None] + \
                acc_b * a2.transpose(0, 2, 1)[..., None]
            m = m_new
        else:
            s = jnp.einsum("bqhd,bkhd->bhqk", q, k_blk) \
                .astype(jnp.float32) * scale
            if causal:
                k_pos = src * t_loc + local_pos
                mask = q_pos[:, None] >= k_pos[None, :]  # (Tq, Tk)
                s = jnp.where(mask[None, None], s, -1e30)
            o_acc, m, l = _flash_block_update((o_acc, m, l), s, v_blk)
        # rotate K/V to the next device on the ring (skip after last)
        perm = [(j, (j + 1) % n) for j in range(n)]
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        return o_acc, m, l, k_blk, v_blk

    o0 = jnp.zeros((b, t_loc, h, d), jnp.float32)
    m0 = jnp.full((b, h, t_loc), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, h, t_loc), jnp.float32)
    o, m, l, _, _ = jax.lax.fori_loop(0, n, step, (o0, m0, l0, k, v))
    denom = l.transpose(0, 2, 1)[..., None]              # (B, Tq, H, 1)
    return (o / jnp.maximum(denom, 1e-30)).astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _ring_local_flash(q, k, v, axis_name, causal, scale):
    return _ring_attention_local(q, k, v, axis_name, causal, scale,
                                 use_flash=True)


def _ring_local_flash_fwd(q, k, v, axis_name, causal, scale):
    return _ring_local_flash(q, k, v, axis_name, causal, scale), \
        (q, k, v)


def _ring_local_flash_bwd(axis_name, causal, scale, res, g):
    # backward recomputes via the differentiable jnp ring path (the
    # Pallas block kernel has no VJP); same recursion ⇒ same gradient.
    # NOTE: the replayed forward repeats the ring's ppermute rotations,
    # so grad steps pay the ICI communication twice; saving (m, l) as
    # residuals to skip the replay's softmax passes is a known lever
    q, k, v = res
    _, vjp = jax.vjp(
        lambda q, k, v: _ring_attention_local(
            q, k, v, axis_name, causal, scale, use_flash=False),
        q, k, v)
    return vjp(g)


_ring_local_flash.defvjp(_ring_local_flash_fwd, _ring_local_flash_bwd)


def ring_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                   mesh: Mesh, axis: str = "seq",
                   causal: bool = False,
                   scale: Optional[float] = None,
                   impl: Optional[str] = None) -> jnp.ndarray:
    """Sequence-parallel attention. q,k,v: (B, T, H, D) with T sharded
    over `axis`; returns (B, T, H, D) sharded the same way. Falls back
    to a single-block computation when the axis is absent or size 1.

    `impl`: "auto" (the default: Pallas partial-softmax kernel per
    ring step on TPU when local T is 128-divisible and past the
    dense/flash crossover, else jnp blockwise softmax), "flash"
    (force the kernel), or "xla" (force jnp); default from
    ``ZOO_TPU_ATTENTION`` like `ops.attention.dot_product_attention`.
    """
    from analytics_zoo_tpu.ops.attention import (
        flash_backend_ok, flash_profitable, resolve_attention_impl)
    impl = resolve_attention_impl(impl)
    if axis not in mesh.axis_names or mesh.shape[axis] == 1:
        from analytics_zoo_tpu.ops.attention import dot_product_attention
        return dot_product_attention(q, k, v, causal=causal, scale=scale,
                                     impl=impl)
    n = mesh.shape[axis]
    t_loc = q.shape[1] // n
    compatible = t_loc % 128 == 0 and q.shape[-1] <= 256
    use_flash = compatible and (impl == "flash" or (
        impl == "auto" and flash_backend_ok()
        and flash_profitable(t_loc)))
    if impl == "flash" and not use_flash:
        raise ValueError(
            f"impl='flash' needs local T (={t_loc}) divisible by 128 "
            f"and head dim <= 256")
    scale_v = scale if scale is not None else 1.0 / (q.shape[-1] ** 0.5)
    spec = P(None, axis, None, None)
    if use_flash:
        # positional call: custom_vjp nondiff_argnums are positional
        def local(q, k, v):
            return _ring_local_flash(q, k, v, axis, causal,
                                     float(scale_v))
    else:
        local = functools.partial(_ring_attention_local, axis_name=axis,
                                  causal=causal, scale=scale)
    fn = jax.shard_map(local, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec, check_vma=False)
    return fn(q, k, v)
