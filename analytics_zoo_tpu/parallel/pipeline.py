"""Pipeline parallelism (GPipe-style) over a mesh axis.

Absent from the reference (data-parallel only, SURVEY.md §2.10) and
listed there as TPU-native headroom: stage parameters live sharded
over the mesh's ``pipe`` axis, microbatches march through the stages
with `lax.ppermute` neighbor exchanges (ICI), and the whole schedule
is ONE differentiable jitted program — `jax.grad` flows through the
scan and the permutes (ppermute's transpose is the reverse permute),
so the same function serves forward, training, and inference.

The collective-pipeline recipe (scaling-book style):

- stage params are stacked on a leading axis and sharded over
  ``pipe`` — device i holds stage i's slice;
- the input is split into M microbatches; at schedule step t, device 0
  feeds microbatch t (if any), every device applies its stage to its
  current buffer, and the result rotates one hop forward;
- after ``M + S - 1`` steps the last device has emitted every
  microbatch; bubble outputs are sliced off.

Uniform stages (same signature/shapes, e.g. transformer blocks) are
the supported shape — the same restriction scan-over-layers imposes.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def stack_stage_params(param_list):
    """Stack per-stage param pytrees (same structure) on a new leading
    stage axis — the layout `gpipe_apply` expects (shard it over the
    ``pipe`` axis with :func:`shard_stage_params`)."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *param_list)


def shard_stage_params(stacked, mesh: Mesh, axis: str = "pipe"):
    """Place stacked stage params with the leading axis sharded over
    ``axis`` (device i holds stage i)."""
    def put(leaf):
        spec = P(axis, *([None] * (leaf.ndim - 1)))
        return jax.device_put(leaf, NamedSharding(mesh, spec))
    return jax.tree_util.tree_map(put, stacked)


def gpipe_apply(stage_fn: Callable, stacked_params, x, *,
                mesh: Mesh, axis: str = "pipe",
                microbatches: int, microbatched_args=(),
                broadcast_args=(), pass_mb_index: bool = False):
    """Run ``x`` through ``S = mesh.shape[axis]`` pipeline stages.

    ``stage_fn(params_i, h, *extras) -> h`` must preserve ``h``'s
    shape (a uniform residual-block/transformer-layer pipeline).
    ``x``: ``(batch, ...)`` with ``batch % microbatches == 0``;
    stages see microbatches of ``batch // microbatches``. Returns
    ``stage_{S-1}(... stage_0(x))`` exactly (validated against the
    sequential composition in tests), computed with GPipe scheduling:
    per-device activation memory is one microbatch, utilization is
    ``M / (M + S - 1)``.

    Stage extras, in the order ``stage_fn`` receives them after the
    activation: the scalar microbatch index (when ``pass_mb_index``),
    then ``microbatched_args`` (leading dim MUST be ``batch``; split
    like ``x`` — device i at schedule step t receives the slice for
    microbatch ``t - i``, the one resident on it: attention masks,
    per-sample weights, ...), then ``broadcast_args`` (microbatch-
    independent arrays handed to every stage whole: broadcastable
    masks, shared conditioning, ...).
    """
    s = mesh.shape[axis]
    m = int(microbatches)
    batch = x.shape[0]
    if batch % m != 0:
        raise ValueError(f"batch {batch} % microbatches {m} != 0")
    mb = batch // m
    xs = x.reshape((m, mb) + x.shape[1:])
    margs = []
    for a in microbatched_args:
        a = jnp.asarray(a)
        if a.shape[0] != batch:
            raise ValueError(
                f"microbatched arg leading dim {a.shape[0]} != batch "
                f"{batch}; pass microbatch-independent arrays via "
                f"broadcast_args")
        margs.append(a.reshape((m, mb) + a.shape[1:]))
    bargs = tuple(jnp.asarray(a) for a in broadcast_args)
    t_total = m + s - 1

    def per_device(params_local, xs_all, *rest):
        # params_local: (1, ...) slice of the stacked stage params;
        # xs_all/margs: full (M, ...) stacks; bargs whole (replicated)
        margs_all = rest[: len(margs)]
        bargs_all = rest[len(margs):]
        params_i = jax.tree_util.tree_map(lambda a: a[0], params_local)
        idx = jax.lax.axis_index(axis)
        perm = [(i, (i + 1) % s) for i in range(s)]
        # the carry becomes device-varying after the first ppermute;
        # type the initial zeros accordingly (shard_map vma typing)
        buf0 = jax.lax.pcast(jnp.zeros_like(xs_all[0]), (axis,),
                             to="varying")

        def step(buf, t):
            # device i processes microbatch t - i (clamped in the
            # fill/drain bubbles); device 0 injects it from the input
            sel = jnp.clip(t - idx, 0, m - 1)
            h_in = jnp.where(idx == 0, xs_all[sel], buf)
            extras = (((sel,) if pass_mb_index else ())
                      + tuple(a[sel] for a in margs_all)
                      + tuple(bargs_all))
            h_out = stage_fn(params_i, h_in, *extras)
            buf_next = jax.lax.ppermute(h_out, axis, perm)
            return buf_next, h_out

        _, outs = jax.lax.scan(step, buf0, jnp.arange(t_total))
        return outs[None]  # (1, T, mb, ...) — stacked over pipe

    outs = jax.shard_map(
        per_device, mesh=mesh,
        in_specs=(P(axis), P()) + (P(),) * (len(margs) + len(bargs)),
        out_specs=P(axis))(stacked_params, xs, *margs, *bargs)
    # device S-1's emissions at steps S-1 .. T-1 are the pipeline
    # outputs, in microbatch order
    y = outs[s - 1, s - 1:]
    return y.reshape((batch,) + y.shape[2:])
