from analytics_zoo_tpu.parallel.mesh import (
    ShardingRules,
    logical_sharding,
    shard_params,
    shard_batch,
    DP_RULES,
    FSDP_RULES,
    TP_RULES,
)

__all__ = [
    "ShardingRules",
    "logical_sharding",
    "shard_params",
    "shard_batch",
    "DP_RULES",
    "FSDP_RULES",
    "TP_RULES",
]
