from analytics_zoo_tpu.parallel.mesh import (
    ShardingRules,
    logical_sharding,
    shard_params,
    shard_batch,
    place_inference_params,
    replica_device_slices,
    DP_RULES,
    FSDP_RULES,
    TP_RULES,
)

__all__ = [
    "ShardingRules",
    "logical_sharding",
    "shard_params",
    "shard_batch",
    "place_inference_params",
    "replica_device_slices",
    "DP_RULES",
    "FSDP_RULES",
    "TP_RULES",
    "gpipe_apply",
    "shard_stage_params",
    "stack_stage_params",
]


def __getattr__(name):
    # pipeline helpers lazily (keep `import analytics_zoo_tpu.parallel`
    # light; mirrors the ring/ulysses dispatch below)
    if name in ("gpipe_apply", "shard_stage_params",
                "stack_stage_params"):
        import importlib
        mod = importlib.import_module(
            "analytics_zoo_tpu.parallel.pipeline")
        return getattr(mod, name)
    raise AttributeError(name)


def get_sp_attention(mode: str):
    """Resolve a sequence-parallel attention implementation by name —
    the single validation/dispatch point for `sequence_parallel_mode`
    ("ring" → ring_attention, "ulysses" → ulysses_attention)."""
    if mode == "ring":
        from analytics_zoo_tpu.parallel.ring_attention import \
            ring_attention
        return ring_attention
    if mode == "ulysses":
        from analytics_zoo_tpu.parallel.ulysses import ulysses_attention
        return ulysses_attention
    raise ValueError(
        f"sequence_parallel_mode must be ring|ulysses, got {mode!r}")
