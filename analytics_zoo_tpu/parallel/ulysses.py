"""Ulysses-style sequence parallelism — all-to-all head repartition.

The second canonical long-context strategy next to
[`ring_attention`](ring_attention.py) (absent from the reference, whose
max context is 512 — SURVEY.md §5; first-class here per the round
goals). Where ring attention keeps Q resident and rotates K/V blocks
around the ICI ring, Ulysses re-partitions ONE time: an all-to-all
swaps the sharded axis from sequence to heads, every device then holds
the FULL sequence for H/n heads and runs ordinary (flash) attention
locally, and a second all-to-all swaps back.

Trade-off vs ring: 2 all-to-alls of activation size instead of n
ppermute rounds — fewer, larger collectives (better when n is small
and heads are plentiful), but requires ``heads % axis_size == 0`` and
holds the full sequence per device (memory O(S) vs ring's O(S/n) for
K/V).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P


def _ulysses_local(q, k, v, axis_name: str, causal: bool,
                   scale: Optional[float], impl: str):
    """Inside-shard_map body. q,k,v: (B, T_loc, H, D) local blocks."""
    from analytics_zoo_tpu.ops.attention import dot_product_attention

    # seq-sharded → head-sharded: (B, T_loc, H, D) → (B, T, H/n, D)
    def to_heads(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=2,
                                  concat_axis=1, tiled=True)

    def to_seq(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=1,
                                  concat_axis=2, tiled=True)

    qh, kh, vh = to_heads(q), to_heads(k), to_heads(v)
    out = dot_product_attention(qh, kh, vh, causal=causal, scale=scale,
                                impl=impl)
    return to_seq(out)


def ulysses_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                      mesh: Mesh, axis: str = "seq",
                      causal: bool = False,
                      scale: Optional[float] = None,
                      impl: Optional[str] = None) -> jnp.ndarray:
    """Sequence-parallel attention via head all-to-all. q,k,v:
    (B, T, H, D) with T sharded over ``axis``; returns the same
    layout. Requires ``H % mesh.shape[axis] == 0``; falls back to a
    plain single-block computation when the axis is absent or 1.

    `impl`: passed through to the local per-device
    `dot_product_attention` after the head all-to-all ("flash" runs
    the Pallas kernel over the full sequence).
    """
    from analytics_zoo_tpu.ops.attention import resolve_attention_impl
    impl = resolve_attention_impl(impl)
    if axis not in mesh.axis_names or mesh.shape[axis] == 1:
        from analytics_zoo_tpu.ops.attention import dot_product_attention
        return dot_product_attention(q, k, v, causal=causal, scale=scale,
                                     impl=impl)
    n = mesh.shape[axis]
    heads = q.shape[2]
    if heads % n != 0:
        raise ValueError(
            f"ulysses attention needs heads ({heads}) divisible by the "
            f"'{axis}' mesh axis size ({n}); use ring attention for "
            "head-scarce models")
    spec = P(None, axis, None, None)
    fn = jax.shard_map(
        functools.partial(_ulysses_local, axis_name=axis,
                          causal=causal, scale=scale, impl=impl),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    return fn(q, k, v)
