"""Anomaly detection example (reference
`pyzoo/zoo/examples/anomalydetection/anomaly_detection.py`): unroll a
univariate time series, train the stacked-LSTM AnomalyDetector, flag
the top-N largest prediction errors. Synthetic NYC-taxi-shaped series."""

from __future__ import annotations

import argparse

import numpy as np


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--points", type=int, default=600)
    p.add_argument("--unroll", type=int, default=24)
    p.add_argument("--epochs", type=int, default=3)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--anomalies", type=int, default=5)
    args = p.parse_args(argv)

    from analytics_zoo_tpu import init_nncontext
    from analytics_zoo_tpu.models.anomalydetection import AnomalyDetector

    init_nncontext()
    rng = np.random.RandomState(0)
    t = np.arange(args.points)
    series = (np.sin(t / 24 * 2 * np.pi) +
              0.1 * rng.randn(args.points)).astype(np.float32)
    spikes = rng.choice(args.points, args.anomalies, replace=False)
    series[spikes] += 3.0  # injected anomalies

    indexed = AnomalyDetector.unroll(series[:, None], args.unroll)
    x, y = AnomalyDetector.to_arrays(indexed)
    split = int(len(x) * 0.8)
    x_train, y_train = x[:split], y[:split]
    x_test, y_test = x[split:], y[split:]

    ad = AnomalyDetector(feature_shape=(args.unroll, 1),
                         hidden_layers=(16, 8, 4),
                         dropouts=(0.1, 0.1, 0.1))
    ad.compile(optimizer="adam", loss="mse")
    ad.fit(x_train, y_train, batch_size=args.batch_size,
           nb_epoch=args.epochs)

    y_pred = ad.predict(x_test, batch_size=args.batch_size).reshape(-1)
    flagged, threshold = AnomalyDetector.detect_anomalies(
        y_test.reshape(-1), y_pred, anomaly_size=args.anomalies)
    print(f"flagged {len(flagged)} anomalies (threshold "
          f"{threshold:.3f}) at test indices {flagged.tolist()}")
    return flagged


if __name__ == "__main__":
    main()
