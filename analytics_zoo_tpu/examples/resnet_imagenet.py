"""ImageNet-style ResNet-50 training recipe — the reference's
Inception ImageNet example (`Z/examples/inception/Train.scala:70-107`:
SGD + warmup + poly decay, checkpoint every epoch) rebuilt TPU-first:

- data: an image folder via `ImageSet.read` (thread-pool decode) or
  synthetic data; light host resize only;
- augmentation ON DEVICE inside the jitted train step
  (`feature/image/device_transforms`): Inception-style
  random-resized crop, hflip, color jitter, normalize;
- model: `resnet50(space_to_depth=..., fused=...)` — the Pallas
  fused conv+BN bottleneck path when enabled/measured;
- training: Estimator over the mesh's ``data`` axis (bf16 activations
  on TPU by default), SGD momentum + warmup→poly schedule, epoch
  checkpoints (async write capable via ZOO_TPU_ASYNC_CKPT=1).

Demo sizes by default; scale --image-size/--batch-per-device/--epochs
for a real run. On CPU:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python -m analytics_zoo_tpu.examples resnet_imagenet --devices 8
"""

from __future__ import annotations

import argparse

import numpy as np


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--folder", default=None,
                   help="class_name/xxx.jpg image tree; synthetic "
                        "data when omitted")
    p.add_argument("--devices", type=int, default=0)
    p.add_argument("--image-size", type=int, default=64,
                   help="train crop size (224 for the real recipe)")
    p.add_argument("--batch-per-device", type=int, default=8)
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--classes", type=int, default=10)
    p.add_argument("--lr", type=float, default=0.1)
    p.add_argument("--checkpoint", default=None)
    p.add_argument("--fused", default="auto",
                   choices=["auto", "0", "1", "defer"],
                   help="Pallas fused conv+BN path")
    args = p.parse_args(argv)

    import jax

    from analytics_zoo_tpu import init_nncontext
    from analytics_zoo_tpu.feature.image import device_transforms as D
    from analytics_zoo_tpu.models.image.imageclassification.resnet \
        import resnet50
    from analytics_zoo_tpu.ops.optimizers import SGD, poly, warmup
    from analytics_zoo_tpu.pipeline.estimator import Estimator, \
        EveryEpoch

    n = args.devices or len(jax.devices())
    ctx = init_nncontext(tpu_mesh={"data": n},
                         devices=jax.devices()[:n], seed=0)
    s = args.image_size
    batch = args.batch_per_device * n

    # -- data ----------------------------------------------------------
    if args.folder:
        from analytics_zoo_tpu.feature.image import ImageSet
        from analytics_zoo_tpu.feature.image.transforms import \
            ImageResize
        iset = ImageSet.read(args.folder, with_label_from_dirs=True)
        # host side: decode + one resize to a fixed ingest size; all
        # randomized augmentation happens on device
        iset = iset.transform(ImageResize(int(s * 1.15),
                                          int(s * 1.15)))
        x, y = iset.to_arrays()   # stacked float32 NHWC + labels
        classes = int(y.max()) + 1
    else:
        rs = np.random.RandomState(0)
        n_samples = batch * 4
        x = rs.rand(n_samples, int(s * 1.15), int(s * 1.15), 3) \
            .astype(np.float32) * 255
        y = rs.randint(0, args.classes, size=(n_samples, 1))
        classes = args.classes

    if len(x) < batch:
        raise ValueError(
            f"{len(x)} samples < global batch {batch} "
            f"({args.batch_per_device} x {n} devices): every epoch "
            "would run zero steps")

    # -- on-device augmentation (train-only, inside the jitted step) ---
    aug = D.augment_pipeline(
        D.random_resized_crop((s, s), scale=(0.32, 1.0)),
        D.random_hflip(),
        D.random_brightness(32.0),
        D.random_saturation(0.3),
        D.normalize((123.68, 116.779, 103.939),
                    (58.393, 57.12, 57.375)))

    # -- model + recipe ------------------------------------------------
    fused = {"0": False, "1": True, "defer": "defer"}.get(
        args.fused, "auto")
    model = resnet50(input_shape=(s, s, 3), classes=classes,
                     space_to_depth=(s % 2 == 0), fused=fused)
    steps_per_epoch = max(1, (len(x) // batch))
    total_steps = steps_per_epoch * args.epochs
    warm = max(1, total_steps // 20)
    # ramp lr/10 -> lr over `warm` steps, then poly decay from lr
    lr = warmup(args.lr / 10, warm, delta=(args.lr * 0.9) / warm,
                after=poly(args.lr, 0.5, max(1, total_steps - warm)))
    est = Estimator(model, optimizer=SGD(lr=lr, momentum=0.9),
                    loss="sparse_categorical_crossentropy",
                    metrics=["accuracy"], ctx=ctx, augment=aug)
    if args.checkpoint:
        est.set_checkpoint(args.checkpoint, trigger=EveryEpoch())

    res = est.train(x, y, batch_size=batch, nb_epoch=args.epochs)
    print(f"devices={n} crop={s} batch={batch} fused={args.fused} "
          f"steps={est.step}")
    print(f"final epoch loss={res.history[-1]['loss']:.4f} "
          f"throughput={res.history[-1]['throughput']:.1f} img/s")
    return res.history


if __name__ == "__main__":
    main()
