"""LeNet training example (reference `pyzoo/zoo/examples` lenet /
`examples/inception/Train.scala` pattern: CLI options → init context →
build model → fit → evaluate).

Runs on synthetic MNIST-shaped data by default (no dataset download in
this environment); pass --data-dir with `mnist.npz` for the real thing.
"""

from __future__ import annotations

import argparse
import os

import numpy as np


def load_data(data_dir, n_train, n_test, rng):
    if data_dir and os.path.exists(os.path.join(data_dir, "mnist.npz")):
        with np.load(os.path.join(data_dir, "mnist.npz")) as d:
            return (d["x_train"][..., None] / 255.0,
                    d["y_train"].reshape(-1, 1),
                    d["x_test"][..., None] / 255.0,
                    d["y_test"].reshape(-1, 1))
    x_train = rng.rand(n_train, 28, 28, 1).astype(np.float32)
    y_train = rng.randint(0, 10, (n_train, 1)).astype(np.int32)
    x_test = rng.rand(n_test, 28, 28, 1).astype(np.float32)
    y_test = rng.randint(0, 10, (n_test, 1)).astype(np.int32)
    return x_train, y_train, x_test, y_test


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--data-dir", default=None)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--lr", type=float, default=0.01)
    p.add_argument("--n-train", type=int, default=512)
    p.add_argument("--n-test", type=int, default=128)
    args = p.parse_args(argv)

    from analytics_zoo_tpu import init_nncontext
    from analytics_zoo_tpu.models.image.imageclassification import lenet5
    from analytics_zoo_tpu.ops.optimizers import SGD

    init_nncontext()
    rng = np.random.RandomState(0)
    x_train, y_train, x_test, y_test = load_data(
        args.data_dir, args.n_train, args.n_test, rng)

    model = lenet5(input_shape=x_train.shape[1:], classes=10)
    model.compile(optimizer=SGD(lr=args.lr, momentum=0.9),
                  loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
    model.fit(x_train.astype(np.float32), y_train,
              batch_size=args.batch_size, nb_epoch=args.epochs,
              validation_data=(x_test.astype(np.float32), y_test))
    metrics = model.evaluate(x_test.astype(np.float32), y_test,
                             batch_size=args.batch_size)
    print(f"test metrics: {metrics}")
    return metrics


if __name__ == "__main__":
    main()
