"""TFPark example (reference `pyzoo/zoo/examples/tensorflow/tfpark/
keras_dataset.py`): wrap a compiled tf.keras model in
`tfpark.KerasModel` — the graph is rewritten to explicit weights,
compiled by XLA (GraphDef→jnp bridge), trained on the TPU mesh, and
the trained weights are assigned back into the live tf.keras model."""

from __future__ import annotations

import argparse

import numpy as np


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--samples", type=int, default=512)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--epochs", type=int, default=5)
    args = p.parse_args(argv)

    import tensorflow as tf

    from analytics_zoo_tpu import init_nncontext
    from analytics_zoo_tpu.tfpark import KerasModel

    init_nncontext()
    model = tf.keras.Sequential([
        tf.keras.layers.Dense(32, activation="relu", input_shape=(10,)),
        tf.keras.layers.Dropout(0.1),
        tf.keras.layers.Dense(1),
    ])
    model.compile(optimizer=tf.keras.optimizers.Adam(0.01), loss="mse")

    rng = np.random.RandomState(0)
    x = rng.randn(args.samples, 10).astype(np.float32)
    w_true = rng.randn(10, 1).astype(np.float32)
    y = x @ w_true + 0.05 * rng.randn(args.samples, 1).astype(np.float32)

    km = KerasModel(model)
    before = km.evaluate(x, y, batch_size=args.batch_size)["loss"]
    km.fit(x, y, batch_size=args.batch_size, epochs=args.epochs)
    after = km.evaluate(x, y, batch_size=args.batch_size)["loss"]
    print(f"loss {before:.4f} -> {after:.4f}")
    # assign-back contract: the live tf.keras model saw the training
    drift = float(np.abs(km.predict(x[:8], batch_size=8) -
                         model(x[:8]).numpy()).max())
    print(f"tf.keras model holds trained weights (max drift {drift:.2e})")
    return after


if __name__ == "__main__":
    main()
