"""Image-classification predict example (reference
`P/examples/imageclassification/predict.py`): load an ImageClassifier
from the registry (by architecture name, optionally with a weights
file), read an image folder into an ImageSet through the preprocessing
pipeline, and print top-N predictions per image.

Without ``--folder`` it writes a few synthetic PNG-free raw images to
a temp dir, demonstrating the full read → preprocess → predict flow
offline; point ``--folder``/``--weights`` at real data for real
predictions.
"""

from __future__ import annotations

import argparse
import os

import numpy as np


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--folder", default=None,
                   help="directory of images (jpg/png)")
    p.add_argument("--model", default="mobilenet-v2",
                   help="architecture name or save_model path")
    p.add_argument("--weights", default=None)
    p.add_argument("--top-n", type=int, default=3)
    p.add_argument("--image-size", type=int, default=64)
    p.add_argument("--classes", type=int, default=10)
    args = p.parse_args(argv)

    from analytics_zoo_tpu import init_nncontext
    from analytics_zoo_tpu.feature.image import ImageSet
    from analytics_zoo_tpu.feature.image.transforms import (
        ImageMatToFloats, ImageResize)
    from analytics_zoo_tpu.models.image.imageclassification import \
        ImageClassifier

    init_nncontext()
    size = args.image_size
    # random-weights demo only when NO weight source is configured at
    # all — if a pretrained dir is set but resolution fails, raise
    # rather than silently predict with random weights
    imc = ImageClassifier.load_model(
        args.model, weights_path=args.weights,
        input_shape=(size, size, 3), classes=args.classes,
        allow_random=(args.weights is None
                      and not os.environ.get("ZOO_TPU_PRETRAINED_DIR")))
    if args.weights is None:
        imc.compile()  # random weights: demonstrates the pipeline

    if args.folder:
        image_set = ImageSet.read(args.folder)
        image_set = ImageResize(size, size)(image_set)
        image_set = ImageMatToFloats()(image_set)
        x = np.stack([f.floats for f in image_set.features])
        uris = [f[f.URI] for f in image_set.features]
    else:
        rs = np.random.RandomState(0)
        x = rs.rand(4, size, size, 3).astype(np.float32)
        uris = [f"synthetic_{i}" for i in range(len(x))]

    probs = imc.predict(x, batch_size=len(x))
    results = []
    for uri, row in zip(uris, probs):
        top = np.argsort(row)[::-1][:args.top_n]
        results.append((uri, [(int(c), float(row[c])) for c in top]))
        pretty = ", ".join(f"class {c}: {p:.3f}" for c, p in results[-1][1])
        print(f"{uri}: {pretty}")
    return results


if __name__ == "__main__":
    main()
