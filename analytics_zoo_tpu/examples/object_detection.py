"""SSD object-detection inference example (reference
`pyzoo/zoo/examples/objectdetection/predict.py`): load an SSD detector,
run batched detection, print boxes. Random weights + synthetic images
by default (no pretrained-zoo download in this environment); point
--weights at a saved model for real detections."""

from __future__ import annotations

import argparse

import numpy as np


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--model", default="ssd-vgg16-300x300")
    p.add_argument("--weights", default=None,
                   help="optional .zoomodel checkpoint")
    p.add_argument("--images", type=int, default=2)
    p.add_argument("--conf", type=float, default=0.5)
    args = p.parse_args(argv)

    from analytics_zoo_tpu import init_nncontext
    from analytics_zoo_tpu.models.image.objectdetection import (
        ObjectDetector,
    )

    init_nncontext()
    detector = ObjectDetector(args.model)
    if args.weights:
        detector.model.load_weights(args.weights)
    else:
        detector.compile()  # random weights: demonstrates the pipeline

    rng = np.random.RandomState(0)
    size = detector.img_size
    images = rng.rand(args.images, size, size, 3).astype(np.float32)
    results = detector.detect(images, batch_size=args.images,
                              conf_threshold=args.conf)
    for i, dets in enumerate(results):
        print(f"image {i}: {len(dets)} detections")
        for d in dets[:5]:
            print(f"  class={d.class_id} score={d.score:.3f} "
                  f"box={np.round(d.box, 3).tolist()}")
    return results


if __name__ == "__main__":
    main()
