"""Runnable examples (reference: `pyzoo/zoo/examples/`, L12).

Each module exposes ``main(argv)``; run via
``python -m analytics_zoo_tpu.examples <name> [args...]`` or the
``zoo-tpu-example`` console script.
"""

EXAMPLES = [
    "lenet_mnist",
    "ncf_recommendation",
    "wide_and_deep",
    "text_classification",
    "anomaly_detection",
    "object_detection",
    "nnframes_classification",
    "tfpark_keras",
    "onnx_import",
    "inference_serving",
    "distributed_training",
    "rdd_ingest",
    "quantized_serving",
    "long_context",
    "bert_finetune",
    "resnet_imagenet",
    "chatbot",
    "streaming_inference",
    "autograd_custom",
    "qa_ranker",
    "transformer_sentiment",
    "image_classification",
    "vae_mnist",
    "transfer_learning",
]
