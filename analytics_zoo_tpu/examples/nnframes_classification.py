"""nnframes example (reference
`pyzoo/zoo/examples/nnframes/imageTransferLearning`): Spark-ML-style
NNClassifier over a pandas DataFrame — fit returns an NNClassifierModel
transformer that appends a prediction column."""

from __future__ import annotations

import argparse

import numpy as np
import pandas as pd


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--samples", type=int, default=256)
    p.add_argument("--epochs", type=int, default=5)
    args = p.parse_args(argv)

    from analytics_zoo_tpu import init_nncontext
    from analytics_zoo_tpu.feature.common import SeqToTensor
    from analytics_zoo_tpu.pipeline.api.keras import Sequential, layers as L
    from analytics_zoo_tpu.pipeline.nnframes import NNClassifier

    init_nncontext()
    rng = np.random.RandomState(0)
    feats = rng.randn(args.samples, 6).astype(np.float32)
    # 0-based class ids — the TPU losses and argmax predictions are
    # 0-based (divergence from BigDL's 1-based ClassNLL convention)
    labels = (feats.sum(axis=1) > 0).astype(np.int64)
    df = pd.DataFrame({"features": list(feats), "label": labels})

    net = Sequential()
    net.add(L.Dense(16, input_shape=(6,), activation="relu"))
    net.add(L.Dense(2, activation="softmax"))

    clf = (NNClassifier(net, "sparse_categorical_crossentropy",
                        SeqToTensor((6,)))
           .set_batch_size(32)
           .set_max_epoch(args.epochs)
           .set_learning_rate(0.05)
           .set_optim_method("adam"))
    model = clf.fit(df)
    out = model.transform(df)
    acc = float((out["prediction"] == out["label"]).mean())
    print(f"train accuracy: {acc:.3f}")
    return acc


if __name__ == "__main__":
    main()
