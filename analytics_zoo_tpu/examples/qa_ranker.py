"""QA ranking example (reference `P/examples/qaranker/qa_ranker.py`):
question/answer corpora flow through the TextSet pipeline
(tokenize → normalize → word2idx → shape_sequence), relations become
alternating positive/negative training pairs, KNRM trains with
`rank_hinge`, and NDCG@3/5 + MAP are evaluated on relation lists.

Runs on a tiny synthetic QA corpus by default; pass ``--data-path``
with ``question_corpus.csv`` / ``answer_corpus.csv`` /
``relation_train.csv`` / ``relation_valid.csv`` (the reference's
layout) to use real data.
"""

from __future__ import annotations

import argparse
import os

import numpy as np


def _synthetic_corpus(tmpdir):
    """WikiQA-shaped toy data: each question has one on-topic answer
    (shared keyword) and off-topic distractors."""
    topics = ["rain", "sun", "moon", "wind", "snow", "fire", "tree",
              "fish"]
    qs, ans, rel_train, rel_valid = [], [], [], []
    for i, t in enumerate(topics):
        qs.append((f"q{i}", f"what causes {t} to appear"))
        ans.append((f"a{i}p", f"the {t} appears because of {t} physics"))
        ans.append((f"a{i}n", "unrelated text about something else"))
        dst = rel_train if i < 6 else rel_valid
        dst.append((f"q{i}", f"a{i}p", 1))
        dst.append((f"q{i}", f"a{i}n", 0))
    def write(name, rows, header):
        path = os.path.join(tmpdir, name)
        with open(path, "w", encoding="utf-8") as f:
            f.write(header + "\n")
            for r in rows:
                f.write(",".join(str(c) for c in r) + "\n")
        return path
    write("question_corpus.csv", qs, "id,text")
    write("answer_corpus.csv", ans, "id,text")
    write("relation_train.csv", rel_train, "id1,id2,label")
    write("relation_valid.csv", rel_valid, "id1,id2,label")
    return tmpdir


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--data-path", default=None)
    p.add_argument("--question-length", type=int, default=10)
    p.add_argument("--answer-length", type=int, default=40)
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--nb-epoch", type=int, default=3)
    p.add_argument("--learning-rate", type=float, default=1e-2)
    args = p.parse_args(argv)

    from analytics_zoo_tpu import init_nncontext
    from analytics_zoo_tpu.feature.text import Relations, TextSet
    from analytics_zoo_tpu.models.textmatching import KNRM
    from analytics_zoo_tpu.ops.optimizers import Adam

    init_nncontext()
    data = args.data_path
    if data is None:
        import tempfile
        data = _synthetic_corpus(tempfile.mkdtemp(prefix="qaranker_"))

    q_set = TextSet.read_csv(os.path.join(data, "question_corpus.csv")) \
        .tokenize().normalize().word2idx(min_freq=1) \
        .shape_sequence(args.question_length)
    a_set = TextSet.read_csv(os.path.join(data, "answer_corpus.csv")) \
        .tokenize().normalize() \
        .word2idx(min_freq=1, existing_map=q_set.get_word_index()) \
        .shape_sequence(args.answer_length)
    vocab = max(a_set.get_word_index().values()) + 1

    train_rel = Relations.read(os.path.join(data, "relation_train.csv"))
    x1, x2 = TextSet.from_relation_pairs(train_rel, q_set, a_set, seed=0)
    x = np.concatenate([x1, x2], axis=1).astype(np.float32)
    y = np.zeros((x.shape[0], 1), np.float32)  # ignored by rank_hinge

    knrm = KNRM(args.question_length, args.answer_length, vocab,
                embed_size=16, kernel_num=5)
    knrm.compile(optimizer=Adam(lr=args.learning_rate),
                 loss="rank_hinge")
    knrm.fit(x, y, batch_size=args.batch_size, nb_epoch=args.nb_epoch)

    valid_rel = Relations.read(os.path.join(data, "relation_valid.csv"))
    l1, l2, labels, gids = TextSet.from_relation_lists(
        valid_rel, q_set, a_set)
    xv = np.concatenate([l1, l2], axis=1).astype(np.float32)
    scores = knrm.predict(xv, batch_size=args.batch_size).reshape(-1)
    metrics = {
        "ndcg@3": knrm.evaluate_ndcg(scores, labels, gids, k=3),
        "ndcg@5": knrm.evaluate_ndcg(scores, labels, gids, k=5),
        "map": knrm.evaluate_map(scores, labels, gids),
    }
    print("qa_ranker:", {k: round(v, 4) for k, v in metrics.items()})
    return metrics


if __name__ == "__main__":
    main()
