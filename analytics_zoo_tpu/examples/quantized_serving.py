"""INT8 quantized serving example (reference analog: the BigDL white
paper's int8 inference claim, `wp-bigdl.md:192-196` — ~2x speedup, 4x
model size, <0.1% accuracy drop).

Trains a small classifier, serves it float and int8 through
`InferenceModel`, and reports agreement + kernel-size reduction."""

from __future__ import annotations

import argparse
import time

import numpy as np


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--n", type=int, default=512)
    p.add_argument("--dim", type=int, default=32)
    p.add_argument("--classes", type=int, default=5)
    p.add_argument("--epochs", type=int, default=8)
    args = p.parse_args(argv)

    from analytics_zoo_tpu import init_nncontext
    from analytics_zoo_tpu.pipeline.api.keras import Sequential, \
        layers as L
    from analytics_zoo_tpu.pipeline.inference import InferenceModel

    init_nncontext(tpu_mesh={"data": -1})
    rs = np.random.RandomState(0)
    x = rs.randn(args.n, args.dim).astype(np.float32)
    w = rs.randn(args.dim, args.classes).astype(np.float32)
    y = np.argmax(x @ w, -1).astype(np.int32).reshape(-1, 1)

    model = Sequential()
    model.add(L.Dense(64, activation="relu",
                      input_shape=(args.dim,)))
    model.add(L.Dense(args.classes))
    model.compile(optimizer="adam", loss="softmax_cross_entropy")
    model.fit(x, y, batch_size=64, nb_epoch=args.epochs)

    im_f32 = InferenceModel().load_keras_net(model, example_inputs=[x])
    im_int8 = InferenceModel().load_keras_net(model, example_inputs=[x],
                                              quantize=True)

    t0 = time.perf_counter()
    f32_pred = np.argmax(im_f32.predict(x), -1)
    t_f32 = time.perf_counter() - t0
    t0 = time.perf_counter()
    int8_pred = np.argmax(im_int8.predict(x), -1)
    t_int8 = time.perf_counter() - t0

    agree = float(np.mean(f32_pred == int8_pred))
    f_bytes, q_bytes = im_int8.quantized.size_bytes()
    result = {"agreement": agree,
              "kernel_bytes_f32": f_bytes,
              "kernel_bytes_int8": q_bytes,
              "t_f32_s": round(t_f32, 4),
              "t_int8_s": round(t_int8, 4)}
    print("int8 serving:", result)
    return result


if __name__ == "__main__":
    main()
