"""Wide&Deep recommendation example (reference
`zoo/examples/recommendation/Ml1mWideAndDeep.scala:40-115` and
`apps/recommendation-wide-n-deep/wide_n_deep.ipynb`): the ml-1m
recipe — wide base (occupation, gender), wide cross (age×gender
hash-bucketed to 100), indicators (genres, gender), userId/itemId
embeddings, continuous age — trained with Adam on 5 rating classes,
then `predict_user_item_pair` / `recommend_for_user` /
`recommend_for_item`. Synthetic ml-1m-shaped data by default (the
real ratings.dat/users.dat/movies.dat are a download away; this
environment is offline)."""

from __future__ import annotations

import argparse

import numpy as np

BUCKET = 100          # reference bucketSize for the age-gender cross
N_OCC, N_GENDER, N_GENRES = 21, 3, 19


def synth_ml1m(n, users, items, rng):
    """Synthetic ratings joined with user/item profiles: rating
    depends on user/item affinity + age, so the model has signal."""
    uid = rng.randint(1, users + 1, n)
    iid = rng.randint(1, items + 1, n)
    gender = rng.randint(1, N_GENDER, n)           # 1..2 like M/F
    age = rng.choice([18, 25, 35, 45, 50, 56], n)
    occupation = rng.randint(0, N_OCC, n)
    genres = rng.randint(0, N_GENRES, n)
    affinity = ((uid * 7 + iid * 3) % 10) / 9.0
    score = 2.5 * affinity + 1.2 * (age / 56.0) + \
        0.3 * rng.randn(n)
    rating = np.clip(np.round(score + 1.5), 1, 5).astype(np.int64)
    return dict(uid=uid, iid=iid, gender=gender, age=age,
                occupation=occupation, genres=genres, rating=rating)


def assembly_feature(d, info):
    """The reference `assemblyFeature` (Utils.scala): multi-hot wide
    vector + [indicators | embed ids | continuous] deep vector."""
    n = len(d["uid"])
    x_wide = np.zeros((n, info.wide_dim), np.float32)
    x_wide[np.arange(n), d["occupation"]] = 1.0          # base 0..20
    x_wide[np.arange(n), N_OCC + d["gender"]] = 1.0      # base gender
    cross = (d["age"] * 3 + d["gender"]) % BUCKET        # hash cross
    x_wide[np.arange(n), N_OCC + N_GENDER + cross] = 1.0

    ind_genres = np.eye(N_GENRES, dtype=np.float32)[d["genres"]]
    ind_gender = np.eye(N_GENDER, dtype=np.float32)[d["gender"]]
    x_deep = np.concatenate([
        ind_genres, ind_gender,
        (d["uid"] - 1)[:, None].astype(np.float32),
        (d["iid"] - 1)[:, None].astype(np.float32),
        (d["age"][:, None] / 56.0).astype(np.float32),
    ], axis=1)
    return x_wide, x_deep


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--model-type", default="wide_n_deep",
                   choices=["wide", "deep", "wide_n_deep"])
    p.add_argument("--users", type=int, default=200)
    p.add_argument("--items", type=int, default=100)
    p.add_argument("--samples", type=int, default=4096)
    p.add_argument("--batch-size", type=int, default=512)
    p.add_argument("--epochs", type=int, default=4)
    args = p.parse_args(argv)

    from analytics_zoo_tpu import init_nncontext
    from analytics_zoo_tpu.models.recommendation import (
        ColumnFeatureInfo, UserItemFeature, WideAndDeep)
    from analytics_zoo_tpu.ops.optimizers import Adam

    init_nncontext(seed=0)
    rng = np.random.RandomState(0)
    d = synth_ml1m(args.samples, args.users, args.items, rng)

    # the reference Ml1mWideAndDeep localColumnInfo, verbatim
    info = ColumnFeatureInfo(
        wide_base_cols=["occupation", "gender"],
        wide_base_dims=[N_OCC, N_GENDER],
        wide_cross_cols=["age-gender"],
        wide_cross_dims=[BUCKET],
        indicator_cols=["genres", "gender"],
        indicator_dims=[N_GENRES, N_GENDER],
        embed_cols=["userId", "itemId"],
        embed_in_dims=[args.users, args.items],
        embed_out_dims=[64, 64],
        continuous_cols=["age"])

    wnd = WideAndDeep(args.model_type, num_classes=5,
                      column_info=info)
    # class_nll pairs with the log-softmax head (reference
    # LogSoftMax + ClassNLLCriterion + Adam(1e-2))
    wnd.compile(optimizer=Adam(lr=1e-2), loss="class_nll",
                metrics=["accuracy"])

    x_wide, x_deep = assembly_feature(d, info)
    y = (d["rating"] - 1).reshape(-1, 1).astype(np.int32)
    x = {"wide": x_wide, "deep": x_deep,
         "wide_n_deep": [x_wide, x_deep]}[args.model_type]
    n_train = int(0.8 * args.samples)
    wnd.fit(x[:n_train] if isinstance(x, np.ndarray)
            else [a[:n_train] for a in x],
            y[:n_train], batch_size=args.batch_size,
            nb_epoch=args.epochs)

    x_val = (x[n_train:] if isinstance(x, np.ndarray)
             else [a[n_train:] for a in x])
    logp = wnd.predict(x_val, batch_size=args.batch_size)
    acc = float((np.argmax(logp, -1) == y[n_train:, 0]).mean())
    print(f"validation accuracy: {acc:.3f} "
          f"({args.samples - n_train} samples)")

    # ranking surface over the validation window
    def row(i):
        if isinstance(x, np.ndarray):
            return x[n_train + i]
        return [a[n_train + i] for a in x]
    pairs = [UserItemFeature(user_id=int(d["uid"][n_train + i]),
                             item_id=int(d["iid"][n_train + i]),
                             feature=row(i))
             for i in range(min(200, args.samples - n_train))]
    print("predict_user_item_pair:")
    for pred in wnd.predict_user_item_pair(pairs)[:5]:
        print(f"  user {pred.user_id} item {pred.item_id}: rating "
              f"{pred.prediction + 1} (p={pred.probability:.3f})")
    print("recommend_for_user (top-3):")
    for pred in wnd.recommend_for_user(pairs, max_items=3)[:6]:
        print(f"  user {pred.user_id}: item {pred.item_id} "
              f"({pred.prediction + 1}, p={pred.probability:.3f})")
    print("recommend_for_item (top-3):")
    for pred in wnd.recommend_for_item(pairs, max_users=3)[:6]:
        print(f"  item {pred.item_id}: user {pred.user_id} "
              f"({pred.prediction + 1}, p={pred.probability:.3f})")
    return {"accuracy": acc}


if __name__ == "__main__":
    main()
