"""RDD/Spark ingest example (reference analog: every reference example
feeds `RDD[Sample]` into `fit`; `pyzoo/zoo/examples/nnframes` feeds
Spark DataFrames).

Demonstrates the duck-typed RDD protocol: the same code path accepts a
real ``pyspark.RDD`` when pyspark is installed (swap the LocalRdd
constructor for ``sc.parallelize``), with each JAX process keeping its
round-robin partition share (multi-host ingest)."""

from __future__ import annotations

import argparse

import numpy as np


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--n", type=int, default=256)
    p.add_argument("--partitions", type=int, default=8)
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--batch-size", type=int, default=32)
    args = p.parse_args(argv)

    from analytics_zoo_tpu import init_nncontext
    from analytics_zoo_tpu.feature import FeatureSet, LocalRdd, Sample
    from analytics_zoo_tpu.pipeline.api.keras import Sequential, \
        layers as L

    init_nncontext(tpu_mesh={"data": -1})
    rs = np.random.RandomState(0)
    w_true = rs.randn(8, 3).astype(np.float32)
    records = []
    for _ in range(args.n):
        x = rs.randn(8).astype(np.float32)
        y = int(np.argmax(x @ w_true))
        records.append(Sample(feature=x, label=np.array([y], np.int32)))

    # any object with mapPartitionsWithIndex/collect/getNumPartitions
    # works here — e.g. a pyspark RDD from sc.parallelize(records, 8)
    rdd = LocalRdd(records, num_partitions=args.partitions)
    fs = FeatureSet.from_rdd(rdd)
    print(f"ingested: {fs}")

    model = Sequential()
    model.add(L.Dense(16, activation="relu", input_shape=(8,)))
    model.add(L.Dense(3))
    model.compile(optimizer="adam", loss="softmax_cross_entropy",
                  metrics=["accuracy"])
    model.fit(fs, batch_size=args.batch_size, nb_epoch=args.epochs)
    metrics = model.evaluate(fs, batch_size=args.batch_size)
    print("metrics:", metrics)
    return metrics


if __name__ == "__main__":
    main()
