"""BERT fine-tune over a data-parallel TPU mesh — BASELINE config #5
("TFPark TFOptimizer: distributed BERT-base fine-tune on TPU pod").

The reference fine-tunes BERT by running its frozen TF graph through
TFOptimizer on BigDL's data-parallel loop (`P/tfpark/`, SURVEY.md
§2.5). Here the zoo's native :class:`BERT` encoder
(`layers/transformer.py`, reference `BERT.scala:53-110`) trains under
the Estimator's jitted SPMD step: batch sharded over the mesh's
``data`` axis, gradient all-reduce as an XLA collective over ICI,
``remat=True`` to fit long contexts, flash attention auto-routed past
the measured crossover.

Synthetic sentence-pair classification data stands in for GLUE (the
reference apps ship no corpora either); real token ids drop in
unchanged. On CPU:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python -m analytics_zoo_tpu.examples bert_finetune --devices 8
"""

from __future__ import annotations

import argparse

import numpy as np


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--devices", type=int, default=0,
                   help="0 = use all visible devices")
    p.add_argument("--seq-len", type=int, default=128)
    p.add_argument("--hidden", type=int, default=128,
                   help="128 keeps the demo fast; BERT-base is 768")
    p.add_argument("--blocks", type=int, default=2,
                   help="2 keeps the demo fast; BERT-base is 12")
    p.add_argument("--batch-per-device", type=int, default=4)
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--freeze-encoder", action="store_true",
                   help="train only the classifier head (feature-"
                        "extraction fine-tune)")
    args = p.parse_args(argv)

    import jax

    from analytics_zoo_tpu import init_nncontext
    from analytics_zoo_tpu.ops.optimizers import Adam, warmup
    from analytics_zoo_tpu.pipeline.api.autograd import Lambda
    from analytics_zoo_tpu.pipeline.api.keras import layers as L
    from analytics_zoo_tpu.pipeline.api.keras.models import Sequential
    from analytics_zoo_tpu.pipeline.estimator import Estimator

    n = args.devices or len(jax.devices())
    ctx = init_nncontext(tpu_mesh={"data": n},
                         devices=jax.devices()[:n], seed=0)
    t, h = args.seq_len, args.hidden
    batch = args.batch_per_device * n
    n_cls, vocab = 2, 1000

    # -- model: BERT encoder + pooled-output classifier head ----------
    bert = L.BERT(vocab=vocab, hidden_size=h, n_block=args.blocks,
                  n_head=max(2, h // 64), seq_len=t,
                  intermediate_size=4 * h, output_all_block=False,
                  remat=True, name="bert",
                  input_shape=[(t,)] * 4)
    if args.freeze_encoder:
        bert.trainable = False
    model = Sequential()
    model.add(bert)
    # BERT outputs [sequence_output, pooled_output]; classify on pooled
    model.add(Lambda(lambda outs: outs[1], name="take_pooled",
                     output_shape=(h,)))
    model.add(L.Dropout(0.1))
    model.add(L.Dense(n_cls, activation="softmax", name="classifier"))

    # -- synthetic sentence-pair batch (GLUE-shaped) -------------------
    rs = np.random.RandomState(0)
    n_samples = batch * 8
    tok = rs.randint(1, vocab, size=(n_samples, t)).astype(np.int32)
    seg = (np.arange(t)[None, :] >= t // 2).astype(np.int32) \
        * np.ones((n_samples, 1), np.int32)
    pos = np.tile(np.arange(t, dtype=np.int32), (n_samples, 1))
    mask = np.ones((n_samples, t), np.float32)
    # separable labels: class = whether the first segment's mean token
    # id is above the vocab midpoint (learnable from embeddings alone)
    y = (tok[:, : t // 2].mean(axis=1) > vocab / 2).astype(
        np.int32)[:, None]

    est = Estimator(
        model,
        optimizer=Adam(lr=warmup(5e-5, 8, delta=(5e-4 - 5e-5) / 8)),
        loss="sparse_categorical_crossentropy",
        metrics=["accuracy"], ctx=ctx)
    res = est.train([tok, seg, pos, mask], y, batch_size=batch,
                    nb_epoch=args.epochs)
    scores = est.evaluate([tok, seg, pos, mask], y, batch_size=batch)
    print(f"devices={n} seq_len={t} blocks={args.blocks} "
          f"frozen={args.freeze_encoder}")
    print(f"final train loss={res.history[-1]['loss']:.4f} "
          f"eval={scores}")
    return scores


if __name__ == "__main__":
    main()
