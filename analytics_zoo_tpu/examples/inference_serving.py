"""Serving example (reference `pyzoo/zoo/examples` web-service samples
+ `InferenceModel`): load a model into the concurrent serving pool
(native C++ queue under the hood) and answer predictions from several
threads."""

from __future__ import annotations

import argparse
import threading

import numpy as np


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--concurrency", type=int, default=4)
    p.add_argument("--requests", type=int, default=16)
    args = p.parse_args(argv)

    from analytics_zoo_tpu import init_nncontext
    from analytics_zoo_tpu.pipeline.api.keras import Sequential, layers as L
    from analytics_zoo_tpu.pipeline.inference import InferenceModel

    init_nncontext()
    net = Sequential()
    net.add(L.Dense(32, input_shape=(8,), activation="relu"))
    net.add(L.Dense(3, activation="softmax"))
    net.compile(optimizer="adam", loss="sparse_categorical_crossentropy")

    model = InferenceModel(supported_concurrent_num=args.concurrency)
    model.load_keras_net(net)

    rng = np.random.RandomState(0)
    results = [None] * args.requests

    def worker(i):
        x = rng.rand(4, 8).astype(np.float32)
        results[i] = model.predict(x)

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(args.requests)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    shapes = {np.asarray(r).shape for r in results}
    print(f"served {args.requests} requests over "
          f"{args.concurrency} model copies; output shapes: {shapes}")
    return results


if __name__ == "__main__":
    main()
