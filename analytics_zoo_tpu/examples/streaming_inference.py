"""Streaming micro-batch inference — the reference's Spark Streaming
examples (`Z/examples/streaming/{objectdetection,textclassification}`:
a DStream of records scored per micro-batch) rebuilt without Spark:
a producer thread feeds a bounded queue (the stream source), a
consumer drains it into micro-batches on a time/size trigger, and an
`InferenceModel` pool (compiled-executable queue, `pipeline/inference`)
scores each batch concurrently. Prints per-batch latency and a final
throughput summary.

The demo streams synthetic text through the TextClassifier; swap the
producer for a socket/Kafka reader for real streams.
"""

from __future__ import annotations

import argparse
import queue
import threading
import time

import numpy as np


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--records", type=int, default=96,
                   help="total records the producer emits")
    p.add_argument("--rate", type=float, default=400.0,
                   help="producer records/sec")
    p.add_argument("--batch-max", type=int, default=16)
    p.add_argument("--batch-interval-ms", type=int, default=100,
                   help="micro-batch trigger (reference: the DStream "
                        "batch duration)")
    p.add_argument("--concurrency", type=int, default=2)
    args = p.parse_args(argv)

    from analytics_zoo_tpu import init_nncontext
    from analytics_zoo_tpu.models.textclassification import \
        TextClassifier
    from analytics_zoo_tpu.pipeline.inference import InferenceModel

    init_nncontext(seed=0)
    seq_len, token_len, classes = 32, 16, 3

    # model under test: a TextClassifier scored through the
    # InferenceModel pool (weights random — the pipeline is the demo);
    # records arrive pre-embedded (T, token_len) like the reference's
    # WordEmbedding-preprocessed stream
    tc = TextClassifier(class_num=classes, token_length=token_len,
                        sequence_length=seq_len, encoder="cnn")
    tc.compile(optimizer="adam",
               loss="sparse_categorical_crossentropy")
    im = InferenceModel(supported_concurrent_num=args.concurrency)
    im.load_keras_net(tc.model)   # params auto-initialized

    # -- stream source: producer thread -> bounded queue ---------------
    q: "queue.Queue" = queue.Queue(maxsize=args.batch_max * 4)
    rs = np.random.RandomState(0)
    records = rs.randn(args.records, seq_len, token_len) \
        .astype(np.float32)

    def produce():
        for rec in records:
            q.put(rec)
            time.sleep(1.0 / args.rate)
        q.put(None)  # end-of-stream

    threading.Thread(target=produce, daemon=True).start()

    # -- micro-batch consumer ------------------------------------------
    interval = args.batch_interval_ms / 1000.0
    done, n_scored, n_batches = False, 0, 0
    lat_ms = []
    t_start = time.time()
    while not done:
        batch, deadline = [], time.time() + interval
        while len(batch) < args.batch_max:
            timeout = deadline - time.time()
            if timeout <= 0:
                break
            try:
                item = q.get(timeout=timeout)
            except queue.Empty:
                break
            if item is None:
                done = True
                break
            batch.append(item)
        if not batch:
            continue
        t0 = time.time()
        x = np.zeros((args.batch_max, seq_len, token_len),
                     np.float32)
        x[: len(batch)] = np.stack(batch)      # pad to compiled shape
        scores = np.asarray(im.predict([x]))
        preds = scores[: len(batch)].argmax(-1)
        dt = (time.time() - t0) * 1000
        lat_ms.append(dt)
        n_scored += len(batch)
        n_batches += 1
        print(f"batch {n_batches}: {len(batch)} records "
              f"classes={np.bincount(preds, minlength=classes)} "
              f"latency={dt:.1f}ms")
    wall = time.time() - t_start
    print(f"stream done: {n_scored} records in {n_batches} "
          f"micro-batches, {n_scored / wall:.0f} rec/s end-to-end, "
          f"median batch latency {np.median(lat_ms):.1f}ms")
    return {"records": n_scored, "batches": n_batches}


if __name__ == "__main__":
    main()
