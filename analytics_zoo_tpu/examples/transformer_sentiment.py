"""Transformer sentiment example (reference
`P/examples/attention/transformer.py`): IMDB sequences padded to a
fixed length, classified by TransformerLayer → GlobalAveragePooling1D
→ Dropout → Dense(2, softmax).

Uses `keras.datasets.imdb` (real cache file when present, synthetic
stand-in offline). Sizes default small enough to smoke-run on CPU;
scale them up (`--hidden-size 128 --n-head 8 --max-len 200`) to match
the reference's configuration.
"""

from __future__ import annotations

import argparse

import numpy as np


def pad_sequences(seqs, maxlen):
    out = np.zeros((len(seqs), maxlen), np.int32)
    for i, s in enumerate(seqs):
        s = list(s)[-maxlen:]            # keras 'pre' truncation
        out[i, maxlen - len(s):] = s     # keras 'pre' padding
    return out


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--max-features", type=int, default=2000)
    p.add_argument("--max-len", type=int, default=64)
    p.add_argument("--hidden-size", type=int, default=32)
    p.add_argument("--n-head", type=int, default=4)
    p.add_argument("--n-block", type=int, default=1)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--epochs", type=int, default=1)
    p.add_argument("--n-train", type=int, default=256)
    args = p.parse_args(argv)

    from analytics_zoo_tpu import init_nncontext
    from analytics_zoo_tpu.ops.optimizers import Adam
    from analytics_zoo_tpu.pipeline.api.keras import Sequential, \
        layers as L
    from analytics_zoo_tpu.pipeline.api.keras.datasets import imdb

    init_nncontext()
    (x_train, y_train), _ = imdb.load_data(
        nb_words=args.max_features)
    x = pad_sequences(x_train[:args.n_train], args.max_len)
    y = np.asarray(y_train[:args.n_train], np.int32).reshape(-1, 1)

    model = Sequential()
    model.add(L.TransformerLayer(
        n_block=args.n_block, hidden_size=args.hidden_size,
        n_head=args.n_head, seq_len=args.max_len,
        vocab=args.max_features, bidirectional=True,
        input_shape=(args.max_len,)))
    model.add(L.GlobalAveragePooling1D())
    model.add(L.Dropout(0.2))
    model.add(L.Dense(2, activation="softmax"))
    model.compile(optimizer=Adam(lr=1e-3),
                  loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
    model.fit(x, y, batch_size=args.batch_size, nb_epoch=args.epochs)
    metrics = model.evaluate(x, y, batch_size=args.batch_size)
    print("transformer_sentiment:", metrics)
    return metrics


if __name__ == "__main__":
    main()
