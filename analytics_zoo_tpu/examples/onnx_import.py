"""ONNX import example (reference `pyzoo/zoo/examples/onnx/`): build an
ONNX model with the framework's own proto builder (stand-in for a file
exported elsewhere), load it with `OnnxLoader`, predict, fine-tune."""

from __future__ import annotations

import argparse

import numpy as np


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--path", default="/tmp/example_mlp.onnx")
    p.add_argument("--epochs", type=int, default=10)
    args = p.parse_args(argv)

    from analytics_zoo_tpu import init_nncontext
    from analytics_zoo_tpu.pipeline.api.onnx import (
        OnnxLoader,
        helper,
        onnx_pb,
    )
    from analytics_zoo_tpu.pipeline.api.onnx.onnx_pb import TensorProto

    init_nncontext()
    rng = np.random.RandomState(0)

    # fabricate an MLP .onnx file (any exporter's file works the same)
    w1 = (rng.randn(32, 8) * 0.3).astype(np.float32)
    b1 = np.zeros(32, np.float32)
    w2 = (rng.randn(4, 32) * 0.3).astype(np.float32)
    nodes = [
        helper.make_node("Gemm", ["x", "w1", "b1"], ["h"], transB=1),
        helper.make_node("Relu", ["h"], ["hr"]),
        helper.make_node("Gemm", ["hr", "w2"], ["out"], transB=1),
    ]
    graph = helper.make_graph(
        nodes, "mlp",
        [helper.make_tensor_value_info("x", TensorProto.FLOAT,
                                       ["N", 8])],
        [helper.make_tensor_value_info("out", TensorProto.FLOAT,
                                       ["N", 4])],
        [helper.make_tensor("w1", w1), helper.make_tensor("b1", b1),
         helper.make_tensor("w2", w2)])
    onnx_pb.save_model(helper.make_model(graph), args.path)
    print(f"wrote {args.path}")

    net = OnnxLoader.load_model(args.path)
    net.compile(optimizer="adam", loss="mse")
    x = rng.randn(128, 8).astype(np.float32)
    y = rng.randn(128, 4).astype(np.float32)
    print("imported forward:", net.predict(x, batch_size=64).shape)
    net.fit(x, y, batch_size=64, nb_epoch=args.epochs)
    print("fine-tuned imported ONNX model on TPU")


if __name__ == "__main__":
    main()
