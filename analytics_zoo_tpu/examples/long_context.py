"""Long-context attention example: the Pallas flash kernel and the
two sequence-parallel strategies (ring, Ulysses) on one model.

The reference's longest context is BERT-512 (`BERT.scala`); this
framework treats long context as first-class (SURVEY.md §5): the
flash kernel keeps softmax statistics in VMEM (no O(T²) HBM logits),
ring attention shards the sequence over a mesh axis and rotates K/V
around the ICI ring, and Ulysses swaps sequence-sharding for
head-sharding with two all-to-alls.

On a real multi-chip slice the mesh maps onto ICI automatically. To
try it on CPU:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python -m analytics_zoo_tpu.examples long_context
"""

from __future__ import annotations

import argparse

import numpy as np


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--seq-len", type=int, default=1024,
                   help="context length (multiple of 128*devices)")
    p.add_argument("--devices", type=int, default=0,
                   help="0 = use all visible devices")
    args = p.parse_args(argv)

    import jax
    import jax.numpy as jnp

    from analytics_zoo_tpu import init_nncontext
    from analytics_zoo_tpu.ops.attention import dot_product_attention
    from analytics_zoo_tpu.parallel.ring_attention import ring_attention
    from analytics_zoo_tpu.parallel.ulysses import ulysses_attention
    from jax.sharding import NamedSharding, PartitionSpec as P

    devices = jax.devices()
    n = args.devices or len(devices)
    t = args.seq_len
    rs = np.random.RandomState(0)

    ctx = init_nncontext(tpu_mesh={"seq": n}, devices=devices[:n])
    b, h, d = 2, 8, 64
    mk = lambda: rs.randn(b, t, h, d).astype(np.float32) * 0.5
    q, k, v = mk(), mk(), mk()

    # single-device flash kernel (Pallas; falls back to interpret mode
    # off-TPU so this runs anywhere)
    dense = dot_product_attention(jnp.asarray(q), jnp.asarray(k),
                                  jnp.asarray(v), causal=True,
                                  impl="auto")
    print(f"flash/auto attention: T={t} out={dense.shape}")

    # sequence-parallel: T sharded over the mesh's seq axis
    sh = NamedSharding(ctx.mesh, P(None, "seq"))
    qs, ks, vs = (jax.device_put(x, sh) for x in (q, k, v))

    ring = ring_attention(qs, ks, vs, ctx.mesh, axis="seq",
                          causal=True, impl="auto")
    err = float(jnp.max(jnp.abs(ring - dense)))
    print(f"ring attention over {n} devices: max err vs dense {err:.2e}")

    if h % n == 0:
        uly = ulysses_attention(qs, ks, vs, ctx.mesh, axis="seq",
                                causal=True)
        err = float(jnp.max(jnp.abs(uly - dense)))
        print(f"ulysses attention over {n} devices: max err vs dense "
              f"{err:.2e}")
    else:
        print(f"ulysses skipped (heads {h} % devices {n} != 0)")
    print("long_context example OK")


if __name__ == "__main__":
    main()
