"""Text classification example (reference
`pyzoo/zoo/examples/textclassification/text_classification.py`):
TextSet pipeline (tokenize → word2idx → shape_sequence →
generate_sample) into the CNN TextClassifier. Synthetic 20-newsgroups-
shaped corpus by default."""

from __future__ import annotations

import argparse

import numpy as np


def synth_corpus(rng, n_per_class, classes):
    vocab = {
        0: ["game", "team", "score", "season", "coach", "win"],
        1: ["gpu", "kernel", "driver", "compile", "memory", "bug"],
        2: ["senate", "vote", "policy", "bill", "election", "law"],
    }
    texts, labels = [], []
    for c in range(classes):
        words = vocab[c % len(vocab)]
        for _ in range(n_per_class):
            n = rng.randint(8, 20)
            texts.append(" ".join(rng.choice(words, n)))
            labels.append(c)
    order = rng.permutation(len(texts))
    return [texts[i] for i in order], [labels[i] for i in order]


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--classes", type=int, default=3)
    p.add_argument("--per-class", type=int, default=64)
    p.add_argument("--sequence-length", type=int, default=32)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--epochs", type=int, default=4)
    p.add_argument("--encoder", default="cnn",
                   choices=["cnn", "lstm", "gru"])
    args = p.parse_args(argv)

    from analytics_zoo_tpu import init_nncontext
    from analytics_zoo_tpu.feature.text import TextSet
    from analytics_zoo_tpu.models.textclassification import TextClassifier

    init_nncontext()
    rng = np.random.RandomState(0)
    texts, labels = synth_corpus(rng, args.per_class, args.classes)

    text_set = TextSet.from_texts(texts, labels)
    transformed = (text_set.tokenize()
                   .word2idx()
                   .shape_sequence(args.sequence_length)
                   .generate_sample())
    x, y = transformed.to_arrays()
    vocab_size = len(transformed.get_word_index()) + 2

    from analytics_zoo_tpu.pipeline.api.keras.layers import Embedding
    clf = TextClassifier(class_num=args.classes,
                         sequence_length=args.sequence_length,
                         encoder=args.encoder, encoder_output_dim=32,
                         embedding=Embedding(
                             vocab_size, 32,
                             input_shape=(args.sequence_length,)))
    clf.compile(optimizer="adam",
                loss="sparse_categorical_crossentropy",
                metrics=["accuracy"])
    clf.fit(x, y, batch_size=args.batch_size, nb_epoch=args.epochs)
    metrics = clf.evaluate(x, y, batch_size=args.batch_size)
    print(f"train-set metrics: {metrics}")
    return metrics


if __name__ == "__main__":
    main()
