"""Custom-loss autograd example (reference
`P/examples/autograd/customloss.py`, `custom.py`): fit y = 2·x₁+2·x₂
+0.4 with a Dense(1) under a mean-absolute-error loss written with the
autograd variable ops, then recover the weights.

The reference runs the lambda through py4j into BigDL's autograd; here
the same expression traces straight into the XLA training program.
"""

from __future__ import annotations

import argparse

import numpy as np


def mean_absolute_error(y_true, y_pred):
    from analytics_zoo_tpu.pipeline.api import autograd as A
    return A.mean(A.abs(y_true - y_pred), axis=1)


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--n", type=int, default=1000)
    p.add_argument("--epochs", type=int, default=60)
    args = p.parse_args(argv)

    from analytics_zoo_tpu import init_nncontext
    from analytics_zoo_tpu.ops.optimizers import SGD
    from analytics_zoo_tpu.pipeline.api.autograd import CustomLoss
    from analytics_zoo_tpu.pipeline.api.keras import Sequential, \
        layers as L

    init_nncontext()
    rs = np.random.RandomState(0)
    x = rs.uniform(0, 1, (args.n, 2)).astype(np.float32)
    y = ((2 * x).sum(1) + 0.4).reshape(args.n, 1).astype(np.float32)

    model = Sequential()
    model.add(L.Dense(1, input_shape=(2,)))
    model.compile(optimizer=SGD(lr=1e-1),
                  loss=CustomLoss(mean_absolute_error,
                                  y_pred_shape=(1,)))
    model.fit(x, y, batch_size=32, nb_epoch=args.epochs)
    pred = model.predict(x)
    mae = float(np.mean(np.abs(pred - y)))
    kernel = np.asarray(model.get_weights()[0]).reshape(-1)
    print(f"learned weights ~ [2, 2]: {kernel.round(2)}  mae={mae:.4f}")
    return {"mae": mae, "weights": kernel}


if __name__ == "__main__":
    main()
