"""Variational autoencoder example (reference app
`apps/variational-autoencoder/
using_variational_autoencoder_to_generate_digital_numbers.ipynb`,
which builds VAE from BigDL `GaussianSampler`/`KLDCriterion`).

TPU-first redesign: the reparameterization trick and the ELBO are
plain autograd Variable expressions — the model takes [image, eps]
and OUTPUTS the per-sample loss (BCE reconstruction + KL), trained
with an identity objective; no bespoke sampler/criterion modules
needed. After training, the decoder layers are rebuilt into a
standalone generator (weights copied by layer name) and digits are
sampled from the prior.
"""

from __future__ import annotations

import argparse

import numpy as np


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--latent", type=int, default=2)
    p.add_argument("--hidden", type=int, default=64)
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--n-train", type=int, default=512)
    p.add_argument("--batch-size", type=int, default=64)
    args = p.parse_args(argv)

    from analytics_zoo_tpu import init_nncontext
    from analytics_zoo_tpu.ops.optimizers import Adam
    from analytics_zoo_tpu.pipeline.api import autograd as A
    from analytics_zoo_tpu.pipeline.api.autograd import CustomLoss
    from analytics_zoo_tpu.pipeline.api.keras.datasets import mnist
    from analytics_zoo_tpu.pipeline.api.keras.engine import Input
    from analytics_zoo_tpu.pipeline.api.keras.layers import Dense
    from analytics_zoo_tpu.pipeline.api.keras.models import Model

    init_nncontext()
    (x_train, _), _ = mnist.load_data()
    x = (x_train[:args.n_train].reshape(-1, 784) / 255.0) \
        .astype(np.float32)
    rs = np.random.RandomState(0)
    eps = rs.randn(len(x), args.latent).astype(np.float32)

    # encoder -> reparameterized z -> decoder, ELBO as the output
    x_in = Input((784,), name="image")
    eps_in = Input((args.latent,), name="eps")
    h = Dense(args.hidden, activation="relu", name="enc_h")(x_in)
    z_mean = Dense(args.latent, name="enc_mean")(h)
    z_logvar = Dense(args.latent, name="enc_logvar")(h)
    z = z_mean + A.exp(z_logvar * 0.5) * eps_in   # reparameterization
    dec_h = Dense(args.hidden, activation="relu", name="dec_h")
    dec_out = Dense(784, activation="sigmoid", name="dec_out")
    recon = dec_out(dec_h(z))
    recon = A.clip(recon, 1e-6, 1.0 - 1e-6)
    bce = -A.sum(x_in * A.log(recon) +
                 (1.0 - x_in) * A.log(1.0 - recon),
                 axis=1, keepdims=True)
    kl = A.sum(A.square(z_mean) + A.exp(z_logvar) - z_logvar - 1.0,
               axis=1, keepdims=True) * 0.5
    vae = Model([x_in, eps_in], bce + kl, name="vae")
    # identity objective (ELBO is the model output); y_true * 0 keeps
    # the loss graph connected to both inputs
    vae.compile(optimizer=Adam(lr=1e-3),
                loss=CustomLoss(
                    lambda y_true, y_pred: y_pred + y_true * 0.0,
                    y_pred_shape=(1,)))
    dummy_y = np.zeros((len(x), 1), np.float32)
    res = vae.fit([x, eps], dummy_y, batch_size=args.batch_size,
                  nb_epoch=args.epochs)
    elbo = res.history[-1]["loss"]
    print(f"vae: final per-sample loss (BCE+KL) = {elbo:.2f}")

    # standalone generator: same decoder layer objects, weights copied
    # by layer name from the trained params
    z_in = Input((args.latent,), name="z")
    gen = Model(z_in, dec_out(dec_h(z_in)), name="generator")
    gen.compile(optimizer="sgd", loss="mse")
    gen.copy_weights_from(vae)  # decoder layers matched by name
    samples = gen.predict(
        rs.randn(4, args.latent).astype(np.float32), batch_size=4)
    print(f"generated {samples.shape[0]} digits, pixel range "
          f"[{samples.min():.2f}, {samples.max():.2f}]")
    return {"loss": float(elbo), "samples": samples}


if __name__ == "__main__":
    main()
