"""Neural Collaborative Filtering example (reference
`pyzoo/zoo/examples/recommendation/ncf_explicit_feedback.py`): build
NeuralCF, train on (user, item) → rating pairs, then
`recommend_for_user`. Synthetic ml-1m-shaped data by default."""

from __future__ import annotations

import argparse

import numpy as np


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--users", type=int, default=200)
    p.add_argument("--items", type=int, default=100)
    p.add_argument("--samples", type=int, default=2048)
    p.add_argument("--batch-size", type=int, default=256)
    p.add_argument("--epochs", type=int, default=3)
    args = p.parse_args(argv)

    from analytics_zoo_tpu import init_nncontext
    from analytics_zoo_tpu.models.recommendation import (
        NeuralCF,
        UserItemFeature,
    )

    init_nncontext()
    rng = np.random.RandomState(0)
    users = rng.randint(1, args.users + 1, args.samples)
    items = rng.randint(1, args.items + 1, args.samples)
    # implicit 5-class ratings correlated with user/item parity
    ratings = ((users + items) % 5 + 1).astype(np.int32)

    ncf = NeuralCF(user_count=args.users, item_count=args.items,
                   num_classes=5, user_embed=16, item_embed=16,
                   hidden_layers=(32, 16, 8), mf_embed=16)
    # class_nll pairs with NeuralCF's log-softmax head (the
    # reference's LogSoftMax + ClassNLLCriterion); a probability-space
    # CE here would clip the log-probs and learn nothing
    ncf.compile(optimizer="adam", loss="class_nll",
                metrics=["accuracy"])
    x = np.stack([users, items], axis=1).astype(np.int32)
    y = (ratings - 1).reshape(-1, 1)
    ncf.fit(x, y, batch_size=args.batch_size, nb_epoch=args.epochs)

    pairs = [UserItemFeature(user_id=int(u), item_id=int(i),
                             feature=np.array([u, i], np.int32))
             for u, i in zip(users[:50], items[:50])]
    recs = ncf.recommend_for_user(pairs, max_items=3)
    for r in recs[:5]:
        print(f"user {r.user_id}: item {r.item_id} rated "
              f"{r.prediction + 1} (p={r.probability:.3f})")
    return recs


if __name__ == "__main__":
    main()
