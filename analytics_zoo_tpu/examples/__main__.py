"""Example dispatcher: ``python -m analytics_zoo_tpu.examples <name>``
(the reference's per-example spark-submit mains, Net.scala L12 analog).
"""

import importlib
import sys

from analytics_zoo_tpu.examples import EXAMPLES


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ("-h", "--help", "list"):
        print("usage: python -m analytics_zoo_tpu.examples "
              "<name> [args...]\n\nexamples:")
        for e in EXAMPLES:
            print(f"  {e}")
        return 0
    name = argv[0].replace("-", "_")
    if name not in EXAMPLES:
        print(f"unknown example {argv[0]!r}; run with 'list' to see "
              "available names", file=sys.stderr)
        return 2
    mod = importlib.import_module(f"analytics_zoo_tpu.examples.{name}")
    ret = mod.main(argv[1:])
    # example mains return result payloads (metrics dicts etc.), not
    # exit codes; only an explicit int is a process status
    return ret if isinstance(ret, int) else 0


if __name__ == "__main__":
    sys.exit(main())
