"""Example dispatcher: ``python -m analytics_zoo_tpu.examples <name>``
(the reference's per-example spark-submit mains, Net.scala L12 analog).
"""

import ast
import importlib
import os
import sys

from analytics_zoo_tpu.examples import EXAMPLES


def _hook(name: str) -> str:
    """First sentence of the example's docstring, width-capped —
    source-scanned so the listing never imports jax."""
    try:
        path = os.path.join(os.path.dirname(__file__), name + ".py")
        with open(path) as f:
            doc = ast.get_docstring(ast.parse(f.read())) or ""
        first = " ".join(doc.split("\n\n")[0].split())
        first = first.split(". ")[0].rstrip(".")
        return first[:52] + ("…" if len(first) > 52 else "")
    except Exception:
        return ""


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ("-h", "--help", "list"):
        print("usage: python -m analytics_zoo_tpu.examples "
              "<name> [args...]\n\nexamples:")
        for e in EXAMPLES:
            print(f"  {e:24s} {_hook(e)}")
        return 0
    name = argv[0].replace("-", "_")
    if name not in EXAMPLES:
        print(f"unknown example {argv[0]!r}; run with 'list' to see "
              "available names", file=sys.stderr)
        return 2
    # (JAX_PLATFORMS is pinned authoritatively by the package
    # __init__, imported above)
    mod = importlib.import_module(f"analytics_zoo_tpu.examples.{name}")
    ret = mod.main(argv[1:])
    # example mains return result payloads (metrics dicts etc.), not
    # exit codes; only an explicit int is a process status
    return ret if isinstance(ret, int) else 0


if __name__ == "__main__":
    sys.exit(main())
