"""Distributed training example: data-parallel + FSDP + ring-attention
sequence parallelism over a TPU mesh (replaces the reference's
BigDL-on-Spark `DistriOptimizer` double-job loop, SURVEY.md §2.10).

On a real multi-chip slice the mesh maps onto ICI automatically. To try
it on CPU:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/distributed_training.py --devices 8
"""

from __future__ import annotations

import argparse

import numpy as np


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--devices", type=int, default=0,
                   help="0 = use all visible devices")
    p.add_argument("--batch-per-device", type=int, default=8)
    p.add_argument("--steps", type=int, default=4)
    args = p.parse_args(argv)

    import jax

    from analytics_zoo_tpu import init_nncontext
    from analytics_zoo_tpu.ops.optimizers import Adam
    from analytics_zoo_tpu.pipeline.api.keras import Sequential, layers as L
    from analytics_zoo_tpu.pipeline.estimator import Estimator

    devices = jax.devices()
    n = args.devices or len(devices)
    rng = np.random.RandomState(0)

    # -- 1. pure data parallel -------------------------------------------
    ctx = init_nncontext(tpu_mesh={"data": n}, devices=devices[:n])
    net = Sequential()
    net.add(L.Dense(64, input_shape=(16,), activation="relu"))
    net.add(L.Dense(4))
    est = Estimator(net, optimizer=Adam(lr=1e-3),
                    loss="softmax_cross_entropy", ctx=ctx)
    batch = args.batch_per_device * n
    x = rng.randn(batch * args.steps, 16).astype(np.float32)
    y = rng.randint(0, 4, (batch * args.steps, 1)).astype(np.int32)
    est.train(x, y, batch_size=batch, nb_epoch=1)
    print(f"DP over {dict(ctx.mesh.shape)}: {est.step} steps")

    # -- 2. FSDP + ring-attention sequence parallelism -------------------
    if n >= 4 and n % 4 == 0:
        axes = {"data": n // 4, "fsdp": 2, "seq": 2}
    elif n % 2 == 0:
        axes = {"data": n // 2, "seq": 2}
    else:
        print("need an even device count for fsdp/seq demo; done")
        return
    ctx2 = init_nncontext(tpu_mesh=axes, devices=devices[:n])
    seq_len = 16
    tnet = Sequential()
    tnet.add(L.TransformerLayer(
        n_block=2, hidden_size=32, n_head=4, seq_len=seq_len, vocab=64,
        sequence_parallel_axis="seq"))
    tnet.add(L.Select(1, -1))
    tnet.add(L.Dense(4))
    est2 = Estimator(tnet, optimizer=Adam(lr=1e-3),
                     loss="softmax_cross_entropy", ctx=ctx2,
                     parallel_mode="fsdp" if "fsdp" in axes else "dp")
    tb = 2 * ctx2.data_parallel_size
    xt = rng.randint(0, 64, (tb * 2, seq_len)).astype(np.int32)
    yt = rng.randint(0, 4, (tb * 2, 1)).astype(np.int32)
    est2.train(xt, yt, batch_size=tb, nb_epoch=1)
    print(f"{'FSDP+' if 'fsdp' in axes else ''}ring-attention over "
          f"{dict(ctx2.mesh.shape)}: {est2.step} steps")


if __name__ == "__main__":
    main()
