"""Chatbot example — the reference's Scala chatbot example
(`Z/examples/chatbot/Train.scala`: ZooDictionary + Seq2seq over a
dialog corpus, greedy generation) on the TPU-native stack:
`ZooDictionary` builds the word↔index vocab, tokens become one-hot
vectors, `Seq2seq` (LSTM encoder/decoder + dense bridge + Dense
generator) trains teacher-forced, and `infer` greedily generates a
reply word by word.

A tiny built-in dialog corpus keeps the demo offline; point
``--corpus`` at a two-column TSV (utterance<TAB>reply) for real data.
"""

from __future__ import annotations

import argparse

import numpy as np

_TINY_DIALOGS = [
    ("hello", "hi there"),
    ("hi", "hello"),
    ("how are you", "i am fine"),
    ("what is your name", "i am zoo"),
    ("bye", "goodbye"),
    ("thanks", "you are welcome"),
]


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--corpus", default=None,
                   help="TSV file: utterance<TAB>reply per line")
    p.add_argument("--epochs", type=int, default=30)
    p.add_argument("--hidden", type=int, default=32)
    p.add_argument("--max-len", type=int, default=6)
    p.add_argument("--ask", default="how are you")
    p.add_argument("--beam", type=int, default=1,
                   help=">1 switches the reply decode to beam search")
    args = p.parse_args(argv)

    from analytics_zoo_tpu import init_nncontext
    from analytics_zoo_tpu.common.dictionary import ZooDictionary
    from analytics_zoo_tpu.models.seq2seq import (
        Bridge, RNNDecoder, RNNEncoder, Seq2seq)
    from analytics_zoo_tpu.ops.optimizers import Adam
    from analytics_zoo_tpu.pipeline.api.keras.layers import Dense

    ctx = init_nncontext(seed=0)
    if args.corpus:
        pairs = []
        with open(args.corpus) as f:
            for line in f:
                parts = line.rstrip("\n").split("\t")
                if len(parts) == 2:
                    pairs.append((parts[0], parts[1]))
    else:
        pairs = _TINY_DIALOGS
    if not pairs:
        raise SystemExit(
            "no utterance<TAB>reply lines found in --corpus")

    # -- vocab (reference: ZooDictionary over the corpus) --------------
    sos, eos, pad = "<sos>", "<eos>", "<pad>"
    sentences = [q.split() for q, _ in pairs] + \
        [a.split() for _, a in pairs] + [[sos, eos, pad]]
    vocab = ZooDictionary.from_corpus(sentences)
    v = len(vocab)
    t = args.max_len

    def encode(words, add_sos=False, add_eos=False):
        # unseen words map to <pad> (no KeyError for novel --ask words)
        unk = vocab.get_index(pad)
        keep = t - int(add_sos) - int(add_eos)
        ids = vocab.encode(words, unk_index=unk)[:keep]
        if add_sos:
            ids = [vocab.get_index(sos)] + ids
        if add_eos:
            ids = ids + [vocab.get_index(eos)]
        ids += [unk] * (t - len(ids))
        return ids[:t]

    def onehot(ids):
        out = np.zeros((len(ids), v), np.float32)
        out[np.arange(len(ids)), ids] = 1.0
        return out

    enc_in = np.stack([onehot(encode(q.split())) for q, _ in pairs])
    dec_in = np.stack([onehot(encode(a.split(), add_sos=True))
                       for _, a in pairs])
    target = np.stack([onehot(encode(a.split(), add_eos=True))
                       for _, a in pairs])

    # -- model (teacher-forced training) -------------------------------
    s2s = Seq2seq(encoder=RNNEncoder("lstm", 1, args.hidden),
                  decoder=RNNDecoder("lstm", 1, args.hidden),
                  input_shape=(t, v), output_shape=(t, v),
                  bridge=Bridge("dense"),
                  generator=Dense(v, activation="softmax",
                                  name="generator"))
    s2s.compile(optimizer=Adam(lr=0.02), loss="categorical_crossentropy")
    # batch must divide over the data-parallel mesh axis; tile the tiny
    # corpus up to a multiple of it
    dp = ctx.data_parallel_size
    total = -(-len(pairs) // dp) * dp
    idx = np.resize(np.arange(len(pairs)), total)
    batch = min(total, -(-8 // dp) * dp)   # ~8, dp-divisible
    res = s2s.fit([enc_in[idx], dec_in[idx]], target[idx],
                  batch_size=batch, nb_epoch=args.epochs)

    # -- chat: greedy (reference infer loop) or beam search ------------
    q = onehot(encode(args.ask.split()))[None]
    if args.beam > 1:
        ids, score = s2s.infer_beam(
            q[0], start_token=vocab.get_index(sos),
            beam_size=args.beam, max_seq_len=t,
            stop_token=vocab.get_index(eos))
        words = [vocab.get_word(i) for i in ids]
    else:
        start = onehot([vocab.get_index(sos)])[0]
        gen = s2s.infer(q[0], start_sign=start, max_seq_len=t)
        words = []
        for step in range(1, gen.shape[1]):    # skip the <sos> start
            w = vocab.get_word(int(np.argmax(gen[0, step])))
            if w in (eos, pad, sos):  # stop at end/filler tokens
                break
            words.append(w)
    words = [w for w in words if w not in (eos, pad, sos)]
    reply = " ".join(words)
    print(f"loss: {res.history[0]['loss']:.3f} -> "
          f"{res.history[-1]['loss']:.3f} over {args.epochs} epochs")
    print(f"> {args.ask}")
    print(f"< {reply or '(silence)'}")
    return {"loss": res.history[-1]["loss"], "reply": reply}


if __name__ == "__main__":
    main()
