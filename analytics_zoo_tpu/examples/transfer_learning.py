"""Transfer-learning example (reference apps `dogs-vs-cats`,
`examples/nnframes/finetune` + `imageTransferLearning`): take a
pretrained-style backbone, cut the graph at a feature node
(`new_graph`), freeze everything up to it (`freeze_up_to`), attach a
fresh 2-class head, and fine-tune only the head.

Offline it trains the backbone briefly on synthetic "pets" first
(standing in for published weights); pass ``--weights`` to start from
a real save_weights file.
"""

from __future__ import annotations

import argparse

import numpy as np


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--weights", default=None)
    p.add_argument("--image-size", type=int, default=32)
    p.add_argument("--n", type=int, default=128)
    p.add_argument("--epochs", type=int, default=2)
    args = p.parse_args(argv)

    from analytics_zoo_tpu import init_nncontext
    from analytics_zoo_tpu.pipeline.api.keras.engine import Input
    from analytics_zoo_tpu.pipeline.api.keras.layers import (
        Convolution2D, Dense, Flatten, GlobalAveragePooling2D,
        MaxPooling2D)
    from analytics_zoo_tpu.pipeline.api.keras.models import Model

    init_nncontext()
    size = args.image_size
    rs = np.random.RandomState(0)

    # backbone graph with named nodes (the published-model stand-in)
    inp = Input((size, size, 3), name="image")
    c1 = Convolution2D(8, 3, border_mode="same", activation="relu",
                       name="conv1")(inp)
    p1 = MaxPooling2D(name="pool1")(c1)
    c2 = Convolution2D(16, 3, border_mode="same", activation="relu",
                       name="conv2")(p1)
    feat = GlobalAveragePooling2D(name="features")(c2)
    old_head = Dense(10, activation="softmax", name="old_head")(feat)
    backbone = Model(inp, old_head, name="backbone")
    backbone.compile(optimizer="adam",
                     loss="sparse_categorical_crossentropy")
    if args.weights:
        backbone.load_weights(args.weights)
    else:  # brief pretraining on a 10-class synthetic task
        x0 = rs.rand(args.n, size, size, 3).astype(np.float32)
        y0 = rs.randint(0, 10, (args.n, 1)).astype(np.int32)
        backbone.fit(x0, y0, batch_size=32, nb_epoch=1)

    # -- the transfer-learning surgery (NetUtils.scala:47-140 analog) --
    trunk = backbone.new_graph(["features"])
    trunk.freeze_up_to("features")
    frozen_feat = trunk.outputs[0] if isinstance(trunk.outputs, list) \
        else trunk.outputs
    new_out = Dense(2, activation="softmax", name="cats_dogs")(
        frozen_feat)
    tuned = Model(trunk.inputs, new_out, name="tuned")
    tuned.compile(optimizer="adam",
                  loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
    tuned.copy_weights_from(backbone)  # by layer name

    # separable synthetic cats-vs-dogs: class shifts the channel mix
    y = rs.randint(0, 2, (args.n, 1)).astype(np.int32)
    x = rs.rand(args.n, size, size, 3).astype(np.float32)
    x[:, :, :, 0] += 0.8 * y.reshape(-1, 1, 1)
    before = np.asarray(
        backbone.estimator.params["conv1"]["kernel"])
    tuned.fit(x, y, batch_size=32, nb_epoch=args.epochs)
    after = np.asarray(tuned.estimator.params["conv1"]["kernel"])
    assert np.array_equal(before, after), "frozen conv1 must not move"
    metrics = tuned.evaluate(x, y, batch_size=32)
    print(f"transfer_learning: frozen-backbone fine-tune metrics "
          f"{metrics}")
    return metrics


if __name__ == "__main__":
    main()
