"""Performance accounting helpers (FLOPs audit, executed-vs-model
ratios, the live goodput/MFU ledger) shared by bench.py,
scripts/flops_audit.py, the Estimator train loop and tests."""

from analytics_zoo_tpu.perf import autotune, flops, goodput

__all__ = ["autotune", "flops", "goodput"]
