"""Performance accounting helpers (FLOPs audit, executed-vs-model
ratios) shared by bench.py, scripts/flops_audit.py and tests."""
