"""Executed-semantics FLOP accounting over HLO text.

Why not XLA's HloCostAnalysis: its conv handler DISCOUNTS window
positions that read padding or dilation-inserted zeros, so an
input-dilated backward conv (jax's transpose rule for a strided
conv's dx) is costed as if the hardware skipped the zeros. A
systolic conv unit does not skip them — it executes
``out_elems x window_taps x Cin`` MACs regardless of what the taps
read. That gap is exactly the executed-FLOPs excess PERF.md round 6
pinned (~1.95x model on ResNet-50), and it is invisible to
`cost_analysis()`; these counters make it visible so the
phase-decomposition lever (ops.conv_grad) is measurable on CPU.

Counting rules (MXU ops only — vector/elementwise work is excluded,
which understates absolute FLOPs but leaves conv/dot ratios exact):

- ``convolution``: 2 x out_elems x effective_window_taps x kernel
  input-feature extent. Dilation zeros are EXECUTED, not skipped,
  on both sides: `lhs_dilate` inflates out_elems (a dilated dx
  produces the FULL-resolution gradient with the full kernel at
  every position — the s^2 waste), and `rhs_dilate` inflates the
  effective window to (size-1)*d+1 per dim (a dilated dw slides
  the full dilated footprint — the waste phase_dw eliminates).
- ``dot``: 2 x out_elems x prod(lhs contracting extents).

FLOPs here are 2 x MACs (one multiply + one add). Beware the
torchvision/fvcore "GFLOPs" convention, which counts MACs:
ResNet-50's canonical 4.09e9 is MACs, i.e. 8.18e9 in this unit.

Parses both post-optimization HLO (``compiled.as_text()``) and
pre-optimization HLO (``lowered.compiler_ir(dialect="hlo")``), which
share the op syntax.
"""

from __future__ import annotations

import re
from typing import List, NamedTuple


class OpCost(NamedTuple):
    name: str
    kind: str        # "convolution" | "dot"
    flops: float
    detail: str      # shapes/window snippet for the audit printout


_DEF = re.compile(
    r"^\s*(?:ROOT )?%?([\w.\-]+) = [a-z0-9]+\[([0-9,]*)\]",
    re.M)
_CONV = re.compile(
    r"%?([\w.\-]+) = \S+?\[([0-9,]*)\][^=\n]*? convolution\((.*?)\)"
    r"(.*)")
_DOT = re.compile(
    r"%?([\w.\-]+) = \S+?\[([0-9,]*)\][^=\n]*? dot\((.*?)\), (.*)")


def _prod(dims: str) -> int:
    out = 1
    for d in dims.split(","):
        if d:
            out *= int(d)
    return out


def _split_operands(args: str) -> List[str]:
    """Split an operand list on top-level commas only (shape dims
    and layouts contain commas: ``f32[2,28,28,128]{3,2,1,0} %a``)."""
    out, depth, cur = [], 0, []
    for ch in args:
        if ch in "[{(":
            depth += 1
        elif ch in "]})":
            depth -= 1
        if ch == "," and depth == 0:
            out.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    if cur:
        out.append("".join(cur))
    return out


def _operand_dims(args: str, defs) -> List[str]:
    """Per-operand dims: inline type when present (optimized HLO
    prints ``f32[...]{...} %name``), else the operand name resolved
    through the module's definition lines (unoptimized HLO prints
    bare names)."""
    out = []
    for entry in _split_operands(args):
        entry = entry.strip()
        if not entry:
            continue
        m = re.match(r"[a-z0-9]+\[([0-9,]*)\]", entry)
        if m:
            out.append(m.group(1))
            continue
        name = entry.split()[-1].lstrip("%")
        out.append(defs.get(name, ""))
    return out


def parse_hlo_ops(text: str) -> List[OpCost]:
    """All convolution/dot ops in an HLO module text with their
    executed-semantics FLOPs (each op counted once, like
    HloCostAnalysis — a scan body's cost is one trip's)."""
    defs = {m.group(1): m.group(2) for m in _DEF.finditer(text)}
    ops = []
    for m in _CONV.finditer(text):
        name, out_dims, args, attrs = m.groups()
        taps = 1
        wm = re.search(r"window=\{[^}]*size=([0-9x]+)", attrs)
        rd = re.search(r"rhs_dilate=([0-9x]+)", attrs)
        if wm:
            sizes = [int(d) for d in wm.group(1).split("x")]
            dil = ([int(d) for d in rd.group(1).split("x")]
                   if rd else [1] * len(sizes))
            for s, d in zip(sizes, dil):
                taps *= (s - 1) * d + 1
        lm = re.search(r"dim_labels=(\S+?)(?:[,\s]|$)", attrs)
        kin = 1
        shapes = _operand_dims(args, defs)
        if lm and len(shapes) >= 2 and shapes[1]:
            rhs = lm.group(1).split("_", 1)[1].split("-", 1)[0]
            if "i" in rhs:
                kin = int(shapes[1].split(",")[rhs.index("i")])
        ops.append(OpCost(
            name, "convolution", 2.0 * _prod(out_dims) * taps * kin,
            f"out=[{out_dims}] taps={taps} kin={kin}"
            f"{' ' + attrs.strip(', ')[:60] if attrs else ''}"))
    for m in _DOT.finditer(text):
        name, out_dims, args, attrs = m.groups()
        shapes = _operand_dims(args, defs)
        contract = 1
        cm = re.search(r"lhs_contracting_dims=\{([0-9,]*)\}", attrs)
        if cm and shapes and shapes[0]:
            ldims = shapes[0].split(",")
            for d in cm.group(1).split(","):
                if d:
                    contract *= int(ldims[int(d)])
        ops.append(OpCost(
            name, "dot", 2.0 * _prod(out_dims) * contract,
            f"out=[{out_dims}] lhs=[{shapes[0] if shapes else ''}] "
            f"contract={contract}"))
    return ops


def executed_flops(text: str) -> float:
    """Total executed-semantics MXU FLOPs of an HLO module text."""
    return sum(op.flops for op in parse_hlo_ops(text))


def top_ops(text: str, n: int = 10) -> List[OpCost]:
    return sorted(parse_hlo_ops(text), key=lambda o: -o.flops)[:n]


class PadWaste(NamedTuple):
    name: str
    role: str        # "lhs_f" | "rhs_i" | "rhs_o"
    extent: int
    util: float      # extent / lane-padded extent


def channel_padding(text: str, lane: int = 128) -> List[PadWaste]:
    """Convolution feature extents that are not multiples of the TPU
    lane width: the MXU zero-pads features to ``lane``, so such an
    op executes ``extent/ceil_lane(extent)`` useful work on that
    axis (ResNet's 3-channel stem: 3/128). Feed this the
    ``*after_optimizations*`` module of an ``--xla_dump_to`` dump to
    see what the layout passes actually left padded."""
    defs = {m.group(1): m.group(2) for m in _DEF.finditer(text)}
    out = []
    for m in _CONV.finditer(text):
        name, _, args, attrs = m.groups()
        lm = re.search(r"dim_labels=(\S+?)(?:[,\s]|$)", attrs)
        if not lm:
            continue
        lhs_l, rest = lm.group(1).split("_", 1)
        rhs_l = rest.split("-", 1)[0]
        shapes = _operand_dims(args, defs)
        roles = []
        if "f" in lhs_l and shapes and shapes[0]:
            roles.append(
                ("lhs_f",
                 int(shapes[0].split(",")[lhs_l.index("f")])))
        if len(shapes) >= 2 and shapes[1]:
            rdims = shapes[1].split(",")
            for ch, role in (("i", "rhs_i"), ("o", "rhs_o")):
                if ch in rhs_l:
                    roles.append((role, int(rdims[rhs_l.index(ch)])))
        for role, ext in roles:
            if ext % lane:
                padded = -(-ext // lane) * lane
                out.append(PadWaste(name, role, ext, ext / padded))
    return out


def hlo_text(obj) -> str:
    """HLO text from a jax Lowered/Compiled (or a plain string).
    Compiled ``as_text()`` is already HLO; Lowered ``as_text()`` is
    StableHLO, so go through ``compiler_ir(dialect="hlo")`` — no
    backend compile needed."""
    if isinstance(obj, str):
        return obj
    ir = getattr(obj, "compiler_ir", None)
    if ir is not None:
        try:
            return ir(dialect="hlo").as_hlo_text()
        except Exception:
            pass
    txt = obj.as_text()
    if "HloModule" not in txt.split("\n", 1)[0]:
        raise ValueError("could not extract HLO text "
                         f"from {type(obj).__name__}")
    return txt
