"""Persistent kernel autotuner: sweep once, memoize to disk.

Every Pallas crossover in the tree used to be a hand-measured
constant — the `_pick_blocks` heuristics in ``ops/conv_bn.py`` and
``ops/flash_attention.py``, the dense-vs-flash gates in
``ops/attention.py``, the ``ZOO_TPU_CONV_BN_PALLAS_BWD`` backward
toggle. This module replaces those constants with a search-and-
memoize layer in the AutoTVM/Ansor mold: measured configs beat
analytic heuristics, and a persistent cache makes the search a
one-time cost.

Decisions are keyed by ``(op, shape-signature, dtype, device-kind)``
and resolved in strict precedence order (docs/autotune.md):

1. ``forced()`` — thread-local test/sweep pin;
2. **flag** — the op's legacy ``ZOO_TPU_*`` env flag, honored
   verbatim when set (``source="flag"``; the tuner is bypassed, so
   flags are overrides, not requirements);
3. **cache** — a previously swept winner from the JSON cache
   (``ZOO_TPU_AUTOTUNE_CACHE``, default
   ``~/.cache/zoo_tpu/autotune.json``);
4. **defaults** — the committed per-device table in
   ``perf/autotune_defaults/<device>.json`` (cold starts without
   sweep budget still get tuned configs);
5. **heuristic** — the op's analytic fallback (the pre-tuner
   constants, verbatim).

Sweeping is opt-in: ``ZOO_TPU_AUTOTUNE=1`` sweeps a bounded
candidate set on first sight of a key (compile time excluded via
``diagnostics.expected_compiles()``), ``2`` force-resweeps each key
once per process, unset/``0`` never times anything. Sweeps never run
inside an active jax trace (``jax.core.trace_state_clean``) — a
decision needed mid-trace falls back to cache/defaults/heuristic and
``make autotune`` populates the cache ahead of time at the bench
shapes. The heuristic config always competes in its own sweep and
wins ties within the noise margin, so a tuned pick is never slower
than the heuristic beyond noise *by construction*.

The steady-state hit path is one dict lookup — no locking; the lock
only guards sweep+persist. Persistence is atomic (tmp+rename) with a
versioned schema. Counters: ``zoo_tpu_autotune_hits_total`` /
``zoo_tpu_autotune_misses_total`` / ``zoo_tpu_autotune_sweeps_total``
plus an ``autotune/sweep`` span per sweep.

Op specs are registered by the ops modules themselves (so their
legacy env flags keep being *read* under ``ops/`` — the lint
``check_autotune_overrides`` gate cross-references those reads
against :data:`OVERRIDE_FLAGS` and docs/perf_flags.md in both
directions).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

__all__ = [
    "SCHEMA_VERSION", "OVERRIDE_FLAGS", "OpSpec", "AutotuneCache",
    "register", "registered_ops", "decide", "heuristic",
    "candidates", "forced", "get_cache", "reset_cache", "stats",
    "device_kind", "make_key", "sweep_enabled",
]

SCHEMA_VERSION = 1

# sweep budget: at most this many candidates timed per key, each
# best-of-SWEEP_REPS with the compile excluded; a non-heuristic
# winner must beat the heuristic by more than NOISE_MARGIN or the
# heuristic is kept (tuned is never slower than heuristic beyond
# noise, structurally)
SWEEP_MAX_CANDIDATES = 16
SWEEP_REPS = 3
NOISE_MARGIN = 0.02

# Every ZOO_TPU_* gate flag read under analytics_zoo_tpu/ops/, mapped
# to the autotuner op it overrides. A plain value means the op's spec
# consults the flag via ``flag_value`` (set -> tuner bypassed,
# source="flag"); an ``:pin`` suffix marks a flag that pins an
# implementation choice outside the tuner's sweep space (impl
# selectors, debug/kill switches) — registered here so the lint gate
# proves every ops/ gate is accounted for, in both directions.
# MUST stay a pure literal: scripts/lint.py ast.literal_eval's it.
OVERRIDE_FLAGS = {
    "ZOO_TPU_FLASH_MIN_T": "attn_crossover",
    "ZOO_TPU_DECODE_FLASH_MIN_T": "decode_crossover",
    "ZOO_TPU_CONV_BN_PALLAS_BWD": "conv_bn_bwd",
    "ZOO_TPU_ATTENTION": "attn_crossover:pin",
    "ZOO_TPU_FLASH_FORCE_INTERPRET": "attn_crossover:pin",
    "ZOO_TPU_FUSED_WIN": "conv_bn_blocks:pin",
    "ZOO_TPU_CONV3_BWD_F32": "conv_bn_bwd:pin",
    "ZOO_TPU_PHASE_BWD": "conv_phase_bwd:pin",
    "ZOO_TPU_MAXPOOL_MASK_BWD": "maxpool_bwd:pin",
}

_DEVICE_ALIASES = {
    "tpu-v5-lite": "v5e",
    "tpu-v5e": "v5e",
    "tpu-v5litepod": "v5e",
}


class OpSpec:
    """One tunable decision point.

    - ``heuristic(params) -> config``: the analytic pick (the
      pre-tuner constants, verbatim) — always a sweep candidate.
    - ``candidates(params) -> [config, ...]``: the bounded sweep
      space; must respect the op's own feasibility constraints
      (divisibility, dtype-aware VMEM caps).
    - ``flag_value(params) -> config | None``: the legacy env-flag
      override, or None when the flag is unset. Defined in the ops
      module so the env read stays under ``ops/``.
    - ``runner(params, config) -> callable | None``: builds a
      zero-arg blocking probe for timing, or None when this
      candidate cannot be timed here (e.g. interpreter budget
      off-chip) — the candidate is skipped.
    """

    __slots__ = ("name", "heuristic", "candidates", "flag_value",
                 "runner")

    def __init__(self, name: str,
                 heuristic: Callable[[dict], dict],
                 candidates: Optional[
                     Callable[[dict], List[dict]]] = None,
                 flag_value: Optional[
                     Callable[[dict], Optional[dict]]] = None,
                 runner: Optional[
                     Callable[[dict, dict],
                              Optional[Callable[[], Any]]]] = None):
        self.name = name
        self.heuristic = heuristic
        self.candidates = candidates
        self.flag_value = flag_value
        self.runner = runner


_SPECS: Dict[str, OpSpec] = {}
_tls = threading.local()
_device: Optional[str] = None


def register(spec: OpSpec) -> OpSpec:
    """Register (or replace) an op spec. Called at import time by the
    ops modules that own each decision point."""
    _SPECS[spec.name] = spec
    return spec


def registered_ops() -> List[str]:
    return sorted(_SPECS)


def heuristic(op: str, params: dict) -> dict:
    """The analytic pick for ``op`` at ``params`` (A/B baselines)."""
    return _SPECS[op].heuristic(dict(params))


def candidates(op: str, params: dict) -> List[dict]:
    """The bounded sweep space for ``op`` at ``params``, heuristic
    included and deduplicated (conformance tests iterate this)."""
    spec = _SPECS[op]
    out = [spec.heuristic(dict(params))]
    if spec.candidates is not None:
        for cfg in spec.candidates(dict(params)):
            if cfg not in out:
                out.append(cfg)
    return out[:SWEEP_MAX_CANDIDATES]


class forced:
    """Thread-locally pin ``op`` to ``config`` (highest precedence).

    The conformance tests and the sweep runners use this to route a
    specific candidate through the real call sites; re-entrant per
    op (inner pin wins)."""

    def __init__(self, op: str, config: dict):
        self.op = op
        self.config = config

    def __enter__(self):
        stack = getattr(_tls, "forced", None)
        if stack is None:
            stack = _tls.forced = {}
        stack.setdefault(self.op, []).append(self.config)
        return self

    def __exit__(self, *exc):
        _tls.forced[self.op].pop()
        if not _tls.forced[self.op]:
            del _tls.forced[self.op]
        return False


def sweep_enabled() -> int:
    """The ``ZOO_TPU_AUTOTUNE`` mode: 0 = never sweep (cache +
    defaults + heuristic only), 1 = sweep on first sight of a key,
    2 = force re-sweep each key once per process."""
    raw = os.environ.get("ZOO_TPU_AUTOTUNE", "0")
    try:
        return max(0, min(2, int(raw)))
    except ValueError:
        return 0


def device_kind() -> str:
    """Normalized device kind of the default backend (``cpu``,
    ``v5e``, ...) — the device component of every cache key."""
    global _device
    if _device is None:
        import jax
        d = jax.devices()[0]
        kind = (getattr(d, "device_kind", "") or d.platform or
                "unknown")
        kind = kind.strip().lower().replace(" ", "-")
        _device = _DEVICE_ALIASES.get(kind, kind)
    return _device


def make_key(op: str, params: dict, dtype: str, device: str) -> str:
    sig = ",".join(f"{k}={params[k]}" for k in sorted(params))
    return f"{op}|{sig}|{dtype}|{device}"


def _default_cache_path() -> str:
    env = os.environ.get("ZOO_TPU_AUTOTUNE_CACHE")
    if env:
        return env
    return os.path.join(os.path.expanduser("~"), ".cache",
                        "zoo_tpu", "autotune.json")


def _defaults_dir() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "autotune_defaults")


def _count(which: str):
    from analytics_zoo_tpu.common import observability as obs
    if which == "hit":
        obs.counter("zoo_tpu_autotune_hits_total",
                    help="autotune decisions served from the "
                         "cache/defaults tables").inc()
    elif which == "miss":
        obs.counter("zoo_tpu_autotune_misses_total",
                    help="autotune decisions with no cached entry "
                         "(heuristic served unless a sweep ran)").inc()
    else:
        obs.counter("zoo_tpu_autotune_sweeps_total",
                    help="candidate sweeps executed and "
                         "persisted").inc()


class AutotuneCache:
    """The persistent decision cache. One process-wide instance via
    :func:`get_cache`; tests construct their own against tmp paths.

    Hot path (:meth:`decide` on a warm key) is a single dict lookup
    with no locking; ``self._lock`` only serializes sweep+persist."""

    def __init__(self, path: Optional[str] = None,
                 device: Optional[str] = None):
        self.path = path or _default_cache_path()
        self.device = device or device_kind()
        self._entries: Dict[str, dict] = {}
        self._lock = threading.RLock()
        self._reswept: set = set()
        self.hits = 0
        self.misses = 0
        self.sweeps = 0
        self.sources: Dict[str, int] = {}
        self._load_defaults()
        self._load_disk()

    # -- loading --------------------------------------------------------

    def _load_file(self, path: str, source: str):
        try:
            with open(path, encoding="utf-8") as fh:
                d = json.load(fh)
        except (OSError, ValueError):
            return
        if not isinstance(d, dict) or \
                d.get("schema") != SCHEMA_VERSION:
            return
        entries = d.get("entries")
        if not isinstance(entries, dict):
            return
        for key, entry in entries.items():
            if not isinstance(entry, dict) or \
                    not isinstance(entry.get("config"), dict):
                continue
            e = dict(entry)
            e["source"] = source
            self._entries[key] = e

    def _load_defaults(self):
        self._load_file(
            os.path.join(_defaults_dir(), f"{self.device}.json"),
            "defaults")

    def _load_disk(self):
        self._load_file(self.path, "cache")

    # -- the decision ---------------------------------------------------

    def decide(self, op: str, params: dict,
               dtype: str = "any") -> dict:
        pinned = getattr(_tls, "forced", None)
        if pinned and op in pinned:
            self._note("forced")
            return pinned[op][-1]
        spec = _SPECS.get(op)
        if spec is not None and spec.flag_value is not None:
            cfg = spec.flag_value(dict(params))
            if cfg is not None:
                self._note("flag")
                return cfg
        key = make_key(op, params, dtype, self.device)
        mode = sweep_enabled()
        entry = self._entries.get(key)
        if entry is not None and not (
                mode == 2 and key not in self._reswept):
            self.hits += 1
            _count("hit")
            self._note(entry.get("source", "cache"))
            return entry["config"]
        self.misses += 1
        _count("miss")
        if spec is None:
            raise KeyError(f"unknown autotune op {op!r} and no "
                           f"cached entry for {key!r}")
        heur = spec.heuristic(dict(params))
        if (mode >= 1 and spec.runner is not None
                and not getattr(_tls, "in_sweep", False)
                and _trace_clean()):
            swept = self._sweep(spec, op, dict(params), dtype, key,
                                heur, force=(mode == 2))
            if swept is not None:
                return swept
        self._note("heuristic")
        return heur

    def _note(self, source: str):
        self.sources[source] = self.sources.get(source, 0) + 1

    # -- sweeping -------------------------------------------------------

    def _sweep(self, spec: OpSpec, op: str, params: dict,
               dtype: str, key: str, heur: dict,
               force: bool) -> Optional[dict]:
        from analytics_zoo_tpu.common import observability as obs
        with self._lock:
            self._reswept.add(key)
            entry = self._entries.get(key)
            if entry is not None and not force:
                # another thread swept the key while we waited
                self.hits += 1
                _count("hit")
                self._note(entry.get("source", "cache"))
                return entry["config"]
            cands = [heur]
            if spec.candidates is not None:
                for cfg in spec.candidates(params):
                    if cfg not in cands:
                        cands.append(cfg)
            cands = cands[:SWEEP_MAX_CANDIDATES]
            timed: List[dict] = []
            _tls.in_sweep = True
            try:
                with obs.span("autotune/sweep", op=op, key=key):
                    for cfg in cands:
                        ms = self._time_candidate(spec, params, cfg)
                        if ms is not None:
                            timed.append({"config": cfg, "ms": ms})
            finally:
                _tls.in_sweep = False
            if not timed:
                return None    # nothing measurable here (no probe)
            heur_ms = next((t["ms"] for t in timed
                            if t["config"] == heur), None)
            best = min(timed, key=lambda t: t["ms"])
            if heur_ms is not None and \
                    best["ms"] >= heur_ms * (1.0 - NOISE_MARGIN):
                best = {"config": heur, "ms": heur_ms}
            entry = {
                "op": op, "params": params, "dtype": dtype,
                "config": best["config"], "ms": round(best["ms"], 4),
                "heuristic_ms": (None if heur_ms is None
                                 else round(heur_ms, 4)),
                "candidates": len(timed), "source": "sweep",
            }
            self._entries[key] = entry
            self.sweeps += 1
            _count("sweep")
            self._persist()
            self._note("sweep")
            return entry["config"]

    def _time_candidate(self, spec: OpSpec, params: dict,
                        cfg: dict) -> Optional[float]:
        """Best-of-``SWEEP_REPS`` wall ms of the spec's probe, with
        the compile excluded (the warm-up call runs inside an
        ``expected_compiles`` bracket so deliberate sweep compiles
        never read as a recompile storm)."""
        from analytics_zoo_tpu.common import diagnostics
        try:
            fn = spec.runner(params, cfg)
            if fn is None:
                return None
            with diagnostics.expected_compiles():
                fn()                       # compile + warm
            best = float("inf")
            for _ in range(SWEEP_REPS):
                t0 = time.perf_counter()
                fn()
                best = min(best, time.perf_counter() - t0)
            return best * 1e3
        except Exception:
            return None        # infeasible candidate: skip, not fatal

    # -- persistence ----------------------------------------------------

    def _persist(self):
        """Merge this cache's swept entries into the on-disk file,
        atomically (tmp+rename). Called with ``self._lock`` held.
        Only ``source == "sweep"`` entries are persisted — defaults
        stay in their committed table."""
        disk: Dict[str, dict] = {}
        try:
            with open(self.path, encoding="utf-8") as fh:
                d = json.load(fh)
            if isinstance(d, dict) and \
                    d.get("schema") == SCHEMA_VERSION and \
                    isinstance(d.get("entries"), dict):
                disk = d["entries"]
        except (OSError, ValueError):
            pass
        for key, entry in self._entries.items():
            if entry.get("source") == "sweep":
                out = dict(entry)
                out["device"] = self.device
                disk[key] = out
        payload = {"schema": SCHEMA_VERSION, "entries": disk}
        try:
            os.makedirs(os.path.dirname(self.path) or ".",
                        exist_ok=True)
            tmp = f"{self.path}.tmp.{os.getpid()}"
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump(payload, fh, indent=1, sort_keys=True)
            os.replace(tmp, self.path)
        except OSError:
            pass               # read-only FS: the cache stays warm
                               # in-process, just not persistent

    # -- introspection --------------------------------------------------

    def entries(self) -> Dict[str, dict]:
        return dict(self._entries)

    def stats(self) -> dict:
        """Bench-provenance block: ``{enabled, cache_hits,
        cache_misses, sweeps, source}`` where ``source`` is the
        dominant decision source so far (``none`` before any)."""
        src = max(self.sources, key=self.sources.get) \
            if self.sources else "none"
        return {"enabled": sweep_enabled() >= 1,
                "cache_hits": self.hits,
                "cache_misses": self.misses,
                "sweeps": self.sweeps,
                "source": src}


def _trace_clean() -> bool:
    import jax
    try:
        return bool(jax.core.trace_state_clean())
    except AttributeError:
        return False


_cache: Optional[AutotuneCache] = None
_cache_lock = threading.Lock()


def get_cache() -> AutotuneCache:
    """The process-wide cache (constructed on first use, so the env
    and backend are settled by then)."""
    global _cache
    c = _cache
    if c is None:
        with _cache_lock:
            c = _cache
            if c is None:
                c = _cache = AutotuneCache()
    return c


def reset_cache():
    """Forget the singleton (tests repoint ``ZOO_TPU_AUTOTUNE_CACHE``
    and call this; the next decide() rebuilds from disk)."""
    global _cache
    with _cache_lock:
        _cache = None


def decide(op: str, params: dict, dtype: str = "any") -> dict:
    """Resolve one tuned decision — the single entry point every
    wired call site uses. See the module docstring for precedence."""
    return get_cache().decide(op, params, dtype)


def stats() -> dict:
    """Provenance of the process-wide cache (bench artifacts embed
    this under ``"autotune"``)."""
    return get_cache().stats()
