"""Live goodput / MFU ledger for the training loop.

PERF.md's MFU numbers were hand-computed after each bench round —
and went dark when rounds 3–5 lost chip access. This module makes
the roofline chase (ROADMAP item 5, 0.45 MFU) a *live* signal
instead: every Estimator step feeds a :class:`GoodputLedger`, which
maintains

- ``zoo_tpu_mfu`` — executed-semantics FLOPs per step (from
  :mod:`analytics_zoo_tpu.perf.flops`, the same counter behind
  ``make flops-audit``) ÷ step wall time ÷ the device-kind peak from
  :data:`PEAK_FLOPS_BY_DEVICE_KIND` (``ZOO_TPU_PEAK_TFLOPS``
  overrides);
- ``zoo_tpu_goodput_ratio`` — the share of step wall time spent in
  compute, where wall time decomposes into
  compute / data-wait / dispatch / checkpoint using the PR 5
  step-trace fields (compute is the residual, so the shares sum to
  1.0 by construction);
- ``zoo_tpu_goodput_share{component}`` — the full decomposition
  (the ``data_wait`` share also feeds the shipped training SLO in
  :mod:`analytics_zoo_tpu.common.slo`).

Per-epoch summaries (:meth:`GoodputLedger.epoch_summary`) land in the
Estimator's training history and — via
``bench_common.attach_metrics_snapshot`` — in every bench artifact,
so the perf trajectory stays measurable even on CPU fallback.

``ZOO_TPU_GOODPUT=0`` disables the ledger entirely;
``ZOO_TPU_GOODPUT_FLOPS=0`` skips the one-off train-step lowering
used to count FLOPs (the decomposition gauges stay live, MFU reads
0). jax is never imported at module scope — the peak-FLOPs lookup
takes a device-kind string.
"""

from __future__ import annotations

import os
import threading
from collections import deque
from typing import Any, Dict, Optional

from analytics_zoo_tpu.common import observability as obs

__all__ = [
    "GoodputLedger",
    "PEAK_FLOPS_BY_DEVICE_KIND",
    "COMPONENTS",
    "resolve_peak_flops",
    "ledger_for_backend",
    "recent_summaries",
    "reset_goodput",
    "enabled",
    "flops_enabled",
]

# Per-chip dense peak FLOP/s at the dtype the train step actually
# runs (bf16 on TPU). Matched by lowercase substring against
# ``jax.devices()[0].device_kind``; first hit wins, most specific
# first. The CPU entry is a deliberately honest single-core figure so
# fallback MFU numbers stay comparable round-over-round rather than
# flattering.
PEAK_FLOPS_BY_DEVICE_KIND = (
    ("v5p", 459e12),
    ("v5e", 197e12),
    ("v5 lite", 197e12),
    ("v6e", 918e12),
    ("v6 lite", 918e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
    ("cpu", 1e11),
)

# Wall-time decomposition components; "compute" is the residual so
# the shares always sum to 1.0.
COMPONENTS = ("compute", "data_wait", "dispatch", "checkpoint")

_DEFAULT_PEAK = 197e12  # unrecognized accelerator: assume v5e


def enabled() -> bool:
    return os.environ.get("ZOO_TPU_GOODPUT", "1") != "0"


def flops_enabled() -> bool:
    """Gate for the one-off ``train_step.lower()`` retrace used to
    count executed FLOPs (skippable for huge models)."""
    return os.environ.get("ZOO_TPU_GOODPUT_FLOPS", "1") != "0"


def resolve_peak_flops(device_kind: str,
                       platform: str = "") -> float:
    """Peak FLOP/s for a device-kind string.
    ``ZOO_TPU_PEAK_TFLOPS`` (the same knob bench.py uses for its MFU
    denominator) overrides the table."""
    raw = os.environ.get("ZOO_TPU_PEAK_TFLOPS")
    if raw:
        try:
            return float(raw) * 1e12
        except ValueError:
            pass
    kind = (device_kind or "").lower()
    for sub, peak in PEAK_FLOPS_BY_DEVICE_KIND:
        if sub in kind:
            return peak
    if (platform or "").lower() == "cpu":
        return dict(PEAK_FLOPS_BY_DEVICE_KIND)["cpu"]
    return _DEFAULT_PEAK


class GoodputLedger:
    """Accumulates per-step wall-time decomposition + FLOPs into live
    gauges and per-epoch summaries. Thread-safe (the train loop owns
    it, but `/debug` surfaces may read concurrently)."""

    def __init__(self, peak_flops: Optional[float] = None,
                 device_kind: str = "", platform: str = "",
                 n_devices: int = 1,
                 registry: "Optional[obs.MetricsRegistry]" = None):
        if peak_flops is None:
            peak_flops = resolve_peak_flops(device_kind, platform)
        self.peak_flops = float(peak_flops) * max(1, int(n_devices))
        self.device_kind = device_kind
        self.flops_per_step: Optional[float] = None
        self._lock = threading.Lock()
        self._registry = registry or obs.get_registry()
        self._reset_epoch_locked()

    def _reset_epoch_locked(self):
        self._steps = 0
        self._wall_s = 0.0
        self._parts = {c: 0.0 for c in COMPONENTS}

    def set_flops_per_step(self, flops: Optional[float]):
        with self._lock:
            self.flops_per_step = (
                float(flops) if flops else None)

    def note_step(self, wall_s: float, data_wait_s: float = 0.0,
                  dispatch_s: float = 0.0,
                  checkpoint_s: float = 0.0) -> dict:
        """Feed one step's wall time and its measured non-compute
        components (each clamped into the wall); compute is the
        residual. Updates the live gauges and returns this step's
        decomposition."""
        wall_s = max(float(wall_s), 1e-9)
        parts = {"data_wait": max(float(data_wait_s), 0.0),
                 "dispatch": max(float(dispatch_s), 0.0),
                 "checkpoint": max(float(checkpoint_s), 0.0)}
        overhead = sum(parts.values())
        if overhead > wall_s:  # measurement skew: scale into the wall
            scale = wall_s / overhead
            parts = {k: v * scale for k, v in parts.items()}
            overhead = wall_s
        parts["compute"] = wall_s - overhead
        with self._lock:
            self._steps += 1
            self._wall_s += wall_s
            for k, v in parts.items():
                self._parts[k] += v
            flops = self.flops_per_step
        goodput = parts["compute"] / wall_s
        mfu = ((flops / wall_s) / self.peak_flops
               if flops and self.peak_flops > 0 else 0.0)
        reg = self._registry
        reg.gauge("zoo_tpu_mfu",
                  help="model FLOPs utilization of the last train "
                       "step (executed FLOPs / wall / peak)"
                  ).set(mfu)
        reg.gauge("zoo_tpu_goodput_ratio",
                  help="compute share of the last train step's wall "
                       "time").set(goodput)
        for comp in COMPONENTS:
            reg.gauge("zoo_tpu_goodput_share",
                      help="train-step wall-time decomposition "
                           "(shares sum to 1)",
                      labels={"component": comp}
                      ).set(parts[comp] / wall_s)
        return {k: parts[k] / wall_s for k in COMPONENTS}

    def epoch_summary(self, epoch: Optional[int] = None,
                      reset: bool = True) -> Optional[dict]:
        """Aggregate decomposition for the epoch so far (None when no
        steps landed): per-component seconds + shares (summing to
        ~1.0), mean MFU, and goodput ratio. Emitted as a
        ``perf/goodput_epoch`` event, appended to the module summary
        ring (bench artifacts attach it), and — by default — the
        epoch accumulators reset."""
        with self._lock:
            if self._steps == 0:
                return None
            steps, wall = self._steps, self._wall_s
            parts = dict(self._parts)
            flops = self.flops_per_step
            if reset:
                self._reset_epoch_locked()
        shares = {k: v / wall for k, v in parts.items()}
        mfu = ((flops * steps / wall) / self.peak_flops
               if flops and self.peak_flops > 0 and wall > 0
               else 0.0)
        summary: "Dict[str, Any]" = {
            "epoch": epoch,
            "steps": steps,
            "wall_s": round(wall, 6),
            "seconds": {k: round(v, 6) for k, v in parts.items()},
            "shares": {k: round(v, 6) for k, v in shares.items()},
            "goodput_ratio": round(shares["compute"], 6),
            # significant figures, not decimal places: a toy CPU fit
            # has an MFU of ~1e-9 and must not summarize as 0.0
            "mfu": float(f"{mfu:.6g}"),
            "flops_per_step": flops,
            "peak_flops": self.peak_flops,
            "device_kind": self.device_kind,
        }
        obs.event("perf/goodput_epoch", **summary)
        with _summaries_lock:
            _summaries.append(summary)
        return summary


# Recent epoch summaries, process-wide: bench_common attaches these
# to every artifact so CPU-fallback rounds still carry a goodput
# trajectory.
_summaries_lock = threading.Lock()
_summaries: "deque" = deque(maxlen=32)


def recent_summaries() -> "list[dict]":
    with _summaries_lock:
        return list(_summaries)


def reset_goodput():
    """Clear the process-global summary ring (test isolation)."""
    with _summaries_lock:
        _summaries.clear()


def ledger_for_backend(
        registry: "Optional[obs.MetricsRegistry]" = None
) -> Optional[GoodputLedger]:
    """A ledger sized for the current jax backend (device kind, peak
    FLOPs, local device count); None when ``ZOO_TPU_GOODPUT=0`` or
    jax is unavailable."""
    if not enabled():
        return None
    try:
        import jax
        dev = jax.local_devices()[0]
        kind = getattr(dev, "device_kind", "") or ""
        platform = getattr(dev, "platform", "") or ""
        n = jax.local_device_count()
    except Exception:
        return None
    return GoodputLedger(device_kind=kind, platform=platform,
                         n_devices=n, registry=registry)
