"""Mask-based backward for 2-D max pooling.

PERF.md carries the stem maxpool backward as an open small lever
(~1.5% of the ResNet step): jax differentiates `reduce_window(max)`
through XLA's `select_and_scatter`, a sequential window scan that
lowers poorly on TPU. The backward here is dense vector work
instead: re-extract the k^2 strided window patches of the (padded)
input, mask each against the pooled output (``patch == y``), and
distribute the cotangent by mask / tie-count — k^2 compares, one
count, k^2 pad-shifted adds, all trivially fusable element-wise HLO.

Tie semantics differ from XLA on purpose: `select_and_scatter`
routes the whole cotangent to the FIRST max in scan order; the mask
backward splits it EQUALLY among tied maxima (count-normalized), a
valid subgradient either way (ties have measure zero under
continuous inputs; tests pin the split behaviour explicitly).

``ZOO_TPU_MAXPOOL_MASK_BWD=0`` reverts to jax's select_and_scatter
backward (read at trace time).
"""

from __future__ import annotations

import functools
import os
from typing import Tuple

import jax
import jax.numpy as jnp

from analytics_zoo_tpu.ops.conv_grad import normalize_padding

# test observability, like ops.conv_grad.invocations
invocations = {"fwd": 0, "bwd_mask": 0}


def mask_bwd_enabled() -> bool:
    """Whether MaxPooling2D routes through the mask backward
    (default on; ``ZOO_TPU_MAXPOOL_MASK_BWD=0`` reverts to the
    select_and_scatter transpose rule)."""
    return os.environ.get("ZOO_TPU_MAXPOOL_MASK_BWD") != "0"


def _reduce_max(x, window, strides, pads4):
    init = jnp.array(-jnp.inf, x.dtype)
    return jax.lax.reduce_window(
        x, init, jax.lax.max,
        (1,) + window + (1,), (1,) + strides + (1,), pads4)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
def _maxpool2d(x, window, strides, pads):
    return _reduce_max(x, window, strides, ((0, 0),) + pads +
                       ((0, 0),))


def _maxpool2d_fwd(x, window, strides, pads):
    y = _maxpool2d(x, window, strides, pads)
    return y, (x, y)


def _maxpool2d_bwd(window, strides, pads, res, g):
    x, y = res
    invocations["bwd_mask"] += 1
    kh, kw = window
    sh, sw = strides
    (lo_h, hi_h), (lo_w, hi_w) = pads
    n, hx, wx, c = x.shape
    ho, wo = y.shape[1], y.shape[2]
    ht, wt = hx + lo_h + hi_h, wx + lo_w + hi_w
    f32 = jnp.float32

    # -inf padding never ties with a window max (every SAME window
    # overlaps at least one real element)
    xt = jnp.pad(x, ((0, 0), (lo_h, hi_h), (lo_w, hi_w), (0, 0)),
                 constant_values=-jnp.inf)

    # strided window patches: patch[kh,kw][p, q] = xt[s*p+kh, s*q+kw]
    masks = []
    for dh in range(kh):
        for dw in range(kw):
            patch = jax.lax.slice(
                xt, (0, dh, dw, 0),
                (n, dh + (ho - 1) * sh + 1, dw + (wo - 1) * sw + 1,
                 c),
                (1, sh, sw, 1))
            masks.append((patch == y).astype(f32))
    count = sum(masks)                  # >= 1: the max is in-window
    gn = g.astype(f32) / count          # equal split among ties

    # scatter-back built from pure pads (no scatter op): zero-
    # interleave each contribution to stride spacing, shift by the
    # window offset (lax.pad accepts the negative high pads where
    # the window overhangs), and sum
    dxt = jnp.zeros((n, ht, wt, c), f32)
    i = 0
    for dh in range(kh):
        for dw in range(kw):
            v = masks[i] * gn
            i += 1
            v6 = v[:, :, None, :, None, :]
            v6 = jnp.pad(v6, ((0, 0), (0, 0), (0, sh - 1), (0, 0),
                              (0, sw - 1), (0, 0)))
            vz = v6.reshape(n, ho * sh, wo * sw, c)
            dxt = dxt + jax.lax.pad(
                vz, jnp.array(0.0, f32),
                ((0, 0, 0), (dh, ht - ho * sh - dh, 0),
                 (dw, wt - wo * sw - dw, 0), (0, 0, 0)))
    dx = dxt[:, lo_h:lo_h + hx, lo_w:lo_w + wx, :]
    return (dx.astype(x.dtype),)


_maxpool2d.defvjp(_maxpool2d_fwd, _maxpool2d_bwd)


def maxpool2d(x: jnp.ndarray, pool_size: Tuple[int, int],
              strides: Tuple[int, int], padding) -> jnp.ndarray:
    """NHWC 2-D max pool whose backward is the mask/count
    distribution above instead of `select_and_scatter`. Forward is
    the identical `lax.reduce_window` the plain path emits; float
    dtypes only (the -inf padding and tie-count need them)."""
    window = tuple(int(p) for p in pool_size)
    strides = tuple(int(s) for s in strides)
    pads = normalize_padding(padding, x.shape[1:3], window, strides)
    invocations["fwd"] += 1
    return _maxpool2d(x, window, strides, pads)
