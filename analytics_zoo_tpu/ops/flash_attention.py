"""Pallas TPU flash-attention kernel.

The native-kernel tier for the attention hot path (SURVEY.md §2.11:
the reference's per-layer perf tier is MKL/MKL-DNN JNI kernels, e.g.
`TransformerLayer.scala`/`BERT.scala` bottoming out in BigDL MKL; the
TPU analog is XLA + Pallas). XLA already fuses the dense O(T²)
attention well, but it materialises the (B, H, Tq, Tk) logits in HBM;
this kernel keeps the running softmax statistics in VMEM so HBM
traffic stays O(T·D) — the flash-attention recipe tiled for the MXU
(128-lane blocks, f32 accumulators, bf16 matmul inputs).

Forward and backward are both Pallas kernels: the backward follows
the FlashAttention-2 recipe — the forward saves only the per-row
logsumexp, and two kernels (dk/dv over q-blocks, dq over k-blocks)
recompute the probabilities blockwise in VMEM — so gradient memory
stays O(T·D) too (measured: 3.72x over XLA dense fwd+bwd at T=4096
bf16, and grads at T=8192 where dense OOMs; `parallel.ring_attention`
owns the sharded longer-T regime).

On non-TPU backends the same kernel runs under `interpret=True`
(numerics identical, speed irrelevant) so the CPU test mesh exercises
the exact kernel code path.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from analytics_zoo_tpu.perf import autotune

# jax ≥0.5 renamed TPUCompilerParams → CompilerParams; bind whichever
# this jax ships so the kernels compile on both sides of the rename
_CompilerParams = getattr(pltpu, "CompilerParams",
                          getattr(pltpu, "TPUCompilerParams", None))

_NEG_INF = -1e30

# Incremented (at trace time) on every flash_attention /
# flash_block_partial entry, so tests can assert that a given API
# call actually routed to the Pallas kernel.
invocations = 0


def _apply_causal_mask(s, qi, ki, off, block_q, block_k,
                       fill=_NEG_INF):
    """End-aligned causal mask (query i sees keys <= i + off) on one
    (block_q, block_k) tile — the single copy of the masking rule,
    shared by forward and backward (`fill=0.0` masks gradient tiles
    the way the dense reference's `where` cuts grads at masked
    positions)."""
    q_pos = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    k_pos = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    return jnp.where(q_pos + off >= k_pos, s, fill)


def _attn_body(off, q_ref, k_ref, v_ref, acc_ref, m_ref, l_ref, *,
               scale: float, causal: bool, block_q: int, block_k: int,
               kmask_ref=None):
    """Shared init + blockwise-softmax accumulation for one
    (batch, head, q-block, k-block) grid step — the single copy of the
    flash recursion used by both `_fwd_kernel` and `_block_kernel`
    (they differ only in how `off` is sourced and what the last k step
    writes).

    `off`: causal offset (int, static or traced) — end-aligned like
    the dense reference's tril(k=Tk-Tq): query i sees keys <= i + off.
    `kmask_ref`: optional key-validity block ref, (1, 8, block_k) f32
    0/1 replicated over the sublane dim (TPU tiling needs the
    second-to-last block dim divisible by 8) — keys with 0 are masked
    for every query row (the BERT padding-mask shape (B, 1, 1, Tk)).

    Scratch (VMEM, persistent across the innermost `k` grid dim):
      acc_ref (block_q, D) f32   un-normalised output accumulator
      m_ref   (block_q, 128) f32 running row max (lanes replicated)
      l_ref   (block_q, 128) f32 running softmax denominator
    """
    ki = pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    qi = pl.program_id(2)
    # the whole k-block is masked iff its first key position exceeds
    # the q-block's last query position — skip it entirely
    run = (ki * block_k <=
           qi * block_q + (block_q - 1) + off) if causal else (ki >= 0)

    @pl.when(run)
    def _step():
        q = q_ref[0, 0]                      # (block_q, D)
        k = k_ref[0, 0]                      # (block_k, D)
        v = v_ref[0, 0]                      # (block_k, D)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            s = _apply_causal_mask(s, qi, ki, off, block_q, block_k)
        if kmask_ref is not None:
            s = jnp.where(kmask_ref[0][:1, :] > 0, s, _NEG_INF)

        m_prev = m_ref[:, :1]                # (block_q, 1)
        l_prev = l_ref[:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)      # rescale old accumulator
        p = jnp.exp(s - m_new)               # (block_q, block_k) f32
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        acc_ref[:] = acc_ref[:] * alpha + pv
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)


def _fwd_finalize(o_ref, acc_ref, l_ref):
    @pl.when(pl.program_id(3) == pl.num_programs(3) - 1)
    def _final():
        l = l_ref[:, :1]
        o_ref[0, 0] = (acc_ref[:] /
                       jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref, *,
                scale: float, causal: bool, block_q: int, block_k: int,
                causal_offset: int):
    """Self-contained flash forward: normalised output, static offset."""
    _attn_body(causal_offset, q_ref, k_ref, v_ref, acc_ref, m_ref,
               l_ref, scale=scale, causal=causal, block_q=block_q,
               block_k=block_k)
    _fwd_finalize(o_ref, acc_ref, l_ref)


def _fwd_kernel_masked(q_ref, k_ref, v_ref, km_ref, o_ref,
                       acc_ref, m_ref, l_ref, *,
                       scale: float, causal: bool, block_q: int,
                       block_k: int, causal_offset: int):
    """`_fwd_kernel` + key-validity mask input."""
    _attn_body(causal_offset, q_ref, k_ref, v_ref, acc_ref, m_ref,
               l_ref, scale=scale, causal=causal, block_q=block_q,
               block_k=block_k, kmask_ref=km_ref)
    _fwd_finalize(o_ref, acc_ref, l_ref)


def _kmask8(key_mask, tk):
    """(B, Tk) 0/1 → (B, 8, Tk) f32, sublane-replicated for tiling."""
    km = jnp.asarray(key_mask).astype(jnp.float32)
    return jnp.broadcast_to(km[:, None, :], (km.shape[0], 8, tk))


def _flash_fwd(q, k, v, scale, causal, block_q, block_k, interpret,
               key_mask=None):
    """q,k,v: (B, H, T, D) — head-major layout for contiguous blocks.
    `key_mask`: optional (B, Tk) 0/1 key-validity mask."""
    b, h, tq, d = q.shape
    tk = k.shape[2]
    nq, nk = tq // block_q, tk // block_k
    cfg = dict(scale=scale, causal=causal, block_q=block_q,
               block_k=block_k, causal_offset=tk - tq)
    blk = lambda bs, im: pl.BlockSpec((1, 1, bs, d), im)
    in_specs = [
        blk(block_q, lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
        blk(block_k, lambda bi, hi, qi, ki: (bi, hi, ki, 0)),
        blk(block_k, lambda bi, hi, qi, ki: (bi, hi, ki, 0)),
    ]
    args = [q, k, v]
    if key_mask is None:
        kernel = functools.partial(_fwd_kernel, **cfg)
    else:
        kernel = functools.partial(_fwd_kernel_masked, **cfg)
        in_specs.append(pl.BlockSpec(
            (1, 8, block_k), lambda bi, hi, qi, ki: (bi, 0, ki)))
        args.append(_kmask8(key_mask, tk))
    return pl.pallas_call(
        kernel,
        grid=(b, h, nq, nk),
        in_specs=in_specs,
        out_specs=blk(block_q, lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b, h, tq, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(*args)


def _recompute_p(q_blk, k_blk, m_col, l_col, qi, ki, off, scale,
                 causal, block_q, block_k, km_ref=None):
    """Recompute the softmax probabilities of one (q-block, k-block)
    tile from the saved row statistics — shared by both backward
    kernels. p = exp(s - m)/l, NOT exp(s - (m + log l)): the fused
    logsumexp catastrophically absorbs log(l) when m = -1e30
    (fully-masked causal rows), yielding p = 1 per key instead of the
    forward's uniform 1/l and overscaling those rows' gradients by Tk.
    `m_col`, `l_col`: (block_q, 1) f32."""
    s = jax.lax.dot_general(
        q_blk, k_blk, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale
    if causal:
        s = _apply_causal_mask(s, qi, ki, off, block_q, block_k)
    if km_ref is not None:
        s = jnp.where(km_ref[0][:1, :] > 0, s, _NEG_INF)
    return jnp.exp(s - m_col) / jnp.maximum(l_col, 1e-30)


def _mask_ds(ds, qi, ki, off, causal, block_q, block_k, km_ref):
    """Zero ds at masked positions: the dense reference's where-mask
    passes no gradient there; fully-masked rows have NONZERO uniform p
    (it feeds dv like the dense path) but must not leak into dq/dk."""
    if causal:
        ds = _apply_causal_mask(ds, qi, ki, off, block_q, block_k,
                                fill=0.0)
    if km_ref is not None:
        ds = jnp.where(km_ref[0][:1, :] > 0, ds, 0.0)
    return ds


def _bwd_dkdv_impl(q_ref, k_ref, v_ref, do_ref, m_in_ref, l_in_ref,
                   delta_ref, dk_ref, dv_ref, dk_acc, dv_acc, *,
                   scale: float, causal: bool, block_q: int,
                   block_k: int, causal_offset: int, km_ref=None):
    """Grid (B, H, nk, nq): each k-block accumulates dk/dv over all
    q-blocks. delta = rowsum(do ⊙ o) (precomputed outside)."""
    qi = pl.program_id(3)
    nq = pl.num_programs(3)
    ki = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    run = (qi * block_q + (block_q - 1) + causal_offset >=
           ki * block_k) if causal else (qi >= 0)

    @pl.when(run)
    def _step():
        q = q_ref[0, 0]                      # (block_q, D)
        k = k_ref[0, 0]                      # (block_k, D)
        v = v_ref[0, 0]
        do = do_ref[0, 0]                    # (block_q, D)
        p = _recompute_p(q, k, m_in_ref[0, 0][:, :1],
                         l_in_ref[0, 0][:, :1], qi, ki,
                         causal_offset, scale, causal, block_q,
                         block_k, km_ref=km_ref)
        # dv += pᵀ·do ; dp = do·vᵀ ; ds = p⊙(dp − Δ)·scale ; dk += dsᵀ·q
        dv_acc[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0, 0][:, :1]) * scale
        ds = _mask_ds(ds, qi, ki, causal_offset, causal, block_q,
                      block_k, km_ref)
        dk_acc[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qi == nq - 1)
    def _final():
        dk_ref[0, 0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[:].astype(dv_ref.dtype)


def _bwd_dkdv_kernel(q_ref, k_ref, v_ref, do_ref, m_in_ref, l_in_ref,
                     delta_ref, dk_ref, dv_ref, dk_acc, dv_acc,
                     **cfg):
    _bwd_dkdv_impl(q_ref, k_ref, v_ref, do_ref, m_in_ref, l_in_ref,
                   delta_ref, dk_ref, dv_ref, dk_acc, dv_acc, **cfg)


def _bwd_dkdv_kernel_masked(q_ref, k_ref, v_ref, do_ref, km_ref,
                            m_in_ref, l_in_ref, delta_ref,
                            dk_ref, dv_ref, dk_acc, dv_acc, **cfg):
    _bwd_dkdv_impl(q_ref, k_ref, v_ref, do_ref, m_in_ref, l_in_ref,
                   delta_ref, dk_ref, dv_ref, dk_acc, dv_acc,
                   km_ref=km_ref, **cfg)


def _bwd_dq_impl(q_ref, k_ref, v_ref, do_ref, m_in_ref, l_in_ref,
                 delta_ref, dq_ref, dq_acc, *,
                 scale: float, causal: bool,
                 block_q: int, block_k: int, causal_offset: int,
                 km_ref=None):
    """Grid (B, H, nq, nk): each q-block accumulates dq over k-blocks."""
    ki = pl.program_id(3)
    nk = pl.num_programs(3)
    qi = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    run = (ki * block_k <=
           qi * block_q + (block_q - 1) + causal_offset) if causal \
        else (ki >= 0)

    @pl.when(run)
    def _step():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        p = _recompute_p(q, k, m_in_ref[0, 0][:, :1],
                         l_in_ref[0, 0][:, :1], qi, ki,
                         causal_offset, scale, causal, block_q,
                         block_k, km_ref=km_ref)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0, 0][:, :1]) * scale
        ds = _mask_ds(ds, qi, ki, causal_offset, causal, block_q,
                      block_k, km_ref)
        dq_acc[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _final():
        dq_ref[0, 0] = dq_acc[:].astype(dq_ref.dtype)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, m_in_ref, l_in_ref,
                   delta_ref, dq_ref, dq_acc, **cfg):
    _bwd_dq_impl(q_ref, k_ref, v_ref, do_ref, m_in_ref, l_in_ref,
                 delta_ref, dq_ref, dq_acc, **cfg)


def _bwd_dq_kernel_masked(q_ref, k_ref, v_ref, do_ref, km_ref,
                          m_in_ref, l_in_ref, delta_ref, dq_ref,
                          dq_acc, **cfg):
    _bwd_dq_impl(q_ref, k_ref, v_ref, do_ref, m_in_ref, l_in_ref,
                 delta_ref, dq_ref, dq_acc, km_ref=km_ref, **cfg)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _flash(q, k, v, key_mask, scale, causal, block_q, block_k,
           interpret):
    """`key_mask`: (B, Tk) 0/1 f32 or an all-ones dummy when the
    static `masked` bit of the caller is off (it is a diff arg so it
    can be traced; its gradient is defined as zeros)."""
    return _flash_fwd(q, k, v, scale, causal, block_q, block_k,
                      interpret,
                      key_mask=key_mask if key_mask.ndim == 2 else None)


def _flash_vjp_fwd(q, k, v, key_mask, scale, causal, block_q, block_k,
                   interpret):
    # run the partials kernel (unnormalised acc + m/l) so the row
    # statistics needed by the Pallas backward come out of the same
    # pass; normalise outside — same math as _fwd_kernel's in-kernel
    # divide, one extra O(T·D) HBM round-trip at trace-under-grad only
    tk, tq = k.shape[2], q.shape[2]
    km = key_mask if key_mask.ndim == 2 else None
    acc, m, l = _block_partials(q, k, v, tk - tq, causal, scale,
                                block_q, block_k, interpret,
                                key_mask=km)
    out = (acc / jnp.maximum(l, 1e-30)[..., None]).astype(q.dtype)
    return out, (q, k, v, key_mask, out, m, l)


def _flash_vjp_bwd(scale, causal, block_q, block_k, interpret, res, g):
    """FlashAttention-2 backward as two Pallas kernels (dk/dv then dq);
    probabilities are recomputed blockwise from the saved row
    statistics, so grad-time memory stays O(T·D) like the forward."""
    q, k, v, key_mask, out, m, l = res
    b, h, tq, d = q.shape
    tk = k.shape[2]
    masked = key_mask.ndim == 2
    do = g.astype(q.dtype)
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)                 # (B, H, Tq)
    # lanes-replicated (B, H, Tq, 128) rows — see _block_kernel._final
    lanes = (b, h, tq, 128)
    m_r = jnp.broadcast_to(m[..., None], lanes)
    l_r = jnp.broadcast_to(l[..., None], lanes)
    delta_r = jnp.broadcast_to(delta[..., None], lanes)
    off = tk - tq
    blk = lambda bs, im: pl.BlockSpec((1, 1, bs, d), im)
    row = lambda bs, im: pl.BlockSpec((1, 1, bs, 128), im)
    common = dict(scale=scale, causal=causal, block_q=block_q,
                  block_k=block_k, causal_offset=off)
    params = _CompilerParams(
        dimension_semantics=("parallel", "parallel", "parallel",
                             "arbitrary"))
    km8 = _kmask8(key_mask, tk) if masked else None
    km_spec_kv = pl.BlockSpec((1, 8, block_k),
                              lambda bi, hi, ki, qi: (bi, 0, ki))
    km_spec_q = pl.BlockSpec((1, 8, block_k),
                             lambda bi, hi, qi, ki: (bi, 0, ki))

    in_specs_kv = [
        blk(block_q, lambda bi, hi, ki, qi: (bi, hi, qi, 0)),
        blk(block_k, lambda bi, hi, ki, qi: (bi, hi, ki, 0)),
        blk(block_k, lambda bi, hi, ki, qi: (bi, hi, ki, 0)),
        blk(block_q, lambda bi, hi, ki, qi: (bi, hi, qi, 0)),
    ] + ([km_spec_kv] if masked else []) + [
        row(block_q, lambda bi, hi, ki, qi: (bi, hi, qi, 0)),
        row(block_q, lambda bi, hi, ki, qi: (bi, hi, qi, 0)),
        row(block_q, lambda bi, hi, ki, qi: (bi, hi, qi, 0)),
    ]
    args_kv = [q, k, v, do] + ([km8] if masked else []) + \
        [m_r, l_r, delta_r]
    dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_dkdv_kernel_masked if masked else _bwd_dkdv_kernel,
            **common),
        grid=(b, h, tk // block_k, tq // block_q),
        in_specs=in_specs_kv,
        out_specs=[
            blk(block_k, lambda bi, hi, ki, qi: (bi, hi, ki, 0)),
            blk(block_k, lambda bi, hi, ki, qi: (bi, hi, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        compiler_params=params,
        interpret=interpret,
    )(*args_kv)

    in_specs_q = [
        blk(block_q, lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
        blk(block_k, lambda bi, hi, qi, ki: (bi, hi, ki, 0)),
        blk(block_k, lambda bi, hi, qi, ki: (bi, hi, ki, 0)),
        blk(block_q, lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
    ] + ([km_spec_q] if masked else []) + [
        row(block_q, lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
        row(block_q, lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
        row(block_q, lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
    ]
    args_q = [q, k, v, do] + ([km8] if masked else []) + \
        [m_r, l_r, delta_r]
    dq = pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel_masked if masked else _bwd_dq_kernel,
            **common),
        grid=(b, h, tq // block_q, tk // block_k),
        in_specs=in_specs_q,
        out_specs=blk(block_q, lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=params,
        interpret=interpret,
    )(*args_q)

    return dq, dk, dv, jnp.zeros_like(key_mask)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def _block_finalize(o_ref, m_out_ref, l_out_ref, acc_ref, m_ref,
                    l_ref):
    @pl.when(pl.program_id(3) == pl.num_programs(3) - 1)
    def _final():
        o_ref[0, 0] = acc_ref[:]
        # m/l leave the kernel lanes-replicated at (block_q, 128) — a
        # (1, 1, bq) block over (B, H, T) violates the TPU tiling rule
        # (last two block dims must divide (8, 128) or equal the array
        # dims); (B, H, T, 128) is the official flash kernel's layout
        m_out_ref[0, 0] = m_ref[:]
        l_out_ref[0, 0] = l_ref[:]


def _block_kernel(off_ref, q_ref, k_ref, v_ref,
                  o_ref, m_out_ref, l_out_ref,
                  acc_ref, m_ref, l_ref, *,
                  scale: float, causal: bool,
                  block_q: int, block_k: int):
    """Partial-softmax block attention: same recursion as
    `_fwd_kernel` (via `_attn_body`) but emits the UNNORMALISED
    accumulator plus running (m, l) statistics, so a caller (ring
    attention, the custom VJP forward) can merge partials or build the
    backward's row statistics.
    `off_ref` (SMEM, (1,1) int32) holds the global causal offset
    q_global_start - k_global_start, which is traced (it depends on
    `lax.axis_index` inside shard_map) and therefore can't be a Python
    static like `_fwd_kernel`'s causal_offset."""
    _attn_body(off_ref[0, 0], q_ref, k_ref, v_ref, acc_ref, m_ref,
               l_ref, scale=scale, causal=causal, block_q=block_q,
               block_k=block_k)
    _block_finalize(o_ref, m_out_ref, l_out_ref, acc_ref, m_ref, l_ref)


def _block_kernel_masked(off_ref, q_ref, k_ref, v_ref, km_ref,
                         o_ref, m_out_ref, l_out_ref,
                         acc_ref, m_ref, l_ref, *,
                         scale: float, causal: bool,
                         block_q: int, block_k: int):
    """`_block_kernel` + key-validity mask input."""
    _attn_body(off_ref[0, 0], q_ref, k_ref, v_ref, acc_ref, m_ref,
               l_ref, scale=scale, causal=causal, block_q=block_q,
               block_k=block_k, kmask_ref=km_ref)
    _block_finalize(o_ref, m_out_ref, l_out_ref, acc_ref, m_ref, l_ref)


def _block_partials(qt, kt, vt, qk_offset, causal, scale,
                    block_q, block_k, interpret, key_mask=None):
    """Head-major core of `flash_block_partial` (also the forward of
    the custom VJP, which needs the row statistics). qt/kt/vt:
    (B, H, T, D); returns (acc (B, H, Tq, D) f32 unnormalised,
    m (B, H, Tq) f32, l (B, H, Tq) f32)."""
    b, h, tq, d = qt.shape
    tk = kt.shape[2]
    off = jnp.asarray(qk_offset, jnp.int32).reshape(1, 1)
    masked = key_mask is not None
    kernel = functools.partial(
        _block_kernel_masked if masked else _block_kernel,
        scale=scale, causal=causal, block_q=block_q, block_k=block_k)
    blk = lambda bs, im: pl.BlockSpec((1, 1, bs, d), im)
    in_specs = [
        pl.BlockSpec(memory_space=pltpu.SMEM),
        blk(block_q, lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
        blk(block_k, lambda bi, hi, qi, ki: (bi, hi, ki, 0)),
        blk(block_k, lambda bi, hi, qi, ki: (bi, hi, ki, 0)),
    ]
    args = [off, qt, kt, vt]
    if masked:
        in_specs.append(pl.BlockSpec(
            (1, 8, block_k), lambda bi, hi, qi, ki: (bi, 0, ki)))
        args.append(_kmask8(key_mask, tk))
    acc, m, l = pl.pallas_call(
        kernel,
        grid=(b, h, tq // block_q, tk // block_k),
        in_specs=in_specs,
        out_specs=[
            blk(block_q, lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 128),
                         lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 128),
                         lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, tq, d), jnp.float32),
            jax.ShapeDtypeStruct((b, h, tq, 128), jnp.float32),
            jax.ShapeDtypeStruct((b, h, tq, 128), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(*args)
    return acc, m[..., 0], l[..., 0]


def flash_block_partial(q, k, v, qk_offset, causal: bool, scale: float,
                        interpret: Optional[bool] = None):
    """One flash pass over a K/V block, returning partials for
    cross-block merging (the ring-attention inner op).

    q, k, v: (B, Tq, H, D) / (B, Tk, H, D); `qk_offset` a traced int32
    scalar = q_global_start - k_global_start (causal only). Returns
    (acc (B, Tq, H, D) f32 unnormalised, m (B, H, Tq) f32,
    l (B, H, Tq) f32) with softmax base `m`.
    """
    global invocations
    invocations += 1
    if interpret is None:
        interpret = jax.default_backend() not in ("tpu", "axon")
    b, tq, h, d = q.shape
    tk = k.shape[1]
    bq, bk = _pick_blocks(tq, tk, jnp.dtype(q.dtype).itemsize)
    acc, m, l = _block_partials(
        jnp.transpose(q, (0, 2, 1, 3)),
        jnp.transpose(k, (0, 2, 1, 3)),
        jnp.transpose(v, (0, 2, 1, 3)),
        qk_offset, causal, scale, bq, bk, interpret)
    return jnp.transpose(acc, (0, 2, 1, 3)), m, l


def flash_decode_attention(q: jnp.ndarray, k: jnp.ndarray,
                           v: jnp.ndarray, key_mask: jnp.ndarray,
                           scale: float,
                           interpret: Optional[bool] = None,
                           k_scales: Optional[jnp.ndarray] = None,
                           v_scales: Optional[jnp.ndarray] = None
                           ) -> jnp.ndarray:
    """Single-query decode attention over a cached context, as a
    Pallas kernel reusing the flash block machinery.

    q: (S, H, D) — ONE new token per slot; k, v: (S, T, H, D) — the
    dense page-table gather of the cache; key_mask: (S, T) 0/1
    validity (1 = real cached token). Returns (S, H, D).

    Int8 caches (``ZOO_TPU_KV_DTYPE=int8``) pass the gathered views
    still quantized plus per-row scales ``k_scales``/``v_scales``
    (S, T, H): dequant runs here at the kernel's gather boundary, as
    one fused scale-multiply XLA folds into the transposes feeding
    VMEM, so the kernel body itself stays dtype-agnostic (int8's
    (32, 128) native tile would force a different block geometry —
    see the Pallas guide's quantization pattern; not worth it for a
    1-query kernel whose win is HBM traffic, already halved by
    reading int8 pages from HBM).

    The query tile is the kernel's only novelty: TPU blocks need a
    sublane dim divisible by 8, so the single query row is replicated
    to an (8, D) tile and row 0 of the output is taken — the other 7
    rows compute the identical softmax for free (the VPU processes
    8×128 lanes regardless). Everything else IS `_attn_body` +
    `_fwd_finalize` — same accumulation, same masking rule, same
    VMEM scratch — on grid (S, H, 1, nk), causal off (the cache only
    holds visible positions; `key_mask` owns validity). Inference
    only: no VJP is defined (decode never differentiates).
    """
    global invocations
    invocations += 1
    if k_scales is not None:
        from analytics_zoo_tpu.ops import kv_cache as kvc
        k = kvc.dequantize_rows(k, k_scales, q.dtype)
        v = kvc.dequantize_rows(v, v_scales, q.dtype)
    s, h, d = q.shape
    t = k.shape[1]
    _, bk = _pick_blocks(t, t, jnp.dtype(q.dtype).itemsize)
    if bk is None or d > 256:
        raise ValueError(
            f"flash_decode_attention needs T divisible by 128 and "
            f"D <= 256; got T={t} D={d} (use decode_attention's "
            f"dense path)")
    if interpret is None:
        interpret = jax.default_backend() not in ("tpu", "axon")
    qt = jnp.broadcast_to(q[:, :, None, :], (s, h, 8, d))
    kt = jnp.transpose(k, (0, 2, 1, 3))      # (S, H, T, D)
    vt = jnp.transpose(v, (0, 2, 1, 3))
    kernel = functools.partial(
        _fwd_kernel_masked, scale=scale, causal=False,
        block_q=8, block_k=bk, causal_offset=0)
    blk = lambda bs, im: pl.BlockSpec((1, 1, bs, d), im)
    out = pl.pallas_call(
        kernel,
        grid=(s, h, 1, t // bk),
        in_specs=[
            blk(8, lambda bi, hi, qi, ki: (bi, hi, 0, 0)),
            blk(bk, lambda bi, hi, qi, ki: (bi, hi, ki, 0)),
            blk(bk, lambda bi, hi, qi, ki: (bi, hi, ki, 0)),
            pl.BlockSpec((1, 8, bk),
                         lambda bi, hi, qi, ki: (bi, 0, ki)),
        ],
        out_specs=blk(8, lambda bi, hi, qi, ki: (bi, hi, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((s, h, 8, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((8, d), jnp.float32),
            pltpu.VMEM((8, 128), jnp.float32),
            pltpu.VMEM((8, 128), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(qt, kt, vt, _kmask8(key_mask, t))
    return out[:, :, 0]


def as_key_mask(mask, b: int, tk: int):
    """Reduce an attention mask (broadcastable to (B, H, Tq, Tk)) to
    the kernel-native (B, Tk) key-validity form, or None if it varies
    per query/head (detected STATICALLY from the shape: dims 1 and 2
    must be broadcast dims). Only the explicit 4-D (B|1, 1, 1, Tk)
    form qualifies — exactly BERT's padding mask (`layers/BERT.scala`
    extended attention mask); a 2-D mask is NOT accepted because the
    dense path broadcasts 2-D as (Tq, Tk), a different meaning."""
    if mask is None:
        return None
    shp = tuple(mask.shape)
    if mask.ndim == 4 and shp[1] == 1 and shp[2] == 1 and \
            shp[3] == tk and shp[0] in (1, b):
        km = mask[:, 0, 0, :]
        return jnp.broadcast_to(km, (b, tk))
    return None


def supports(tq: int, tk: int, d: int,
             mask: Optional[jnp.ndarray], b: Optional[int] = None
             ) -> bool:
    """Whether the kernel handles this problem (else caller falls back
    to the XLA path): block-divisible sequence lengths, a head dim
    that fits VMEM tiles, and a mask that is either absent or a pure
    key-padding mask (causal is native). Feasibility only — block
    divisibility is identical for every tuner candidate, so this
    consults the heuristic and never the cache."""
    bq, bk = _heuristic_blocks(tq, tk)
    if bq is None or bk is None or d > 256:
        return False
    if mask is None:
        return True
    return b is not None and as_key_mask(mask, b, tk) is not None


def _heuristic_blocks(tq: int, tk: int, itemsize: int = 2):
    # biggest wins on v5e (measured: [1024,1024] beats [256,512] by
    # 1.2-2.2x at T=2k-8k), but the BACKWARD holds ~4 f32
    # (block_q, block_k) tiles in VMEM at once, which at f32 operands
    # with 1024-blocks exceeds the 16MB scoped-VMEM budget (measured
    # 17.05M) — cap f32 at 512. Forward and backward MUST share the
    # blocks: the causal whole-block skip decides which fully-masked
    # query rows participate, and a fwd/bwd mismatch desyncs their
    # gradients.
    cap = 512 if itemsize >= 4 else 1024
    sizes = tuple(b for b in (1024, 512, 256, 128) if b <= cap)
    bq = next((b for b in sizes if tq % b == 0), None)
    bk = next((b for b in sizes if tk % b == 0), None)
    return bq, bk


def _pick_blocks(tq: int, tk: int, itemsize: int = 2):
    """Tuned (block_q, block_k) via the autotuner ("flash_blocks"
    op); the heuristic above stays the fallback and the sweep
    baseline. (None, None) for non-128-divisible T remains the
    static infeasibility signal and never reaches the tuner."""
    bq, bk = _heuristic_blocks(tq, tk, itemsize)
    if bq is None or bk is None:
        return bq, bk
    cfg = autotune.decide(
        "flash_blocks", {"tq": tq, "tk": tk, "isz": itemsize},
        dtype="f32" if itemsize >= 4 else "bf16")
    return cfg["bq"], cfg["bk"]


def flash_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                    causal: bool = False,
                    scale: Optional[float] = None,
                    key_mask: Optional[jnp.ndarray] = None,
                    interpret: Optional[bool] = None) -> jnp.ndarray:
    """Flash attention. q,k,v: (B, T, H, D) → (B, T, H, D).

    Same contract as :func:`ops.attention.dot_product_attention`
    (f32 softmax, bf16-safe); Tq/Tk must be multiples of 128.
    `key_mask`: optional (B, Tk) 0/1 key-validity (padding) mask,
    applied natively in the kernel (fwd AND bwd).
    `interpret=None` auto-selects the Pallas interpreter off-TPU.
    """
    global invocations
    invocations += 1
    d = q.shape[-1]
    scale = float(scale if scale is not None else 1.0 / (d ** 0.5))
    b, tq, tk = q.shape[0], q.shape[1], k.shape[1]
    bq, bk = _pick_blocks(tq, tk, jnp.dtype(q.dtype).itemsize)
    if bq is None or bk is None:
        raise ValueError(
            f"flash_attention needs Tq/Tk divisible by 128; got "
            f"Tq={tq} Tk={tk} (use dot_product_attention)")
    if interpret is None:
        interpret = jax.default_backend() not in ("tpu", "axon")
    qt = jnp.transpose(q, (0, 2, 1, 3))      # (B, H, T, D)
    kt = jnp.transpose(k, (0, 2, 1, 3))
    vt = jnp.transpose(v, (0, 2, 1, 3))
    if key_mask is None:
        # scalar dummy: ndim != 2 is the static "no mask" bit of the
        # custom_vjp (the mask must be a diff arg because it is traced)
        km = jnp.zeros((), jnp.float32)
    else:
        if tuple(key_mask.shape) != (b, tk):
            raise ValueError(
                f"key_mask must be (B, Tk)=({b}, {tk}); got "
                f"{tuple(key_mask.shape)}")
        km = key_mask.astype(jnp.float32)
    out = _flash(qt, kt, vt, km, scale, causal, bq, bk,
                 bool(interpret))
    return jnp.transpose(out, (0, 2, 1, 3))


# -- autotuner spec ---------------------------------------------------------
# "flash_blocks": the shared fwd/bwd (block_q, block_k) tiling, swept
# over every divisibility-feasible pair under the dtype-aware VMEM cap
# (the same cap the heuristic enforces). No legacy env flag exists for
# the blocks, so there is no flag_value. The probe times fwd+bwd
# together — the blocks are shared, so a fwd-only winner that loses
# the backward budget must not win the sweep.

def _blocks_heuristic(p):
    bq, bk = _heuristic_blocks(p["tq"], p["tk"], p["isz"])
    return {"bq": bq, "bk": bk}


def _blocks_candidates(p):
    cap = 512 if p["isz"] >= 4 else 1024
    sizes = [b for b in (1024, 512, 256, 128) if b <= cap]
    return [{"bq": bq, "bk": bk}
            for bq in sizes if p["tq"] % bq == 0
            for bk in sizes if p["tk"] % bk == 0]


def _blocks_runner(p, cfg):
    tq, tk, isz = p["tq"], p["tk"], p["isz"]
    interpret = jax.default_backend() not in ("tpu", "axon")
    if interpret and max(tq, tk) > 512:
        return None    # interpreter probes are for smoke shapes only
    import numpy as np
    dtype = jnp.float32 if isz >= 4 else jnp.bfloat16
    rs = np.random.RandomState(0)
    b, h, d = 1, 2, 64
    q = jnp.asarray(rs.randn(b, h, tq, d), dtype)
    k = jnp.asarray(rs.randn(b, h, tk, d), dtype)
    v = jnp.asarray(rs.randn(b, h, tk, d), dtype)
    km = jnp.zeros((), jnp.float32)
    scale = 1.0 / (d ** 0.5)

    @jax.jit
    def probe(q, k, v):
        def loss(q):
            out = _flash(q, k, v, km, scale, True, cfg["bq"],
                         cfg["bk"], interpret)
            return jnp.sum(out.astype(jnp.float32))
        val, dq = jax.value_and_grad(loss)(q)
        return val + jnp.sum(dq.astype(jnp.float32))

    def run():
        jax.block_until_ready(probe(q, k, v))
    return run


autotune.register(autotune.OpSpec(
    "flash_blocks", heuristic=_blocks_heuristic,
    candidates=_blocks_candidates, runner=_blocks_runner))
