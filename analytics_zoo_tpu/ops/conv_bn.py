"""Fused 1×1-conv (matmul) + BatchNorm Pallas kernel.

The ResNet-50 training step is HBM-bound on BatchNorm traffic, not
MXU-bound (PERF.md profile: BN statistics reductions ≈33% and BN
apply/FMA fusions ≈24% of device time vs ≈25% for the convs). The
reference hits the same wall differently — its MKL-DNN engine fuses
conv+BN+ReLU into one primitive (`zoo/.../IRconvertor` lowers
conv_bn chains to fused MKL ops); this module is the TPU analog for
the 1×1 convs that dominate a bottleneck block, where a 1×1 NHWC conv
IS a matmul over (N·H·W, Cin):

- **prologue**: the PREVIOUS BN's folded apply (``x·scale+shift``)
  and ReLU run on the input tile in VMEM while it feeds the MXU — the
  normalized activation never exists in HBM;
- **matmul**: (M, K) @ (K, N) in bf16 on the MXU, f32 accumulator;
- **epilogue**: per-channel ``Σy`` and ``Σy²`` (f32, shifted by the
  moving mean for cancellation safety — same scheme as
  `keras.layers.BatchNormalization`) accumulate while the output tile
  is written — THIS layer's BN statistics cost no extra HBM pass.

Per conv+BN+ReLU the activation traffic drops from
write + stats-read + apply-read + apply-write (4 passes) to a single
write, and the input-side apply pass of the previous layer disappears.

The backward is a `jax.custom_vjp` expressed in JAX: the statistics
cotangents fold into ONE augmented cotangent
``g = dy + dΣ + 2(y−shift)·dΣ²`` feeding both backward matmuls, and
the prologue's VJP (ReLU mask × scale, plus the reductions giving
d(scale)/d(shift)) fuses into the dx pass — fewer reduction passes
than autodiff of the unfused graph.
"""

from __future__ import annotations

import functools
import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from analytics_zoo_tpu.ops import conv_grad
from analytics_zoo_tpu.perf import autotune

# jax ≥0.5 renamed TPUCompilerParams → CompilerParams; bind whichever
# this jax ships so the kernels compile on both sides of the rename
_CompilerParams = getattr(pltpu, "CompilerParams",
                          getattr(pltpu, "TPUCompilerParams", None))

# test observability, like ops.flash_attention.invocations
invocations = 0

# Measured-win gate for the fused-ResNet "auto" default (the flash
# playbook, VERDICT r3 next-round #3): flip to True once
# scripts/measure_fused.py shows the fused bottlenecks beating the
# XLA graph on real hardware. Until then "auto" resolves unfused —
# the kernels stay opt-in (ZOO_TPU_FUSED_RESNET=1) because they are
# conformance-clean but chip-unmeasured (the round-3 tunnel outage).
MEASURED_WIN = False


def fused_profitable() -> bool:
    """Whether the "auto" fused-ResNet default may route to the Pallas
    conv+BN bottlenecks: a real TPU backend AND a measured on-chip win
    (``MEASURED_WIN``). ``ZOO_TPU_FUSED_WIN=0/1`` overrides both (1:
    CPU kernel-coverage tests and measurement runs; 0: kill switch)."""
    env = os.environ.get("ZOO_TPU_FUSED_WIN")
    if env is not None:
        return env == "1"
    return MEASURED_WIN and jax.default_backend() in ("tpu", "axon")


def _heuristic_blocks(m: int, k: int, n: int, itemsize: int = 2
                      ) -> Tuple[int, int]:
    """Analytic (block_m, block_k); N is never tiled (ResNet channel
    counts are ≤2048 and 128-multiples, so the whole (bm, N) f32
    accumulator and the (bk, N) weight tile fit VMEM comfortably)."""
    # any admitted k is a 64-multiple, so 64 terminates the search
    bk = next(b for b in (512, 384, 256, 128, 64) if k % b == 0) \
        if k > 512 else k
    # VMEM budget ~ acc(bm·n·4) + x(bm·bk·isz) + w(bk·n·isz): keep
    # ≲6MB (leaves headroom for Pallas double-buffering in 16MB VMEM)
    bm = 512
    while bm > 128 and \
            bm * n * 4 + (bm * bk + bk * n) * itemsize > 6 * 2 ** 20:
        bm //= 2
    return max(bm, 128), bk


def _pick_blocks(m: int, k: int, n: int, itemsize: int = 2
                 ) -> Tuple[int, int]:
    """(block_m, block_k) for one fused matmul, via the autotuner
    ("conv_bn_blocks" op; itemsize keys the sweep so residual-doubled
    budgets tune separately). Falls back to
    :func:`_heuristic_blocks` when nothing is swept or cached."""
    cfg = autotune.decide(
        "conv_bn_blocks",
        {"m": m, "k": k, "n": n, "isz": itemsize})
    return cfg["bm"], cfg["bk"]


def _prologue_accumulate(x_ref, w_ref, s_ref, t_ref, acc_ref, ki,
                         relu_in, affine_in, r_ref=None):
    """The compute path SHARED by the stats (`_kernel`) and apply
    (`_apply_kernel`) epilogues: zero the accumulator at ki==0, apply
    the input affine (+ optional residual tile) + ReLU prologue in
    VMEM, accumulate one (bm, bk)@(bk, N) MXU tap in f32. The
    residual adds AFTER the affine, BEFORE the ReLU — the form of a
    deferred bottleneck output ``relu(y3·scale3+shift3 + shortcut)``
    consumed by the NEXT block's 1×1 (the round-5 deferred-apply
    lever)."""
    @pl.when(ki == 0)
    def _init_acc():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    x = x_ref[...]
    if affine_in:
        x = x.astype(jnp.float32) * s_ref[0, :][None, :] + \
            t_ref[0, :][None, :]
    if r_ref is not None:
        x = x.astype(jnp.float32) + r_ref[...].astype(jnp.float32)
    if relu_in:
        x = jnp.maximum(x, 0.0)
    x = x.astype(w_ref.dtype)
    acc_ref[...] += jax.lax.dot_general(
        x, w_ref[...], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


def _kernel(x_ref, w_ref, s_ref, t_ref, sh_ref, *rest,
            n_k: int, relu_in: bool, affine_in: bool, has_res: bool,
            out_dtype):
    """One (mi, ki) grid step. Refs:
    x (bm, bk) input tile; w (bk, N); s/t (1, bk) prologue
    scale/shift; sh (1, N) stats shift; ``rest`` is Pallas's
    input→output→scratch tail ``([r (bm, bk),] y (bm, N), sum/sq
    (1, N) f32 accumulated across mi, acc (bm, N) f32 scratch)``.
    Grid order (mi, ki): ki innermost."""
    if has_res:
        r_ref, y_ref, sum_ref, sq_ref, acc_ref = rest
    else:
        r_ref = None
        y_ref, sum_ref, sq_ref, acc_ref = rest
    mi = pl.program_id(0)
    ki = pl.program_id(1)
    _prologue_accumulate(x_ref, w_ref, s_ref, t_ref, acc_ref, ki,
                         relu_in, affine_in, r_ref=r_ref)

    @pl.when(ki == n_k - 1)
    def _finalize():
        acc = acc_ref[...]
        y_ref[...] = acc.astype(out_dtype)
        d = acc - sh_ref[0, :][None, :]      # shifted for stability

        @pl.when(mi == 0)
        def _first():
            sum_ref[...] = jnp.sum(d, axis=0, keepdims=True)
            sq_ref[...] = jnp.sum(d * d, axis=0, keepdims=True)

        @pl.when(mi != 0)
        def _rest():
            sum_ref[...] += jnp.sum(d, axis=0, keepdims=True)
            sq_ref[...] += jnp.sum(d * d, axis=0, keepdims=True)


def _matmul_bn_fwd_pallas(x, w, s, t, sh, r, relu_in, affine_in,
                          interpret):
    m, k = x.shape
    n = w.shape[1]
    has_res = r is not None
    isz = max(jnp.dtype(x.dtype).itemsize,
              jnp.dtype(w.dtype).itemsize)
    # the residual adds a second (bm, bk) double-buffered input tile:
    # doubling the x-tile itemsize keeps the budget formula honest
    bm, bk = _pick_blocks(m, k, n, isz * 2 if has_res else isz)
    if m % bm:                       # pad rows to a block multiple
        pad = bm - m % bm
        x = jnp.pad(x, ((0, pad), (0, 0)))
        if has_res:
            r = jnp.pad(r, ((0, pad), (0, 0)))
        mp = m + pad
    else:
        mp = m
    n_m, n_k = mp // bm, k // bk
    kernel = functools.partial(
        _kernel, n_k=n_k, relu_in=relu_in, affine_in=affine_in,
        has_res=has_res, out_dtype=jnp.dtype(x.dtype))
    in_specs = [
        pl.BlockSpec((bm, bk), lambda mi, ki: (mi, ki)),
        pl.BlockSpec((bk, n), lambda mi, ki: (ki, 0)),
        pl.BlockSpec((1, bk), lambda mi, ki: (0, ki)),
        pl.BlockSpec((1, bk), lambda mi, ki: (0, ki)),
        pl.BlockSpec((1, n), lambda mi, ki: (0, 0)),
    ]
    operands = [x, w, s, t, sh]
    if has_res:
        in_specs.append(pl.BlockSpec((bm, bk), lambda mi, ki: (mi, ki)))
        operands.append(r)
    y, ssum, ssq = pl.pallas_call(
        kernel,
        grid=(n_m, n_k),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((bm, n), lambda mi, ki: (mi, 0)),
            pl.BlockSpec((1, n), lambda mi, ki: (0, 0)),
            pl.BlockSpec((1, n), lambda mi, ki: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((mp, n), x.dtype),
            jax.ShapeDtypeStruct((1, n), jnp.float32),
            jax.ShapeDtypeStruct((1, n), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((bm, n), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")),
        interpret=interpret,
    )(*operands)
    if mp != m:
        # padded (all-zero) input rows still produce a nonzero output
        # row when the prologue has a shift/ReLU: y0 = prologue(0) @ w
        # (the residual pads with ZEROS, so row0 is unchanged by it).
        # Subtract their exact statistics contribution.
        extra = jnp.float32(mp - m)
        if affine_in:
            row0 = t[0, :]
            if relu_in:
                row0 = jnp.maximum(row0, 0.0)
            # match the kernel's compute path exactly: the prologue
            # output is cast to the weight dtype before the MXU dot
            y0 = jax.lax.dot_general(
                row0.astype(w.dtype)[None, :], w,
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)[0]
        else:
            y0 = jnp.zeros((n,), jnp.float32)
        d0 = y0 - sh[0, :]
        ssum = ssum - extra * d0[None, :]
        ssq = ssq - extra * (d0 ** 2)[None, :]
        y = y[:m]
    return y, ssum[0], ssq[0]


@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 7, 8))
def _matmul_bn(x, w, s, t, sh, r, relu_in, affine_in, interpret):
    return _matmul_bn_fwd_pallas(x, w, s, t, sh, r, relu_in,
                                 affine_in, interpret)


def _matmul_bn_vjp_fwd(x, w, s, t, sh, r, relu_in, affine_in,
                       interpret):
    out = _matmul_bn_fwd_pallas(x, w, s, t, sh, r, relu_in, affine_in,
                                interpret)
    y, _, _ = out
    return out, (x, w, s, t, sh, r, y)


def _pallas_bwd_wins(m: int, k: int, n: int) -> bool:
    """Whether the fused Pallas backward beats the XLA reference at
    this matmul shape — the autotuned form of the old
    ``ZOO_TPU_CONV_BN_PALLAS_BWD`` constant toggle. The flag, when
    set, is honored verbatim (source="flag"); unset, the tuner's
    cache/defaults decide, heuristic Pallas-on (the pre-tuner
    default)."""
    return bool(autotune.decide("conv_bn_bwd",
                                {"m": m, "k": k, "n": n})["pallas"])


def _matmul_bn_vjp_bwd(relu_in, affine_in, interpret, res, cots):
    x, w, s, t, sh, r, y = res
    dy, dsum, dsq = cots
    # with a residual the Pallas dx kernel recomputes the ReLU/
    # residual VJP in VMEM and emits the residual cotangent through
    # the same epilogue (dr = masked g@Wᵀ) — the augmented cotangent
    # never exists in HBM on either path
    if _pallas_bwd_wins(x.shape[0], x.shape[1], w.shape[1]):
        out = _bwd_pallas(x, w, s, t, sh, y, dy, dsum, dsq,
                          relu_in, affine_in, interpret, r=r)
    else:
        out = _bwd_jax(x, w, s, t, sh, y, dy, dsum, dsq,
                       relu_in, affine_in, r=r)
    # custom_vjp wants a 6-tuple; no residual input → cotangent None
    return out if r is not None else out + (None,)


def _bwd_jax(x, w, s, t, sh, y, dy, dsum, dsq, relu_in, affine_in,
             r=None):
    """XLA-expressed backward (the `ZOO_TPU_CONV_BN_PALLAS_BWD=0`
    reference path, and the ground truth the Pallas backward is
    conformance-tested against)."""
    f32 = jnp.float32
    # stats cotangents fold into one augmented output cotangent:
    # y feeds (y, Σ(y-sh), Σ(y-sh)²) so g = dy + dΣ + 2(y-sh)·dΣ²
    g = dy.astype(f32) + dsum[None, :] + \
        2.0 * (y.astype(f32) - sh[0, :][None, :]) * dsq[None, :]
    # recompute the prologue (cheaper than saving x' — one read of x
    # instead of a second M×K tensor in HBM)
    if affine_in:
        xa = x.astype(f32) * s[0, :][None, :] + t[0, :][None, :]
    else:
        xa = x.astype(f32)
    if r is not None:
        xa = xa + r.astype(f32)
    xp = jnp.maximum(xa, 0.0) if relu_in else xa
    # backward matmuls run in the forward's compute dtype (bf16 on the
    # MXU) with f32 accumulation — mixed-precision standard; only the
    # elementwise algebra stays f32
    cd = x.dtype
    gc = g.astype(cd)
    dw = jax.lax.dot_general(xp.astype(cd), gc,
                             (((0,), (0,)), ((), ())),
                             preferred_element_type=f32)
    dxp = jax.lax.dot_general(gc, w.astype(cd),
                              (((1,), (1,)), ((), ())),
                              preferred_element_type=f32)
    if relu_in:
        dxp = jnp.where(xa > 0.0, dxp, 0.0)
    if affine_in:
        dx = dxp * s[0, :][None, :]
        ds = jnp.sum(dxp * x.astype(f32), axis=0, keepdims=True)
        dt = jnp.sum(dxp, axis=0, keepdims=True)
    else:
        dx = dxp
        ds = jnp.zeros_like(s)
        dt = jnp.zeros_like(t)
    base = (dx.astype(x.dtype), dw.astype(w.dtype),
            ds.astype(s.dtype), dt.astype(t.dtype),
            jnp.zeros_like(sh))
    # 5-tuple without r (matching _bwd_pallas and its fallbacks into
    # this function); 6-tuple with the residual cotangent otherwise
    return base if r is None else base + (dxp.astype(r.dtype),)


def _g_tile(dy, y, sh_row, dsum_row, dsq_row):
    """The augmented cotangent on one tile, in f32 (single copy of the
    formula shared by both backward kernels)."""
    return (dy.astype(jnp.float32) + dsum_row +
            2.0 * (y.astype(jnp.float32) - sh_row) * dsq_row)


def _dx_kernel(dy_ref, y_ref, x_ref, w_ref, s_ref, t_ref, sh_ref,
               dsum_ref, dsq_ref, *rest,
               relu_in: bool, affine_in: bool, has_res: bool,
               out_dtype, res_dtype=None):
    """Grid (mi,): dx tile = prologue'(x) ⊙ (g @ Wᵀ); ds/dt accumulate
    across mi. g is recomputed from dy/y in VMEM — it never exists in
    HBM (the XLA path materialises it as both matmuls' operand). With
    ``has_res`` the prologue recomputation includes the residual tile
    (xa = x·s+t+r) and the residual cotangent dr = masked g@Wᵀ leaves
    through an extra output in the same epilogue — the deferred
    block's elementwise-tail VJP never touches HBM either."""
    if has_res:
        r_ref, dx_ref, ds_ref, dt_ref, dr_ref = rest
    else:
        r_ref = dr_ref = None
        dx_ref, ds_ref, dt_ref = rest
    mi = pl.program_id(0)
    g = _g_tile(dy_ref[...], y_ref[...], sh_ref[0, :][None, :],
                dsum_ref[0, :][None, :], dsq_ref[0, :][None, :])
    dxp = jax.lax.dot_general(
        g.astype(w_ref.dtype), w_ref[...],
        (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
    xf = x_ref[...].astype(jnp.float32)
    if affine_in:
        xa = xf * s_ref[0, :][None, :] + t_ref[0, :][None, :]
    else:
        xa = xf
    if has_res:
        xa = xa + r_ref[...].astype(jnp.float32)
    if relu_in:
        dxp = jnp.where(xa > 0.0, dxp, 0.0)
    if has_res:
        dr_ref[...] = dxp.astype(res_dtype)
    if affine_in:
        dx_ref[...] = (dxp * s_ref[0, :][None, :]).astype(out_dtype)
        ds_new = jnp.sum(dxp * xf, axis=0, keepdims=True)
        dt_new = jnp.sum(dxp, axis=0, keepdims=True)
    else:
        dx_ref[...] = dxp.astype(out_dtype)
        ds_new = jnp.zeros_like(ds_ref)
        dt_new = jnp.zeros_like(dt_ref)

    @pl.when(mi == 0)
    def _first():
        ds_ref[...] = ds_new
        dt_ref[...] = dt_new

    @pl.when(mi != 0)
    def _rest():
        ds_ref[...] += ds_new
        dt_ref[...] += dt_new


def _dw_kernel(dy_ref, y_ref, x_ref, s_ref, t_ref, sh_ref,
               dsum_ref, dsq_ref, *rest,
               n_m: int, relu_in: bool, affine_in: bool,
               has_res: bool):
    """Grid (ni, mi): dW[:, ni] += prologue(x)ᵀ @ g, accumulated over
    mi in a VMEM scratch, written at the last mi. ``has_res``: the
    prologue recomputation includes the residual tile, like
    `_dx_kernel`."""
    if has_res:
        r_ref, dw_ref, acc_ref = rest
    else:
        r_ref = None
        dw_ref, acc_ref = rest
    mi = pl.program_id(1)

    @pl.when(mi == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    g = _g_tile(dy_ref[...], y_ref[...], sh_ref[0, :][None, :],
                dsum_ref[0, :][None, :], dsq_ref[0, :][None, :])
    xf = x_ref[...].astype(jnp.float32)
    if affine_in:
        xf = xf * s_ref[0, :][None, :] + t_ref[0, :][None, :]
    if has_res:
        xf = xf + r_ref[...].astype(jnp.float32)
    if relu_in:
        xf = jnp.maximum(xf, 0.0)
    cd = x_ref.dtype
    acc_ref[...] += jax.lax.dot_general(
        xf.astype(cd), g.astype(cd), (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(mi == n_m - 1)
    def _write():
        dw_ref[...] = acc_ref[...]


def _bwd_pallas(x, w, s, t, sh, y, dy, dsum, dsq, relu_in, affine_in,
                interpret, r=None):
    m, k = x.shape
    n = w.shape[1]
    f32 = jnp.float32
    has_res = r is not None
    x_isz = jnp.dtype(x.dtype).itemsize
    w_isz = jnp.dtype(w.dtype).itemsize
    r_isz = jnp.dtype(r.dtype).itemsize if has_res else 0
    if k * n * w_isz >= 8 * 2 ** 20:
        # the dx kernel keeps the whole (K, N) weight resident; beyond
        # ~8MB that cannot fit VMEM with the row tiles — use the XLA
        # backward (ResNet's largest is 1024x2048 bf16 = 4MB)
        return _bwd_jax(x, w, s, t, sh, y, dy, dsum, dsq,
                        relu_in, affine_in, r=r)
    # dW scratch + output block are (K, bn_w) f32: bound K·bn_w, not
    # K·N; no qualifying column tile (extreme K) → XLA backward
    bn_w = next((b for b in (2048, 1024, 512, 256, 128, 64)
                 if n % b == 0 and k * b * 4 <= 4 * 2 ** 20), None)
    if bn_w is None:
        return _bwd_jax(x, w, s, t, sh, y, dy, dsum, dsq,
                        relu_in, affine_in, r=r)
    dsum2 = dsum.astype(f32).reshape(1, n)
    dsq2 = dsq.astype(f32).reshape(1, n)
    # block rows: bound VMEM by the fattest resident set, INCLUDING
    # the (K, N) weight tile the dx kernel holds (a residual adds an
    # r input tile and a dr output tile, both (bm, K))
    def _resident(bm):
        return bm * 2 * n * x_isz + bm * k * x_isz + \
            bm * k * 4 + k * n * w_isz + bm * k * 2 * r_isz
    bm = 512
    while bm > 128 and _resident(bm) > 8 * 2 ** 20:
        bm //= 2
    if _resident(bm) > 8 * 2 ** 20:
        # even the smallest row tile busts VMEM (f32 at large K·N):
        # fall back rather than fail Mosaic allocation on chip
        return _bwd_jax(x, w, s, t, sh, y, dy, dsum, dsq,
                        relu_in, affine_in, r=r)
    if m % bm:
        pad = bm - m % bm
        # zero-padded rows: g_pad = dsum (nonzero!) but relu'/affine
        # masks make dx rows garbage we slice off; for ds/dt the
        # padded rows contribute dxp_pad·0 (xf=0) to ds and dxp_pad to
        # dt — correct dt exactly below. dW pads xp rows as
        # prologue(0) like the forward — corrected below too. The
        # residual pads with ZEROS, so xa_pad stays prologue(0) and
        # every correction below is unchanged; dr pad rows slice off.
        x_p = jnp.pad(x, ((0, pad), (0, 0)))
        dy_p = jnp.pad(dy, ((0, pad), (0, 0)))
        y_p = jnp.pad(y, ((0, pad), (0, 0)))
        r_p = jnp.pad(r, ((0, pad), (0, 0))) if has_res else None
    else:
        pad = 0
        x_p, dy_p, y_p, r_p = x, dy, y, r
    mp = m + pad
    n_m = mp // bm

    dx_specs = [
        pl.BlockSpec((bm, n), lambda mi: (mi, 0)),    # dy
        pl.BlockSpec((bm, n), lambda mi: (mi, 0)),    # y
        pl.BlockSpec((bm, k), lambda mi: (mi, 0)),    # x
        pl.BlockSpec((k, n), lambda mi: (0, 0)),      # w
        pl.BlockSpec((1, k), lambda mi: (0, 0)),      # s
        pl.BlockSpec((1, k), lambda mi: (0, 0)),      # t
        pl.BlockSpec((1, n), lambda mi: (0, 0)),      # sh
        pl.BlockSpec((1, n), lambda mi: (0, 0)),      # dsum
        pl.BlockSpec((1, n), lambda mi: (0, 0)),      # dsq
    ]
    dx_ops = [dy_p, y_p, x_p, w, s, t, sh, dsum2, dsq2]
    dx_out_specs = [
        pl.BlockSpec((bm, k), lambda mi: (mi, 0)),
        pl.BlockSpec((1, k), lambda mi: (0, 0)),
        pl.BlockSpec((1, k), lambda mi: (0, 0)),
    ]
    dx_out_shape = [
        jax.ShapeDtypeStruct((mp, k), x.dtype),
        jax.ShapeDtypeStruct((1, k), f32),
        jax.ShapeDtypeStruct((1, k), f32),
    ]
    if has_res:
        dx_specs.append(pl.BlockSpec((bm, k), lambda mi: (mi, 0)))
        dx_ops.append(r_p)
        # dr leaves through the same epilogue as dx
        dx_out_specs.append(pl.BlockSpec((bm, k), lambda mi: (mi, 0)))
        dx_out_shape.append(jax.ShapeDtypeStruct((mp, k), r.dtype))
    outs = pl.pallas_call(
        functools.partial(_dx_kernel, relu_in=relu_in,
                          affine_in=affine_in, has_res=has_res,
                          out_dtype=jnp.dtype(x.dtype),
                          res_dtype=jnp.dtype(r.dtype) if has_res
                          else None),
        grid=(n_m,),
        in_specs=dx_specs,
        out_specs=dx_out_specs,
        out_shape=dx_out_shape,
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(*dx_ops)
    if has_res:
        dx, ds, dt, dr = outs
    else:
        (dx, ds, dt), dr = outs, None

    dw_specs = [
        pl.BlockSpec((bm, bn_w), lambda ni, mi: (mi, ni)),  # dy
        pl.BlockSpec((bm, bn_w), lambda ni, mi: (mi, ni)),  # y
        pl.BlockSpec((bm, k), lambda ni, mi: (mi, 0)),      # x
        pl.BlockSpec((1, k), lambda ni, mi: (0, 0)),        # s
        pl.BlockSpec((1, k), lambda ni, mi: (0, 0)),        # t
        pl.BlockSpec((1, bn_w), lambda ni, mi: (0, ni)),    # sh
        pl.BlockSpec((1, bn_w), lambda ni, mi: (0, ni)),    # dsum
        pl.BlockSpec((1, bn_w), lambda ni, mi: (0, ni)),    # dsq
    ]
    dw_ops = [dy_p, y_p, x_p, s, t, sh, dsum2, dsq2]
    if has_res:
        dw_specs.append(pl.BlockSpec((bm, k),
                                     lambda ni, mi: (mi, 0)))
        dw_ops.append(r_p)
    dw = pl.pallas_call(
        functools.partial(_dw_kernel, n_m=n_m, relu_in=relu_in,
                          affine_in=affine_in, has_res=has_res),
        grid=(n // bn_w, n_m),
        in_specs=dw_specs,
        out_specs=pl.BlockSpec((k, bn_w), lambda ni, mi: (0, ni)),
        out_shape=jax.ShapeDtypeStruct((k, n), f32),
        scratch_shapes=[pltpu.VMEM((k, bn_w), f32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")),
        interpret=interpret,
    )(*dw_ops)

    if pad:
        dx = dx[:m]
        if has_res:
            dr = dr[:m]
        if affine_in:
            # padded-row corrections (exact; dy=y=x=0 on those rows):
            # g_pad = dsum − 2·sh·dsq, xp_pad = prologue(0) = relu(t)
            cd = x.dtype
            g_pad = dsum2[0] - 2.0 * sh[0, :] * dsq2[0]     # (N,)
            row0 = jnp.maximum(t[0, :], 0.0) if relu_in else t[0, :]
            # dW accumulated pad·(xp_pad ⊗ g_pad) — subtract it
            dw = dw - jnp.float32(pad) * jax.lax.dot_general(
                row0.astype(cd)[:, None], g_pad.astype(cd)[None, :],
                (((1,), (0,)), ((), ())), preferred_element_type=f32)
            # dt accumulated pad·dxp_pad where dxp_pad is the masked
            # backward of one padded row (ds got dxp_pad·x = 0: exact)
            dxp_pad = jax.lax.dot_general(
                g_pad.astype(cd)[None, :], w.astype(cd),
                (((1,), (1,)), ((), ())),
                preferred_element_type=f32)[0]
            if relu_in:
                dxp_pad = jnp.where(t[0, :] > 0.0, dxp_pad, 0.0)
            dt = dt - jnp.float32(pad) * dxp_pad[None, :]
        # no affine: xp_pad = 0 (and relu mask kills dxp_pad), so dW
        # needs no correction and ds/dt are zeroed below anyway

    if not affine_in:
        ds = jnp.zeros((1, k), f32)
        dt = jnp.zeros((1, k), f32)
    base = (dx, dw.astype(w.dtype), ds.astype(s.dtype),
            dt.astype(t.dtype), jnp.zeros_like(sh))
    # 5-tuple without r, 6-tuple with the residual cotangent —
    # matching _bwd_jax
    return base if not has_res else base + (dr,)


_matmul_bn.defvjp(_matmul_bn_vjp_fwd, _matmul_bn_vjp_bwd)


def matmul_bn(x: jnp.ndarray, w: jnp.ndarray,
              in_scale: Optional[jnp.ndarray] = None,
              in_shift: Optional[jnp.ndarray] = None,
              relu_in: bool = False,
              stat_shift: Optional[jnp.ndarray] = None,
              in_residual: Optional[jnp.ndarray] = None,
              interpret: Optional[bool] = None):
    """Fused ``relu(x·in_scale+in_shift [+ in_residual]) @ w`` with
    BN-statistics epilogue.

    x: (M, K); w: (K, N) — K, N must be 64-multiples (128 preferred:
    the native lane width; 64 covers ResNet's stage-0 convs via lane
    padding). Returns ``(y (M, N), sum (N,), sumsq (N,))`` where
    the statistics are over ``y - stat_shift`` in f32 (pass the BN's
    moving mean, stop-gradded, as ``stat_shift``; see
    `BatchNormalization.apply` for the scheme).

    `in_scale`/`in_shift` (K,): previous-BN folded apply on the input,
    in VMEM (skip both for a raw matmul); ``relu_in`` applies ReLU
    after the affine. ``in_residual`` (M, K) adds after the affine,
    before the ReLU — the shape of a DEFERRED bottleneck output
    ``relu(y3·scale3+shift3 + shortcut)`` consumed here instead of
    being materialized by its own whole-tensor pass (the round-5
    deferred-apply lever). The backward recomputes the ReLU/residual
    VJP in VMEM inside the Pallas dx kernel and emits the residual
    cotangent through the same epilogue — it never exists in HBM
    (``ZOO_TPU_CONV_BN_PALLAS_BWD=0`` selects the XLA reference
    backward). Differentiable in x, w, in_scale, in_shift,
    in_residual.
    """
    global invocations
    invocations += 1
    m, k = x.shape
    n = w.shape[1]
    if k % 64 or n % 64:
        # 128 is the native lane width; 64 still compiles (Mosaic pads
        # lanes) and covers ResNet's stage-0 64-channel convs
        raise ValueError(f"K={k} and N={n} must be 64-multiples")
    if in_residual is not None and in_residual.shape != (m, k):
        raise ValueError(f"in_residual must be {(m, k)}, got "
                         f"{in_residual.shape}")
    if interpret is None:
        interpret = jax.default_backend() not in ("tpu", "axon")
    # shift-only callers get scale=1, not a silently dropped shift
    affine_in = in_scale is not None or in_shift is not None
    f32 = jnp.float32
    s = (in_scale.astype(f32) if in_scale is not None else
         jnp.ones((k,), f32)).reshape(1, k)
    t = (in_shift.astype(f32) if in_shift is not None else
         jnp.zeros((k,), f32)).reshape(1, k)
    sh = (stat_shift.astype(f32) if stat_shift is not None else
          jnp.zeros((n,), f32)).reshape(1, n)
    return _matmul_bn(x, w.astype(x.dtype), s, t, sh, in_residual,
                      relu_in, affine_in, bool(interpret))


def _apply_kernel(x_ref, w_ref, s_ref, t_ref, os_ref, ot_ref,
                  *rest, n_k: int, relu_in: bool,
                  affine_in: bool, has_res: bool, relu_out: bool,
                  out_dtype):
    """Eval-mode variant of `_kernel`: no statistics epilogue; instead
    the OUTPUT affine (this BN's moving-stats fold), an optional
    residual tile, and an optional ReLU apply while the tile writes —
    the raw conv output never exists in HBM. ``rest`` is Pallas's
    input→output→scratch tail: ``([r_ref,] y_ref, acc_ref)``."""
    if has_res:
        r_ref, y_ref, acc_ref = rest
    else:
        y_ref, acc_ref = rest
    ki = pl.program_id(1)
    _prologue_accumulate(x_ref, w_ref, s_ref, t_ref, acc_ref, ki,
                         relu_in, affine_in)

    @pl.when(ki == n_k - 1)
    def _finalize():
        y = acc_ref[...] * os_ref[0, :][None, :] + \
            ot_ref[0, :][None, :]
        if has_res:
            y = y + r_ref[...].astype(jnp.float32)
        if relu_out:
            y = jnp.maximum(y, 0.0)
        y_ref[...] = y.astype(out_dtype)


def _apply_ref(x, w, s, t, os_, ot, res, relu_in, affine_in,
               relu_out):
    """Reference expression for `matmul_bn_apply` (ground truth +
    the autodiff backward). Accepts the affine vectors 1-D or as the
    kernel's (1, K)/(1, N) rows."""
    f32 = jnp.float32
    s = None if s is None else s.reshape(-1)
    t = None if t is None else t.reshape(-1)
    os_ = os_.reshape(-1)
    ot = ot.reshape(-1)
    xf = x.astype(f32)
    if affine_in:
        xf = xf * s[None, :] + t[None, :]
    if relu_in:
        xf = jnp.maximum(xf, 0.0)
    y = jax.lax.dot_general(xf.astype(w.dtype), w,
                            (((1,), (0,)), ((), ())),
                            preferred_element_type=f32)
    y = y * os_[None, :] + ot[None, :]
    if res is not None:
        y = y + res.astype(f32)
    if relu_out:
        y = jnp.maximum(y, 0.0)
    return y.astype(x.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(7, 8, 9, 10))
def _matmul_apply(x, w, s, t, os_, ot, res, relu_in, affine_in,
                  relu_out, interpret):
    m, k = x.shape
    n = w.shape[1]
    bm, bk = _pick_blocks(
        m, k, n, max(jnp.dtype(x.dtype).itemsize,
                     jnp.dtype(w.dtype).itemsize))
    has_res = res is not None
    if m % bm:
        pad = bm - m % bm
        x = jnp.pad(x, ((0, pad), (0, 0)))
        if has_res:
            res = jnp.pad(res, ((0, pad), (0, 0)))
        mp = m + pad
    else:
        mp = m
    n_m, n_k = mp // bm, k // bk
    kernel = functools.partial(
        _apply_kernel, n_k=n_k, relu_in=relu_in, affine_in=affine_in,
        has_res=has_res, relu_out=relu_out, out_dtype=jnp.dtype(x.dtype))
    in_specs = [
        pl.BlockSpec((bm, bk), lambda mi, ki: (mi, ki)),
        pl.BlockSpec((bk, n), lambda mi, ki: (ki, 0)),
        pl.BlockSpec((1, bk), lambda mi, ki: (0, ki)),
        pl.BlockSpec((1, bk), lambda mi, ki: (0, ki)),
        pl.BlockSpec((1, n), lambda mi, ki: (0, 0)),
        pl.BlockSpec((1, n), lambda mi, ki: (0, 0)),
    ]
    operands = [x, w, s, t, os_, ot]
    if has_res:
        in_specs.append(pl.BlockSpec((bm, n), lambda mi, ki: (mi, 0)))
        operands.append(res)
    y = pl.pallas_call(
        kernel,
        grid=(n_m, n_k),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((bm, n), lambda mi, ki: (mi, 0)),
        out_shape=jax.ShapeDtypeStruct((mp, n), x.dtype),
        scratch_shapes=[pltpu.VMEM((bm, n), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")),
        interpret=interpret,
    )(*operands)
    return y[:m] if mp != m else y


def _matmul_apply_vjp_fwd(x, w, s, t, os_, ot, res, relu_in,
                          affine_in, relu_out, interpret):
    y = _matmul_apply(x, w, s, t, os_, ot, res, relu_in, affine_in,
                      relu_out, interpret)
    return y, (x, w, s, t, os_, ot, res)


def _matmul_apply_vjp_bwd(relu_in, affine_in, relu_out, interpret,
                          primals, dy):
    # the apply path is an INFERENCE fold; a rare grad through it uses
    # autodiff of the reference expression (XLA-fused, exact)
    x, w, s, t, os_, ot, res = primals
    if res is None:
        def f(x, w, s, t, os_, ot):
            return _apply_ref(x, w, s, t, os_, ot, None, relu_in,
                              affine_in, relu_out)
        _, vjp = jax.vjp(f, x, w, s, t, os_, ot)
        return vjp(dy) + (None,)
    _, vjp = jax.vjp(
        lambda x, w, s, t, os_, ot, res: _apply_ref(
            x, w, s, t, os_, ot, res, relu_in, affine_in, relu_out),
        x, w, s, t, os_, ot, res)
    return vjp(dy)


_matmul_apply.defvjp(_matmul_apply_vjp_fwd, _matmul_apply_vjp_bwd)


def matmul_bn_apply(x: jnp.ndarray, w: jnp.ndarray,
                    in_scale: Optional[jnp.ndarray] = None,
                    in_shift: Optional[jnp.ndarray] = None,
                    relu_in: bool = False,
                    out_scale: Optional[jnp.ndarray] = None,
                    out_shift: Optional[jnp.ndarray] = None,
                    residual: Optional[jnp.ndarray] = None,
                    relu_out: bool = False,
                    interpret: Optional[bool] = None) -> jnp.ndarray:
    """Inference fold of ``relu(prologue(x) @ w · out_scale +
    out_shift + residual)`` — :func:`matmul_bn` for EVAL mode, where
    this BN's moving-stats fold (``out_scale``/``out_shift``) is known
    BEFORE the matmul, so the epilogue applies it (plus the residual
    add and ReLU) while the tile writes: the raw conv output and a
    separate whole-tensor apply pass never exist in HBM. Returns just
    ``y (M, N)`` (no statistics — eval uses moving stats)."""
    global invocations
    invocations += 1
    m, k = x.shape
    n = w.shape[1]
    if k % 64 or n % 64:
        raise ValueError(f"K={k} and N={n} must be 64-multiples")
    if interpret is None:
        interpret = jax.default_backend() not in ("tpu", "axon")
    affine_in = in_scale is not None or in_shift is not None
    f32 = jnp.float32
    s_v = (in_scale.astype(f32) if in_scale is not None else
           jnp.ones((k,), f32)).reshape(1, k)
    t_v = (in_shift.astype(f32) if in_shift is not None else
           jnp.zeros((k,), f32)).reshape(1, k)
    os_v = (out_scale.astype(f32) if out_scale is not None else
            jnp.ones((n,), f32)).reshape(1, n)
    ot_v = (out_shift.astype(f32) if out_shift is not None else
            jnp.zeros((n,), f32)).reshape(1, n)
    return _matmul_apply(x, w, s_v, t_v, os_v, ot_v, residual,
                         relu_in, affine_in, relu_out, bool(interpret))


def conv1x1_bn_apply(x: jnp.ndarray, w: jnp.ndarray, stride: int = 1,
                     residual: Optional[jnp.ndarray] = None,
                     **kwargs) -> jnp.ndarray:
    """NHWC wrapper over :func:`matmul_bn_apply` (eval fold).
    ``residual``: (N, H', W', F), added pre-ReLU."""
    if w.ndim == 4:
        w = w[0, 0]
    if stride != 1:
        x = x[:, ::stride, ::stride, :]
    b, h, wd, c = x.shape
    res2 = residual.reshape(b * h * wd, w.shape[-1]) \
        if residual is not None else None
    y2 = matmul_bn_apply(x.reshape(b * h * wd, c), w, residual=res2,
                         **kwargs)
    return y2.reshape(b, h, wd, w.shape[-1])


def conv1x1_bn(x: jnp.ndarray, w: jnp.ndarray, stride: int = 1,
               in_residual: Optional[jnp.ndarray] = None,
               **kwargs):
    """NHWC 1×1 conv + BN statistics via :func:`matmul_bn`.
    x: (N, H, W, C); w: (1, 1, C, F) or (C, F); ``in_residual``
    (N, H', W', C) joins the prologue (see `matmul_bn`). Returns
    ``(y (N, H', W', F), sum (F,), sumsq (F,))``."""
    if w.ndim == 4:
        w = w[0, 0]
    if stride != 1:
        x = x[:, ::stride, ::stride, :]
    b, h, wd, c = x.shape
    if in_residual is not None:
        kwargs["in_residual"] = in_residual.reshape(b * h * wd, c)
    y2, ssum, ssq = matmul_bn(x.reshape(b * h * wd, c), w, **kwargs)
    return y2.reshape(b, h, wd, w.shape[-1]), ssum, ssq


# ---------------------------------------------------------------------------
# 3×3 stride-1 SAME conv + BN (the residual-block 3×3s)
# ---------------------------------------------------------------------------

def _conv3_ref(x, w, s, t, sh, relu_in, affine_in, stride=1):
    """Reference expression for conv3x3_bn — the ground truth the
    kernel is tested against AND the function whose `jax.vjp` is the
    backward (exact gradients, standard XLA conv backward perf)."""
    f32 = jnp.float32
    xf = x.astype(f32)
    if affine_in:
        xf = xf * s[None, None, None, :] + t[None, None, None, :]
    if relu_in:
        xf = jnp.maximum(xf, 0.0)
    # compute-dtype conv without a promoted output type: the conv
    # transpose rule needs all three dtypes equal, so a promoted-f32
    # output makes bf16 autodiff through this expression crash.
    # conv_grad.conv2d == the same lax conv forward, but its backward
    # is gated between the transpose rule and the phase decomposition
    # (no dilated operand — ZOO_TPU_PHASE_BWD, trace-time)
    y = conv_grad.conv2d(
        xf.astype(x.dtype), w.astype(x.dtype),
        stride=(stride, stride), padding="SAME")
    d = y.astype(f32) - sh[None, None, None, :]
    return (y, jnp.sum(d, axis=(0, 1, 2)),
            jnp.sum(d * d, axis=(0, 1, 2)))


def _conv3_acc(x_ref, w_ref, s_ref, t_ref, relu_in, affine_in,
               stride):
    """3×3-tap compute SHARED by the stats and apply conv kernels:
    prologue (affine+ReLU) once on the full-plane tile, then the 3×3
    as shifted (bb·Ho·Wo, Cin)@(Cin, Cout) MXU taps accumulated in
    f32. ``stride=2`` (even H/W, SAME ⇒ pad (0,1)): each tap takes
    every other row/column via an even reshape — no strided loads.
    Returns (acc, bb, ho, wo, cout)."""
    xb = x_ref[...].astype(jnp.float32)
    if affine_in:
        xb = xb * s_ref[0, :] + t_ref[0, :]
    if relu_in:
        xb = jnp.maximum(xb, 0.0)
    xb = xb.astype(w_ref.dtype)
    bb, h, wd, cin = xb.shape
    cout = w_ref.shape[3]
    if stride == 1:
        ho, wo = h, wd
        xp = jnp.pad(xb, ((0, 0), (1, 1), (1, 1), (0, 0)))

        def tap(dh, dw):
            return jax.lax.slice(
                xp, (0, dh, dw, 0), (bb, dh + h, dw + wd, cin))
    else:
        ho, wo = h // 2, wd // 2
        # SAME @ stride 2, even extent: pad (0, 1); one extra row/col
        # of zeros keeps the every-other-row reshape even
        xp = jnp.pad(xb, ((0, 0), (0, 2), (0, 2), (0, 0)))

        def tap(dh, dw):
            win = jax.lax.slice(
                xp, (0, dh, dw, 0),
                (bb, dh + 2 * ho, dw + 2 * wo, cin))
            win = win.reshape(bb, ho, 2, wo, 2, cin)
            return win[:, :, 0, :, 0, :]
    acc = jnp.zeros((bb * ho * wo, cout), jnp.float32)
    for dh in range(3):
        for dw in range(3):
            acc += jax.lax.dot_general(
                tap(dh, dw).reshape(bb * ho * wo, cin), w_ref[dh, dw],
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
    return acc, bb, ho, wo, cout


def _conv3_kernel(x_ref, w_ref, s_ref, t_ref, sh_ref,
                  y_ref, sum_ref, sq_ref, *,
                  relu_in: bool, affine_in: bool, out_dtype,
                  stride: int = 1):
    """Grid (bi,): one batch tile, FULL spatial plane in VMEM — no
    halos; the epilogue reduces the accumulator for the BN
    statistics (compute path shared with `_conv3_apply_kernel`)."""
    bi = pl.program_id(0)
    acc, bb, ho, wo, cout = _conv3_acc(x_ref, w_ref, s_ref, t_ref,
                                       relu_in, affine_in, stride)
    y_ref[...] = acc.reshape(bb, ho, wo, cout).astype(out_dtype)
    d = acc - sh_ref[0, :]
    snew = jnp.sum(d, axis=0, keepdims=True)
    qnew = jnp.sum(d * d, axis=0, keepdims=True)

    @pl.when(bi == 0)
    def _first():
        sum_ref[...] = snew
        sq_ref[...] = qnew

    @pl.when(bi != 0)
    def _rest():
        sum_ref[...] += snew
        sq_ref[...] += qnew


def _conv3_apply_kernel(x_ref, w_ref, s_ref, t_ref, os_ref, ot_ref,
                        y_ref, *, relu_in: bool, affine_in: bool,
                        relu_out: bool, out_dtype, stride: int = 1):
    """Eval-mode conv3 epilogue: this BN's moving-stats fold (+ReLU)
    applies while the tile writes — no statistics, no separate
    whole-tensor apply pass (compute path shared with
    `_conv3_kernel`)."""
    acc, bb, ho, wo, cout = _conv3_acc(x_ref, w_ref, s_ref, t_ref,
                                       relu_in, affine_in, stride)
    y = acc * os_ref[0, :][None, :] + ot_ref[0, :][None, :]
    if relu_out:
        y = jnp.maximum(y, 0.0)
    y_ref[...] = y.reshape(bb, ho, wo, cout).astype(out_dtype)


def _conv3_apply_ref(x, w, s, t, os_, ot, relu_in, affine_in,
                     relu_out, stride):
    """Ground truth + autodiff backward for `conv3x3_bn_apply`."""
    f32 = jnp.float32
    xf = x.astype(f32)
    if affine_in:
        xf = xf * s.reshape(-1)[None, None, None, :] + \
            t.reshape(-1)[None, None, None, :]
    if relu_in:
        xf = jnp.maximum(xf, 0.0)
    y = jax.lax.conv_general_dilated(
        xf.astype(x.dtype), w.astype(x.dtype),
        window_strides=(stride, stride), padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    y = y.astype(f32) * os_.reshape(-1)[None, None, None, :] + \
        ot.reshape(-1)[None, None, None, :]
    if relu_out:
        y = jnp.maximum(y, 0.0)
    return y.astype(x.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 7, 8, 9, 10))
def _conv3_apply(x, w, s, t, os_, ot, relu_in, affine_in, relu_out,
                 stride, interpret):
    b, h, wd, cin = x.shape
    cout = w.shape[3]
    ho, wo = h // stride, wd // stride
    bb = _conv3_batch_tile(x.shape, cout,
                           jnp.dtype(x.dtype).itemsize, stride)
    return pl.pallas_call(
        functools.partial(_conv3_apply_kernel, relu_in=relu_in,
                          affine_in=affine_in, relu_out=relu_out,
                          out_dtype=jnp.dtype(x.dtype), stride=stride),
        grid=(b // bb,),
        in_specs=[
            pl.BlockSpec((bb, h, wd, cin), lambda bi: (bi, 0, 0, 0)),
            pl.BlockSpec((3, 3, cin, cout), lambda bi: (0, 0, 0, 0)),
            pl.BlockSpec((1, cin), lambda bi: (0, 0)),
            pl.BlockSpec((1, cin), lambda bi: (0, 0)),
            pl.BlockSpec((1, cout), lambda bi: (0, 0)),
            pl.BlockSpec((1, cout), lambda bi: (0, 0)),
        ],
        out_specs=pl.BlockSpec((bb, ho, wo, cout),
                               lambda bi: (bi, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((b, ho, wo, cout), x.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(x, w.astype(x.dtype), s, t, os_, ot)


def _conv3_apply_vjp_fwd(x, w, s, t, os_, ot, relu_in, affine_in,
                         relu_out, stride, interpret):
    y = _conv3_apply(x, w, s, t, os_, ot, relu_in, affine_in,
                     relu_out, stride, interpret)
    return y, (x, w, s, t, os_, ot)


def _conv3_apply_vjp_bwd(relu_in, affine_in, relu_out, stride,
                         interpret, primals, dy):
    # inference fold; a rare grad uses autodiff of the reference
    x, w, s, t, os_, ot = primals
    _, vjp = jax.vjp(
        lambda x, w, s, t, os_, ot: _conv3_apply_ref(
            x, w, s, t, os_, ot, relu_in, affine_in, relu_out,
            stride),
        x, w, s, t, os_, ot)
    return vjp(dy)


_conv3_apply.defvjp(_conv3_apply_vjp_fwd, _conv3_apply_vjp_bwd)


def conv3x3_bn_apply(x: jnp.ndarray, w: jnp.ndarray,
                     in_scale: Optional[jnp.ndarray] = None,
                     in_shift: Optional[jnp.ndarray] = None,
                     relu_in: bool = False,
                     out_scale: Optional[jnp.ndarray] = None,
                     out_shift: Optional[jnp.ndarray] = None,
                     relu_out: bool = False,
                     stride: int = 1,
                     interpret: Optional[bool] = None) -> jnp.ndarray:
    """Inference fold of the 3×3: :func:`conv3x3_bn` for EVAL mode —
    the known moving-stats fold (``out_scale``/``out_shift``) and ReLU
    apply in the epilogue; returns just ``y``. Same constraints as
    `conv3x3_bn`; oversized planes/odd strided extents fall back to
    the XLA reference expression."""
    global invocations
    invocations += 1
    if w.shape[:2] != (3, 3):
        raise ValueError(f"kernel must be 3x3, got {w.shape[:2]}")
    if stride not in (1, 2):
        raise ValueError(f"stride must be 1 or 2, got {stride}")
    cin, cout = w.shape[2], w.shape[3]
    if cin % 64 or cout % 64:
        raise ValueError(f"Cin={cin} and Cout={cout} must be "
                         "64-multiples")
    if interpret is None:
        interpret = jax.default_backend() not in ("tpu", "axon")
    affine_in = in_scale is not None or in_shift is not None
    f32 = jnp.float32
    s_v = (in_scale.astype(f32) if in_scale is not None else
           jnp.ones((cin,), f32))
    t_v = (in_shift.astype(f32) if in_shift is not None else
           jnp.zeros((cin,), f32))
    os_v = (out_scale.astype(f32) if out_scale is not None else
            jnp.ones((cout,), f32))
    ot_v = (out_shift.astype(f32) if out_shift is not None else
            jnp.zeros((cout,), f32))
    odd = stride == 2 and (x.shape[1] % 2 or x.shape[2] % 2)
    if odd or _conv3_batch_tile(x.shape, cout,
                                jnp.dtype(x.dtype).itemsize,
                                stride) is None:
        return _conv3_apply_ref(x, w, s_v, t_v, os_v, ot_v, relu_in,
                                affine_in, relu_out, stride)
    return _conv3_apply(x, w, s_v.reshape(1, cin), t_v.reshape(1, cin),
                        os_v.reshape(1, cout), ot_v.reshape(1, cout),
                        relu_in, affine_in, relu_out, int(stride),
                        bool(interpret))


def _conv3_batch_tile(shape, cout, itemsize, stride=1) -> Optional[int]:
    """Largest divisor of B whose full-plane residency (input tile +
    padded prologue copy + f32 accumulator + output tile + weights)
    fits the VMEM budget; None when even one image does not fit."""
    b, h, wd, cin = shape
    ho, wo = h // stride, wd // stride
    per_img = (h * wd * cin * itemsize +
               (h + 2) * (wd + 2) * cin * itemsize +
               ho * wo * cout * 4 +
               ho * wo * cout * itemsize)
    w_bytes = 9 * cin * cout * itemsize
    for cand in range(min(b, 16), 0, -1):
        if b % cand == 0 and \
                cand * per_img + w_bytes <= 6 * 2 ** 20:
            return cand
    return None


def _conv3_fwd_pallas(x, w, s, t, sh, relu_in, affine_in, stride,
                      interpret):
    b, h, wd, cin = x.shape
    cout = w.shape[3]
    ho, wo = h // stride, wd // stride
    bb = _conv3_batch_tile(x.shape, cout,
                           jnp.dtype(x.dtype).itemsize, stride)
    assert bb is not None  # conv3x3_bn falls back before reaching here
    f32 = jnp.float32
    y, ssum, ssq = pl.pallas_call(
        functools.partial(_conv3_kernel, relu_in=relu_in,
                          affine_in=affine_in,
                          out_dtype=jnp.dtype(x.dtype),
                          stride=stride),
        grid=(b // bb,),
        in_specs=[
            pl.BlockSpec((bb, h, wd, cin), lambda bi: (bi, 0, 0, 0)),
            pl.BlockSpec((3, 3, cin, cout), lambda bi: (0, 0, 0, 0)),
            pl.BlockSpec((1, cin), lambda bi: (0, 0)),
            pl.BlockSpec((1, cin), lambda bi: (0, 0)),
            pl.BlockSpec((1, cout), lambda bi: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bb, ho, wo, cout), lambda bi: (bi, 0, 0, 0)),
            pl.BlockSpec((1, cout), lambda bi: (0, 0)),
            pl.BlockSpec((1, cout), lambda bi: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, ho, wo, cout), x.dtype),
            jax.ShapeDtypeStruct((1, cout), f32),
            jax.ShapeDtypeStruct((1, cout), f32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(x, w.astype(x.dtype), s, t, sh)
    return y, ssum[0], ssq[0]


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8))
def _conv3(x, w, s, t, sh, relu_in, affine_in, stride, interpret):
    return _conv3_fwd_pallas(x, w, s, t, sh, relu_in, affine_in,
                             stride, interpret)


def _conv3_vjp_fwd(x, w, s, t, sh, relu_in, affine_in, stride,
                   interpret):
    out = _conv3_fwd_pallas(x, w, s, t, sh, relu_in, affine_in,
                            stride, interpret)
    y, _, _ = out
    return out, (x, w, s, t, sh, y)


def _same_pads_k3(sz, stride):
    """(lo, hi) SAME padding for the k=3 conv over extent ``sz``."""
    ho = -(-sz // stride)
    total = max((ho - 1) * stride + 3 - sz, 0)
    lo = total // 2
    return lo, total - lo


def _conv3_dilated_bwd(gc, wc, xpc, stride, hh, ww_):
    """jax's own conv transpose formulations written explicitly (the
    pre-phase-decomposition backward, kept for the ZOO_TPU_PHASE_BWD
    A/B): dXp slides the full kernel over the stride-DILATED
    cotangent, so at stride 2 three quarters of its MACs multiply
    inserted zeros (the executed-FLOPs excess ops.conv_grad removes).
    Padding algebra is the SAME-padding k=3 specialization of jax's
    _conv_general_vjp_{lhs,rhs}_padding."""
    f32 = jnp.float32

    def _pads(sz):
        ho = -(-sz // stride)               # SAME output extent
        total = max((ho - 1) * stride + 3 - sz, 0)
        lo = total // 2
        return lo, 1 + (ho - 1) * stride    # lo, dilated out size

    lo_h, od_h = _pads(hh)
    lo_w, od_w = _pads(ww_)
    # dXp: conv of the (stride-dilated) cotangent with the
    # spatially-reversed, I/O-swapped kernel
    dx_pad = ((2 - lo_h, (hh + 2) - od_h - (2 - lo_h)),
              (2 - lo_w, (ww_ + 2) - od_w - (2 - lo_w)))
    dxp = jax.lax.conv_general_dilated(
        gc, jax.lax.rev(wc, (0, 1)),
        window_strides=(1, 1), padding=dx_pad,
        lhs_dilation=(stride, stride), rhs_dilation=(1, 1),
        dimension_numbers=("NHWC", "HWOI", "NHWC"),
        preferred_element_type=f32)
    # dW: contract over batch — x' as ("CHWN") against the
    # stride-dilated cotangent as ("IHWO"), producing ("HWNC")
    dw_pad = ((lo_h, (od_h - hh) + (2 - lo_h)),
              (lo_w, (od_w - ww_) + (2 - lo_w)))
    dw = jax.lax.conv_general_dilated(
        xpc, gc, window_strides=(1, 1), padding=dw_pad,
        lhs_dilation=(1, 1), rhs_dilation=(stride, stride),
        dimension_numbers=("CHWN", "IHWO", "HWNC"),
        preferred_element_type=f32)
    return dxp, dw


def _conv3_vjp_bwd(relu_in, affine_in, stride, interpret, res, cots):
    """XLA backward: the conv is linear in each operand, so
    `jax.linear_transpose` gives dW/dxp without re-running the
    forward; the stats cotangents fold into the same augmented g as
    the matmul kernel's backward."""
    x, w, s, t, sh, y = res
    dy, dsum, dsq = cots
    f32 = jnp.float32
    g = dy.astype(f32) + dsum[None, None, None, :] + \
        2.0 * (y.astype(f32) - sh[0][None, None, None, :]) * \
        dsq[None, None, None, :]
    xf = x.astype(f32)
    if affine_in:
        xa = xf * s[0] + t[0]
    else:
        xa = xf
    xp = jnp.maximum(xa, 0.0) if relu_in else xa
    cd = x.dtype

    xpc = xp.astype(cd)
    wc = w.astype(cd)
    if os.environ.get("ZOO_TPU_CONV3_BWD_F32") == "1":
        # escape hatch: the round-4 f32-operand backward (for A/B and
        # numerics debugging)
        def conv(l, r):
            return jax.lax.conv_general_dilated(
                l.astype(f32), r.astype(f32),
                window_strides=(stride, stride), padding="SAME",
                dimension_numbers=("NHWC", "HWIO", "NHWC"))
        dw = jax.linear_transpose(lambda ww: conv(xpc, ww), wc)(g)[0]
        dxp = jax.linear_transpose(
            lambda xx: conv(xx, wc), xpc)(g)[0].astype(f32)
    else:
        # bf16-operand backward convs with f32 accumulation
        # (preferred_element_type) — full MXU rate, the standard
        # mixed-precision recipe (VERDICT r4 next-round #3). These are
        # jax's own conv transpose formulations written explicitly:
        # `linear_transpose` can't be used because the transpose rule
        # rebuilds a conv between the cotangent and the saved operand
        # and conv_general_dilated requires equal operand dtypes —
        # with f32 cotangents and bf16 residuals it crashes, and
        # casting the operands up (round 4) halves backward MXU
        # throughput. Padding algebra below is the SAME-padding k=3
        # specialization of jax's _conv_general_vjp_{lhs,rhs}_padding.
        gc = g.astype(cd)
        hh, ww_ = xp.shape[1], xp.shape[2]
        if stride != 1 and conv_grad.phase_bwd_enabled():
            # phase-decomposed backward (ops.conv_grad): same sums
            # reassociated into stride-1 convs over UNDILATED
            # operands — the executed-FLOPs lever; the dilated
            # formulation below wastes (s^2-1)/s^2 of its dx MACs on
            # inserted zeros
            sp = tuple(_same_pads_k3(sz, stride) for sz in (hh, ww_))
            dxp = conv_grad.phase_dx(
                gc, wc, (hh, ww_), (stride, stride), sp,
                preferred_element_type=f32)
            dw = conv_grad.phase_dw(
                xpc, gc, (3, 3), (stride, stride), sp,
                preferred_element_type=f32)
        else:
            dxp, dw = _conv3_dilated_bwd(gc, wc, xpc, stride, hh,
                                         ww_)
    if relu_in:
        dxp = jnp.where(xa > 0.0, dxp, 0.0)
    if affine_in:
        dx = (dxp * s[0]).astype(x.dtype)
        ds = jnp.sum(dxp * xf, axis=(0, 1, 2)).reshape(1, -1)
        dt = jnp.sum(dxp, axis=(0, 1, 2)).reshape(1, -1)
    else:
        dx = dxp.astype(x.dtype)
        ds = jnp.zeros_like(s)
        dt = jnp.zeros_like(t)
    return (dx, dw.astype(w.dtype), ds.astype(s.dtype),
            dt.astype(t.dtype), jnp.zeros_like(sh))


_conv3.defvjp(_conv3_vjp_fwd, _conv3_vjp_bwd)


def conv3x3_bn(x: jnp.ndarray, w: jnp.ndarray,
               in_scale: Optional[jnp.ndarray] = None,
               in_shift: Optional[jnp.ndarray] = None,
               relu_in: bool = False,
               stat_shift: Optional[jnp.ndarray] = None,
               stride: int = 1,
               interpret: Optional[bool] = None):
    """Fused 3×3 SAME conv + BN statistics (the VERDICT r3 target:
    the residual-block 3×3s). x: (B, H, W, Cin); w: (3, 3, Cin, Cout),
    Cin/Cout 64-multiples; ``stride`` 1 or 2 (2 covers the stage-
    transition blocks — VERDICT r4 lever; even H/W required, else the
    XLA reference path). Prologue/epilogue and returns exactly like
    :func:`matmul_bn`; ``stat_shift`` must be non-differentiated (pass
    the BN's moving mean stop-gradded — its cotangent is defined as
    zero, like matmul_bn's). Backward runs as two explicit XLA
    transpose convs with compute-dtype (bf16) operands and f32
    accumulation (`ZOO_TPU_CONV3_BWD_F32=1` selects the f32-operand
    `linear_transpose` form instead). Planes too large for a
    one-image VMEM tile fall back to the XLA reference expression."""
    global invocations
    invocations += 1
    if w.shape[:2] != (3, 3):
        raise ValueError(f"kernel must be 3x3, got {w.shape[:2]}")
    if stride not in (1, 2):
        raise ValueError(f"stride must be 1 or 2, got {stride}")
    cin, cout = w.shape[2], w.shape[3]
    if cin % 64 or cout % 64:
        raise ValueError(f"Cin={cin} and Cout={cout} must be "
                         "64-multiples")
    if interpret is None:
        interpret = jax.default_backend() not in ("tpu", "axon")
    affine_in = in_scale is not None or in_shift is not None
    f32 = jnp.float32
    s_v = (in_scale.astype(f32) if in_scale is not None else
           jnp.ones((cin,), f32))
    t_v = (in_shift.astype(f32) if in_shift is not None else
           jnp.zeros((cin,), f32))
    sh_v = (stat_shift.astype(f32) if stat_shift is not None else
            jnp.zeros((cout,), f32))
    odd = stride == 2 and (x.shape[1] % 2 or x.shape[2] % 2)
    if odd or _conv3_batch_tile(x.shape, cout,
                                jnp.dtype(x.dtype).itemsize,
                                stride) is None:
        # plane too large for VMEM (or odd strided extent): the
        # reference expression (autodiff supplies the same gradients
        # the custom path computes)
        return _conv3_ref(x, w, s_v, t_v, sh_v, relu_in, affine_in,
                          stride)
    return _conv3(x, w, s_v.reshape(1, cin), t_v.reshape(1, cin),
                  sh_v.reshape(1, cout), relu_in, affine_in,
                  int(stride), bool(interpret))


# -- autotuner specs --------------------------------------------------------
# Registered here so the legacy env flag stays read under ops/ (the
# lint override gate) and the probes exercise the real custom_vjp
# call sites via autotune.forced(), not a reimplementation.

def _blocks_heuristic(p):
    bm, bk = _heuristic_blocks(p["m"], p["k"], p["n"], p["isz"])
    return {"bm": bm, "bk": bk}


def _blocks_candidates(p):
    """Every (bm, bk) pair that divides the problem and respects the
    dtype-aware ~6MB VMEM budget — the same feasibility rule the
    heuristic enforces, enumerated instead of solved greedily."""
    k, n, isz = p["k"], p["n"], p["isz"]
    bks = [b for b in (512, 384, 256, 128, 64) if k % b == 0] \
        if k > 512 else [k]
    return [{"bm": bm, "bk": bk}
            for bk in bks
            for bm in (512, 256, 128)
            if bm * n * 4 + (bm * bk + bk * n) * isz <= 6 * 2 ** 20]


def _fused_probe_operands(p):
    import numpy as np
    rs = np.random.RandomState(0)
    m, k, n = p["m"], p["k"], p["n"]
    dt = jnp.float32 if p.get("isz", 2) >= 4 else jnp.bfloat16
    x = jnp.asarray(rs.randn(m, k), dt)
    w = jnp.asarray(rs.randn(k, n) * 0.05, dt)
    s = jnp.asarray(rs.rand(1, k) + 0.5, jnp.float32)
    t = jnp.asarray(rs.randn(1, k), jnp.float32)
    sh = jnp.zeros((1, n), jnp.float32)
    return x, w, s, t, sh


def _blocks_runner(p, cfg):
    m, k, n = p["m"], p["k"], p["n"]
    if k % 64 or n % 64 or m % 8:
        return None
    interpret = jax.default_backend() not in ("tpu", "axon")
    if interpret and m * k > (1 << 18):
        return None            # interpreter budget off-chip
    x, w, s, t, sh = _fused_probe_operands(p)

    @jax.jit
    def probe(x, w, s, t, sh):
        y, su, sq = _matmul_bn(x, w, s, t, sh, None, True, True,
                               interpret)
        return (jnp.sum(y.astype(jnp.float32)) + jnp.sum(su) +
                jnp.sum(sq))

    def run():
        # forced() pins the candidate through the real _pick_blocks
        # call at trace time (first call, inside expected_compiles)
        with autotune.forced("conv_bn_blocks", cfg):
            jax.block_until_ready(probe(x, w, s, t, sh))
    return run


def _bwd_flag(p):
    env = os.environ.get("ZOO_TPU_CONV_BN_PALLAS_BWD")
    if env is None:
        return None
    return {"pallas": env == "1"}


def _bwd_runner(p, cfg):
    m, k, n = p["m"], p["k"], p["n"]
    if k % 64 or n % 64 or m % 8:
        return None
    interpret = jax.default_backend() not in ("tpu", "axon")
    if interpret and m * k > (1 << 18):
        return None
    x, w, s, t, sh = _fused_probe_operands(p)

    @jax.jit
    def probe(x, w, s, t, sh):
        def loss(x, w):
            y, su, sq = _matmul_bn(x, w, s, t, sh, None, True, True,
                                   interpret)
            return (jnp.sum(y.astype(jnp.float32)) + jnp.sum(su) +
                    jnp.sum(sq))
        val, (dx, dw) = jax.value_and_grad(loss, argnums=(0, 1))(x, w)
        return (val + jnp.sum(dx.astype(jnp.float32)) +
                jnp.sum(dw.astype(jnp.float32)))

    def run():
        with autotune.forced("conv_bn_bwd", cfg):
            jax.block_until_ready(probe(x, w, s, t, sh))
    return run


autotune.register(autotune.OpSpec(
    "conv_bn_blocks", heuristic=_blocks_heuristic,
    candidates=_blocks_candidates, runner=_blocks_runner))

autotune.register(autotune.OpSpec(
    "conv_bn_bwd",
    heuristic=lambda p: {"pallas": True},
    candidates=lambda p: [{"pallas": True}, {"pallas": False}],
    flag_value=_bwd_flag, runner=_bwd_runner))
