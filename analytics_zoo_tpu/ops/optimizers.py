"""Optimizers + learning-rate schedules.

Reference surface: zoo `Adam` with schedules
(`Z/pipeline/api/keras/optimizers/Adam.scala:124`) and BigDL optim methods
(SGD + Poly/Warmup used by the Inception recipe,
`examples/inception/Train.scala:78-89`), plus the TF→BigDL translation
table (`P/pipeline/api/net.py:592-688`).

Here every optim method is an optax `GradientTransformation` factory with
a Keras-style class facade. The gradient all-reduce the reference did via
Spark shuffle is implicit: grads of a pjit'd loss over a sharded batch
come out already averaged across devices.
"""

from __future__ import annotations

from typing import Callable, Optional, Union

import optax

ScheduleLike = Union[float, Callable[[int], float]]


# -- LR schedules -----------------------------------------------------------

def poly(lr: float, power: float = 0.5, max_iteration: int = 100000,
         end_lr: float = 0.0):
    """BigDL `SGD.Poly` (Inception recipe, Train.scala:83)."""
    return optax.polynomial_schedule(
        init_value=lr, end_value=end_lr, power=power,
        transition_steps=max_iteration)


def warmup(base_lr: float, warmup_iterations: int, delta: float = 0.0,
           after: Optional[Callable[[int], float]] = None):
    """BigDL `SGD.Warmup`: linear ramp from base_lr by `delta` per
    iteration for `warmup_iterations`, then `after` (Train.scala:78-89)."""
    peak = base_lr + delta * warmup_iterations
    ramp = optax.linear_schedule(base_lr, peak, warmup_iterations)
    if after is None:
        return ramp
    return optax.join_schedules([ramp, after], [warmup_iterations])


def exponential_decay(lr: float, decay_rate: float, decay_steps: int,
                      staircase: bool = False):
    return optax.exponential_decay(lr, decay_steps, decay_rate,
                                   staircase=staircase)


def step_decay(lr: float, step_size: int, gamma: float = 0.1):
    return optax.exponential_decay(lr, step_size, gamma, staircase=True)


def plateau(lr: float, *args, **kwargs):
    raise NotImplementedError(
        "metric-reactive Plateau schedules are host-driven; use "
        "Estimator's reduce_lr_on_plateau hook (planned) or a step "
        "schedule")


# -- optim methods ----------------------------------------------------------

class ZooOptimizer:
    """Base facade: `to_optax()` yields the GradientTransformation."""

    def __init__(self, lr: ScheduleLike = 1e-3):
        self.lr = lr

    def _lr(self):
        return self.lr

    def to_optax(self) -> optax.GradientTransformation:
        raise NotImplementedError

    def __repr__(self):
        return f"{type(self).__name__}(lr={self.lr})"


class SGD(ZooOptimizer):
    def __init__(self, lr: ScheduleLike = 0.01, momentum: float = 0.0,
                 dampening: float = 0.0, nesterov: bool = False,
                 weight_decay: float = 0.0):
        super().__init__(lr)
        self.momentum = momentum
        self.nesterov = nesterov
        self.weight_decay = weight_decay

    def to_optax(self):
        parts = []
        if self.weight_decay:
            parts.append(optax.add_decayed_weights(self.weight_decay))
        parts.append(optax.sgd(self._lr(),
                               momentum=self.momentum or None,
                               nesterov=self.nesterov))
        return optax.chain(*parts)


class Adam(ZooOptimizer):
    """(reference zoo `keras/optimizers/Adam.scala:124` — Adam with an
    attachable schedule.)"""

    def __init__(self, lr: ScheduleLike = 1e-3, beta_1: float = 0.9,
                 beta_2: float = 0.999, epsilon: float = 1e-8,
                 weight_decay: float = 0.0):
        super().__init__(lr)
        self.beta_1, self.beta_2, self.epsilon = beta_1, beta_2, epsilon
        self.weight_decay = weight_decay

    def to_optax(self):
        if self.weight_decay:
            return optax.adamw(self._lr(), b1=self.beta_1, b2=self.beta_2,
                               eps=self.epsilon,
                               weight_decay=self.weight_decay)
        return optax.adam(self._lr(), b1=self.beta_1, b2=self.beta_2,
                          eps=self.epsilon)


class AdamW(Adam):
    def __init__(self, lr=1e-3, weight_decay=0.01, **kw):
        super().__init__(lr, weight_decay=weight_decay, **kw)


class RMSprop(ZooOptimizer):
    def __init__(self, lr: ScheduleLike = 1e-3, decay_rate: float = 0.9,
                 epsilon: float = 1e-8):
        super().__init__(lr)
        self.decay_rate = decay_rate
        self.epsilon = epsilon

    def to_optax(self):
        return optax.rmsprop(self._lr(), decay=self.decay_rate,
                             eps=self.epsilon)


class Adagrad(ZooOptimizer):
    def to_optax(self):
        return optax.adagrad(self._lr())


class Adadelta(ZooOptimizer):
    def __init__(self, lr: ScheduleLike = 1.0, rho: float = 0.95,
                 epsilon: float = 1e-8):
        super().__init__(lr)
        self.rho = rho
        self.epsilon = epsilon

    def to_optax(self):
        return optax.adadelta(self._lr(), rho=self.rho, eps=self.epsilon)


class Adamax(ZooOptimizer):
    def to_optax(self):
        return optax.adamax(self._lr())


_REGISTRY = {
    "sgd": SGD,
    "adam": Adam,
    "adamw": AdamW,
    "rmsprop": RMSprop,
    "adagrad": Adagrad,
    "adadelta": Adadelta,
    "adamax": Adamax,
}


def get(spec: "str | ZooOptimizer | optax.GradientTransformation"):
    """Resolve to an optax GradientTransformation."""
    if isinstance(spec, ZooOptimizer):
        return spec.to_optax()
    if isinstance(spec, optax.GradientTransformation):
        return spec
    key = spec.lower()
    if key not in _REGISTRY:
        raise ValueError(f"unknown optimizer '{spec}'; known: "
                         f"{sorted(_REGISTRY)}")
    return _REGISTRY[key]().to_optax()
