"""Objectives (losses), Keras-1 names and semantics.

Reference surface: `Z/pipeline/api/keras/objectives/` — 15 losses
(SURVEY.md §2.4): BCE, CCE, SparseCCE, ClassNLL, MSE/MAE/MAPE/MSLE,
Hinge/SquaredHinge/RankHinge, KLD, Poisson, CosineProximity.

Every loss is a pure ``fn(y_true, y_pred) -> scalar`` (mean over the
batch), traceable and differentiable; under pjit the mean over a sharded
batch compiles to a cross-device all-reduce automatically.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp

EPSILON = 1e-7

LossFn = Callable[[jnp.ndarray, jnp.ndarray], jnp.ndarray]


def mean_squared_error(y_true, y_pred):
    return jnp.mean(jnp.square(y_pred - y_true))


def mean_absolute_error(y_true, y_pred):
    return jnp.mean(jnp.abs(y_pred - y_true))


def mean_absolute_percentage_error(y_true, y_pred):
    diff = jnp.abs((y_true - y_pred) /
                   jnp.clip(jnp.abs(y_true), EPSILON, None))
    return 100.0 * jnp.mean(diff)


def mean_squared_logarithmic_error(y_true, y_pred):
    a = jnp.log(jnp.clip(y_pred, EPSILON, None) + 1.0)
    b = jnp.log(jnp.clip(y_true, EPSILON, None) + 1.0)
    return jnp.mean(jnp.square(a - b))


def binary_crossentropy(y_true, y_pred):
    p = jnp.clip(y_pred, EPSILON, 1.0 - EPSILON)
    return jnp.mean(-(y_true * jnp.log(p) +
                      (1.0 - y_true) * jnp.log(1.0 - p)))


def categorical_crossentropy(y_true, y_pred):
    p = jnp.clip(y_pred, EPSILON, 1.0)
    per_sample = -jnp.sum(y_true * jnp.log(p), axis=-1)
    return jnp.mean(per_sample)


def sparse_categorical_crossentropy(y_true, y_pred):
    labels = y_true.astype(jnp.int32)
    if labels.ndim == y_pred.ndim:
        labels = labels[..., 0]
    p = jnp.clip(y_pred, EPSILON, 1.0)
    logp = jnp.log(p)
    picked = jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
    return -jnp.mean(picked)


def class_nll(y_true, y_pred):
    """Negative log-likelihood over log-probabilities (BigDL
    `ClassNLLCriterion` semantics with 0-based labels; pair with a
    log_softmax output)."""
    labels = y_true.astype(jnp.int32)
    if labels.ndim == y_pred.ndim:
        labels = labels[..., 0]
    picked = jnp.take_along_axis(y_pred, labels[..., None], axis=-1)[..., 0]
    return -jnp.mean(picked)


def softmax_cross_entropy(y_true, y_pred):
    """Stable fused log-softmax CE over *logits* with sparse int labels
    (TPU-preferred: avoids materializing probabilities; the BigDL analog
    is CrossEntropyCriterion = LogSoftMax + ClassNLL)."""
    labels = y_true.astype(jnp.int32)
    if labels.ndim == y_pred.ndim:
        labels = labels[..., 0]
    logp = jax.nn.log_softmax(y_pred.astype(jnp.float32), axis=-1)
    picked = jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
    return -jnp.mean(picked)


def sigmoid_cross_entropy(y_true, y_pred):
    """Stable BCE over logits."""
    z = y_pred.astype(jnp.float32)
    t = y_true.astype(jnp.float32)
    return jnp.mean(jnp.maximum(z, 0) - z * t +
                    jnp.log1p(jnp.exp(-jnp.abs(z))))


def hinge(y_true, y_pred):
    return jnp.mean(jnp.maximum(1.0 - y_true * y_pred, 0.0))


def squared_hinge(y_true, y_pred):
    return jnp.mean(jnp.square(jnp.maximum(1.0 - y_true * y_pred, 0.0)))


def rank_hinge(y_true, y_pred, margin: float = 1.0):
    """Pairwise ranking hinge (reference `objectives/RankHinge.scala`,
    used by KNRM text matching): batch rows alternate
    positive, negative, positive, negative, ...; y_true is ignored."""
    scores = y_pred.reshape(-1)
    pos = scores[0::2]
    neg = scores[1::2]
    return jnp.mean(jnp.maximum(margin - pos + neg, 0.0))


def kullback_leibler_divergence(y_true, y_pred):
    t = jnp.clip(y_true, EPSILON, 1.0)
    p = jnp.clip(y_pred, EPSILON, 1.0)
    return jnp.mean(jnp.sum(t * jnp.log(t / p), axis=-1))


def poisson(y_true, y_pred):
    return jnp.mean(y_pred - y_true * jnp.log(y_pred + EPSILON))


def cosine_proximity(y_true, y_pred):
    t = y_true / jnp.maximum(
        jnp.linalg.norm(y_true, axis=-1, keepdims=True), EPSILON)
    p = y_pred / jnp.maximum(
        jnp.linalg.norm(y_pred, axis=-1, keepdims=True), EPSILON)
    return -jnp.mean(jnp.sum(t * p, axis=-1))


_REGISTRY: "dict[str, LossFn]" = {
    "mean_squared_error": mean_squared_error,
    "mse": mean_squared_error,
    "mean_absolute_error": mean_absolute_error,
    "mae": mean_absolute_error,
    "mean_absolute_percentage_error": mean_absolute_percentage_error,
    "mape": mean_absolute_percentage_error,
    "mean_squared_logarithmic_error": mean_squared_logarithmic_error,
    "msle": mean_squared_logarithmic_error,
    "binary_crossentropy": binary_crossentropy,
    "categorical_crossentropy": categorical_crossentropy,
    "sparse_categorical_crossentropy": sparse_categorical_crossentropy,
    "class_nll": class_nll,
    "softmax_cross_entropy": softmax_cross_entropy,
    "sparse_categorical_crossentropy_from_logits": softmax_cross_entropy,
    "sigmoid_cross_entropy": sigmoid_cross_entropy,
    "hinge": hinge,
    "squared_hinge": squared_hinge,
    "rank_hinge": rank_hinge,
    "kullback_leibler_divergence": kullback_leibler_divergence,
    "kld": kullback_leibler_divergence,
    "poisson": poisson,
    "cosine_proximity": cosine_proximity,
}


def get(name: "str | LossFn") -> LossFn:
    if callable(name):
        return name
    key = name.lower()
    if key not in _REGISTRY:
        raise ValueError(f"unknown loss '{name}'; known: "
                         f"{sorted(_REGISTRY)}")
    return _REGISTRY[key]
