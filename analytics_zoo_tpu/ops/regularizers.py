"""Weight regularizers (L1/L2), Keras-1 style.

(reference: `wRegularizer`/`bRegularizer` args on layers, BigDL
`L1L2Regularizer`; loss contribution added during training.)
"""

from __future__ import annotations

from typing import Callable, Optional

import jax.numpy as jnp

Regularizer = Callable[[jnp.ndarray], jnp.ndarray]


class L1L2:
    def __init__(self, l1: float = 0.0, l2: float = 0.0):
        self.l1 = float(l1)
        self.l2 = float(l2)

    def __call__(self, w: jnp.ndarray) -> jnp.ndarray:
        loss = jnp.zeros((), dtype=jnp.float32)
        if self.l1:
            loss = loss + self.l1 * jnp.sum(jnp.abs(w)).astype(jnp.float32)
        if self.l2:
            loss = loss + self.l2 * jnp.sum(jnp.square(w)).astype(jnp.float32)
        return loss

    def __repr__(self):
        return f"L1L2(l1={self.l1}, l2={self.l2})"


def l1(v: float = 0.01) -> L1L2:
    return L1L2(l1=v)


def l2(v: float = 0.01) -> L1L2:
    return L1L2(l2=v)


def l1l2(v1: float = 0.01, v2: float = 0.01) -> L1L2:
    return L1L2(l1=v1, l2=v2)


def get(spec) -> Optional[Regularizer]:
    if spec is None:
        return None
    if callable(spec):
        return spec
    if isinstance(spec, str):
        name = spec.lower()
        if name == "l1":
            return l1()
        if name == "l2":
            return l2()
        if name in ("l1l2", "l1_l2"):
            return l1l2()
    raise ValueError(f"unknown regularizer {spec!r}")
