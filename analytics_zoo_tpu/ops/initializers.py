"""Weight initializers (Keras-1 names).

The reference's layers take `init = "glorot_uniform"`-style strings that
BigDL resolves to init methods; here they resolve to `jax.nn.initializers`
functions. (reference: `Z/pipeline/api/keras/layers/Dense.scala` `init` arg.)
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax.nn import initializers as jinit

Initializer = Callable[..., jnp.ndarray]


def _uniform_scale(scale=0.05):
    def init(key, shape, dtype=jnp.float32):
        return jax.random.uniform(key, shape, dtype, -scale, scale)
    return init


def _normal_scale(stddev=0.05):
    def init(key, shape, dtype=jnp.float32):
        return jax.random.normal(key, shape, dtype) * stddev
    return init


def _identity():
    def init(key, shape, dtype=jnp.float32):
        if len(shape) != 2 or shape[0] != shape[1]:
            raise ValueError("identity init requires a square 2D shape, "
                             f"got {shape}")
        return jnp.eye(shape[0], dtype=dtype)
    return init


_REGISTRY: "dict[str, Callable[[], Initializer]]" = {
    "glorot_uniform": lambda: jinit.glorot_uniform(),
    "glorot_normal": lambda: jinit.glorot_normal(),
    "xavier": lambda: jinit.glorot_uniform(),
    "he_uniform": lambda: jinit.he_uniform(),
    "he_normal": lambda: jinit.he_normal(),
    "lecun_uniform": lambda: jinit.lecun_uniform(),
    "lecun_normal": lambda: jinit.lecun_normal(),
    "orthogonal": lambda: jinit.orthogonal(),
    "uniform": lambda: _uniform_scale(),
    "normal": lambda: _normal_scale(),
    "zero": lambda: jinit.zeros,
    "zeros": lambda: jinit.zeros,
    "one": lambda: jinit.ones,
    "ones": lambda: jinit.ones,
    "identity": lambda: _identity(),
}


class NamedInitializer:
    """Picklable by-name initializer (jax initializer factories return
    closures, which would make every layer object unpicklable)."""

    def __init__(self, name: str):
        if name not in _REGISTRY:
            raise ValueError(
                f"unknown initializer '{name}'; known: "
                f"{sorted(_REGISTRY)}")
        self.name = name

    def __call__(self, key, shape, dtype=jnp.float32):
        return _REGISTRY[self.name]()(key, shape, dtype)

    def __repr__(self):
        return f"NamedInitializer({self.name})"


def get(name: "str | Initializer | None") -> Initializer:
    """Resolve an initializer by Keras name (or pass a callable through)."""
    if name is None:
        return NamedInitializer("glorot_uniform")
    if callable(name):
        return name
    return NamedInitializer(name.lower())
