"""Attention ops.

The reference's longest context is BERT-512 with dense attention inside
`TransformerLayer.scala`/`BERT.scala` (SURVEY.md §5 "Long-context:
absent"). Here attention is a first-class op with two interchangeable
implementations:

- :func:`dot_product_attention` — plain XLA (fused by the compiler),
  or the Pallas flash kernel (`impl="flash"` / ``ZOO_TPU_ATTENTION``
  env, `ops.flash_attention`) which keeps softmax statistics in VMEM
  instead of materialising the (B, H, Tq, Tk) logits in HBM;
- `parallel.ring_attention` — sequence-parallel ring attention over a
  mesh axis for long contexts (K/V blocks rotate over ICI while each
  device accumulates flash-style softmax statistics).
- `parallel.ulysses` — all-to-all head-repartition sequence
  parallelism (two large collectives instead of n ring rounds; needs
  heads % axis == 0).

Ring shares this module's blockwise-softmax accumulation math, so
ring == dense numerically; ulysses runs ordinary dense attention
locally after the head all-to-all (both tested to 1e-5 vs dense).
"""

from __future__ import annotations

import os
from typing import Optional

import jax
import jax.numpy as jnp

from analytics_zoo_tpu.perf import autotune


def resolve_attention_impl(impl: Optional[str]) -> str:
    """Resolve an attention-impl selector: None → ``ZOO_TPU_ATTENTION``
    env (default "auto" — the Pallas flash kernel whenever it wins);
    validates against the known impls. The single copy of this policy —
    used by dot_product_attention, the sequence-parallel attentions,
    and the transformer layers."""
    impl = impl or os.environ.get("ZOO_TPU_ATTENTION", "auto")
    if impl not in ("xla", "flash", "auto"):
        raise ValueError(f"unknown attention impl {impl!r}")
    return impl


def flash_backend_ok() -> bool:
    """Whether "auto" may route to the Pallas kernel on this backend:
    real TPU, or anywhere when ``ZOO_TPU_FLASH_FORCE_INTERPRET=1``
    (CPU kernel-coverage tests). Explicit ``impl="flash"`` ignores
    this and runs the interpreter off-TPU."""
    if os.environ.get("ZOO_TPU_FLASH_FORCE_INTERPRET") == "1":
        return True
    return jax.default_backend() in ("tpu", "axon")


def flash_profitable(tk: int) -> bool:
    """Whether flash beats XLA dense at this key length. Measured on
    the v5e (fwd+bwd, B=4 H=16 D=64 bf16, causal): dense wins at
    Tk ≤ 512 (0.48x/0.13x at 256/512), flash wins from 1024 up
    (1.82x/2.47x/3.7x at 1024/2048/4096 — PERF.md); that 1024
    crossover is now the autotuner heuristic for the
    "attn_crossover" op, and swept winners override it per (Tk,
    device). ``ZOO_TPU_FLASH_MIN_T`` set bypasses the tuner
    verbatim (source="flag")."""
    return bool(autotune.decide("attn_crossover",
                                {"tk": tk})["use_flash"])


def decode_flash_profitable(tk: int) -> bool:
    """Whether the Pallas decode kernel beats XLA dense single-query
    attention at this cached length. A 1-query attention is tiny —
    the dense logits are only (S, H, 1, Tk) — so the kernel's win is
    HBM traffic at long contexts, not FLOPs; the crossover sits
    higher than the training kernel's (heuristic 2048, tuned per
    device as the "decode_crossover" op).
    ``ZOO_TPU_DECODE_FLASH_MIN_T`` set bypasses the tuner verbatim
    (source="flag")."""
    return bool(autotune.decide("decode_crossover",
                                {"tk": tk})["use_flash"])


def decode_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                     seq_lens: jnp.ndarray,
                     scale: Optional[float] = None,
                     impl: Optional[str] = None,
                     k_scales: Optional[jnp.ndarray] = None,
                     v_scales: Optional[jnp.ndarray] = None
                     ) -> jnp.ndarray:
    """Single-query (decode-mode) attention against a cached context.

    The generation-time sibling of :func:`dot_product_attention`,
    sharing its impl selector: ``q`` is ONE new token per slot,
    (S, H, D); ``k``/``v`` are the gathered cache, (S, T, H, D) (the
    dense view from `ops.kv_cache.gather_layer`); ``seq_lens`` (S,)
    int32 masks positions ``>= seq_lens[s]`` (stale pages, pad rows).
    Returns (S, H, D). Softmax in f32 regardless of input dtype.

    Int8 caches pass the gathered views still quantized plus their
    per-row scales ``k_scales``/``v_scales`` (S, T, H): dequant
    happens here, at the consumption boundary, so the model layer
    never touches quantization (the flash path forwards the scales
    into `flash_decode_attention`, which dequantizes at its gather).

    Routing mirrors the training path: "auto" takes the Pallas decode
    kernel (`ops.flash_attention.flash_decode_attention`, which
    reuses the flash block machinery with the query replicated across
    one sublane tile) when the backend qualifies, T is 128-divisible,
    and T is past the decode crossover (`decode_flash_profitable` —
    higher than the training crossover because single-query dense is
    so cheap); otherwise XLA dense. No causal mask is needed — the
    cache only ever holds positions the new token may see.
    """
    impl = resolve_attention_impl(impl)
    d = q.shape[-1]
    t = k.shape[1]
    scale = float(scale if scale is not None else 1.0 / (d ** 0.5))
    use_kernel = t % 128 == 0 and d <= 256 and (
        impl == "flash" or (impl == "auto" and flash_backend_ok()
                            and decode_flash_profitable(t)))
    if use_kernel:
        from analytics_zoo_tpu.ops import flash_attention as fa
        key_mask = (jnp.arange(t, dtype=jnp.int32)[None, :] <
                    seq_lens[:, None])
        return fa.flash_decode_attention(q, k, v, key_mask,
                                         scale=scale,
                                         k_scales=k_scales,
                                         v_scales=v_scales)
    if k_scales is not None:
        from analytics_zoo_tpu.ops import kv_cache as kvc
        k = kvc.dequantize_rows(k, k_scales, q.dtype)
        v = kvc.dequantize_rows(v, v_scales, q.dtype)
    # dense: (S, H, 1, T) logits never materialise more than one
    # query row per slot — already cheap at serving contexts
    logits = jnp.einsum("shd,sthd->sht", q, k).astype(jnp.float32)
    logits = logits * scale
    valid = (jnp.arange(t, dtype=jnp.int32)[None, None, :] <
             seq_lens[:, None, None])
    logits = jnp.where(valid, logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("sht,sthd->shd", probs, v)


def chunk_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                    q_positions: jnp.ndarray,
                    scale: Optional[float] = None,
                    k_scales: Optional[jnp.ndarray] = None,
                    v_scales: Optional[jnp.ndarray] = None
                    ) -> jnp.ndarray:
    """Multi-query decode attention for a CHUNK of new tokens per
    slot — the workhorse of chunked prefill and speculative verify.

    ``q``: (S, C, H, D) — C new tokens per slot at absolute positions
    ``q_positions`` (S, C); ``k``/``v``: (S, T, H, D) gathered cache
    views that ALREADY contain the chunk's own rows (callers scatter
    before gathering, exactly like `decode_step`). The mask
    ``key_pos <= q_pos`` then yields both intra-chunk causality and
    validity in one comparison: every cache position at or before a
    query's own position is a real token of that slot, everything
    after (stale pages, the chunk's later rows) is invisible. Rows of
    inactive slots produce garbage that callers drop — with every
    key masked the f32 softmax degrades to uniform, never NaN.

    Dense XLA only: chunks are small (C ≪ T) and the (S, H, C, T)
    logits are MXU-shaped already; the single-query Pallas kernel's
    HBM win does not apply at C > 1 sublane occupancy. Int8 caches
    pass scales as in :func:`decode_attention`. Returns (S, C, H, D).
    """
    d = q.shape[-1]
    t = k.shape[1]
    scale = float(scale if scale is not None else 1.0 / (d ** 0.5))
    if k_scales is not None:
        from analytics_zoo_tpu.ops import kv_cache as kvc
        k = kvc.dequantize_rows(k, k_scales, q.dtype)
        v = kvc.dequantize_rows(v, v_scales, q.dtype)
    logits = jnp.einsum("schd,sthd->shct", q, k).astype(jnp.float32)
    logits = logits * scale
    visible = (jnp.arange(t, dtype=jnp.int32)[None, None, :] <=
               q_positions[:, :, None])                   # (S, C, T)
    logits = jnp.where(visible[:, None], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("shct,sthd->schd", probs, v)


def dot_product_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                          mask: Optional[jnp.ndarray] = None,
                          causal: bool = False,
                          scale: Optional[float] = None,
                          impl: Optional[str] = None) -> jnp.ndarray:
    """Standard attention. q,k,v: (B, T, H, D) → (B, T, H, D).

    `mask`: broadcastable to (B, H, Tq, Tk), 1 = attend. Softmax in f32
    regardless of input dtype (bf16-safe).

    `impl`: "auto" (the default: Pallas flash kernel when the problem
    qualifies — 128-divisible sequence lengths, a mask that is absent
    or a pure key-padding mask like BERT's (B, 1, 1, Tk), a TPU
    backend, and Tk past the measured dense/flash crossover — else
    XLA dense), "flash" (force the kernel; interpret mode off-TPU),
    or "xla" (force dense). ``ZOO_TPU_ATTENTION`` sets the default
    process-wide.
    """
    impl = resolve_attention_impl(impl)
    # cheap gates first so the default ("auto") path off-TPU / below
    # the crossover never imports pallas or inspects the mask
    if impl == "flash" or (impl == "auto" and flash_backend_ok()
                           and flash_profitable(k.shape[1])):
        from analytics_zoo_tpu.ops import flash_attention as fa
        # single routing decision: shapes kernel-compatible AND the
        # mask (if any) reduces to the kernel's key-padding form
        km = fa.as_key_mask(mask, q.shape[0], k.shape[1])
        supported = fa.supports(q.shape[1], k.shape[1], q.shape[-1],
                                None) and (mask is None or km is not None)
        if supported:
            return fa.flash_attention(q, k, v, causal=causal,
                                      scale=scale, key_mask=km)
        if impl == "flash":
            raise ValueError(
                f"impl='flash' unsupported for Tq={q.shape[1]} "
                f"Tk={k.shape[1]} mask={mask is not None} (need "
                f"128-divisible T and a key-padding-only mask); use "
                f"'auto' to fall back silently")
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / (d ** 0.5)
    # (B, H, Tq, Tk)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32)
    logits = logits * scale
    if causal:
        tq, tk = logits.shape[-2], logits.shape[-1]
        causal_mask = jnp.tril(jnp.ones((tq, tk), jnp.bool_),
                               k=tk - tq)
        logits = jnp.where(causal_mask, logits, -1e30)
    if mask is not None:
        logits = jnp.where(mask.astype(jnp.bool_), logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def _flash_block_update(carry, s, v_blk):
    """One blockwise-softmax accumulation step (shared by ring
    attention). carry = (o_acc, m, l); s: (B, H, Tq, Tk_blk) f32 logits;
    v_blk: (B, Tk_blk, H, D)."""
    o_acc, m, l = carry
    m_blk = jnp.max(s, axis=-1)               # (B, H, Tq)
    m_new = jnp.maximum(m, m_blk)
    alpha = jnp.exp(m - m_new)                # rescale old accumulator
    p = jnp.exp(s - m_new[..., None])         # (B, H, Tq, Tk)
    l_new = l * alpha + jnp.sum(p, axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v_blk.dtype), v_blk)
    o_new = o_acc * alpha.transpose(0, 2, 1)[..., None] + \
        pv.astype(jnp.float32)
    return o_new, m_new, l_new


# -- autotuner specs --------------------------------------------------------
# The dense-vs-flash crossover IS the candidate set: the tuner times
# both routings at the call shape and memoizes the winner, retiring
# the hand-measured ZOO_TPU_{FLASH,DECODE_FLASH}_MIN_T constants to
# verbatim overrides (set -> tuner bypassed, source="flag"). The env
# reads stay in this module so lint's check_autotune_overrides sees
# every ops/ gate where it is consumed.

def _attn_flag(p):
    env = os.environ.get("ZOO_TPU_FLASH_MIN_T")
    if env is None:
        return None
    return {"use_flash": p["tk"] >= int(env)}


def _decode_flag(p):
    env = os.environ.get("ZOO_TPU_DECODE_FLASH_MIN_T")
    if env is None:
        return None
    return {"use_flash": p["tk"] >= int(env)}


def _crossover_candidates(p):
    return [{"use_flash": False}, {"use_flash": True}]


def _attn_runner(p, cfg):
    """fwd+bwd probe at (B=1, H=2, D=64, Tq=Tk) bf16 causal — the
    PERF.md crossover measurement's geometry, scaled down."""
    tk = p["tk"]
    interpret = jax.default_backend() not in ("tpu", "axon")
    if interpret and (tk > 4096 or (cfg["use_flash"] and tk > 512)):
        return None
    if cfg["use_flash"] and tk % 128 != 0:
        return None
    import numpy as np
    rs = np.random.RandomState(0)
    b, h, d = 1, 2, 64
    shape = (b, tk, h, d)
    q = jnp.asarray(rs.randn(*shape), jnp.bfloat16)
    k = jnp.asarray(rs.randn(*shape), jnp.bfloat16)
    v = jnp.asarray(rs.randn(*shape), jnp.bfloat16)
    if cfg["use_flash"]:
        from analytics_zoo_tpu.ops import flash_attention as fa

        @jax.jit
        def probe(q, k, v):
            def loss(q):
                out = fa.flash_attention(q, k, v, causal=True)
                return jnp.sum(out.astype(jnp.float32))
            val, dq = jax.value_and_grad(loss)(q)
            return val + jnp.sum(dq.astype(jnp.float32))
    else:
        @jax.jit
        def probe(q, k, v):
            def loss(q):
                out = dot_product_attention(q, k, v, causal=True,
                                            impl="xla")
                return jnp.sum(out.astype(jnp.float32))
            val, dq = jax.value_and_grad(loss)(q)
            return val + jnp.sum(dq.astype(jnp.float32))

    def run():
        jax.block_until_ready(probe(q, k, v))
    return run


def _decode_runner(p, cfg):
    """Single-query decode probe at (S=4, H=2, D=64) over a T-length
    cache — forward only (decode never differentiates)."""
    t = p["tk"]
    interpret = jax.default_backend() not in ("tpu", "axon")
    if t % 128 != 0 or (interpret and
                        (t > 4096 or (cfg["use_flash"] and t > 512))):
        return None
    import numpy as np
    rs = np.random.RandomState(0)
    s, h, d = 4, 2, 64
    q = jnp.asarray(rs.randn(s, h, d), jnp.bfloat16)
    k = jnp.asarray(rs.randn(s, t, h, d), jnp.bfloat16)
    v = jnp.asarray(rs.randn(s, t, h, d), jnp.bfloat16)
    seq_lens = jnp.full((s,), t, jnp.int32)
    if cfg["use_flash"]:
        from analytics_zoo_tpu.ops import flash_attention as fa
        key_mask = jnp.ones((s, t), jnp.float32)

        @jax.jit
        def probe(q, k, v):
            return jnp.sum(fa.flash_decode_attention(
                q, k, v, key_mask,
                scale=1.0 / (d ** 0.5)).astype(jnp.float32))
    else:
        @jax.jit
        def probe(q, k, v):
            return jnp.sum(decode_attention(
                q, k, v, seq_lens, impl="xla").astype(jnp.float32))

    def run():
        jax.block_until_ready(probe(q, k, v))
    return run


autotune.register(autotune.OpSpec(
    "attn_crossover",
    heuristic=lambda p: {"use_flash": p["tk"] >= 1024},
    candidates=_crossover_candidates, flag_value=_attn_flag,
    runner=_attn_runner))

autotune.register(autotune.OpSpec(
    "decode_crossover",
    heuristic=lambda p: {"use_flash": p["tk"] >= 2048},
    candidates=_crossover_candidates, flag_value=_decode_flag,
    runner=_decode_runner))
