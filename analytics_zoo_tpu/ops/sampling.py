"""Token sampling for the compiled decode loop.

One traced program must serve every request mix, so the greedy/
temperature switch is DATA, not structure: ``temperature`` is a
per-slot traced vector and slots with ``temperature <= 0`` take the
argmax while the rest draw from the (optionally top-k-truncated)
softmax — a `where` between two always-computed candidates, the usual
price of branchless batching. ``top_k`` stays a static int (it
changes the lowering via `lax.top_k`), read once per engine from
``ZOO_TPU_GEN_TOP_K`` so the serving step still compiles exactly
once.

Speculative decoding (Leviathan et al., "Fast Inference from
Transformers via Speculative Decoding") reuses the same distribution:
:func:`sampling_probs` exposes the EXACT per-slot distribution
:func:`sample_tokens` draws from (a one-hot at the argmax for greedy
slots), and :func:`speculative_accept` runs the rejection-sampling
acceptance test — accept draft ``d_i`` with probability
``min(1, p_i(d_i) / q_i(d_i))``, and on the first rejection resample
from the residual ``norm(max(p - q, 0))``. The emitted stream is
distributed EXACTLY as target-only sampling; for greedy slots the
one-hot ``p`` collapses the test to ``d_i == argmax p_i`` and the
residual to the argmax itself, so greedy speculation is byte-exact.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def sample_tokens(rng, logits, temperature, top_k: int = 0):
    """Next-token ids for a batch of slots.

    logits: (S, V); temperature: scalar or (S,) — ``<= 0`` means
    greedy for that slot; ``top_k``: static, 0/negative disables
    truncation. Returns (S,) int32.
    """
    logits = logits.astype(jnp.float32)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    temperature = jnp.broadcast_to(
        jnp.asarray(temperature, jnp.float32), logits.shape[:1])
    scaled = logits / jnp.maximum(temperature, 1e-6)[:, None]
    if top_k and top_k > 0 and top_k < logits.shape[-1]:
        kth = jax.lax.top_k(scaled, top_k)[0][:, -1:]
        scaled = jnp.where(scaled >= kth, scaled, -1e30)
    sampled = jax.random.categorical(rng, scaled).astype(jnp.int32)
    return jnp.where(temperature > 0.0, sampled, greedy)


def sampling_probs(logits, temperature, top_k: int = 0):
    """The per-slot distribution :func:`sample_tokens` draws from,
    as explicit probabilities: greedy slots (``temperature <= 0``)
    get a one-hot at the argmax, the rest the top-k-truncated
    temperature softmax. logits: (…, S, V) → (…, S, V) f32.

    This is what speculative verification scores drafts against — it
    must match `sample_tokens` exactly (same truncation, same
    greedy/temperature switch) or acceptance is biased.
    """
    logits = logits.astype(jnp.float32)
    v = logits.shape[-1]
    temperature = jnp.broadcast_to(
        jnp.asarray(temperature, jnp.float32), logits.shape[:-1])
    scaled = logits / jnp.maximum(temperature, 1e-6)[..., None]
    if top_k and top_k > 0 and top_k < v:
        kth = jax.lax.top_k(scaled, top_k)[0][..., -1:]
        scaled = jnp.where(scaled >= kth, scaled, -1e30)
    probs = jax.nn.softmax(scaled, axis=-1)
    greedy = jax.nn.one_hot(jnp.argmax(logits, axis=-1), v,
                            dtype=jnp.float32)
    return jnp.where((temperature > 0.0)[..., None], probs, greedy)


def speculative_accept(rng, p, q, drafts):
    """Rejection-sampling acceptance for one speculative round.

    p / q: (S, K, V) f32 — the target / drafter sampling
    distributions at each of the K draft positions (both from
    :func:`sampling_probs`, so greedy slots carry one-hots); drafts:
    (S, K) int32 proposed ids. Returns ``(n_accept, corrected)``:

    - ``n_accept`` (S,) int32 — length of the accepted draft prefix
      (position i accepted iff ``u_i < p_i(d_i) / q_i(d_i)``, all
      earlier positions accepted);
    - ``corrected`` (S,) int32 — a token drawn from the residual
      ``norm(max(p - q, 0))`` at the first rejected position
      (meaningful only when ``n_accept < K``; whenever a rejection
      occurred the residual has positive mass, since rejection
      implies ``p(d) < q(d)`` there).

    Greedy falls out with no special case: ``p`` one-hot means the
    ratio is ``1/q >= 1`` (always accept) at the argmax and ``0``
    elsewhere, and the residual is a delta at the argmax.
    """
    k = drafts.shape[1]
    p_d = jnp.take_along_axis(p, drafts[..., None], axis=-1)[..., 0]
    q_d = jnp.take_along_axis(q, drafts[..., None], axis=-1)[..., 0]
    r_accept, r_fix = jax.random.split(rng)
    u = jax.random.uniform(r_accept, drafts.shape, jnp.float32)
    # u < p/q without the division (q_d can be 0 for greedy drafters)
    accept = u * q_d < p_d
    good = jnp.cumprod(accept.astype(jnp.int32), axis=1)
    n_accept = jnp.sum(good, axis=1).astype(jnp.int32)
    idx = jnp.minimum(n_accept, k - 1)[:, None, None]
    p_r = jnp.take_along_axis(p, idx, axis=1)[:, 0]
    q_r = jnp.take_along_axis(q, idx, axis=1)[:, 0]
    residual = jnp.maximum(p_r - q_r, 0.0)
    corrected = jax.random.categorical(
        r_fix, jnp.log(residual + 1e-30)).astype(jnp.int32)
    return n_accept, corrected
