"""Token sampling for the compiled decode loop.

One traced program must serve every request mix, so the greedy/
temperature switch is DATA, not structure: ``temperature`` is a
per-slot traced vector and slots with ``temperature <= 0`` take the
argmax while the rest draw from the (optionally top-k-truncated)
softmax — a `where` between two always-computed candidates, the usual
price of branchless batching. ``top_k`` stays a static int (it
changes the lowering via `lax.top_k`), read once per engine from
``ZOO_TPU_GEN_TOP_K`` so the serving step still compiles exactly
once.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def sample_tokens(rng, logits, temperature, top_k: int = 0):
    """Next-token ids for a batch of slots.

    logits: (S, V); temperature: scalar or (S,) — ``<= 0`` means
    greedy for that slot; ``top_k``: static, 0/negative disables
    truncation. Returns (S,) int32.
    """
    logits = logits.astype(jnp.float32)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    temperature = jnp.broadcast_to(
        jnp.asarray(temperature, jnp.float32), logits.shape[:1])
    scaled = logits / jnp.maximum(temperature, 1e-6)[:, None]
    if top_k and top_k > 0 and top_k < logits.shape[-1]:
        kth = jax.lax.top_k(scaled, top_k)[0][:, -1:]
        scaled = jnp.where(scaled >= kth, scaled, -1e30)
    sampled = jax.random.categorical(rng, scaled).astype(jnp.int32)
    return jnp.where(temperature > 0.0, sampled, greedy)
