"""Phase-decomposed backward for strided convolutions.

PERF.md round 6: fusion levers cap out near 0.32–0.36 model-MFU
because XLA executes ~1.95x the model FLOPs, dominated by the
input-dilated stride-2 backward convs — jax's conv transpose rule
computes dx by zero-dilating the cotangent (``lhs_dilation=(s, s)``)
and sliding the full kernel over it, so (s^2-1)/s^2 of the executed
MACs multiply inserted zeros (the hardware conv unit cannot skip
them). The standard fix in TPU convnet stacks is the sub-pixel /
phase decomposition of the transposed conv:

dx: split the kernel into s^2 spatial phases ``w[ph::s, pw::s]``;
each output phase ``dx[s*m+ph]`` is an ordinary *stride-1* conv of
the UNDILATED cotangent with the reversed sub-kernel, and the s^2
phase planes interleave back with a reshape (inverse
space-to-depth). Executed MACs == model MACs — 4x fewer at s=2.

dw: jax's rule dilates the cotangent on the *rhs* side
(``rhs_dilation=(s, s)``). Phase-slice the input instead:
``dw[s*j+ph] = sum_p x~[s*p + s*j + ph] * dy[p]`` is a dense VALID
stride-1 conv of the phase-sliced input ``x~[ph::s]`` against the
cotangent — every tap an ordinary dense reduction, no dilated
operand anywhere.

Exact same sums as the transpose rule, reassociated — gradients
match to f32 roundoff. `ZOO_TPU_PHASE_BWD=0` selects jax's
transpose-rule backward for A/B; the auto default routes through a
measured-win gate like `conv_bn.fused_profitable` (pending an
on-chip verdict from scripts/measure_fused.py).

Note for FLOPs accounting (scripts/flops_audit.py): XLA's
HloCostAnalysis already discounts dilation-inserted zeros, so its
`flops` does NOT drop under this rewrite — the executed-semantics
count (full window taps x output elements, what a systolic array
actually runs) is the number this lever moves.
"""

from __future__ import annotations

import functools
import os
from typing import Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

_DN = ("NHWC", "HWIO", "NHWC")

# test observability, like ops.conv_bn.invocations
invocations = {"conv2d": 0, "bwd_phase": 0, "bwd_ref": 0}

# Measured-win gate for the auto default (the conv_bn.MEASURED_WIN
# playbook): flip to True once scripts/measure_fused.py section E
# shows the phase backward beating the dilated transpose rule on
# real hardware. Until then the phase path is opt-in
# (ZOO_TPU_PHASE_BWD=1) — it is grads-exact and strictly fewer
# executed MACs, but chip-unmeasured (s^2 smaller convs could lose
# to one big dilated conv on grid overhead).
PHASE_MEASURED_WIN = False


def phase_bwd_enabled() -> bool:
    """Whether strided convs default to the phase-decomposed
    backward. ``ZOO_TPU_PHASE_BWD=0/1`` overrides (read at trace
    time); otherwise a real TPU backend AND a measured on-chip win
    (``PHASE_MEASURED_WIN``)."""
    env = os.environ.get("ZOO_TPU_PHASE_BWD")
    if env is not None:
        return env != "0"
    return PHASE_MEASURED_WIN and jax.default_backend() in (
        "tpu", "axon")


def _same_pads(size: int, k: int, stride: int) -> Tuple[int, int]:
    out = -(-size // stride)
    total = max((out - 1) * stride + k - size, 0)
    lo = total // 2
    return lo, total - lo


def normalize_padding(padding, x_spatial: Sequence[int],
                      k_spatial: Sequence[int],
                      stride: Sequence[int]
                      ) -> Tuple[Tuple[int, int], ...]:
    """Resolve "SAME"/"VALID"/explicit padding to per-dim (lo, hi)
    pairs (jax's own SAME algebra: lo = total // 2)."""
    if isinstance(padding, str):
        p = padding.upper()
        if p == "VALID":
            return tuple((0, 0) for _ in x_spatial)
        if p == "SAME":
            return tuple(_same_pads(sz, k, s) for sz, k, s in
                         zip(x_spatial, k_spatial, stride))
        raise ValueError(f"padding must be SAME|VALID, got {padding}")
    return tuple((int(lo), int(hi)) for lo, hi in padding)


def _grid(size: int, lo: int, hi: int, k: int, stride: int
          ) -> Tuple[int, int, int]:
    """(padded extent, conv output extent, phase-plane extent M).
    M = ceil(padded / s) is uniform across phases: every output
    phase plane is computed at extent M and the interleave slices
    the (lo, hi) padding back off."""
    padded = size + lo + hi
    out = (padded - k) // stride + 1
    return padded, out, -(-padded // stride)


def phase_dx(g: jnp.ndarray, w: jnp.ndarray,
             x_spatial: Tuple[int, int],
             stride: Tuple[int, int],
             pads: Tuple[Tuple[int, int], Tuple[int, int]],
             preferred_element_type=None) -> jnp.ndarray:
    """dx of ``conv(x, w, stride, pads)`` (NHWC/HWIO) without a
    dilated operand: s^2 stride-1 convs of the undilated cotangent
    ``g`` with the reversed sub-kernels ``w[ph::s, pw::s]``
    (I/O-swapped dims, like jax's rule), interleaved by an inverse
    space-to-depth reshape. Per-phase padding ``(K_ph - 1, M - Ho)``
    may be negative on the high side (a crop) — lax accepts that.
    Empty phases (e.g. a 1x1 kernel at s=2) are zero planes."""
    n, ho, wo, cout = g.shape
    kh, kw, cin, _ = w.shape
    sh, sw = stride
    (lo_h, hi_h), (lo_w, hi_w) = pads
    hx, wx = x_spatial
    _, oh, mh = _grid(hx, lo_h, hi_h, kh, sh)
    _, ow, mw = _grid(wx, lo_w, hi_w, kw, sw)
    assert (oh, ow) == (ho, wo), ((oh, ow), (ho, wo))
    res_dtype = preferred_element_type or g.dtype

    rows = []
    for ph in range(sh):
        cols = []
        for pw in range(sw):
            wsub = w[ph::sh, pw::sw]
            kph, kpw = wsub.shape[0], wsub.shape[1]
            if kph == 0 or kpw == 0:
                cols.append(jnp.zeros((n, mh, mw, cin), res_dtype))
                continue
            cols.append(jax.lax.conv_general_dilated(
                g, jax.lax.rev(wsub, (0, 1)),
                window_strides=(1, 1),
                padding=((kph - 1, mh - ho), (kpw - 1, mw - wo)),
                dimension_numbers=("NHWC", "HWOI", "NHWC"),
                preferred_element_type=preferred_element_type))
        rows.append(jnp.stack(cols, axis=3))    # (N, Mh, Mw, sw, C)
    dxt = jnp.stack(rows, axis=2)          # (N, Mh, sh, Mw, sw, C)
    dxt = dxt.reshape(n, sh * mh, sw * mw, cin)
    return dxt[:, lo_h:lo_h + hx, lo_w:lo_w + wx, :]


def phase_dw(x: jnp.ndarray, g: jnp.ndarray,
             k_spatial: Tuple[int, int],
             stride: Tuple[int, int],
             pads: Tuple[Tuple[int, int], Tuple[int, int]],
             preferred_element_type=None) -> jnp.ndarray:
    """dw of ``conv(x, w, stride, pads)`` (NHWC/HWIO) without a
    dilated operand: phase-slice the padded input (a pad-to-multiple
    + reshape, no strided gather) so each sub-kernel tap row
    ``dw[s*j+ph]`` is a dense VALID stride-1 conv of ``x[ph::s]``
    against the cotangent-as-kernel (jax's ``("CHWN","IHWO","HWNC")``
    contraction, minus the ``rhs_dilation``). Executed MACs == the
    model's dw count exactly."""
    n, hx, wx, cin = x.shape
    _, ho, wo, cout = g.shape
    kh, kw = k_spatial
    sh, sw = stride
    (lo_h, hi_h), (lo_w, hi_w) = pads
    _, oh, mh = _grid(hx, lo_h, hi_h, kh, sh)
    _, ow, mw = _grid(wx, lo_w, hi_w, kw, sw)
    assert (oh, ow) == (ho, wo), ((oh, ow), (ho, wo))
    res_dtype = preferred_element_type or x.dtype

    # pad: conv padding, then up to the next stride multiple so the
    # phase slice is a plain reshape+index
    xt = jnp.pad(x, ((0, 0),
                     (lo_h, mh * sh - hx - lo_h),
                     (lo_w, mw * sw - wx - lo_w),
                     (0, 0)))
    xt = xt.reshape(n, mh, sh, mw, sw, cin)

    dw = jnp.zeros((kh, kw, cin, cout), res_dtype)
    for ph in range(sh):
        kph = len(range(ph, kh, sh))
        if kph == 0:
            continue
        for pw in range(sw):
            kpw = len(range(pw, kw, sw))
            if kpw == 0:
                continue
            xphase = xt[:, :, ph, :, pw, :]     # (N, Mh, Mw, Cin)
            dw_p = jax.lax.conv_general_dilated(
                xphase, g, window_strides=(1, 1),
                padding=((0, ho - 1 + kph - mh),
                         (0, wo - 1 + kpw - mw)),
                dimension_numbers=("CHWN", "IHWO", "HWNC"),
                preferred_element_type=preferred_element_type)
            dw = dw.at[ph::sh, pw::sw, :, :].set(
                dw_p.astype(res_dtype))
    return dw


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _conv2d(x, w, stride, pads, use_phase):
    return jax.lax.conv_general_dilated(
        x, w, window_strides=stride, padding=pads,
        dimension_numbers=_DN)


def _conv2d_fwd(x, w, stride, pads, use_phase):
    return _conv2d(x, w, stride, pads, use_phase), (x, w)


def _conv2d_bwd(stride, pads, use_phase, res, g):
    x, w = res
    if use_phase:
        invocations["bwd_phase"] += 1
        dx = phase_dx(g, w, x.shape[1:3], stride, pads)
        dw = phase_dw(x, g, w.shape[:2], stride, pads)
    else:
        invocations["bwd_ref"] += 1
        # jax's own transpose rule (dilated operands) for A/B
        _, vjp = jax.vjp(
            lambda xx, ww: jax.lax.conv_general_dilated(
                xx, ww, window_strides=stride, padding=pads,
                dimension_numbers=_DN), x, w)
        dx, dw = vjp(g)
    return dx.astype(x.dtype), dw.astype(w.dtype)


_conv2d.defvjp(_conv2d_fwd, _conv2d_bwd)


def conv2d(x: jnp.ndarray, w: jnp.ndarray,
           stride: Union[int, Tuple[int, int]] = (1, 1),
           padding="SAME", *,
           phase_bwd: Optional[bool] = None) -> jnp.ndarray:
    """NHWC/HWIO 2-D conv whose backward never materializes a
    dilated operand (gated): forward is a plain
    `lax.conv_general_dilated`; the custom VJP computes dx/dw via
    :func:`phase_dx`/:func:`phase_dw` when the phase backward is on
    (``phase_bwd=None`` resolves :func:`phase_bwd_enabled` at trace
    time; pass True/False for an in-process A/B, e.g.
    scripts/measure_fused.py section E). Groups and kernel dilation
    are not supported — callers gate on that."""
    if isinstance(stride, int):
        stride = (stride, stride)
    stride = tuple(int(s) for s in stride)
    pads = normalize_padding(padding, x.shape[1:3], w.shape[:2],
                             stride)
    if phase_bwd is None:
        phase_bwd = phase_bwd_enabled()
    invocations["conv2d"] += 1
    return _conv2d(x, w, stride, pads, bool(phase_bwd))
