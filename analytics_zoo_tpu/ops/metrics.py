"""Validation metrics.

Reference surface: `Z/pipeline/api/keras/metrics/{Accuracy,AUC,MAE}.scala`
+ BigDL Top1/Top5/Loss (SURVEY.md §2.4, §5 "Metrics").

Design for jit: each metric exposes ``batch_stats(y_true, y_pred) ->
dict[str, array]`` (pure, traceable — runs inside the pjit'd eval step,
so partial sums are all-reduced by XLA across the sharded batch) and
``aggregate(stats) -> float`` (host-side, after summing stats over
batches). This splits cleanly across the device/host boundary the way
BigDL's ValidationMethod accumulates `ValidationResult`s.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np


class Metric:
    name = "metric"

    def batch_stats(self, y_true, y_pred,
                    mask=None) -> "dict[str, jnp.ndarray]":
        """``mask`` is an optional per-sample {0,1} float vector of
        length batch; samples with mask 0 (padding added so a tail
        batch divides the data-parallel size) contribute nothing."""
        raise NotImplementedError

    def aggregate(self, stats: "dict[str, np.ndarray]") -> float:
        raise NotImplementedError


def _sample_mask(mask, ref):
    """Broadcast a per-sample mask over a (batch, ...) values array;
    returns (masked values multiplier, effective element count)."""
    if mask is None:
        return None, jnp.asarray(ref.size, jnp.float32)
    m = jnp.broadcast_to(
        mask.astype(jnp.float32).reshape((-1,) + (1,) * (ref.ndim - 1)),
        ref.shape)
    return m, jnp.sum(m)


class Accuracy(Metric):
    """Auto-dispatching accuracy like the reference zoo `Accuracy`
    (`keras/metrics/Accuracy.scala:36`): softmax outputs → argmax vs
    (sparse or one-hot) labels; single-unit sigmoid outputs → 0.5
    threshold."""

    name = "accuracy"

    def batch_stats(self, y_true, y_pred, mask=None):
        if y_pred.ndim >= 2 and y_pred.shape[-1] > 1:
            pred = jnp.argmax(y_pred, axis=-1)
            if y_true.ndim == y_pred.ndim and y_true.shape[-1] > 1:
                true = jnp.argmax(y_true, axis=-1)  # one-hot
            else:
                true = y_true.reshape(pred.shape).astype(jnp.int32)
        else:
            pred = (y_pred.reshape(y_pred.shape[0], -1)[:, 0] >
                    0.5).astype(jnp.int32)
            true = y_true.reshape(y_true.shape[0], -1)[:, 0] \
                .astype(jnp.int32)
        hits = (pred == true).astype(jnp.float32)
        m, count = _sample_mask(mask, hits)
        correct = jnp.sum(hits if m is None else hits * m)
        return {"correct": correct, "count": count}

    def aggregate(self, stats):
        return float(stats["correct"] / np.maximum(stats["count"], 1.0))


SparseCategoricalAccuracy = Accuracy
CategoricalAccuracy = Accuracy
BinaryAccuracy = Accuracy


class Top5Accuracy(Metric):
    """(BigDL `Top5Accuracy`, used by the ImageNet recipes.)"""

    name = "top5accuracy"

    def batch_stats(self, y_true, y_pred, mask=None):
        true = (jnp.argmax(y_true, axis=-1)
                if y_true.ndim == y_pred.ndim and y_true.shape[-1] > 1
                else y_true.reshape(y_pred.shape[0]).astype(jnp.int32))
        _, top5 = jax.lax.top_k(y_pred, 5)
        hits = jnp.any(top5 == true[:, None], axis=-1).astype(jnp.float32)
        m, count = _sample_mask(mask, hits)
        return {"correct": jnp.sum(hits if m is None else hits * m),
                "count": count}

    def aggregate(self, stats):
        return float(stats["correct"] / np.maximum(stats["count"], 1.0))


class MAE(Metric):
    """(reference `keras/metrics/MAE.scala:27`.)"""

    name = "mae"

    def batch_stats(self, y_true, y_pred, mask=None):
        err = jnp.abs(y_pred - y_true).astype(jnp.float32)
        m, count = _sample_mask(mask, err)
        return {"abs_sum": jnp.sum(err if m is None else err * m),
                "count": count}

    def aggregate(self, stats):
        return float(stats["abs_sum"] / np.maximum(stats["count"], 1.0))


class MSE(Metric):
    name = "mse"

    def batch_stats(self, y_true, y_pred, mask=None):
        err = jnp.square(y_pred - y_true).astype(jnp.float32)
        m, count = _sample_mask(mask, err)
        return {"sq_sum": jnp.sum(err if m is None else err * m),
                "count": count}

    def aggregate(self, stats):
        return float(stats["sq_sum"] / np.maximum(stats["count"], 1.0))


class Loss(Metric):
    """Wraps a loss fn as a metric (BigDL `Loss` validation method)."""

    name = "loss"

    def __init__(self, loss_fn: Callable):
        self.loss_fn = loss_fn

    def batch_stats(self, y_true, y_pred, mask=None):
        if mask is None:
            n = jnp.asarray(y_pred.shape[0], jnp.float32)
            return {"loss_sum": self.loss_fn(y_true, y_pred) * n,
                    "count": n}
        # per-sample losses (each a mean over one sample's elements) so
        # padded samples can be zeroed out
        per = jax.vmap(
            lambda t, p: self.loss_fn(t[None], p[None]))(y_true, y_pred)
        m = mask.astype(jnp.float32)
        return {"loss_sum": jnp.sum(per * m), "count": jnp.sum(m)}

    def aggregate(self, stats):
        return float(stats["loss_sum"] / np.maximum(stats["count"], 1.0))


class AUC(Metric):
    """Streaming ROC-AUC via thresholded confusion counts (reference
    `keras/metrics/AUC.scala:128`; same approach as tf.metrics.auc)."""

    name = "auc"

    def __init__(self, thresholds: int = 200):
        self.n_thresholds = int(thresholds)

    def batch_stats(self, y_true, y_pred, mask=None):
        scores = y_pred.reshape(-1).astype(jnp.float32)
        labels = y_true.reshape(-1).astype(jnp.float32)
        if mask is None:
            w = jnp.ones_like(scores)
        else:
            w, _ = _sample_mask(mask, y_pred)
            w = w.reshape(-1)
        ts = jnp.linspace(0.0, 1.0, self.n_thresholds)
        pred_pos = scores[None, :] >= ts[:, None]  # (T, N)
        is_pos = labels[None, :] > 0.5
        tp = jnp.sum(jnp.where(pred_pos & is_pos, w[None, :], 0.0),
                     axis=1)
        fp = jnp.sum(jnp.where(pred_pos & ~is_pos, w[None, :], 0.0),
                     axis=1)
        pos = jnp.sum(jnp.where(is_pos[0], w, 0.0))
        neg = jnp.sum(w) - pos
        return {"tp": tp, "fp": fp, "pos": pos, "neg": neg}

    def aggregate(self, stats):
        tpr = stats["tp"] / np.maximum(stats["pos"], 1.0)
        fpr = stats["fp"] / np.maximum(stats["neg"], 1.0)
        # thresholds ascend → fpr/tpr descend; integrate |trapezoid|
        return float(np.abs(np.trapezoid(tpr, fpr)))


_REGISTRY: "dict[str, Callable[[], Metric]]" = {
    "accuracy": Accuracy,
    "acc": Accuracy,
    "top5accuracy": Top5Accuracy,
    "top5": Top5Accuracy,
    "mae": MAE,
    "mse": MSE,
    "auc": AUC,
}


def get(spec: "str | Metric") -> Metric:
    if isinstance(spec, Metric):
        return spec
    key = spec.lower()
    if key not in _REGISTRY:
        raise ValueError(f"unknown metric '{spec}'; known: "
                         f"{sorted(_REGISTRY)}")
    return _REGISTRY[key]()
