from analytics_zoo_tpu.ops import activations, initializers, regularizers
