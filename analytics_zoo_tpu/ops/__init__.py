from analytics_zoo_tpu.ops import (activations, initializers, kv_cache,
                                   regularizers, sampling)
