"""Activation functions by Keras name.

(reference: activation strings accepted across
`Z/pipeline/api/keras/layers/*.scala`, e.g. `Dense.scala` `activation` arg;
standalone layers in `layers/Activation*.scala`.)
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp

Activation = Callable[[jnp.ndarray], jnp.ndarray]


def linear(x):
    return x


def hard_sigmoid(x):
    return jnp.clip(0.2 * x + 0.5, 0.0, 1.0)


def softmax(x):
    return jax.nn.softmax(x, axis=-1)


def log_softmax(x):
    return jax.nn.log_softmax(x, axis=-1)


_REGISTRY: "dict[str, Activation]" = {
    "linear": linear,
    "relu": jax.nn.relu,
    "relu6": jax.nn.relu6,
    "tanh": jnp.tanh,
    "sigmoid": jax.nn.sigmoid,
    "hard_sigmoid": hard_sigmoid,
    "softmax": softmax,
    "log_softmax": log_softmax,
    "softplus": jax.nn.softplus,
    "softsign": jax.nn.soft_sign,
    "elu": jax.nn.elu,
    "selu": jax.nn.selu,
    "gelu": jax.nn.gelu,
    "silu": jax.nn.silu,
    "swish": jax.nn.silu,
    "exp": jnp.exp,
}


class NamedActivation:
    """Picklable by-name activation (several jnp/jax.nn functions are
    re-exports that pickle can't resolve by qualified name)."""

    def __init__(self, name: str):
        if name not in _REGISTRY:
            raise ValueError(
                f"unknown activation '{name}'; known: "
                f"{sorted(_REGISTRY)}")
        self.name = name

    def __call__(self, x):
        return _REGISTRY[self.name](x)

    def __repr__(self):
        return f"NamedActivation({self.name})"


def get(name: "str | Activation | None") -> Optional[Activation]:
    """Resolve an activation by name; None → None (identity)."""
    if name is None:
        return None
    if callable(name):
        return name
    return NamedActivation(name.lower())
