"""Paged KV cache for autoregressive decode.

vLLM's PagedAttention (SOSP '23) insight, applied to this stack:
instead of one contiguous (B, T_max, H, D) K/V buffer per layer —
whose T axis either reallocates as sequences grow (recompile) or pads
every sequence to the worst case (HBM waste) — K/V live in a
fixed-size pool of small pages, `(max_pages, page_size, heads,
head_dim)` per layer, preallocated once. A per-slot page table maps
logical token positions to physical pages, so sequence growth only
ever writes one (heads, head_dim) row into an existing page (or walks
onto a freshly assigned one) and NO array shape ever changes: the
whole decode loop stays one compiled program regardless of how many
sequences join, leave, or how long they run.

Everything device-side here is shape-static and jit-safe:

- :func:`init_cache` — allocate the pool (zeros) + identity tables;
- :func:`append_layer` — scatter one new token's K/V per slot into
  one layer's pool (inactive slots are routed out-of-range and
  dropped, so padded batch slots never corrupt live pages);
- :func:`write_prompt_layer` — bulk-scatter a whole (right-padded)
  prompt's K/V at prefill (pad rows land in pages past `seq_len` and
  are never gathered — the length mask owns validity); a per-slot
  ``start`` offset writes a partial chunk of the prompt instead, the
  primitive chunked prefill is built on;
- :func:`gather_layer` / :func:`length_mask` — page-table gather back
  to a dense (S, T, H, D) view + key-validity mask for attention.

Int8 pages (``ZOO_TPU_KV_DTYPE=int8``): the pool stores int8 rows
plus a per-row-per-head scale array of the same page geometry
(`(num_layers, max_pages, page_size, heads)` f32 — "per-page scales
stored alongside the pages"). :func:`quantize_rows` computes the
symmetric scale `max|x| / 127` over ``head_dim`` at every write
(append and prompt scatter share the coordinate math, so the scale
rows land exactly where their K/V rows do), and
:func:`dequantize_rows` restores values at the gather before
attention — roughly 2x resident-sequence capacity for a bounded,
tested accuracy cost (tests/test_generate.py's kv-dtype conformance
matrix).

The host-side :class:`PageAllocator` is the bookkeeping half: a free
list of physical page ids for the continuous batcher, which assigns
pages at admission / token-boundary growth and reclaims them at
retirement (`pipeline/inference/batching.py::ContinuousBatcher`).
"""

from __future__ import annotations

import base64
from typing import NamedTuple, Sequence

import jax.numpy as jnp
import numpy as np


class PagedKVCache(NamedTuple):
    """The device-side cache state threaded through the decode loop.

    ``k_pages``/``v_pages``: (num_layers, max_pages, page_size,
    heads, head_dim) — the preallocated pools.
    ``page_table``: (max_slots, pages_per_slot) int32 physical page
    ids (logical page j of slot s lives in ``page_table[s, j]``).
    ``seq_lens``: (max_slots,) int32 tokens currently cached per slot
    (0 = free slot; doubles as the active mask).
    ``k_scales``/``v_scales``: (num_layers, max_pages, page_size,
    heads) f32 per-row-per-head dequant scales, present only when the
    pools are int8 (None otherwise — None leaves are empty pytree
    nodes, so the jit'd programs simply specialize per cache dtype).
    """

    k_pages: jnp.ndarray
    v_pages: jnp.ndarray
    page_table: jnp.ndarray
    seq_lens: jnp.ndarray
    k_scales: "jnp.ndarray | None" = None
    v_scales: "jnp.ndarray | None" = None

    @property
    def page_size(self) -> int:
        return self.k_pages.shape[2]

    @property
    def max_context(self) -> int:
        return self.page_table.shape[1] * self.page_size

    @property
    def max_slots(self) -> int:
        return self.page_table.shape[0]

    @property
    def quantized(self) -> bool:
        return self.k_scales is not None


def init_cache(num_layers: int, max_slots: int, max_context: int,
               heads: int, head_dim: int, page_size: int = 16,
               max_pages: int = 0,
               dtype=jnp.float32) -> PagedKVCache:
    """Allocate the pool. ``max_context`` rounds up to whole pages.
    ``max_pages`` defaults to ``max_slots * pages_per_slot`` (every
    slot can reach max_context simultaneously) and the table starts as
    the identity mapping — the compiled-loop `generate()` path uses it
    as-is; the continuous batcher overwrites tables from its
    :class:`PageAllocator` as sequences come and go."""
    pages_per_slot = -(-int(max_context) // int(page_size))
    max_pages = int(max_pages) or int(max_slots) * pages_per_slot
    if max_pages < max_slots * pages_per_slot:
        raise ValueError(
            f"max_pages {max_pages} < max_slots*pages_per_slot "
            f"{max_slots * pages_per_slot}; the identity table "
            f"would alias pages")
    shape = (num_layers, max_pages, page_size, heads, head_dim)
    table = np.arange(max_slots * pages_per_slot, dtype=np.int32)
    quantized = jnp.dtype(dtype) == jnp.dtype(jnp.int8)
    scale_shape = (num_layers, max_pages, page_size, heads)
    return PagedKVCache(
        k_pages=jnp.zeros(shape, dtype),
        v_pages=jnp.zeros(shape, dtype),
        page_table=jnp.asarray(
            table.reshape(max_slots, pages_per_slot)),
        seq_lens=jnp.zeros((max_slots,), jnp.int32),
        k_scales=jnp.zeros(scale_shape, jnp.float32)
        if quantized else None,
        v_scales=jnp.zeros(scale_shape, jnp.float32)
        if quantized else None,
    )


# int8 pages: symmetric per-(token, head) quantization over head_dim.
# 127 (not 128) keeps the grid symmetric so dequant is a plain scale.
INT8_QMAX = 127.0


def quantize_rows(x):
    """Quantize K/V rows ``(…, heads, head_dim)`` to int8 with one
    f32 scale per ``(…, heads)`` row: ``scale = max|x| / 127`` over
    head_dim, ``q = round(x / scale)``. Zero rows get scale 0 and
    dequantize back to exact zeros."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1)
    scale = amax / INT8_QMAX
    q = jnp.round(xf / jnp.maximum(scale, 1e-12)[..., None])
    q = jnp.clip(q, -INT8_QMAX, INT8_QMAX).astype(jnp.int8)
    return q, scale


def dequantize_rows(q, scale, dtype=jnp.float32):
    """Inverse of :func:`quantize_rows`: ``(…, H, D)`` int8 + ``(…,
    H)`` f32 scales back to ``dtype`` values."""
    out = q.astype(jnp.float32) * scale.astype(jnp.float32)[..., None]
    return out.astype(dtype)


def _scatter_coords(page_table, seq_lens, positions, page_size,
                    active):
    """(physical page, in-page offset) per (slot, position); inactive
    rows are pushed out of range so ``mode="drop"`` discards them."""
    pages_per_slot = page_table.shape[1]
    logical = positions // page_size                 # (S, ...) int32
    # clamp the table lookup; `active` (which callers AND with
    # position < max_context) owns whether the row lands at all
    logical = jnp.minimum(logical, pages_per_slot - 1)
    phys = jnp.take_along_axis(
        page_table, logical.reshape(page_table.shape[0], -1), axis=1
    ).reshape(logical.shape)
    offset = positions % page_size
    max_pages_shape = page_table.shape[0] * page_table.shape[1]
    # any value past every real page id works as the drop sentinel
    phys = jnp.where(active, phys, max_pages_shape + 2 ** 20)
    return phys, offset


def _quantize_for(pages, x):
    """Route a write through :func:`quantize_rows` when the pool is
    int8; (values, scales-or-None) otherwise."""
    if pages.dtype == jnp.int8:
        return quantize_rows(x)
    return x.astype(pages.dtype), None


def append_layer(k_pages, v_pages, page_table, seq_lens,
                 k_new, v_new, active=None,
                 k_scales=None, v_scales=None):
    """Scatter one decode step's K/V into one layer's pool.

    k_pages/v_pages: (P, page, H, D); k_new/v_new: (S, H, D) — the new
    token of every slot, written at position ``seq_lens[s]``. Slots
    with ``active == False`` (or ``seq_lens == 0`` when active is
    None... callers pass the done-mask) are dropped, not written.
    Returns the updated (k_pages, v_pages), plus the updated
    (k_scales, v_scales) when scale pools are passed (int8 pages:
    values are quantized per row and the scale rows scatter through
    the SAME coordinates, so drop semantics match exactly).
    Shape-static; safe inside scan/while_loop."""
    page_size = k_pages.shape[1]
    if active is None:
        active = jnp.ones(seq_lens.shape, jnp.bool_)
    max_ctx = page_table.shape[1] * page_size
    active = jnp.logical_and(active, seq_lens < max_ctx)
    phys, offset = _scatter_coords(page_table, seq_lens, seq_lens,
                                   page_size, active)
    k_new, k_s = _quantize_for(k_pages, k_new)
    v_new, v_s = _quantize_for(v_pages, v_new)
    k_pages = k_pages.at[phys, offset].set(k_new, mode="drop")
    v_pages = v_pages.at[phys, offset].set(v_new, mode="drop")
    if k_scales is None:
        return k_pages, v_pages
    k_scales = k_scales.at[phys, offset].set(k_s, mode="drop")
    v_scales = v_scales.at[phys, offset].set(v_s, mode="drop")
    return k_pages, v_pages, k_scales, v_scales


def write_prompt_layer(k_pages, v_pages, page_table, prompt_lens,
                       k_seq, v_seq, start=None,
                       k_scales=None, v_scales=None):
    """Bulk prefill scatter for one layer: k_seq/v_seq (S, T, H, D)
    hold the (right-padded) prompt K/V; positions past
    ``prompt_lens[s]`` are dropped (never written), so pad tokens
    cannot leak into pages a later admit might reuse.

    ``start`` (S,) int32 shifts each slot's write window: row j of
    k_seq lands at position ``start[s] + j`` (still gated by
    ``position < prompt_lens[s]``, where prompt_lens is the TOTAL
    length the sequence will have after this chunk). This is the
    partial-prompt primitive chunked prefill interleaves with decode
    steps — each chunk is one bounded scatter at its offset, and a
    slot not being chunk-prefilled passes ``prompt_lens == 0`` and is
    untouched. Scale pools (int8) behave as in
    :func:`append_layer`."""
    s, t = k_seq.shape[0], k_seq.shape[1]
    page_size = k_pages.shape[1]
    positions = jnp.broadcast_to(
        jnp.arange(t, dtype=jnp.int32)[None, :], (s, t))
    if start is not None:
        positions = positions + jnp.asarray(start, jnp.int32)[:, None]
    max_ctx = page_table.shape[1] * page_size
    active = jnp.logical_and(positions < prompt_lens[:, None],
                             positions < max_ctx)
    phys, offset = _scatter_coords(page_table, prompt_lens, positions,
                                   page_size, active)
    k_seq, k_s = _quantize_for(k_pages, k_seq)
    v_seq, v_s = _quantize_for(v_pages, v_seq)
    k_pages = k_pages.at[phys, offset].set(k_seq, mode="drop")
    v_pages = v_pages.at[phys, offset].set(v_seq, mode="drop")
    if k_scales is None:
        return k_pages, v_pages
    k_scales = k_scales.at[phys, offset].set(k_s, mode="drop")
    v_scales = v_scales.at[phys, offset].set(v_s, mode="drop")
    return k_pages, v_pages, k_scales, v_scales


def gather_layer(pages, page_table, t_max: int):
    """Page-table gather back to a dense (S, t_max, H, D) view of one
    layer's cache (positions past a slot's ``seq_len`` hold stale/zero
    rows — :func:`length_mask` owns validity). ``t_max`` is static and
    must be a whole number of pages."""
    page_size = pages.shape[1]
    if t_max % page_size:
        raise ValueError(f"t_max {t_max} not a multiple of page_size "
                         f"{page_size}")
    n = t_max // page_size
    picked = jnp.take(pages, page_table[:, :n], axis=0,
                      mode="clip")                 # (S, n, page, H, D)
    s = page_table.shape[0]
    return picked.reshape((s, t_max) + pages.shape[2:])


def length_mask(seq_lens, t: int):
    """(S, t) bool key-validity mask: position p of slot s is a real
    cached token iff ``p < seq_lens[s]``."""
    return jnp.arange(t, dtype=jnp.int32)[None, :] < seq_lens[:, None]


# -- KV-page handoff (prefill/decode disaggregation) ---------------------
#
# DistServe/Splitwise-style pool separation needs one sequence's cache
# state to MOVE between engines. Because the cache is block-granular,
# that transfer is a page gather on the source + a page scatter on the
# destination — never a per-token reshape — and both sides are
# shape-static over the full ``pages_per_slot`` width (unused entries
# ride along masked/dropped), so each engine compiles its half exactly
# once and reuses it for every handoff regardless of sequence length.


def gather_slot_pages(cache: PagedKVCache, page_ids):
    """Gather one slot's pages out of every layer's pool.

    ``page_ids``: (P,) int32 physical page ids — the slot's page-table
    row, fixed width (entries past the used prefix may repeat a real
    page; the caller slices the used prefix host-side). Returns
    ``(k, v, k_scales, v_scales)`` with k/v shaped
    ``(num_layers, P, page_size, heads, head_dim)`` and scales
    ``(num_layers, P, page_size, heads)`` (None for float pools)."""
    k = jnp.take(cache.k_pages, page_ids, axis=1, mode="clip")
    v = jnp.take(cache.v_pages, page_ids, axis=1, mode="clip")
    if cache.k_scales is None:
        return k, v, None, None
    k_s = jnp.take(cache.k_scales, page_ids, axis=1, mode="clip")
    v_s = jnp.take(cache.v_scales, page_ids, axis=1, mode="clip")
    return k, v, k_s, v_s


def scatter_slot_pages(cache: PagedKVCache, page_ids, active, slot,
                       seq_len, k_rows, v_rows, k_srows=None,
                       v_srows=None):
    """Splice gathered pages into freshly allocated destination pages.

    ``page_ids``: (P,) int32 destination physical ids; ``active``:
    (P,) bool — True for the used prefix (inactive entries are routed
    out of range and dropped, so zero padding never lands in live
    pages). ``slot``/``seq_len``: scalars — the destination slot's
    ``seq_lens`` entry is set so the very next decode step appends at
    the correct position. ``k_rows``/``v_rows`` (and scale rows for
    int8 pools) are the :func:`gather_slot_pages` outputs, zero-padded
    to width P. Returns the updated cache; the caller owns writing the
    destination page-table row (host-side bookkeeping)."""
    max_pages = cache.k_pages.shape[1]
    phys = jnp.where(active, page_ids, max_pages + 2 ** 20)
    k_pages = cache.k_pages.at[:, phys].set(k_rows, mode="drop")
    v_pages = cache.v_pages.at[:, phys].set(v_rows, mode="drop")
    seq_lens = cache.seq_lens.at[slot].set(
        jnp.asarray(seq_len, jnp.int32))
    if cache.k_scales is None:
        return cache._replace(k_pages=k_pages, v_pages=v_pages,
                              seq_lens=seq_lens)
    k_scales = cache.k_scales.at[:, phys].set(k_srows, mode="drop")
    v_scales = cache.v_scales.at[:, phys].set(v_srows, mode="drop")
    return cache._replace(k_pages=k_pages, v_pages=v_pages,
                          seq_lens=seq_lens, k_scales=k_scales,
                          v_scales=v_scales)


# Handoff blob: a host-side dict holding one sequence's cache rows plus
# the decode-resume state. Array fields (below) are np arrays sliced to
# the used page count; everything else is plain scalars, so the wire
# codec round-trips through JSON for the HTTP hop.
HANDOFF_VERSION = 1
_WIRE_ARRAYS = ("k", "v", "k_scales", "v_scales")


def _arr_to_wire(a) -> dict:
    a = np.ascontiguousarray(a)
    return {"shape": list(a.shape), "dtype": a.dtype.name,
            "data": base64.b64encode(a.tobytes()).decode("ascii")}


def _arr_from_wire(w):
    a = np.frombuffer(base64.b64decode(w["data"]),
                      dtype=np.dtype(str(w["dtype"])))
    return a.reshape([int(d) for d in w["shape"]]).copy()


def handoff_to_wire(blob: dict) -> dict:
    """JSON-safe encoding of a handoff blob: arrays become
    ``{shape, dtype, data: base64}`` (bfloat16 rides through ml_dtypes'
    registered np dtype; int8 pages keep their ~3.7x size edge on the
    wire)."""
    wire = {k: v for k, v in blob.items() if k not in _WIRE_ARRAYS}
    for name in _WIRE_ARRAYS:
        a = blob.get(name)
        wire[name] = None if a is None else _arr_to_wire(a)
    return wire


def handoff_from_wire(wire: dict) -> dict:
    """Inverse of :func:`handoff_to_wire` — bit-exact array restore."""
    blob = {k: v for k, v in wire.items() if k not in _WIRE_ARRAYS}
    for name in _WIRE_ARRAYS:
        w = wire.get(name)
        blob[name] = None if w is None else _arr_from_wire(w)
    return blob


def handoff_nbytes(blob: dict) -> int:
    """Payload size of the blob's array fields (wire-cost metric)."""
    return sum(int(blob[n].nbytes) for n in _WIRE_ARRAYS
               if blob.get(n) is not None)


class PageAllocator:
    """Host-side free list over the physical page pool (the half of
    PagedAttention that is pure bookkeeping, so it stays in Python:
    the continuous batcher calls it between compiled steps, never
    inside them).

    Not thread-safe by itself — the batcher serializes access under
    its own lock.
    """

    def __init__(self, max_pages: int):
        self.max_pages = int(max_pages)
        self._free = list(range(self.max_pages - 1, -1, -1))

    @property
    def free_pages(self) -> int:
        return len(self._free)

    def can_alloc(self, n: int) -> bool:
        return n <= len(self._free)

    def alloc(self, n: int) -> "list[int]":
        """Pop ``n`` physical page ids; raises MemoryError when the
        pool cannot satisfy the request (callers check
        :meth:`can_alloc` to defer admission instead)."""
        if n > len(self._free):
            raise MemoryError(
                f"page pool exhausted: want {n}, have "
                f"{len(self._free)} of {self.max_pages}")
        if n <= 0:
            return []
        out = self._free[-n:][::-1]
        del self._free[-n:]
        return out

    def free(self, pages: Sequence[int]) -> None:
        for p in pages:
            if not 0 <= p < self.max_pages:
                raise ValueError(f"bad page id {p}")
        self._free.extend(pages)

    @staticmethod
    def pages_needed(tokens: int, page_size: int) -> int:
        return -(-int(tokens) // int(page_size))
