"""Paged KV cache for autoregressive decode.

vLLM's PagedAttention (SOSP '23) insight, applied to this stack:
instead of one contiguous (B, T_max, H, D) K/V buffer per layer —
whose T axis either reallocates as sequences grow (recompile) or pads
every sequence to the worst case (HBM waste) — K/V live in a
fixed-size pool of small pages, `(max_pages, page_size, heads,
head_dim)` per layer, preallocated once. A per-slot page table maps
logical token positions to physical pages, so sequence growth only
ever writes one (heads, head_dim) row into an existing page (or walks
onto a freshly assigned one) and NO array shape ever changes: the
whole decode loop stays one compiled program regardless of how many
sequences join, leave, or how long they run.

Everything device-side here is shape-static and jit-safe:

- :func:`init_cache` — allocate the pool (zeros) + identity tables;
- :func:`append_layer` — scatter one new token's K/V per slot into
  one layer's pool (inactive slots are routed out-of-range and
  dropped, so padded batch slots never corrupt live pages);
- :func:`write_prompt_layer` — bulk-scatter a whole (right-padded)
  prompt's K/V at prefill (pad rows land in pages past `seq_len` and
  are never gathered — the length mask owns validity);
- :func:`gather_layer` / :func:`length_mask` — page-table gather back
  to a dense (S, T, H, D) view + key-validity mask for attention.

The host-side :class:`PageAllocator` is the bookkeeping half: a free
list of physical page ids for the continuous batcher, which assigns
pages at admission / token-boundary growth and reclaims them at
retirement (`pipeline/inference/batching.py::ContinuousBatcher`).
"""

from __future__ import annotations

from typing import NamedTuple, Sequence

import jax.numpy as jnp
import numpy as np


class PagedKVCache(NamedTuple):
    """The device-side cache state threaded through the decode loop.

    ``k_pages``/``v_pages``: (num_layers, max_pages, page_size,
    heads, head_dim) — the preallocated pools.
    ``page_table``: (max_slots, pages_per_slot) int32 physical page
    ids (logical page j of slot s lives in ``page_table[s, j]``).
    ``seq_lens``: (max_slots,) int32 tokens currently cached per slot
    (0 = free slot; doubles as the active mask).
    """

    k_pages: jnp.ndarray
    v_pages: jnp.ndarray
    page_table: jnp.ndarray
    seq_lens: jnp.ndarray

    @property
    def page_size(self) -> int:
        return self.k_pages.shape[2]

    @property
    def max_context(self) -> int:
        return self.page_table.shape[1] * self.page_size

    @property
    def max_slots(self) -> int:
        return self.page_table.shape[0]


def init_cache(num_layers: int, max_slots: int, max_context: int,
               heads: int, head_dim: int, page_size: int = 16,
               max_pages: int = 0,
               dtype=jnp.float32) -> PagedKVCache:
    """Allocate the pool. ``max_context`` rounds up to whole pages.
    ``max_pages`` defaults to ``max_slots * pages_per_slot`` (every
    slot can reach max_context simultaneously) and the table starts as
    the identity mapping — the compiled-loop `generate()` path uses it
    as-is; the continuous batcher overwrites tables from its
    :class:`PageAllocator` as sequences come and go."""
    pages_per_slot = -(-int(max_context) // int(page_size))
    max_pages = int(max_pages) or int(max_slots) * pages_per_slot
    if max_pages < max_slots * pages_per_slot:
        raise ValueError(
            f"max_pages {max_pages} < max_slots*pages_per_slot "
            f"{max_slots * pages_per_slot}; the identity table "
            f"would alias pages")
    shape = (num_layers, max_pages, page_size, heads, head_dim)
    table = np.arange(max_slots * pages_per_slot, dtype=np.int32)
    return PagedKVCache(
        k_pages=jnp.zeros(shape, dtype),
        v_pages=jnp.zeros(shape, dtype),
        page_table=jnp.asarray(
            table.reshape(max_slots, pages_per_slot)),
        seq_lens=jnp.zeros((max_slots,), jnp.int32),
    )


def _scatter_coords(page_table, seq_lens, positions, page_size,
                    active):
    """(physical page, in-page offset) per (slot, position); inactive
    rows are pushed out of range so ``mode="drop"`` discards them."""
    pages_per_slot = page_table.shape[1]
    logical = positions // page_size                 # (S, ...) int32
    # clamp the table lookup; `active` (which callers AND with
    # position < max_context) owns whether the row lands at all
    logical = jnp.minimum(logical, pages_per_slot - 1)
    phys = jnp.take_along_axis(
        page_table, logical.reshape(page_table.shape[0], -1), axis=1
    ).reshape(logical.shape)
    offset = positions % page_size
    max_pages_shape = page_table.shape[0] * page_table.shape[1]
    # any value past every real page id works as the drop sentinel
    phys = jnp.where(active, phys, max_pages_shape + 2 ** 20)
    return phys, offset


def append_layer(k_pages, v_pages, page_table, seq_lens,
                 k_new, v_new, active=None):
    """Scatter one decode step's K/V into one layer's pool.

    k_pages/v_pages: (P, page, H, D); k_new/v_new: (S, H, D) — the new
    token of every slot, written at position ``seq_lens[s]``. Slots
    with ``active == False`` (or ``seq_lens == 0`` when active is
    None... callers pass the done-mask) are dropped, not written.
    Returns the updated (k_pages, v_pages). Shape-static; safe inside
    scan/while_loop."""
    page_size = k_pages.shape[1]
    if active is None:
        active = jnp.ones(seq_lens.shape, jnp.bool_)
    max_ctx = page_table.shape[1] * page_size
    active = jnp.logical_and(active, seq_lens < max_ctx)
    phys, offset = _scatter_coords(page_table, seq_lens, seq_lens,
                                   page_size, active)
    k_pages = k_pages.at[phys, offset].set(k_new, mode="drop")
    v_pages = v_pages.at[phys, offset].set(v_new, mode="drop")
    return k_pages, v_pages


def write_prompt_layer(k_pages, v_pages, page_table, prompt_lens,
                       k_seq, v_seq):
    """Bulk prefill scatter for one layer: k_seq/v_seq (S, T, H, D)
    hold the (right-padded) prompt K/V; positions past
    ``prompt_lens[s]`` are dropped (never written), so pad tokens
    cannot leak into pages a later admit might reuse."""
    s, t = k_seq.shape[0], k_seq.shape[1]
    page_size = k_pages.shape[1]
    positions = jnp.broadcast_to(
        jnp.arange(t, dtype=jnp.int32)[None, :], (s, t))
    active = positions < prompt_lens[:, None]
    phys, offset = _scatter_coords(page_table, prompt_lens, positions,
                                   page_size, active)
    k_pages = k_pages.at[phys, offset].set(k_seq, mode="drop")
    v_pages = v_pages.at[phys, offset].set(v_seq, mode="drop")
    return k_pages, v_pages


def gather_layer(pages, page_table, t_max: int):
    """Page-table gather back to a dense (S, t_max, H, D) view of one
    layer's cache (positions past a slot's ``seq_len`` hold stale/zero
    rows — :func:`length_mask` owns validity). ``t_max`` is static and
    must be a whole number of pages."""
    page_size = pages.shape[1]
    if t_max % page_size:
        raise ValueError(f"t_max {t_max} not a multiple of page_size "
                         f"{page_size}")
    n = t_max // page_size
    picked = jnp.take(pages, page_table[:, :n], axis=0,
                      mode="clip")                 # (S, n, page, H, D)
    s = page_table.shape[0]
    return picked.reshape((s, t_max) + pages.shape[2:])


def length_mask(seq_lens, t: int):
    """(S, t) bool key-validity mask: position p of slot s is a real
    cached token iff ``p < seq_lens[s]``."""
    return jnp.arange(t, dtype=jnp.int32)[None, :] < seq_lens[:, None]


class PageAllocator:
    """Host-side free list over the physical page pool (the half of
    PagedAttention that is pure bookkeeping, so it stays in Python:
    the continuous batcher calls it between compiled steps, never
    inside them).

    Not thread-safe by itself — the batcher serializes access under
    its own lock.
    """

    def __init__(self, max_pages: int):
        self.max_pages = int(max_pages)
        self._free = list(range(self.max_pages - 1, -1, -1))

    @property
    def free_pages(self) -> int:
        return len(self._free)

    def can_alloc(self, n: int) -> bool:
        return n <= len(self._free)

    def alloc(self, n: int) -> "list[int]":
        """Pop ``n`` physical page ids; raises MemoryError when the
        pool cannot satisfy the request (callers check
        :meth:`can_alloc` to defer admission instead)."""
        if n > len(self._free):
            raise MemoryError(
                f"page pool exhausted: want {n}, have "
                f"{len(self._free)} of {self.max_pages}")
        if n <= 0:
            return []
        out = self._free[-n:][::-1]
        del self._free[-n:]
        return out

    def free(self, pages: Sequence[int]) -> None:
        for p in pages:
            if not 0 <= p < self.max_pages:
                raise ValueError(f"bad page id {p}")
        self._free.extend(pages)

    @staticmethod
    def pages_needed(tokens: int, page_size: int) -> int:
        return -(-int(tokens) // int(page_size))
