from analytics_zoo_tpu.common.nncontext import (
    init_nncontext,
    get_nncontext,
    NNContext,
    ZooTpuConf,
)
from analytics_zoo_tpu.common.config import ZooBuildInfo

__all__ = [
    "init_nncontext",
    "get_nncontext",
    "NNContext",
    "ZooTpuConf",
    "ZooBuildInfo",
]
