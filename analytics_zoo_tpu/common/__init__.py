from analytics_zoo_tpu.common.nncontext import (
    init_nncontext,
    get_nncontext,
    NNContext,
    ZooTpuConf,
)
from analytics_zoo_tpu.common.config import ZooBuildInfo
from analytics_zoo_tpu.common import (
    diagnostics, dictionary, observability, safe_pickle, slo,
    tracing, utils)
from analytics_zoo_tpu.common.dictionary import ZooDictionary
from analytics_zoo_tpu.common.observability import (
    MetricsRegistry,
    counter,
    gauge,
    histogram,
    span,
    event,
    snapshot,
    to_prometheus,
    get_registry,
    reset_metrics,
)
from analytics_zoo_tpu.common.safe_pickle import checked_load

__all__ = [
    "init_nncontext",
    "get_nncontext",
    "NNContext",
    "ZooTpuConf",
    "ZooBuildInfo",
    "ZooDictionary",
    "MetricsRegistry",
    "counter",
    "gauge",
    "histogram",
    "span",
    "event",
    "snapshot",
    "to_prometheus",
    "get_registry",
    "reset_metrics",
    "checked_load",
    "diagnostics",
    "dictionary",
    "observability",
    "safe_pickle",
    "slo",
    "tracing",
    "utils",
]
