"""Fleet-wide telemetry plane: metric federation + trace stitching
(the observability layer at fleet scale).

Every observability surface in this repo — the metrics registry, the
trace ring, the SLO engine — is a process-global singleton, so a
PR 7 fleet of :class:`~analytics_zoo_tpu.pipeline.inference.fleet.
HttpReplica` processes is a set of telemetry islands: the router's
``/metrics`` and ``/debug/traces`` show only the router. This module
turns those islands into ONE plane, following the two classic
shapes:

- **Monarch/Prometheus-federation-style metric merging** —
  :func:`merge_snapshots` folds N ``MetricsRegistry.snapshot()``
  dumps into one: counters summed, histogram buckets added (with an
  exact intersection-of-boundaries rule for mismatched bucket
  layouts — cumulative counts at a shared ``le`` stay valid under
  any boundary set), gauges kept per-source under an added
  ``replica=`` label, and type conflicts resolved first-seen-wins
  with the losers reported, never silently mixed.
- **Dapper-style cross-process trace stitching** — the
  :class:`TraceAggregator` joins span records scraped from every
  process by trace id (the ``X-Zoo-Trace-Id`` the serving stack
  already propagates), so ``GET /debug/trace/<id>`` returns one
  stitched timeline and the Perfetto export renders each process as
  its own track group.

The :class:`TelemetryCollector` rides on the ``FleetRouter``: it
scrapes each HTTP replica's ``GET /metrics/json`` and incremental
``GET /debug/traces?since=<seq>`` cursor (collectors never re-read
the ring), merges, publishes fleet summary gauges
(``zoo_tpu_fed_*`` — the federated SLO rules in `common/slo.py`
evaluate those), and feeds the per-replica window stats into
:class:`~analytics_zoo_tpu.common.diagnostics.ReplicaSkewDetector`.
Background ticker interval is ``ZOO_TPU_FED_TICK_S`` (default 5 s);
``<= 0`` starts no thread — drive :meth:`~TelemetryCollector.tick`
manually with an injected ``now`` (the `common/slo.py` convention),
so every behavior is testable without wall-clock sleeps.

Stdlib-only on purpose (urllib for the scrapes): the collector runs
inside the router process next to the serving hot path and must
never drag in jax.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
import urllib.request
from typing import Any, Callable, Dict, List, Optional, Tuple

from analytics_zoo_tpu.common import diagnostics
from analytics_zoo_tpu.common import observability as obs
from analytics_zoo_tpu.common import timeseries
from analytics_zoo_tpu.common import tracing

__all__ = [
    "merge_snapshots",
    "render_prometheus",
    "TraceAggregator",
    "TelemetryCollector",
]


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


# ---------------------------------------------------------------------------
# Metric federation: merge N registry snapshots into one
# ---------------------------------------------------------------------------

def _label_key(labels: "Dict[str, Any]"
               ) -> "Tuple[Tuple[str, str], ...]":
    return tuple(sorted((str(k), str(v))
                        for k, v in (labels or {}).items()))


def _merge_histograms(children: "List[dict]") -> dict:
    """Fold same-label histogram children from multiple sources.

    Identical bucket layouts sum pointwise. Mismatched layouts merge
    over the **intersection** of finite bounds — exact, not an
    approximation: a cumulative count at bound ``le`` ("observations
    ≤ le") is a valid statement regardless of what other bounds a
    source used, so summing cumulative counts at shared bounds loses
    nothing but resolution between dropped bounds. ``+Inf``, count
    and sum always survive."""
    bound_sets = []
    for rec in children:
        bound_sets.append({le for le in rec.get("buckets", {})
                           if le != "+Inf"})
    shared = set.intersection(*bound_sets) if bound_sets else set()
    les = sorted(shared, key=float)
    buckets: "Dict[str, float]" = {le: 0.0 for le in les}
    total = 0.0
    count = 0.0
    hsum = 0.0
    for rec in children:
        b = rec.get("buckets", {})
        c = float(rec.get("count", 0))
        for le in les:
            buckets[le] += float(b.get(le, 0.0))
        total += float(b.get("+Inf", c))
        count += c
        hsum += float(rec.get("sum", 0.0))
    buckets["+Inf"] = total
    return {"count": count, "sum": hsum, "buckets": buckets}


def merge_snapshots(snapshots: "Dict[str, dict]"
                    ) -> "Tuple[dict, List[dict]]":
    """Merge per-source ``MetricsRegistry.snapshot()`` dumps into one
    snapshot-shaped dict (renderable by :func:`render_prometheus`).

    ``snapshots`` maps source name (replica/process) → snapshot.
    Rules:

    - **counters**: summed across sources per label set;
    - **histograms**: counts/sums added; bucket counts added over
      the intersection of bucket boundaries when sources disagree
      (see :func:`_merge_histograms` — exact for cumulative counts);
    - **gauges**: kept per-source — a ``replica=<source>`` label is
      added (a point-in-time value summed across processes is
      meaningless; per-source it stays diagnosable). A child that
      already carries a ``replica`` label keeps it (it is already a
      per-replica identity, e.g. the router's own fleet gauges);
    - **type conflicts**: the first-seen type (sources in sorted
      name order) wins; later sources' conflicting families are
      dropped and reported in the returned conflict list — merging
      a counter into a histogram would corrupt both.

    Returns ``(merged, conflicts)``; ``conflicts`` entries are
    ``{"metric", "source", "type", "kept_type"}``."""
    merged: "Dict[str, dict]" = {}
    conflicts: "List[dict]" = []
    # (name, label_key) -> list of child recs, for counter/histogram
    acc: "Dict[Tuple[str, tuple], List[dict]]" = {}
    for source in sorted(snapshots):
        snap = snapshots[source] or {}
        for name in sorted(snap):
            fam = snap[name]
            mtype = fam.get("type")
            if name not in merged:
                merged[name] = {"type": mtype,
                                "help": fam.get("help", ""),
                                "values": []}
            elif merged[name]["type"] != mtype:
                conflicts.append({
                    "metric": name, "source": source,
                    "type": mtype,
                    "kept_type": merged[name]["type"]})
                continue
            if not merged[name]["help"]:
                merged[name]["help"] = fam.get("help", "")
            for rec in fam.get("values", ()):
                labels = dict(rec.get("labels", {}))
                if mtype == "gauge":
                    if "replica" not in labels:
                        labels["replica"] = source
                    merged[name]["values"].append(
                        {"labels": labels,
                         "value": float(rec.get("value", 0.0))})
                else:
                    acc.setdefault(
                        (name, _label_key(labels)),
                        []).append(rec)
    for (name, lkey), children in acc.items():
        labels = dict(lkey)
        if merged[name]["type"] == "histogram":
            out = dict(_merge_histograms(children), labels=labels)
        else:
            out = {"labels": labels,
                   "value": float(sum(
                       float(r.get("value", 0.0))
                       for r in children))}
        merged[name]["values"].append(out)
    for fam in merged.values():
        fam["values"].sort(
            key=lambda r: _label_key(r.get("labels", {})))
    return merged, conflicts


def render_prometheus(merged: dict) -> str:
    """Prometheus text exposition (format 0.0.4) of a merged
    snapshot. One ``# HELP`` / ``# TYPE`` per family — deduplicated
    by construction, since :func:`merge_snapshots` collapses every
    source's family into one."""
    esc = obs._escape_label
    fmt = obs._fmt
    lines: "List[str]" = []

    def label_str(labels: "Dict[str, str]",
                  extra: "Optional[Tuple[str, str]]" = None) -> str:
        items = sorted(labels.items())
        if extra is not None:
            items = items + [extra]
        if not items:
            return ""
        inner = ",".join(f'{k}="{esc(v)}"' for k, v in items)
        return "{" + inner + "}"

    for name in sorted(merged):
        fam = merged[name]
        if fam.get("help"):
            lines.append(f"# HELP {name} {fam['help']}")
        lines.append(f"# TYPE {name} {fam.get('type', 'untyped')}")
        for rec in fam.get("values", ()):
            labels = rec.get("labels", {})
            if fam.get("type") == "histogram":
                buckets = rec.get("buckets", {})
                les = sorted((le for le in buckets if le != "+Inf"),
                             key=float)
                for le in les:
                    lines.append(
                        f"{name}_bucket"
                        f"{label_str(labels, ('le', le))} "
                        f"{fmt(buckets[le])}")
                inf = buckets.get("+Inf", rec.get("count", 0))
                lines.append(
                    f"{name}_bucket"
                    f"{label_str(labels, ('le', '+Inf'))} "
                    f"{fmt(inf)}")
                lines.append(f"{name}_sum{label_str(labels)} "
                             f"{fmt(rec.get('sum', 0.0))}")
                lines.append(f"{name}_count{label_str(labels)} "
                             f"{fmt(rec.get('count', 0))}")
            else:
                lines.append(f"{name}{label_str(labels)} "
                             f"{fmt(rec.get('value', 0.0))}")
    return "\n".join(lines) + ("\n" if lines else "")


# ---------------------------------------------------------------------------
# Trace stitching: join spans from N processes by trace id
# ---------------------------------------------------------------------------

class TraceAggregator:
    """Router-side store of span records scraped from every process
    in the fleet, joined by trace id. Spans arrive as plain dicts
    (the ``/debug/traces?since=`` wire shape) and are tagged with
    their ``source`` process, so the Perfetto export can give each
    process its own lane. Bounded ring
    (``ZOO_TPU_FED_TRACE_BUFFER`` spans, default 8192) — a flight
    recorder, like the per-process store it federates."""

    def __init__(self, capacity: Optional[int] = None):
        if capacity is None:
            capacity = _env_int("ZOO_TPU_FED_TRACE_BUFFER", 8192)
        self.capacity = max(1, int(capacity))
        self._buf: "collections.deque" = collections.deque(
            maxlen=self.capacity)
        self._lock = threading.Lock()

    def add_spans(self, source: str, spans: "List[dict]") -> int:
        """Ingest one scrape's worth of span dicts from ``source``.
        Returns how many were added."""
        n = 0
        with self._lock:
            for rec in spans:
                if not isinstance(rec, dict) or \
                        not rec.get("trace_id"):
                    continue
                rec = dict(rec)
                rec.setdefault("source", source)
                self._buf.append(rec)
                n += 1
        return n

    def __len__(self) -> int:
        with self._lock:
            return len(self._buf)

    def spans(self, trace_id: str) -> "List[dict]":
        with self._lock:
            recs = [dict(r) for r in self._buf
                    if r.get("trace_id") == trace_id]
        recs.sort(key=lambda r: r.get("t_start", 0.0))
        return recs

    def trace(self, trace_id: str) -> "Optional[dict]":
        """One stitched timeline: every buffered span of
        ``trace_id`` from every source, oldest-start first, plus the
        set of processes it touched. None when unknown."""
        recs = self.spans(trace_id)
        if not recs:
            return None
        t0 = min(r.get("t_start", 0.0) for r in recs)
        t1 = max(r.get("t_start", 0.0) + (r.get("dur_s") or 0.0)
                 for r in recs)
        return {"trace_id": trace_id,
                "t_start": round(t0, 6),
                "dur_s": round(t1 - t0, 6),
                "n_spans": len(recs),
                "sources": sorted({r.get("source", "router")
                                   for r in recs}),
                "spans": recs}

    def chrome(self, trace_id: Optional[str] = None) -> dict:
        """Perfetto-loadable chrome-trace JSON with one process lane
        per SOURCE process (distinct pid per replica), so one
        request renders as parallel tracks: router dispatch on one
        lane, the replica's queue/pad/execute on another."""
        with self._lock:
            recs = list(self._buf)
        if trace_id is not None:
            recs = [r for r in recs if r.get("trace_id") == trace_id]
        return {"traceEvents": tracing.chrome_events(
            recs, source_lanes=True),
            "displayTimeUnit": "ms"}

    def recent(self, n: int = 20) -> "List[dict]":
        """The ``n`` most recently completed stitched traces, newest
        first (same shape as :meth:`trace`, without the full span
        list capped)."""
        with self._lock:
            recs = list(self._buf)
        order: "List[str]" = []
        seen = set()
        for r in recs:
            tid = r.get("trace_id")
            if tid in seen:
                order.remove(tid)
            else:
                seen.add(tid)
            order.append(tid)
        out = []
        for tid in reversed(order[-max(0, n):] if n else []):
            t = self.trace(tid)
            if t is not None:
                out.append(t)
        return out

    def clear(self):
        with self._lock:
            self._buf.clear()


# ---------------------------------------------------------------------------
# The collector: scrape → merge → publish → detect
# ---------------------------------------------------------------------------

def _fed_sources_gauge():
    return obs.gauge("zoo_tpu_fed_sources",
                     help="telemetry sources merged in the last "
                          "federation tick")


def _fed_scrapes(replica: str, ok: bool):
    return obs.counter("zoo_tpu_fed_scrapes_total",
                       help="federation scrape attempts by source "
                            "and outcome",
                       labels={"replica": replica,
                               "ok": "1" if ok else "0"})


def _fed_spans(replica: str):
    return obs.counter("zoo_tpu_fed_spans_total",
                       help="trace spans collected per source",
                       labels={"replica": replica})


def _fed_source_age(replica: str):
    return obs.gauge("zoo_tpu_fed_source_age_s",
                     help="age of each source's newest good "
                          "scrape (carried-forward data shows "
                          "its true staleness here)",
                     labels={"replica": replica})


def _fed_p99_gauge():
    return obs.gauge("zoo_tpu_fed_latency_p99_seconds",
                     help="fleet-wide /predict p99 over the last "
                          "federation window")


def _fed_error_gauge():
    return obs.gauge("zoo_tpu_fed_error_ratio",
                     help="fleet-wide serving error ratio over the "
                          "last federation window")


def _hist_children(snap: dict, metric: str) -> "List[dict]":
    fam = snap.get(metric) or {}
    if fam.get("type") != "histogram":
        return []
    return list(fam.get("values", ()))


def _window_hist_stats(cur: dict, prev: dict, metric: str,
                       label_filter: "Optional[Dict[str, str]]"
                       = None) -> "Tuple[Optional[float], float]":
    """(p99, events) of ``metric`` over the delta between two
    snapshots of ONE source, children summed (optionally filtered by
    a label subset). None p99 when the family is absent or empty."""

    def agg(snap):
        buckets: "Dict[str, float]" = {}
        count = 0.0
        for rec in _hist_children(snap, metric):
            labels = rec.get("labels", {})
            if label_filter and any(
                    labels.get(k) != v
                    for k, v in label_filter.items()):
                continue
            count += float(rec.get("count", 0))
            for le, c in rec.get("buckets", {}).items():
                buckets[le] = buckets.get(le, 0.0) + float(c)
        return buckets, count

    cb, cc = agg(cur)
    pb, pc = agg(prev)
    if not cb:
        return None, 0.0
    les = sorted((le for le in cb if le != "+Inf"), key=float)
    cum = [max(cb[le] - pb.get(le, 0.0), 0.0) for le in les]
    cum.append(max(cb.get("+Inf", cc) - pb.get("+Inf", 0.0), 0.0))
    per, prev_c = [], 0.0
    for c in cum:
        c = max(c, prev_c)
        per.append(c - prev_c)
        prev_c = c
    events = max(cc - pc, 0.0)
    if events <= 0:
        return None, 0.0
    p99 = obs.bucket_quantile([float(le) for le in les], per, 0.99)
    return p99, events


def _counter_sum(snap: dict, metric: str,
                 labels: "Optional[Dict[str, str]]" = None
                 ) -> float:
    fam = snap.get(metric) or {}
    total = 0.0
    for rec in fam.get("values", ()):
        rl = rec.get("labels", {})
        if labels and any(rl.get(k) != v
                          for k, v in labels.items()):
            continue
        total += float(rec.get("value", 0.0))
    return total


class TelemetryCollector:
    """Scrapes every telemetry source of a fleet, merges, publishes.

    Sources: the router's own process (in-process replicas share its
    registry and trace ring, so "router" covers them) plus one
    source per replica exposing a ``.url`` (HttpReplica processes),
    scraped over ``GET /metrics/json`` and the incremental
    ``GET /debug/traces?since=<seq>`` cursor.

    Each :meth:`tick`:

    1. scrapes all sources (a failed scrape keeps the source's last
       snapshot, marked stale — a wedged replica must not blank the
       fleet view);
    2. merges metric snapshots (:func:`merge_snapshots`) for
       ``GET /metrics?fleet=1`` / ``GET /debug/fleet/telemetry``;
    3. ingests new spans into the :class:`TraceAggregator`
       (``GET /debug/trace/<id>`` serves stitched timelines);
    4. publishes fleet summary gauges (``zoo_tpu_fed_*``) that the
       federated SLO rules evaluate;
    5. computes per-replica window stats from the router's
       per-replica dispatch histograms and runs the
       :class:`~analytics_zoo_tpu.common.diagnostics.
       ReplicaSkewDetector`.

    ``tick_s=None`` reads ``ZOO_TPU_FED_TICK_S`` (default 5 s);
    ``<= 0`` starts no thread (manual :meth:`tick`, injectable
    ``now``)."""

    def __init__(self, router, tick_s: Optional[float] = None,
                 clock: "Optional[Callable[[], float]]" = None,
                 scrape_timeout_s: float = 5.0,
                 skew: "Optional[diagnostics.ReplicaSkewDetector]"
                 = None):
        self.router = router
        if tick_s is None:
            tick_s = _env_float("ZOO_TPU_FED_TICK_S", 5.0)
        self.tick_s = float(tick_s)
        self._clock = clock or time.monotonic
        self.scrape_timeout_s = float(scrape_timeout_s)
        self.aggregator = TraceAggregator()
        self.skew = skew if skew is not None else \
            diagnostics.ReplicaSkewDetector()
        self._lock = threading.RLock()
        self._merged: "Optional[dict]" = None
        self._conflicts: "List[dict]" = []
        self._snaps: "Dict[str, dict]" = {}     # last good snapshot
        self._prev_snaps: "Dict[str, dict]" = {}
        self._prev_replica_stats: "Dict[str, dict]" = {}
        self._cursors: "Dict[str, int]" = {}    # source -> trace seq
        self._source_meta: "Dict[str, dict]" = {}
        self._carried: "List[str]" = []
        # fleet-merged metric history: one timeline across replicas
        # (append-only — fed merged snapshots each tick; served via
        # GET /debug/metrics/history?fleet=1)
        self.history = timeseries.MetricHistory(
            registry=None, clock=self._clock)
        self._ticks = 0
        self._last_tick_at: Optional[float] = None
        self._thread: Optional[threading.Thread] = None
        self._stop_evt = threading.Event()

    # -- sources -------------------------------------------------------------
    def _http_sources(self) -> "List[Tuple[str, str]]":
        out = []
        pool = getattr(self.router, "pool", None)
        for r in getattr(pool, "replicas", ()):
            url = getattr(r, "url", None)
            if url:
                out.append((r.name, url))
        return out

    def _fetch_json(self, url: str) -> dict:
        with urllib.request.urlopen(
                url, timeout=self.scrape_timeout_s) as resp:
            return json.loads(resp.read())

    def _scrape_one(self, name: str, url: str,
                    at: Optional[float] = None) -> None:
        """One source's metrics + incremental trace scrape; records
        the outcome, never raises (telemetry must not take down the
        router). ``at`` is the tick timestamp the scrape is stamped
        with (falls back to the clock), so source ages stay on the
        injectable-clock timeline."""
        meta = self._source_meta.setdefault(name, {})
        try:
            payload = self._fetch_json(url + "/metrics/json")
            snap = payload.get("metrics", payload)
            since = self._cursors.get(name, 0)
            tr = self._fetch_json(
                f"{url}/debug/traces?since={since}")
            spans = tr.get("spans", [])
            self._cursors[name] = int(tr.get("seq", since))
        except Exception as e:
            _fed_scrapes(name, ok=False).inc()
            meta["ok"] = False
            meta["error"] = f"{type(e).__name__}: {e}"
            return
        _fed_scrapes(name, ok=True).inc()
        n = self.aggregator.add_spans(name, spans)
        if n:
            _fed_spans(name).inc(n)
        meta.update(ok=True, error=None,
                    last_scrape_at=(self._clock() if at is None
                                    else float(at)),
                    spans_collected=meta.get("spans_collected", 0)
                    + n)
        self._snaps[name] = snap

    def _scrape_router(self, at: Optional[float] = None) -> None:
        """The router's own process is always a source: its registry
        snapshot (which covers in-process replicas) and its local
        trace ring, read through the same incremental cursor."""
        store = tracing.get_store()
        since = self._cursors.get("router", 0)
        seq, recs = store.records_since(since)
        self._cursors["router"] = seq
        n = self.aggregator.add_spans(
            "router", [r.to_dict() for r in recs])
        if n:
            _fed_spans("router").inc(n)
        self._snaps["router"] = obs.snapshot()
        self._source_meta.setdefault("router", {}).update(
            ok=True, error=None,
            last_scrape_at=(self._clock() if at is None
                            else float(at)),
            spans_collected=self._source_meta.get(
                "router", {}).get("spans_collected", 0) + n)

    # -- per-replica skew stats ----------------------------------------------
    def _replica_stats(self) -> "Dict[str, dict]":
        """Per-replica window stats from the router's OWN dispatch
        accounting (`zoo_tpu_fleet_replica_latency_seconds{replica}`
        etc.) — the router measures dispatch-to-resolve for every
        replica, in-process or HTTP, so skew detection is uniform
        across transports."""
        cur = self._snaps.get("router") or {}
        prev = self._prev_snaps.get("router") or {}
        stats: "Dict[str, dict]" = {}
        pool = getattr(self.router, "pool", None)
        for r in getattr(pool, "replicas", ()):
            sel = {"replica": r.name}
            p99, events = _window_hist_stats(
                cur, prev, "zoo_tpu_fleet_replica_latency_seconds",
                sel)
            errs = (_counter_sum(
                cur, "zoo_tpu_fleet_replica_errors_total", sel)
                - _counter_sum(
                    prev, "zoo_tpu_fleet_replica_errors_total",
                    sel))
            attempts = events + max(errs, 0.0)
            stats[r.name] = {
                "p99_s": p99,
                "error_ratio": (max(errs, 0.0) / attempts
                                if attempts > 0 else None),
                "events": attempts,
            }
        return stats

    # -- the tick ------------------------------------------------------------
    def tick(self, now: Optional[float] = None) -> dict:
        """One scrape/merge/publish/detect pass; thread-safe,
        idempotent, callable from the ticker thread, a debug route,
        or a test with an injected ``now``."""
        with self._lock:
            t = self._clock() if now is None else float(now)
            self._prev_snaps = dict(self._snaps)
            self._snaps = {}
            self._scrape_router(at=t)
            for name, url in self._http_sources():
                self._scrape_one(name, url, at=t)
            # carry forward the last good snapshot of a source that
            # failed this tick (stale beats absent for merged views)
            # — but record WHICH sources are stale, and publish each
            # source's true data age so staleness is never hidden
            carried = [name for name in self._prev_snaps
                       if name not in self._snaps]
            self._carried = carried
            for name, snap in self._prev_snaps.items():
                self._snaps.setdefault(name, snap)
            for name in self._snaps:
                at = self._source_meta.get(name, {}).get(
                    "last_scrape_at")
                if at is not None:
                    _fed_source_age(name).set(
                        round(max(t - at, 0.0), 3))
            merged, conflicts = merge_snapshots(self._snaps)
            self._merged, self._conflicts = merged, conflicts
            self.history.append(t, merged)
            self._ticks += 1
            self._last_tick_at = t
            _fed_sources_gauge().set(len(self._snaps))
            self._publish_summaries()
            stats = self._replica_stats()
            self._prev_replica_stats = stats
            if len(stats) >= 2:
                self.skew.observe(stats, now=t)
            return self.status()

    def _publish_summaries(self):
        """Fleet-level summary gauges over the last tick window —
        computed from per-source deltas then combined, so one
        process's restart (counter reset) cannot go negative. The
        federated SLO rules (`DEFAULT_FED_SLOS`) evaluate these."""
        p99s: "List[Tuple[float, float]]" = []  # (p99, events)
        errs = reqs = 0.0
        for name, cur in self._snaps.items():
            prev = self._prev_snaps.get(name) or {}
            p99, events = _window_hist_stats(
                cur, prev, "zoo_tpu_serving_request_seconds",
                {"path": "/predict"})
            if p99 is not None and events > 0:
                p99s.append((p99, events))
            errs += max(
                _counter_sum(cur, "zoo_tpu_serving_errors_total")
                - _counter_sum(prev,
                               "zoo_tpu_serving_errors_total"),
                0.0)
            reqs += max(
                _counter_sum(cur, "zoo_tpu_serving_requests_total")
                - _counter_sum(prev,
                               "zoo_tpu_serving_requests_total"),
                0.0)
        if p99s:
            # conservative fleet p99: the worst source's window p99
            # (bucket merging across sources is exact only on shared
            # bounds; max is both exact and the paging-relevant one)
            _fed_p99_gauge().set(max(p for p, _ in p99s))
        if reqs > 0:
            _fed_error_gauge().set(min(errs / reqs, 1.0))

    # -- exposition ----------------------------------------------------------
    def merged_snapshot(self) -> "Tuple[dict, List[dict]]":
        """Last merged snapshot + conflicts (tick first for a fresh
        one); empty before the first tick."""
        with self._lock:
            return (self._merged or {}), list(self._conflicts)

    def fleet_prometheus(self) -> str:
        """Prometheus text of the merged fleet view (HELP/TYPE
        deduplicated across sources)."""
        merged, _ = self.merged_snapshot()
        return render_prometheus(merged)

    def status(self) -> dict:
        """JSON-able collector state — the
        ``GET /debug/fleet/telemetry`` payload."""
        with self._lock:
            now = self._clock()
            sources = {}
            for name, meta in self._source_meta.items():
                at = meta.get("last_scrape_at")
                sources[name] = {
                    "ok": bool(meta.get("ok")),
                    "error": meta.get("error"),
                    "age_s": (round(now - at, 3)
                              if at is not None else None),
                    "carried_forward": name in self._carried,
                    "spans_collected": meta.get(
                        "spans_collected", 0),
                    "trace_cursor": self._cursors.get(name, 0),
                }
            return {
                "ticks": self._ticks,
                "tick_s": self.tick_s,
                "sources": sources,
                "history": self.history.stats(),
                "conflicts": list(self._conflicts),
                "replica_stats": dict(self._prev_replica_stats),
                "skew": dict(self.skew.last),
                "stitched_spans": len(self.aggregator),
            }

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "TelemetryCollector":
        """Start the background ticker (no thread when
        ``tick_s <= 0``). Idempotent."""
        if self.tick_s <= 0:
            return self
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return self
            self._stop_evt = threading.Event()
            self._thread = threading.Thread(
                target=self._run, name="zoo-fed-collector",
                daemon=True)
            self._thread.start()
        return self

    def _run(self):
        while not self._stop_evt.wait(self.tick_s):
            try:
                self.tick()
            except Exception:
                pass  # the collector must outlive a bad scrape

    def stop(self):
        with self._lock:
            thread = self._thread
            self._thread = None
        self._stop_evt.set()
        if thread is not None:
            thread.join(timeout=5.0)
