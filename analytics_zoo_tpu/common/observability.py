"""Unified telemetry core (the observability layer, L1).

Reference: BigDL's `TrainSummary`/`ValidationSummary` scalars plus
Spark's executor metrics were the reference platform's entire
operational signal (SURVEY §5, `Topology.scala:197-284`). This module
is the TPU-native, serving-aware replacement: one process-global,
thread-safe registry that every layer (training, ingest, serving)
writes into and that two exposition formats read out of.

Three primitives:

- **metrics registry** — named counters, gauges and fixed-bucket
  histograms with label support (`counter()`, `gauge()`,
  `histogram()`); process-global by default, instantiable
  (:class:`MetricsRegistry`) for tests;
- **spans** — ``with span("train/step", step=i): ...`` times a block
  into a wall-time histogram (``zoo_tpu_train_step_seconds``) and,
  when ``ZOO_TPU_EVENT_LOG`` names a file, appends a structured JSONL
  event (the extra keyword fields go to the event log only, never to
  metric labels — unbounded values like step numbers must not explode
  label cardinality);
- **exposition** — :func:`snapshot` (JSON-able dict) and
  :func:`to_prometheus` (Prometheus text format, served by the
  inference servers' ``GET /metrics``).

Zero dependencies beyond the stdlib on purpose: the ingest path runs
inside pickled closures on Spark executors and the serving path inside
the native front-end's worker threads; neither may drag in jax.

Naming convention (see docs/observability.md): every metric is
``zoo_tpu_<area>_<what>[_<unit>]`` with areas ``train``, ``ingest``,
``serving``; counters end in ``_total``, durations in ``_seconds``.
"""

from __future__ import annotations

import bisect
import gzip
import json
import os
import re
import shutil
import threading
import time
from typing import Any, Dict, Optional, Sequence, Tuple

from analytics_zoo_tpu.common import tracing as _tracing

__all__ = [
    "MetricsRegistry",
    "Counter",
    "Gauge",
    "Histogram",
    "Span",
    "counter",
    "gauge",
    "histogram",
    "span",
    "event",
    "snapshot",
    "to_prometheus",
    "get_registry",
    "reset_metrics",
    "bucket_quantile",
    "DEFAULT_BUCKETS",
    "SIZE_BUCKETS",
]

# Prometheus-style latency buckets, widened for both sub-ms dispatch
# and minute-scale epochs/compiles.
DEFAULT_BUCKETS: "Tuple[float, ...]" = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0)

# Power-of-two buckets for batch sizes / record counts.
SIZE_BUCKETS: "Tuple[float, ...]" = (
    1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 4096, 16384)

_NAME_SUB = re.compile(r"[^a-zA-Z0-9_:]")


def _sanitize(name: str) -> str:
    """Coerce to a legal Prometheus metric name."""
    name = _NAME_SUB.sub("_", name)
    if not name or name[0].isdigit():
        name = "_" + name
    return name


def _fmt(v: float) -> str:
    """Prometheus sample value: integral floats print as ints."""
    f = float(v)
    return str(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


def _escape_label(v: Any) -> str:
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _label_key(labels: Optional[Dict[str, Any]]
               ) -> "Tuple[Tuple[str, str], ...]":
    if not labels:
        return ()
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _label_str(key: "Tuple[Tuple[str, str], ...]") -> str:
    if not key:
        return ""
    inner = ",".join(f'{k}="{_escape_label(v)}"' for k, v in key)
    return "{" + inner + "}"


def bucket_quantile(buckets: "Sequence[float]",
                    counts: "Sequence[float]", q: float) -> float:
    """Prometheus-style quantile estimate from per-bucket counts.

    ``buckets`` are the finite upper bounds (sorted ascending);
    ``counts`` are *per-bucket* (not cumulative) observation counts,
    with one extra trailing entry for the ``+Inf`` overflow bucket
    (``len(counts) == len(buckets) + 1``). Linear interpolation
    inside the winning bucket, a lower edge of 0 for the first
    bucket, and — like Prometheus ``histogram_quantile`` — the
    highest finite bound when the rank lands in the overflow bucket.
    Returns NaN when there are no observations.
    """
    if len(counts) != len(buckets) + 1:
        raise ValueError("counts must be per-bucket plus overflow")
    total = float(sum(counts))
    if total <= 0:
        return float("nan")
    q = min(max(float(q), 0.0), 1.0)
    rank = q * total
    acc = 0.0
    for i, hi in enumerate(buckets):
        prev = acc
        acc += counts[i]
        if acc >= rank:
            if counts[i] <= 0:
                return float(hi)
            lo = float(buckets[i - 1]) if i > 0 else 0.0
            frac = (rank - prev) / counts[i]
            return lo + (float(hi) - lo) * min(max(frac, 0.0), 1.0)
    return float(buckets[-1])  # rank fell in the +Inf bucket


class Counter:
    """Monotonic counter (one labeled child of a family)."""

    __slots__ = ("_value", "_lock")

    def __init__(self):
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0):
        if amount < 0:
            raise ValueError("counters only go up; use a gauge")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        return self._value


class Gauge:
    """Point-in-time value."""

    __slots__ = ("_value", "_lock")

    def __init__(self):
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, value: float):
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0):
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0):
        self.inc(-amount)

    @property
    def value(self) -> float:
        return self._value


class Histogram:
    """Fixed-bucket histogram (cumulative on exposition, like
    Prometheus: ``le`` is inclusive)."""

    __slots__ = ("buckets", "_counts", "_sum", "_lock")

    def __init__(self, buckets: "Sequence[float]" = DEFAULT_BUCKETS):
        self.buckets = tuple(sorted(float(b) for b in buckets))
        if not self.buckets:
            raise ValueError("histogram needs at least one bucket")
        self._counts = [0] * (len(self.buckets) + 1)  # last = +Inf
        self._sum = 0.0
        self._lock = threading.Lock()

    def observe(self, value: float):
        v = float(value)
        i = bisect.bisect_left(self.buckets, v)
        with self._lock:
            self._counts[i] += 1
            self._sum += v

    @property
    def count(self) -> int:
        with self._lock:
            return sum(self._counts)

    @property
    def sum(self) -> float:
        return self._sum

    def cumulative(self) -> "list[tuple[str, int]]":
        """[(le_str, cumulative_count), ..., ("+Inf", total)]."""
        with self._lock:
            counts = list(self._counts)
        out, acc = [], 0
        for b, c in zip(self.buckets, counts):
            acc += c
            out.append((_fmt(b), acc))
        out.append(("+Inf", acc + counts[-1]))
        return out

    def quantile(self, q: float) -> float:
        """Estimate the q-quantile (0 ≤ q ≤ 1) from the bucket
        counts via :func:`bucket_quantile`. Accuracy is bounded by
        the bucket width around the true quantile; NaN when empty."""
        with self._lock:
            counts = list(self._counts)
        return bucket_quantile(self.buckets, counts, q)


class _Family:
    """One metric name: type, help, and labeled children."""

    __slots__ = ("name", "mtype", "help", "buckets", "children",
                 "_lock")

    def __init__(self, name: str, mtype: str, help_: str,
                 buckets: "Optional[Sequence[float]]" = None):
        self.name = name
        self.mtype = mtype
        self.help = help_
        self.buckets = buckets
        self.children: "Dict[tuple, Any]" = {}
        self._lock = threading.Lock()

    def child(self, labels: Optional[Dict[str, Any]]):
        key = _label_key(labels)
        with self._lock:
            m = self.children.get(key)
            if m is None:
                if self.mtype == "counter":
                    m = Counter()
                elif self.mtype == "gauge":
                    m = Gauge()
                else:
                    m = Histogram(self.buckets or DEFAULT_BUCKETS)
                self.children[key] = m
            return m


class MetricsRegistry:
    """Thread-safe registry of metric families. The module-level
    helpers use one process-global instance (:func:`get_registry`);
    tests may instantiate their own."""

    def __init__(self):
        self._lock = threading.Lock()
        self._families: "Dict[str, _Family]" = {}

    def _family(self, name: str, mtype: str, help_: str,
                buckets: "Optional[Sequence[float]]" = None) -> _Family:
        name = _sanitize(name)
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = _Family(name, mtype, help_, buckets)
                self._families[name] = fam
            elif fam.mtype != mtype:
                raise ValueError(
                    f"metric {name!r} already registered as "
                    f"{fam.mtype}, not {mtype}")
            return fam

    def counter(self, name: str, help: str = "",
                labels: Optional[Dict[str, Any]] = None) -> Counter:
        return self._family(name, "counter", help).child(labels)

    def gauge(self, name: str, help: str = "",
              labels: Optional[Dict[str, Any]] = None) -> Gauge:
        return self._family(name, "gauge", help).child(labels)

    def histogram(self, name: str, help: str = "",
                  labels: Optional[Dict[str, Any]] = None,
                  buckets: "Optional[Sequence[float]]" = None
                  ) -> Histogram:
        return self._family(name, "histogram", help,
                            buckets).child(labels)

    # -- exposition ---------------------------------------------------------
    def snapshot(self) -> dict:
        """JSON-able dump of every family (histograms include
        cumulative bucket counts, like the text format)."""
        out: "Dict[str, dict]" = {}
        with self._lock:
            fams = sorted(self._families.values(),
                          key=lambda f: f.name)
        for fam in fams:
            with fam._lock:
                items = sorted(fam.children.items())
            values = []
            for key, m in items:
                rec: "Dict[str, Any]" = {"labels": dict(key)}
                if fam.mtype == "histogram":
                    rec["count"] = m.count
                    rec["sum"] = m.sum
                    rec["buckets"] = dict(m.cumulative())
                else:
                    rec["value"] = m.value
                values.append(rec)
            out[fam.name] = {"type": fam.mtype, "help": fam.help,
                             "values": values}
        return out

    def to_prometheus(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        lines: "list[str]" = []
        with self._lock:
            fams = sorted(self._families.values(),
                          key=lambda f: f.name)
        for fam in fams:
            if fam.help:
                lines.append(f"# HELP {fam.name} {fam.help}")
            lines.append(f"# TYPE {fam.name} {fam.mtype}")
            with fam._lock:
                items = sorted(fam.children.items())
            for key, m in items:
                ls = _label_str(key)
                if fam.mtype == "histogram":
                    for le, cum in m.cumulative():
                        bl = _label_str(key + (("le", le),))
                        lines.append(
                            f"{fam.name}_bucket{bl} {cum}")
                    lines.append(
                        f"{fam.name}_sum{ls} {_fmt(m.sum)}")
                    lines.append(
                        f"{fam.name}_count{ls} {m.count}")
                else:
                    lines.append(f"{fam.name}{ls} {_fmt(m.value)}")
        return "\n".join(lines) + ("\n" if lines else "")

    def reset(self):
        with self._lock:
            self._families.clear()


# ---------------------------------------------------------------------------
# Process-global default registry + module-level convenience API
# ---------------------------------------------------------------------------

_REGISTRY = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return _REGISTRY


def counter(name: str, help: str = "",
            labels: Optional[Dict[str, Any]] = None) -> Counter:
    return _REGISTRY.counter(name, help, labels)


def gauge(name: str, help: str = "",
          labels: Optional[Dict[str, Any]] = None) -> Gauge:
    return _REGISTRY.gauge(name, help, labels)


def histogram(name: str, help: str = "",
              labels: Optional[Dict[str, Any]] = None,
              buckets: "Optional[Sequence[float]]" = None) -> Histogram:
    return _REGISTRY.histogram(name, help, labels, buckets)


def snapshot() -> dict:
    return _REGISTRY.snapshot()


def to_prometheus() -> str:
    return _REGISTRY.to_prometheus()


# ---------------------------------------------------------------------------
# Structured event log (JSONL sink, env-selected)
# ---------------------------------------------------------------------------

_event_lock = threading.Lock()
_event_path: Optional[str] = None
_event_fh = None
_rotated_bytes = 0  # total size of rotated segments (metrics feed)


def _event_log_keep() -> int:
    try:
        return int(os.environ.get("ZOO_TPU_EVENT_LOG_KEEP", "3"))
    except ValueError:
        return 3


def _gzip_segment(path: str):
    """Compress a freshly-rotated segment in place (``path`` →
    ``path.gz``). Best-effort: on failure the uncompressed segment
    is kept and the partial ``.gz`` removed."""
    try:
        with open(path, "rb") as src, \
                gzip.open(path + ".gz", "wb") as dst:
            shutil.copyfileobj(src, dst)
        os.remove(path)
    except OSError:
        try:
            os.remove(path + ".gz")
        except OSError:
            pass


def _scan_rotated_bytes() -> int:
    """On-disk size of the rotated segments (``.N.gz`` and legacy
    uncompressed ``.N``) still inside the keep window."""
    if not _event_path:
        return 0
    total = 0
    for i in range(1, _event_log_keep() + 1):
        for ext in (".gz", ""):
            try:
                total += os.path.getsize(
                    f"{_event_path}.{i}{ext}")
            except OSError:
                pass
    return total


def _rotate_locked():
    """Size-based rotation: when ``ZOO_TPU_EVENT_LOG_MAX_MB`` is set
    and the sink grew past it, shift ``path.1 → path.2 → ...``
    (keeping ``ZOO_TPU_EVENT_LOG_KEEP`` rotated files, default 3),
    gzip-compress the fresh ``path.1`` (``ZOO_TPU_EVENT_LOG_GZIP=0``
    keeps it raw) and reopen a fresh ``path``. Each rotation bumps
    ``zoo_tpu_event_log_rotations_total``. Called with
    ``_event_lock`` held."""
    global _event_fh, _rotated_bytes
    raw = os.environ.get("ZOO_TPU_EVENT_LOG_MAX_MB")
    if not raw or _event_fh is None:
        return
    try:
        max_bytes = float(raw) * 1024 * 1024
    except ValueError:
        return
    if max_bytes <= 0:
        return
    try:
        if _event_fh.tell() < max_bytes:
            return
        _event_fh.close()
    except (OSError, ValueError):
        return
    keep = _event_log_keep()
    rotated = False
    try:
        for i in range(max(keep - 1, 0), 0, -1):
            for ext in (".gz", ""):
                src = f"{_event_path}.{i}{ext}"
                if os.path.exists(src):
                    os.replace(src, f"{_event_path}.{i + 1}{ext}")
        if keep >= 1:
            os.replace(_event_path, _event_path + ".1")
            rotated = True
            if os.environ.get("ZOO_TPU_EVENT_LOG_GZIP",
                              "1") != "0":
                _gzip_segment(_event_path + ".1")
        else:
            os.remove(_event_path)
            rotated = True
    except OSError:
        pass  # rotation is best-effort; keep logging regardless
    _event_fh = open(_event_path, "a", encoding="utf-8")
    _rotated_bytes = _scan_rotated_bytes()
    if rotated:
        counter("zoo_tpu_event_log_rotations_total",
                help="event-log segment rotations").inc()


def _event_sink():
    """Cached append handle for ``ZOO_TPU_EVENT_LOG`` (re-resolved
    per call so tests can repoint the env var)."""
    global _event_path, _event_fh, _rotated_bytes
    path = os.environ.get("ZOO_TPU_EVENT_LOG")
    if not path:
        return None
    if path != _event_path:
        if _event_fh is not None:
            try:
                _event_fh.close()
            except OSError:
                pass
        _event_fh = open(path, "a", encoding="utf-8")
        _event_path = path
        _rotated_bytes = _scan_rotated_bytes()
    _rotate_locked()
    return _event_fh


def event(name: str, **fields):
    """Append one structured JSONL event to the ``ZOO_TPU_EVENT_LOG``
    sink (no-op when the env var is unset). Non-JSON-able field
    values are stringified rather than dropped."""
    with _event_lock:
        fh = _event_sink()
        if fh is None:
            return
        rec = {"ts": round(time.time(), 6), "event": name}
        rec.update(fields)
        try:
            line = json.dumps(rec)
        except (TypeError, ValueError):
            rec = {k: (v if isinstance(
                v, (int, float, str, bool, type(None))) else str(v))
                for k, v in rec.items()}
            line = json.dumps(rec)
        fh.write(line + "\n")
        fh.flush()
        try:
            # live + rotated footprint: the disk feed the capacity
            # forecaster extrapolates (docs/observability.md)
            gauge("zoo_tpu_event_log_bytes",
                  help="event-log bytes on disk (live segment + "
                       "rotated)").set(fh.tell() + _rotated_bytes)
        except (OSError, ValueError):
            pass


def _close_event_log():
    global _event_path, _event_fh, _rotated_bytes
    with _event_lock:
        if _event_fh is not None:
            try:
                _event_fh.close()
            except OSError:
                pass
        _event_fh = None
        _event_path = None
        _rotated_bytes = 0


def reset_metrics():
    """Clear the process-global registry and release the event-log
    handle (test isolation)."""
    _REGISTRY.reset()
    _close_event_log()


# ---------------------------------------------------------------------------
# Spans
# ---------------------------------------------------------------------------

class Span:
    """Times a ``with`` block into the wall-time histogram
    ``zoo_tpu_<name>_seconds`` (name sanitized: ``train/step`` →
    ``zoo_tpu_train_step_seconds``) and appends a JSONL event when
    ``ZOO_TPU_EVENT_LOG`` is set. ``fields`` go to the event log only
    — never to metric labels (unbounded values like step indices must
    not explode label cardinality). ``elapsed`` holds the duration in
    seconds after exit.

    When an ambient trace is open (see
    :mod:`~analytics_zoo_tpu.common.tracing`) the span also joins it
    as a child, and the emitted event carries the trace/span ids so
    the event log stays joinable per trace."""

    __slots__ = ("name", "fields", "elapsed", "_t0", "_registry",
                 "_trace_tok")

    def __init__(self, name: str, registry: MetricsRegistry,
                 fields: Dict[str, Any]):
        self.name = name
        self.fields = fields
        self.elapsed = 0.0
        self._t0 = 0.0
        self._registry = registry
        self._trace_tok = None

    def __enter__(self) -> "Span":
        self._trace_tok = _tracing.span_start(self.name)
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.elapsed = time.perf_counter() - self._t0
        metric = "zoo_tpu_" + _sanitize(self.name) + "_seconds"
        self._registry.histogram(
            metric, help=f"wall time of {self.name} spans").observe(
            self.elapsed)
        fields = dict(self.fields)
        fields["dur_s"] = round(self.elapsed, 6)
        if exc_type is not None:
            fields["error"] = exc_type.__name__
        if self._trace_tok is not None:
            _tok, tid, sid, parent, t0_wall = self._trace_tok
            _tracing.span_end(self._trace_tok, self.name,
                              self.elapsed, self.fields)
            fields["trace_id"] = tid
            fields["span_id"] = sid
            fields["parent_id"] = parent
            fields["t_start"] = round(t0_wall, 6)
        event(self.name, **fields)
        return False  # never swallow exceptions


def span(name: str, registry: Optional[MetricsRegistry] = None,
         **fields) -> Span:
    """``with span("train/step", step=i): ...``"""
    return Span(name, registry or _REGISTRY, fields)


# Route tracing's root/explicit span records into the event log.
# (Span emits its own events above, so it bypasses this hook.)
_tracing.set_event_hook(event)
