"""Class-whitelist deserialization for checkpoints / saved models.

Reference analog: `Z/common/CheckedObjectInputStream.scala` — an
ObjectInputStream that only instantiates whitelisted classes, so a
tampered checkpoint file cannot execute arbitrary code on load. The
pickle equivalent: a restricted `Unpickler.find_class` that admits only
the numeric/container types a params pytree or hyper-parameter dict can
contain, plus this framework's own model classes.
"""

from __future__ import annotations

import io
import pickle
from typing import Any, BinaryIO

_SAFE_MODULE_PREFIXES = (
    # CLASSES only (enforced in find_class): a function admitted by
    # prefix would be a REDUCE gadget (e.g. utils.remove). Scoped to
    # the subtrees whose classes legitimately appear in saved files
    # (layers/models/preprocessing); `native` (ctypes), `inference`
    # (file-loading constructors), `tfpark`, and `common` stay out of
    # the gadget surface.
    # every entry ends with "."; `module == p[:-1]` below handles the
    # exact package/module name itself
    "analytics_zoo_tpu.pipeline.api.",
    "analytics_zoo_tpu.pipeline.estimator.",
    "analytics_zoo_tpu.pipeline.nnframes.",
    "analytics_zoo_tpu.feature.",
    "analytics_zoo_tpu.models.",
    "analytics_zoo_tpu.ops.",
)

# optimizer-state containers inside checkpoints: admitted only if the
# class is a NamedTuple (tuple subclass — no side-effecting __init__)
_SAFE_STATE_PREFIXES = ("optax.", "chex.")

_SAFE_CLASSES = {
    ("builtins", "dict"), ("builtins", "list"), ("builtins", "tuple"),
    ("builtins", "set"), ("builtins", "frozenset"),
    ("builtins", "int"), ("builtins", "float"), ("builtins", "str"),
    ("builtins", "bytes"), ("builtins", "bool"), ("builtins", "complex"),
    ("builtins", "bytearray"), ("builtins", "slice"),
    ("collections", "OrderedDict"),
    ("numpy", "ndarray"), ("numpy", "dtype"),
    ("numpy.core.multiarray", "_reconstruct"),
    ("numpy.core.multiarray", "scalar"),
    ("numpy._core.multiarray", "_reconstruct"),
    ("numpy._core.multiarray", "scalar"),
}


class UnsafePickleError(pickle.UnpicklingError):
    pass


class CheckedUnpickler(pickle.Unpickler):
    """(reference `CheckedObjectInputStream`)"""

    def find_class(self, module: str, name: str):
        if (module, name) in _SAFE_CLASSES:
            return super().find_class(module, name)
        if module.startswith("numpy") and name in ("ndarray", "dtype"):
            return super().find_class(module, name)
        if any(module == p[:-1] or module.startswith(p)
               for p in _SAFE_MODULE_PREFIXES):
            obj = super().find_class(module, name)
            if not isinstance(obj, type):
                raise UnsafePickleError(
                    f"refusing to deserialize {module}.{name}: only "
                    "classes are admitted by prefix (functions are "
                    "REDUCE code-execution gadgets)")
            return obj
        if any(module == p[:-1] or module.startswith(p)
               for p in _SAFE_STATE_PREFIXES):
            obj = super().find_class(module, name)
            if not (isinstance(obj, type) and issubclass(obj, tuple)):
                raise UnsafePickleError(
                    f"refusing to deserialize {module}.{name}: only "
                    "NamedTuple state containers are admitted from "
                    "optimizer libraries")
            return obj
        raise UnsafePickleError(
            f"refusing to deserialize {module}.{name}: not in the "
            "checkpoint class whitelist (tampered or foreign file?)")


def checked_load(file: "BinaryIO | str") -> Any:
    """`pickle.load` through the whitelist."""
    if isinstance(file, str):
        with open(file, "rb") as f:
            return CheckedUnpickler(f).load()
    return CheckedUnpickler(file).load()


def checked_loads(data: bytes) -> Any:
    return CheckedUnpickler(io.BytesIO(data)).load()
