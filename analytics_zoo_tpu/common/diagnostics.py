"""Anomaly detection & device diagnostics (the diagnostics layer,
L1.5).

Turns the raw telemetry of
:mod:`~analytics_zoo_tpu.common.observability` into *judgements*:
"this process is recompiling in a storm", "that step was a
straggler", "device memory is near its limit". Every detector emits
one structured ``diagnostics/anomaly`` event plus a
``zoo_tpu_anomalies_total{kind}`` counter, so alerting needs exactly
one PromQL expression (see the anomaly catalog in
docs/observability.md).

Detectors:

- :class:`RecompileMonitor` — listens for XLA ``backend_compile``
  events via ``jax.monitoring`` (the same signal
  ``tests/test_serving_batch.py`` uses to prove zero steady-state
  recompiles) and fires ``kind="recompile_storm"`` when more than
  ``threshold`` compiles land inside a rolling ``window_s`` window.
  A warmed serving process or a shape-stable train loop should
  compile a handful of times and then never again; a storm means a
  shape/dtype leak is thrashing the compile cache.
- :class:`StepTimeWatcher` — rolling-median straggler detection:
  ``kind="step_time_regression"`` when one step exceeds ``factor``
  × the window median (the first compile-heavy steps are excused by
  ``min_samples``).
- :func:`update_device_memory_gauges` — per-device HBM watermarks
  (``zoo_tpu_device_memory_bytes{device,kind}``) from
  ``device.memory_stats()``; silently skips backends (CPU) that
  expose none.

jax is imported lazily so this module stays importable from
executor-side code that must not drag in the runtime.
"""

from __future__ import annotations

import os
import statistics
import threading
import time
from collections import deque
from typing import Optional

from analytics_zoo_tpu.common import observability as obs

__all__ = [
    "anomaly",
    "add_anomaly_listener",
    "remove_anomaly_listener",
    "RecompileMonitor",
    "StepTimeWatcher",
    "ReplicaSkewDetector",
    "install_recompile_monitor",
    "get_recompile_monitor",
    "update_device_memory_gauges",
    "update_process_vitals",
    "build_info",
    "update_build_info",
]

# control loops (e.g. the rollout controller's canary auto-rollback,
# pipeline/inference/registry.py) subscribe here to REACT to
# anomalies instead of polling the counter
_listener_lock = threading.Lock()
_listeners: list = []


def add_anomaly_listener(fn) -> None:
    """Register ``fn(kind, fields)`` to be called synchronously on
    every :func:`anomaly` (after the counter/event are recorded).
    Listener exceptions are swallowed — a broken reactor must not
    mask the anomaly it reacted to."""
    with _listener_lock:
        if fn not in _listeners:
            _listeners.append(fn)


def remove_anomaly_listener(fn) -> None:
    """Unregister a listener (no-op when absent)."""
    with _listener_lock:
        try:
            _listeners.remove(fn)
        except ValueError:
            pass


def anomaly(kind: str, **fields):
    """Record one detected anomaly: bump
    ``zoo_tpu_anomalies_total{kind}`` and append a structured
    ``diagnostics/anomaly`` event (fields carry the evidence), then
    notify registered listeners."""
    obs.counter("zoo_tpu_anomalies_total",
                help="anomalies detected, by kind",
                labels={"kind": kind}).inc()
    obs.event("diagnostics/anomaly", kind=kind, **fields)
    with _listener_lock:
        listeners = list(_listeners)
    for fn in listeners:
        try:
            fn(kind, dict(fields))
        except Exception as e:
            from analytics_zoo_tpu.common.nncontext import logger
            logger.warning("anomaly listener %r failed: %s", fn, e)


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


# -- expected-compile excusal -------------------------------------------
# Deliberate compiles — engine warm-up, AOT bucket builds — fire the
# same jax.monitoring backend_compile events as pathological
# recompiles, and a GenerationEngine.warm() alone (step + a bucket
# ladder of prefills, 9 programs) trips the default storm threshold
# of 5. Callers that KNOW they are compiling bracket the work with
# :func:`expected_compiles`; jax compiles synchronously on the
# calling thread, so a thread-local depth cleanly scopes the excusal
# to exactly those compiles while concurrent traffic on other
# threads stays monitored.
_expected = threading.local()


class expected_compiles:
    """Context manager marking compiles on THIS thread as expected:
    still counted in ``zoo_tpu_xla_compiles_total``, but excluded
    from the RecompileMonitor storm window. Re-entrant."""

    def __enter__(self):
        _expected.depth = getattr(_expected, "depth", 0) + 1
        return self

    def __exit__(self, *exc):
        _expected.depth -= 1
        return False


def compiles_expected() -> bool:
    return getattr(_expected, "depth", 0) > 0


class RecompileMonitor:
    """Rolling-window XLA compile-storm detector.

    :meth:`note` is the pure core (unit-testable with fake clocks);
    :meth:`install` registers a ``jax.monitoring`` event-duration
    listener that calls it on every ``backend_compile`` event. At
    most one anomaly fires per window, so a storm does not itself
    become an event storm. Compiles inside an
    :func:`expected_compiles` bracket (warm-up/AOT spans) are
    counted but never storm."""

    def __init__(self, threshold: Optional[int] = None,
                 window_s: Optional[float] = None):
        if threshold is None:
            threshold = int(_env_float(
                "ZOO_TPU_RECOMPILE_THRESHOLD", 5))
        if window_s is None:
            window_s = _env_float("ZOO_TPU_RECOMPILE_WINDOW_S", 60.0)
        self.threshold = max(1, threshold)
        self.window_s = window_s
        self.storms = 0
        self._times: "deque[float]" = deque()
        self._muted_until = float("-inf")
        self._lock = threading.Lock()
        self._installed = False

    def note(self, now: Optional[float] = None) -> bool:
        """Record one compile at monotonic time ``now`` (defaults to
        the real clock). Returns True when this compile tips the
        window over the threshold (and fires the anomaly). Expected
        compiles (see :func:`expected_compiles`) bump the counter but
        skip the storm window entirely."""
        if now is None:
            now = time.monotonic()
        if compiles_expected():
            obs.counter(
                "zoo_tpu_xla_compiles_total",
                help="XLA backend_compile events observed").inc()
            return False
        with self._lock:
            self._times.append(now)
            cutoff = now - self.window_s
            while self._times and self._times[0] <= cutoff:
                self._times.popleft()
            in_window = len(self._times)
            storm = (in_window > self.threshold
                     and now >= self._muted_until)
            if storm:
                self._muted_until = now + self.window_s
                self.storms += 1
        obs.counter("zoo_tpu_xla_compiles_total",
                    help="XLA backend_compile events observed").inc()
        if storm:
            anomaly("recompile_storm", compiles=in_window,
                    window_s=self.window_s,
                    threshold=self.threshold)
        return storm

    def _listener(self, event_name: str, duration: float, **kw):
        # jax stamps e.g. ".../jax_backend_compile_duration".
        if event_name.endswith("backend_compile_duration"):
            self.note()

    def install(self) -> "RecompileMonitor":
        """Register the jax.monitoring listener (idempotent; there is
        no unregister API, so one listener per process)."""
        with self._lock:
            if self._installed:
                return self
            self._installed = True
        from jax import monitoring
        monitoring.register_event_duration_secs_listener(
            self._listener)
        return self


_monitor_lock = threading.Lock()
_monitor: Optional[RecompileMonitor] = None


def get_recompile_monitor() -> Optional[RecompileMonitor]:
    return _monitor


def install_recompile_monitor() -> RecompileMonitor:
    """Process-global :class:`RecompileMonitor`, installed once; the
    Estimator train loop and the DynamicBatcher both call this on
    start."""
    global _monitor
    with _monitor_lock:
        if _monitor is None:
            _monitor = RecompileMonitor()
    return _monitor.install()


class StepTimeWatcher:
    """Straggler / regression detection over a rolling window of step
    wall times. A step slower than ``factor`` × the window median
    fires ``kind="step_time_regression"``; after firing, detection
    mutes for ``cooldown`` observations so a sustained regression
    (which also drags the median up) reports once, not every step."""

    def __init__(self, window: int = 64, min_samples: int = 16,
                 factor: Optional[float] = None, cooldown: int = 16):
        if factor is None:
            factor = _env_float("ZOO_TPU_STEP_ANOMALY_FACTOR", 3.0)
        self.window = max(2, window)
        self.min_samples = max(1, min_samples)
        self.factor = factor
        self.cooldown = max(0, cooldown)
        self.fired = 0
        self._buf: "deque[float]" = deque(maxlen=self.window)
        self._mute = 0
        self._lock = threading.Lock()

    def observe(self, dur_s: float, step: Optional[int] = None
                ) -> bool:
        """Feed one step's wall time; returns True when it fired."""
        dur_s = float(dur_s)
        fired = False
        median = 0.0
        with self._lock:
            if self._mute > 0:
                self._mute -= 1
            elif (len(self._buf) >= self.min_samples
                  and self.factor > 0):
                median = statistics.median(self._buf)
                if median > 0 and dur_s > self.factor * median:
                    fired = True
                    self.fired += 1
                    self._mute = self.cooldown
            self._buf.append(dur_s)
        if fired:
            anomaly("step_time_regression", step=step,
                    dur_s=round(dur_s, 6),
                    median_s=round(median, 6), factor=self.factor)
        return fired


class ReplicaSkewDetector:
    """Fleet-level outlier detection: one replica drifting away from
    its siblings (a thermally throttled host, a leaking process, a
    bad NIC) while the fleet averages still look healthy.

    :meth:`observe` takes per-replica window stats — latency p99 and
    error ratio, as computed by the telemetry collector from
    consecutive snapshot deltas (`common/federation.py`) — and
    compares each replica against the **median of the other
    replicas** (not the full-fleet median: with N=2 a plain median
    averages the outlier in and can never flag it). A replica whose
    p99 exceeds ``factor`` × that median, or whose error ratio
    exceeds it by ``error_margin`` absolute, fires
    ``zoo_tpu_anomalies_total{kind="replica_skew"}`` — which the
    rollout controller's anomaly listener can act on. After firing,
    the replica mutes for ``cooldown_s`` (one anomaly per breach
    episode, not per tick). Pure function of its inputs + injected
    ``now``: fully unit-testable with fake clocks, no sleeps."""

    def __init__(self, factor: Optional[float] = None,
                 error_margin: Optional[float] = None,
                 min_events: int = 4,
                 cooldown_s: float = 60.0):
        if factor is None:
            factor = _env_float("ZOO_TPU_SKEW_FACTOR", 3.0)
        if error_margin is None:
            error_margin = _env_float("ZOO_TPU_SKEW_ERROR_MARGIN",
                                      0.25)
        self.factor = float(factor)
        self.error_margin = float(error_margin)
        self.min_events = max(1, int(min_events))
        self.cooldown_s = float(cooldown_s)
        self.fired = 0
        self._muted_until: "dict" = {}  # replica -> now threshold
        self._lock = threading.Lock()
        self.last: "dict" = {}  # latest verdicts, for debug payloads

    @staticmethod
    def _median_others(stats, name: str, key: str):
        vals = [s.get(key) for n, s in stats.items()
                if n != name and s.get(key) is not None]
        if not vals:
            return None
        return statistics.median(vals)

    def observe(self, stats: "dict",
                now: Optional[float] = None) -> "list":
        """``stats`` maps replica name → ``{"p99_s": float|None,
        "error_ratio": float|None, "events": int}`` for one window.
        Returns the list of anomalies fired (possibly empty)."""
        if now is None:
            now = time.monotonic()
        fired = []
        verdicts = {}
        for name, s in stats.items():
            events = int(s.get("events") or 0)
            verdict = {"events": events, "skew": None}
            p99 = s.get("p99_s")
            med_p99 = self._median_others(stats, name, "p99_s")
            err = s.get("error_ratio")
            med_err = self._median_others(stats, name,
                                          "error_ratio")
            if events >= self.min_events:
                if (p99 is not None and med_p99 is not None
                        and med_p99 > 0 and self.factor > 0
                        and p99 > self.factor * med_p99):
                    verdict["skew"] = {
                        "metric": "latency_p99",
                        "value": round(float(p99), 6),
                        "fleet_median": round(float(med_p99), 6)}
                elif (err is not None and med_err is not None
                        and err - med_err > self.error_margin):
                    verdict["skew"] = {
                        "metric": "error_ratio",
                        "value": round(float(err), 6),
                        "fleet_median": round(float(med_err), 6)}
            verdicts[name] = verdict
            if verdict["skew"] is None:
                with self._lock:
                    self._muted_until.pop(name, None)
                continue
            with self._lock:
                muted = now < self._muted_until.get(
                    name, float("-inf"))
                if not muted:
                    self._muted_until[name] = now + self.cooldown_s
                    self.fired += 1
            if muted:
                continue
            fields = dict(verdict["skew"], replica=name,
                          factor=self.factor, events=events)
            anomaly("replica_skew", **fields)
            fired.append(fields)
        self.last = verdicts
        return fired


def _read_rss_bytes() -> Optional[int]:
    """Resident-set size from /proc (Linux); None where absent."""
    try:
        with open("/proc/self/statm", "r", encoding="ascii") as fh:
            pages = int(fh.read().split()[1])
        return pages * (os.sysconf("SC_PAGE_SIZE")
                        if hasattr(os, "sysconf") else 4096)
    except (OSError, ValueError, IndexError):
        return None


_PROC_T0 = time.monotonic()  # fallback uptime origin (import time)


def _uptime_s() -> float:
    try:  # true process uptime via /proc (Linux)
        with open("/proc/self/stat", "rb") as fh:
            start_ticks = float(fh.read().rsplit(b")", 1)[-1]
                                .split()[19])
        with open("/proc/uptime", "r", encoding="ascii") as fh:
            host_up = float(fh.read().split()[0])
        hz = os.sysconf("SC_CLK_TCK") if hasattr(os, "sysconf") \
            else 100
        return max(0.0, host_up - start_ticks / float(hz))
    except (OSError, ValueError, IndexError):
        return time.monotonic() - _PROC_T0


def update_process_vitals() -> dict:
    """Refresh this process's vitals gauges —
    ``zoo_tpu_process_rss_bytes``, ``zoo_tpu_process_uptime_s`` and
    (where /proc exists) ``zoo_tpu_process_open_fds`` — so federated
    views can spot a leaking or wedged replica without attaching a
    profiler. Called on every ``/metrics`` render; cheap (three
    /proc reads) and a clean partial no-op on platforms without
    /proc. Returns the values set."""
    out: "dict" = {}
    rss = _read_rss_bytes()
    if rss is not None:
        obs.gauge("zoo_tpu_process_rss_bytes",
                  help="resident set size of this process").set(rss)
        out["rss_bytes"] = rss
    up = _uptime_s()
    obs.gauge("zoo_tpu_process_uptime_s",
              help="seconds since this process started").set(up)
    out["uptime_s"] = up
    try:
        n_fds = len(os.listdir("/proc/self/fd"))
    except OSError:
        n_fds = None
    if n_fds is not None:
        obs.gauge("zoo_tpu_process_open_fds",
                  help="open file descriptors in this "
                       "process").set(n_fds)
        out["open_fds"] = n_fds
    return out


# build_info is stable for the life of the process (version, jax,
# device kind, flag fingerprint) — computed once, cached
_build_info_lock = threading.Lock()
_build_info: "Optional[dict]" = None


def build_info() -> dict:
    """Provenance of this process: package + jax versions, the
    accelerator kind, and a fingerprint (first 12 sha256 hex chars)
    of every active ``ZOO_TPU_*`` flag — enough to answer "what
    exactly was running?" from a scrape or a bench artifact. Cached;
    jax is probed lazily and failure degrades to ``"none"`` /
    ``"unknown"`` (the executor-side import constraint)."""
    global _build_info
    with _build_info_lock:
        if _build_info is not None:
            return dict(_build_info)
        import hashlib

        from analytics_zoo_tpu.version import __version__
        jax_version = "none"
        device = "unknown"
        try:
            import jax

            jax_version = jax.__version__
            devs = jax.devices()
            if devs:
                device = getattr(devs[0], "device_kind",
                                 devs[0].platform)
        except Exception:
            pass
        flags = sorted(f"{k}={v}" for k, v in os.environ.items()
                       if k.startswith("ZOO_TPU_"))
        fp = hashlib.sha256(
            "\n".join(flags).encode()).hexdigest()[:12]
        _build_info = {
            "version": __version__,
            "jax": jax_version,
            "device": str(device),
            "flags_fingerprint": fp,
            "flags": flags,
        }
        return dict(_build_info)


def update_build_info() -> dict:
    """Publish :func:`build_info` as the info-style gauge
    ``zoo_tpu_build_info{version,jax,device,flags}`` (value pinned
    to 1 — the labels ARE the payload, the Prometheus
    ``*_build_info`` convention). Called on every ``/metrics``
    render next to :func:`update_process_vitals`."""
    info = build_info()
    obs.gauge("zoo_tpu_build_info",
              help="build/runtime provenance as labels "
                   "(value is always 1)",
              labels={"version": info["version"],
                      "jax": info["jax"],
                      "device": info["device"],
                      "flags": info["flags_fingerprint"]}).set(1)
    return info


def update_device_memory_gauges() -> int:
    """Refresh ``zoo_tpu_device_memory_bytes{device,kind}`` watermark
    gauges from each local device's ``memory_stats()``. Returns the
    number of samples set (0 on backends without memory stats)."""
    import jax

    n = 0
    for d in jax.local_devices():
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if not stats:
            continue
        for key, kind in (("bytes_in_use", "in_use"),
                          ("peak_bytes_in_use", "peak"),
                          ("bytes_limit", "limit")):
            v = stats.get(key)
            if v is None:
                continue
            obs.gauge("zoo_tpu_device_memory_bytes",
                      help="device memory watermarks by kind",
                      labels={"device": str(d.id),
                              "kind": kind}).set(v)
            n += 1
    return n
