"""Declarative SLOs evaluated against the metrics registry (L2).

:mod:`~analytics_zoo_tpu.common.observability` records what happened
and :mod:`~analytics_zoo_tpu.common.diagnostics` spots local
anomalies; this module holds the *objectives* — "p99 /predict latency
stays under 250 ms", "99% of requests succeed" — and continuously
judges the registry against them, Google-SRE style:

- an :class:`SLO` is a declarative rule: a metric selector (family
  name + label subset), one or more evaluation **windows**, and
  either a plain threshold (``gauge`` / ``rate`` / ``quantile``
  signals) or an error-budget **burn rate** over a
  numerator/denominator pair (``ratio`` signals). Multi-window rules
  breach only when *every* window breaches — the fast window gives
  detection speed, the slow window keeps one bad second from paging.
- the :class:`SLOEngine` snapshots the registry on a background
  ticker (``ZOO_TPU_SLO_TICK_S``, default 5 s; ``0`` = manual
  :meth:`~SLOEngine.tick` only) and evaluates every rule against
  windowed *deltas* of those snapshots, so cumulative counters and
  histograms become per-window rates and quantiles. Snapshot history
  lives in a shared
  :class:`~analytics_zoo_tpu.common.timeseries.MetricHistory` (one
  history, one clock — the same store that backs
  ``/debug/metrics/history`` and the capacity forecaster). Early in
  a process's life, windows clip to engine uptime (the oldest
  snapshot stands in for one that is not old enough yet).
- a healthy→breach transition increments
  ``zoo_tpu_slo_breaches_total{slo}`` exactly once and rides the
  existing :func:`diagnostics.anomaly` pipeline
  (``kind="slo_breach"``); recovery emits a ``slo/recovered`` event.
  ``GET /debug/slo`` on both HTTP front-ends serves
  :meth:`~SLOEngine.status`.

Shipped default objectives live in :data:`DEFAULT_SERVING_SLOS`,
:data:`DEFAULT_FLEET_SLOS`, :data:`DEFAULT_FORECAST_SLOS` and
:data:`DEFAULT_TRAINING_SLOS` as pure dict literals so
``scripts/lint.py`` can validate them (metric names, windows,
duplicate ids) without importing this module. Thresholds are
overridable per rule via ``ZOO_TPU_SLO_<ID>_THRESHOLD`` /
``_OBJECTIVE`` / ``_BURN_RATE``; ``ZOO_TPU_SLO=0`` disables the
whole layer. Tuning guidance: docs/slo.md.

Zero dependencies beyond the stdlib (and the observability /
diagnostics layers, which share that constraint): the engine must be
importable from serving worker threads and executor-side code.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from analytics_zoo_tpu.common import diagnostics
from analytics_zoo_tpu.common import observability as obs
from analytics_zoo_tpu.common import timeseries

__all__ = [
    "SLO",
    "SLOEngine",
    "DEFAULT_SERVING_SLOS",
    "DEFAULT_FLEET_SLOS",
    "DEFAULT_FED_SLOS",
    "DEFAULT_FORECAST_SLOS",
    "DEFAULT_TRAINING_SLOS",
    "get_engine",
    "install_defaults",
    "ensure_default_slos",
    "enabled",
    "reset_slo",
]

_SIGNAL_TYPES = ("gauge", "rate", "quantile", "ratio")

_OPS: "Dict[str, Callable[[float, float], bool]]" = {
    ">": lambda v, t: v > t,
    ">=": lambda v, t: v >= t,
    "<": lambda v, t: v < t,
    "<=": lambda v, t: v <= t,
}


# ---------------------------------------------------------------------------
# Shipped default objectives (pure dict literals — scripts/lint.py
# validates these by AST without importing; keep them literal)
# ---------------------------------------------------------------------------

DEFAULT_SERVING_SLOS = [
    {
        "id": "serving_latency_p99",
        "description": "p99 /predict latency stays under 250 ms",
        "signal": {"type": "quantile",
                   "metric": "zoo_tpu_serving_request_seconds",
                   "labels": {"path": "/predict"},
                   "q": 0.99},
        "threshold": 0.25,
        "op": ">",
        "windows": [60.0, 300.0],
        "min_events": 20,
    },
    {
        "id": "serving_error_rate",
        "description": "99% of HTTP requests succeed "
                       "(multi-window burn rate)",
        "signal": {"type": "ratio",
                   "numerator": {
                       "metric": "zoo_tpu_serving_errors_total"},
                   "denominator": {
                       "metric": "zoo_tpu_serving_requests_total"}},
        "objective": 0.99,
        "burn_rate": 14.0,
        "windows": [60.0, 600.0],
        "min_events": 10,
    },
    {
        "id": "serving_queue_depth",
        "description": "batcher admission queue stays below 75% "
                       "of its default 256-slot bound",
        "signal": {"type": "gauge",
                   "metric": "zoo_tpu_serving_queue_depth"},
        "threshold": 192.0,
        "op": ">",
        "windows": [60.0],
    },
]

DEFAULT_FLEET_SLOS = [
    {
        "id": "fleet_replicas_admitting",
        "description": "the serving fleet keeps at least one "
                       "replica admitting traffic",
        "signal": {"type": "gauge",
                   "metric": "zoo_tpu_fleet_replicas_admitting"},
        "threshold": 1.0,
        "op": "<",
        "windows": [60.0],
    },
    {
        "id": "fleet_error_rate",
        "description": "99% of routed requests resolve (replica "
                       "failures absorbed by sibling retries)",
        "signal": {"type": "ratio",
                   "numerator": {
                       "metric":
                           "zoo_tpu_fleet_requests_failed_total"},
                   "denominator": {
                       "metric": "zoo_tpu_fleet_requests_total"}},
        "objective": 0.99,
        "burn_rate": 14.0,
        "windows": [60.0, 600.0],
        "min_events": 10,
    },
    {
        "id": "fleet_retry_rate",
        "description": "sibling retries stay under 1/s (a dying "
                       "replica burns retry budget before ejection)",
        "signal": {"type": "rate",
                   "metric": "zoo_tpu_fleet_retries_total"},
        "threshold": 1.0,
        "op": ">",
        "windows": [120.0],
    },
]

DEFAULT_FED_SLOS = [
    {
        "id": "fed_latency_p99",
        "description": "fleet-wide federated /predict p99 stays "
                       "under 500 ms (per-source window worst case)",
        "signal": {"type": "gauge",
                   "metric": "zoo_tpu_fed_latency_p99_seconds"},
        "threshold": 0.5,
        "op": ">",
        "windows": [60.0],
    },
    {
        "id": "fed_error_ratio",
        "description": "fleet-wide federated serving error ratio "
                       "stays under 5%",
        "signal": {"type": "gauge",
                   "metric": "zoo_tpu_fed_error_ratio"},
        "threshold": 0.05,
        "op": ">",
        "windows": [60.0],
    },
]

DEFAULT_FORECAST_SLOS = [
    {
        "id": "forecast_capacity_pending",
        "description": "no capacity-exhaustion forecast is "
                       "pending (predictive anomaly rate stays 0)",
        "signal": {"type": "rate",
                   "metric": "zoo_tpu_anomalies_total",
                   "labels": {"kind": "capacity_forecast"}},
        "threshold": 0.0,
        "op": ">",
        "windows": [300.0],
    },
    {
        "id": "forecast_kv_pages_eta",
        "description": "KV-page exhaustion stays more than 2 min "
                       "out at the current admission trend",
        "signal": {"type": "gauge",
                   "metric": "zoo_tpu_forecast_eta_s",
                   "labels": {"resource": "kv_pages"}},
        "threshold": 120.0,
        "op": "<",
        "windows": [60.0],
    },
]

DEFAULT_TRAINING_SLOS = [
    {
        "id": "train_step_p99",
        "description": "p99 train-step wall time stays under 10 s",
        "signal": {"type": "quantile",
                   "metric": "zoo_tpu_train_step_seconds",
                   "q": 0.99},
        "threshold": 10.0,
        "op": ">",
        "windows": [120.0, 600.0],
        "min_events": 20,
    },
    {
        "id": "train_data_wait_share",
        "description": "input pipeline keeps data-wait below 60% "
                       "of step wall time (goodput ledger)",
        "signal": {"type": "gauge",
                   "metric": "zoo_tpu_goodput_share",
                   "labels": {"component": "data_wait"}},
        "threshold": 0.6,
        "op": ">",
        "windows": [60.0],
    },
    {
        "id": "train_recompile_rate",
        "description": "XLA recompiles stay under 1 per 5 s "
                       "(shape/dtype leak detector)",
        "signal": {"type": "rate",
                   "metric": "zoo_tpu_xla_compiles_total"},
        "threshold": 0.2,
        "op": ">",
        "windows": [300.0],
    },
]


def enabled() -> bool:
    """Master switch: ``ZOO_TPU_SLO=0`` disables default install and
    the background ticker (explicit engines still work)."""
    return os.environ.get("ZOO_TPU_SLO", "1") != "0"


def _require(cond: bool, msg: str):
    if not cond:
        raise ValueError(msg)


def _selector(d: "Dict[str, Any]", what: str) -> "Dict[str, Any]":
    _require(isinstance(d, dict) and isinstance(d.get("metric"), str)
             and bool(d.get("metric")),
             f"{what} needs a 'metric' name")
    labels = d.get("labels") or {}
    _require(isinstance(labels, dict), f"{what} labels must be a dict")
    return {"metric": d["metric"],
            "labels": {str(k): str(v) for k, v in labels.items()}}


class SLO:
    """One declarative objective. Build directly or via
    :meth:`from_dict` (the shape of the shipped defaults)."""

    def __init__(self, id: str, signal: "Dict[str, Any]",
                 description: str = "",
                 threshold: Optional[float] = None, op: str = ">",
                 objective: Optional[float] = None,
                 burn_rate: float = 14.0,
                 windows: "Any" = (60.0,), min_events: int = 1):
        _require(isinstance(id, str) and bool(id.strip()),
                 "slo id must be a non-empty string")
        self.id = id.strip()
        _require(isinstance(signal, dict), "signal must be a dict")
        self.kind = signal.get("type")
        _require(self.kind in _SIGNAL_TYPES,
                 f"slo {self.id}: unknown signal type {self.kind!r} "
                 f"(one of {_SIGNAL_TYPES})")
        self.description = str(description or "")
        self.windows = tuple(sorted(float(w) for w in windows))
        _require(bool(self.windows),
                 f"slo {self.id}: needs at least one window")
        _require(all(w > 0 for w in self.windows),
                 f"slo {self.id}: windows must be positive seconds")
        self.min_events = max(1, int(min_events))
        self.op = op
        self.objective = None
        self.burn_rate = None
        self.threshold = None
        self.q = None
        self.num = self.den = self.sel = None
        if self.kind == "ratio":
            _require(objective is not None
                     and 0.0 < float(objective) < 1.0,
                     f"slo {self.id}: ratio signals need an "
                     f"'objective' strictly inside (0, 1)")
            self.objective = float(objective)
            _require(float(burn_rate) > 0,
                     f"slo {self.id}: burn_rate must be > 0")
            self.burn_rate = float(burn_rate)
            self.num = _selector(signal.get("numerator"),
                                 f"slo {self.id}: numerator")
            self.den = _selector(signal.get("denominator"),
                                 f"slo {self.id}: denominator")
        else:
            _require(op in _OPS,
                     f"slo {self.id}: op must be one of "
                     f"{sorted(_OPS)}")
            _require(isinstance(threshold, (int, float)),
                     f"slo {self.id}: {self.kind} signals need a "
                     f"numeric 'threshold'")
            self.threshold = float(threshold)
            self.sel = _selector(signal, f"slo {self.id}: signal")
            if self.kind == "quantile":
                q = signal.get("q")
                _require(isinstance(q, (int, float))
                         and 0.0 < float(q) < 1.0,
                         f"slo {self.id}: quantile signals need "
                         f"'q' strictly inside (0, 1)")
                self.q = float(q)

    @classmethod
    def from_dict(cls, d: "Dict[str, Any]") -> "SLO":
        _require(isinstance(d, dict), "slo definition must be a dict")
        known = {"id", "signal", "description", "threshold", "op",
                 "objective", "burn_rate", "windows", "min_events"}
        extra = set(d) - known
        _require(not extra,
                 f"slo definition has unknown keys: {sorted(extra)}")
        kw = dict(d)
        return cls(kw.pop("id", ""), kw.pop("signal", None), **kw)

    def to_dict(self) -> dict:
        out: "Dict[str, Any]" = {
            "id": self.id, "description": self.description,
            "type": self.kind, "windows": list(self.windows),
            "min_events": self.min_events}
        if self.kind == "ratio":
            out["numerator"] = self.num
            out["denominator"] = self.den
            out["objective"] = self.objective
            out["burn_rate"] = self.burn_rate
        else:
            out["selector"] = self.sel
            out["threshold"] = self.threshold
            out["op"] = self.op
            if self.q is not None:
                out["q"] = self.q
        return out


# ---------------------------------------------------------------------------
# Snapshot math: windowed deltas over MetricsRegistry.snapshot() dicts
# ---------------------------------------------------------------------------

def _children(snap: dict, metric: str,
              labels: "Dict[str, str]") -> "Optional[List[dict]]":
    """Children of ``metric`` whose labels contain ``labels`` as a
    subset; None when the family does not exist (yet)."""
    fam = snap.get(metric)
    if fam is None:
        return None
    out = []
    for rec in fam.get("values", ()):
        rl = rec.get("labels", {})
        if all(rl.get(k) == v for k, v in labels.items()):
            out.append(rec)
    return out


def _scalar_sum(snap: dict, sel: dict) -> Optional[float]:
    kids = _children(snap, sel["metric"], sel["labels"])
    if kids is None:
        return None
    return float(sum(r.get("value", 0.0) for r in kids))


def _counter_delta(cur: dict, base: dict, sel: dict
                   ) -> Optional[float]:
    cur_v = _scalar_sum(cur, sel)
    if cur_v is None:
        return None
    base_v = _scalar_sum(base, sel) or 0.0
    return max(cur_v - base_v, 0.0)


def _hist_delta(cur: dict, base: dict, sel: dict):
    """Windowed histogram delta summed over matching children →
    ``(finite_bounds, per_bucket_counts, count)`` (per-bucket counts
    carry a trailing +Inf entry, the :func:`obs.bucket_quantile`
    contract); None when the family is absent."""
    kids = _children(cur, sel["metric"], sel["labels"])
    if kids is None:
        return None
    base_kids = _children(base, sel["metric"], sel["labels"]) or []

    def agg(children):
        buckets: "Dict[str, float]" = {}
        count = 0.0
        for r in children:
            count += r.get("count", 0)
            for le, c in r.get("buckets", {}).items():
                buckets[le] = buckets.get(le, 0.0) + c
        return buckets, count

    cb, cc = agg(kids)
    bb, bc = agg(base_kids)
    les = sorted((le for le in cb if le != "+Inf"), key=float)
    cum = [cb[le] - bb.get(le, 0.0) for le in les]
    cum.append(cb.get("+Inf", cc) - bb.get("+Inf", 0.0))
    per, prev = [], 0.0
    for c in cum:
        c = max(c, prev)  # deltas of cumulative counts stay monotone
        per.append(c - prev)
        prev = c
    return [float(le) for le in les], per, max(cc - bc, 0.0)


class SLOEngine:
    """Evaluates a set of :class:`SLO` rules against snapshot history
    of a :class:`~analytics_zoo_tpu.common.observability.MetricsRegistry`.

    ``clock`` is injectable (monotonic seconds) so the breach
    lifecycle is unit-testable without sleeps; :meth:`tick` likewise
    accepts an explicit ``now``. Snapshot history lives in a
    :class:`~analytics_zoo_tpu.common.timeseries.MetricHistory`
    (``history``): the global engine shares the process-global
    history that also feeds ``/debug/metrics/history`` and the
    forecaster; explicit-registry engines get a private one on the
    same clock."""

    def __init__(self, registry: "Optional[obs.MetricsRegistry]" = None,
                 clock: "Optional[Callable[[], float]]" = None,
                 history: "Optional[timeseries.MetricHistory]" = None):
        if history is None:
            if registry is None and clock is None:
                history = timeseries.get_history()
            else:
                history = timeseries.MetricHistory(
                    registry=registry or obs.get_registry(),
                    clock=clock)
        self.history = history
        self._registry = registry or obs.get_registry()
        self._clock = clock or time.monotonic
        self._lock = threading.RLock()
        self._rules: "Dict[str, SLO]" = {}
        self._states: "Dict[str, dict]" = {}
        self._ticks = 0
        self._interval_s: Optional[float] = None
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- rule management ----------------------------------------------------
    def add(self, slo: SLO, replace: bool = False) -> SLO:
        with self._lock:
            if slo.id in self._rules and not replace:
                raise ValueError(f"duplicate slo id {slo.id!r}")
            self._rules[slo.id] = slo
            self._states.pop(slo.id, None)
        return slo

    def has(self, slo_id: str) -> bool:
        with self._lock:
            return slo_id in self._rules

    def remove(self, slo_id: str):
        with self._lock:
            self._rules.pop(slo_id, None)
            self._states.pop(slo_id, None)

    def clear(self):
        with self._lock:
            self._rules.clear()
            self._states.clear()
            self.history.clear()

    # -- evaluation ---------------------------------------------------------
    def _baseline(self, now: float, window_s: float):
        """Newest snapshot at least ``window_s`` old; the oldest one
        stands in while the engine is younger than the window
        (delegated to the shared :class:`MetricHistory`)."""
        return self.history.baseline(now, window_s)

    def _window_result(self, rule: SLO, snap: dict, now: float,
                       window_s: float) -> dict:
        out: "Dict[str, Any]" = {"window_s": window_s, "value": None,
                                 "breaching": None}
        base = self._baseline(now, window_s)
        if base is None:
            return out
        bts, bsnap = base
        out["span_s"] = round(max(now - bts, 0.0), 3)
        if rule.kind == "rate":
            delta = _counter_delta(snap, bsnap, rule.sel)
            if delta is None:
                return out
            span = max(now - bts, 1e-9)
            out["value"] = delta / span
            out["breaching"] = _OPS[rule.op](out["value"],
                                             rule.threshold)
        elif rule.kind == "quantile":
            hd = _hist_delta(snap, bsnap, rule.sel)
            if hd is None:
                return out
            les, per, count = hd
            out["events"] = count
            if count < rule.min_events:
                return out
            out["value"] = obs.bucket_quantile(les, per, rule.q)
            out["breaching"] = _OPS[rule.op](out["value"],
                                             rule.threshold)
        else:  # ratio
            num = _counter_delta(snap, bsnap, rule.num)
            den = _counter_delta(snap, bsnap, rule.den)
            if num is None or den is None:
                return out
            out["events"] = den
            if den < rule.min_events:
                return out
            ratio = num / den if den > 0 else 0.0
            budget = 1.0 - rule.objective
            out["value"] = ratio
            out["burn"] = ratio / budget
            out["breaching"] = out["burn"] >= rule.burn_rate
        return out

    def _gauge_result(self, rule: SLO, snap: dict) -> dict:
        value = _scalar_sum(snap, rule.sel)
        if value is None:
            return {"window_s": None, "value": None,
                    "breaching": None}
        return {"window_s": None, "value": value,
                "breaching": _OPS[rule.op](value, rule.threshold)}

    def _evaluate(self, rule: SLO, snap: dict, now: float):
        st = self._states.setdefault(rule.id, {
            "state": "no_data", "breaches": 0, "since": None})
        if rule.kind == "gauge":
            results = [self._gauge_result(rule, snap)]
        else:
            results = [self._window_result(rule, snap, now, w)
                       for w in rule.windows]
        has_data = bool(results) and all(
            r["value"] is not None for r in results)
        breach_now = has_data and all(r["breaching"] for r in results)
        st["windows"] = results
        st["has_data"] = has_data
        st["value"] = results[0]["value"] if results else None
        if not has_data:
            # insufficient signal never transitions the state machine
            if st["state"] not in ("ok", "breach"):
                st["state"] = "no_data"
            return
        prev = st["state"]
        if breach_now:
            if prev != "breach":
                st["breaches"] += 1
                st["since"] = now
                self._registry.counter(
                    "zoo_tpu_slo_breaches_total",
                    help="SLO healthy-to-breach transitions, by "
                         "objective id",
                    labels={"slo": rule.id}).inc()
                diagnostics.anomaly(
                    "slo_breach", slo=rule.id,
                    description=rule.description,
                    value=st["value"],
                    windows=[{k: r.get(k) for k in
                              ("window_s", "value", "burn")}
                             for r in results])
            st["state"] = "breach"
        else:
            if prev == "breach":
                st["since"] = now
                obs.event("slo/recovered", slo=rule.id,
                          value=st["value"])
            st["state"] = "ok"

    def _prune(self, now: float):
        with self._lock:
            max_w = max((r.windows[-1]
                         for r in self._rules.values()),
                        default=600.0)
        # keep the newest snapshot that is already older than the
        # largest window: it is the baseline for full-width windows
        # (the MetricHistory prune contract)
        self.history.prune(now, keep_s=max_w)

    def tick(self, now: Optional[float] = None) -> dict:
        """Snapshot the registry, evaluate every rule against history
        (which holds strictly older snapshots), then append the new
        snapshot to the shared history. Returns :meth:`status`."""
        with self._lock:
            t = self._clock() if now is None else float(now)
            snap = self._registry.snapshot()
            for rule in list(self._rules.values()):
                self._evaluate(rule, snap, t)
            self.history.append(t, snap)
            self._prune(t)
            self._ticks += 1
            return self._status_locked()

    # -- status -------------------------------------------------------------
    def _status_locked(self) -> dict:
        objectives = []
        for rid in sorted(self._rules):
            rule = self._rules[rid]
            st = self._states.get(rid, {})
            rec = rule.to_dict()
            rec.update({
                "state": st.get("state", "no_data"),
                "has_data": st.get("has_data", False),
                "value": st.get("value"),
                "breaches": st.get("breaches", 0),
                "since": st.get("since"),
                "window_results": st.get("windows", []),
            })
            objectives.append(rec)
        return {"enabled": enabled(), "ticks": self._ticks,
                "interval_s": self._interval_s,
                "objectives": objectives}

    def status(self) -> dict:
        with self._lock:
            return self._status_locked()

    # -- background ticker --------------------------------------------------
    def start(self, interval_s: Optional[float] = None) -> "SLOEngine":
        """Start the daemon ticker (idempotent). ``interval_s``
        defaults to ``ZOO_TPU_SLO_TICK_S`` (5 s); ``<= 0`` means no
        thread — callers drive :meth:`tick` themselves."""
        if interval_s is None:
            try:
                interval_s = float(
                    os.environ.get("ZOO_TPU_SLO_TICK_S", "5"))
            except ValueError:
                interval_s = 5.0
        with self._lock:
            self._interval_s = interval_s
            if interval_s <= 0:
                return self
            if self._thread is not None and self._thread.is_alive():
                return self
            self._stop_evt = threading.Event()
            self._thread = threading.Thread(
                target=self._run, name="zoo-tpu-slo-ticker",
                daemon=True)
            self._thread.start()
        return self

    def _run(self):
        while not self._stop_evt.wait(self._interval_s):
            try:
                self.tick()
            except Exception:
                pass  # the ticker must outlive a bad snapshot

    def stop(self):
        with self._lock:
            thread = self._thread
            self._thread = None
        self._stop_evt.set()
        if thread is not None:
            thread.join(timeout=2.0)


# ---------------------------------------------------------------------------
# Process-global engine + shipped-default installation
# ---------------------------------------------------------------------------

_global_lock = threading.Lock()
_engine: Optional[SLOEngine] = None


def get_engine() -> SLOEngine:
    """The process-global engine (shared by both HTTP front-ends and
    the Estimator); created on first use."""
    global _engine
    with _global_lock:
        if _engine is None:
            _engine = SLOEngine()
        return _engine


def _env_overrides(d: dict) -> dict:
    """Per-rule env tuning: ``ZOO_TPU_SLO_<ID>_THRESHOLD`` /
    ``_OBJECTIVE`` / ``_BURN_RATE`` (floats) override the shipped
    literal."""
    base = "ZOO_TPU_SLO_" + d["id"].upper()
    out = dict(d)
    for key in ("threshold", "objective", "burn_rate"):
        raw = os.environ.get(base + "_" + key.upper())
        if raw:
            try:
                out[key] = float(raw)
            except ValueError:
                pass
    return out


def install_defaults(engine: SLOEngine, role: str) -> int:
    """Install the shipped objectives for ``role`` (``"serving"``,
    ``"fleet"``, ``"fed"``, ``"forecast"`` or ``"training"``) into
    ``engine``, skipping ids already present (idempotent;
    user-replaced rules are never clobbered). Returns how many rules
    were added."""
    if role == "serving":
        defaults = DEFAULT_SERVING_SLOS
    elif role == "fleet":
        defaults = DEFAULT_FLEET_SLOS
    elif role == "fed":
        defaults = DEFAULT_FED_SLOS
    elif role == "forecast":
        defaults = DEFAULT_FORECAST_SLOS
    elif role == "training":
        defaults = DEFAULT_TRAINING_SLOS
    else:
        raise ValueError(f"unknown slo role {role!r}")
    n = 0
    for d in defaults:
        if engine.has(d["id"]):
            continue
        engine.add(SLO.from_dict(_env_overrides(d)))
        n += 1
    return n


def ensure_default_slos(role: str) -> Optional[SLOEngine]:
    """Install ``role`` defaults on the global engine and start its
    ticker; no-op (returns None) when ``ZOO_TPU_SLO=0``. Both server
    ``start()`` paths and ``Estimator`` training call this."""
    if not enabled():
        return None
    engine = get_engine()
    install_defaults(engine, role)
    return engine.start()


def reset_slo():
    """Drop the global engine (stopping its ticker) — test isolation,
    mirroring ``observability.reset_metrics``."""
    global _engine
    with _global_lock:
        engine = _engine
        _engine = None
    if engine is not None:
        engine.stop()
