"""Context-propagated tracing (the diagnostics layer, L1.5).

PR 1's :mod:`~analytics_zoo_tpu.common.observability` answers "how
long do spans take in aggregate"; this module answers "what happened
to THIS request / THIS step". A **trace** is a tree of timed spans
sharing one ``trace_id``; the ambient (trace_id, span_id) pair lives
in a :class:`contextvars.ContextVar`, so it is inherited by nested
``with span(...)`` blocks automatically and is per-thread by
construction (the native front-end's worker threads each carry their
own context).

Three moving parts:

- **ambient context** — :func:`trace` opens a root span and sets the
  context; every ``observability.span()`` entered underneath joins it
  as a child (via :func:`span_start`/:func:`span_end`, called by
  ``observability.Span``). Work handed to *another* thread (e.g. the
  batcher's dispatcher) captures :func:`current` at enqueue time and
  either re-enters it with :func:`activate` or records explicit child
  spans with :func:`record_span`.
- **ring-buffered store** — every finished span lands in a bounded
  in-process :class:`TraceStore` (``ZOO_TPU_TRACE_BUFFER`` records,
  default 4096) served by ``GET /debug/traces``.
- **Perfetto export** — :func:`to_chrome_trace` /
  :func:`chrome_events` render spans as chrome-trace JSON
  (``ph: "X"`` complete events, one process per trace) loadable at
  https://ui.perfetto.dev.

``ZOO_TPU_TRACE=0`` disables the whole layer: :func:`span_start`
returns ``None`` before touching the context var and :func:`trace`
yields a no-op handle, so the serving hot path pays nothing.

Stdlib-only on purpose (observability imports *us*, never the other
way around); event-log integration is inverted through
:func:`set_event_hook`.
"""

from __future__ import annotations

import collections
import contextvars
import os
import re
import threading
import time
import uuid
from contextlib import contextmanager
from typing import Any, Dict, List, Optional, Tuple

__all__ = [
    "TRACE_HEADER",
    "SpanRecord",
    "TraceStore",
    "Trace",
    "enabled",
    "new_trace_id",
    "sanitize_trace_id",
    "current",
    "trace",
    "activate",
    "record_span",
    "span_start",
    "span_end",
    "get_store",
    "reset_tracing",
    "chrome_events",
    "to_chrome_trace",
    "set_event_hook",
]

# HTTP header carrying the trace id across the serving front door.
TRACE_HEADER = "X-Zoo-Trace-Id"

# Wire-safe trace ids only: no header/log injection, bounded length.
_ID_RE = re.compile(r"^[A-Za-z0-9_.\-]{1,64}$")


def enabled() -> bool:
    """Tracing is on unless ``ZOO_TPU_TRACE=0``."""
    return os.environ.get("ZOO_TPU_TRACE", "1") != "0"


def new_trace_id() -> str:
    return uuid.uuid4().hex[:16]


def _new_span_id() -> str:
    return uuid.uuid4().hex[:8]


def sanitize_trace_id(trace_id: Optional[str]) -> Optional[str]:
    """Return ``trace_id`` if it is wire-safe, else ``None`` (the
    caller then mints a fresh one — a hostile header never reaches
    the event log or a response header verbatim)."""
    if isinstance(trace_id, str) and _ID_RE.match(trace_id):
        return trace_id
    return None


class SpanRecord:
    """One finished span. ``t_start`` is epoch seconds (wall clock,
    so records from different threads line up); ``dur_s`` is a
    monotonic-clock duration."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name",
                 "t_start", "dur_s", "thread", "fields")

    def __init__(self, trace_id: str, span_id: str,
                 parent_id: Optional[str], name: str, t_start: float,
                 dur_s: float, thread: str,
                 fields: Optional[Dict[str, Any]] = None):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.t_start = t_start
        self.dur_s = dur_s
        self.thread = thread
        self.fields = fields or {}

    def to_dict(self) -> dict:
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "t_start": round(self.t_start, 6),
            "dur_s": round(self.dur_s, 6),
            "thread": self.thread,
            "fields": dict(self.fields),
        }


class TraceStore:
    """Bounded, thread-safe ring buffer of :class:`SpanRecord`.
    Oldest records fall off; a trace whose spans outlive the buffer
    simply truncates — this is a flight recorder, not a database.

    Every record gets a monotonically increasing ``seq`` at insert,
    so collectors can scrape incrementally (:meth:`records_since`)
    without ever re-reading the ring: fetch with the last seq they
    saw, get only newer records plus the new cursor. Records that
    fall off the ring before a scrape are lost (flight-recorder
    semantics), never re-delivered twice."""

    def __init__(self, capacity: Optional[int] = None):
        if capacity is None:
            try:
                capacity = int(os.environ.get(
                    "ZOO_TPU_TRACE_BUFFER", "4096"))
            except ValueError:
                capacity = 4096
        self.capacity = max(1, capacity)
        self._buf: "collections.deque" = collections.deque(
            maxlen=self.capacity)  # (seq, SpanRecord)
        self._seq = 0
        self._lock = threading.Lock()

    def add(self, rec: SpanRecord):
        with self._lock:
            self._seq += 1
            self._buf.append((self._seq, rec))

    def __len__(self) -> int:
        with self._lock:
            return len(self._buf)

    def latest_seq(self) -> int:
        """Seq of the most recently added record (0 when empty ever
        since construction — seqs never reset while the store
        lives)."""
        with self._lock:
            return self._seq

    def records(self) -> "List[SpanRecord]":
        with self._lock:
            return [rec for _seq, rec in self._buf]

    def records_since(self, since: int
                      ) -> "Tuple[int, List[SpanRecord]]":
        """``(cursor, records)``: every buffered record with
        ``seq > since``, oldest first, plus the cursor to pass next
        time. Cursor and records are taken under ONE lock, so a
        record added during the scrape has ``seq > cursor`` and is
        returned by the next call — zero loss, zero duplication (as
        long as it does not fall off the ring first)."""
        with self._lock:
            return self._seq, [rec for seq, rec in self._buf
                               if seq > since]

    def spans(self, trace_id: str) -> "List[SpanRecord]":
        """All buffered spans of one trace, oldest-start first."""
        return sorted((r for r in self.records()
                       if r.trace_id == trace_id),
                      key=lambda r: r.t_start)

    def recent(self, n: int = 20) -> "List[dict]":
        """The ``n`` most recently finished traces, newest first,
        each as ``{"trace_id", "t_start", "dur_s", "spans": [...]}``
        (``dur_s`` spans first start to last end)."""
        by_trace: "Dict[str, List[SpanRecord]]" = {}
        order: "List[str]" = []
        for rec in self.records():
            if rec.trace_id not in by_trace:
                by_trace[rec.trace_id] = []
            else:
                try:
                    order.remove(rec.trace_id)
                except ValueError:
                    pass
            by_trace[rec.trace_id].append(rec)
            order.append(rec.trace_id)
        out = []
        for tid in reversed(order[-max(0, n):] if n else []):
            recs = sorted(by_trace[tid], key=lambda r: r.t_start)
            t0 = recs[0].t_start
            t1 = max(r.t_start + r.dur_s for r in recs)
            out.append({"trace_id": tid,
                        "t_start": round(t0, 6),
                        "dur_s": round(t1 - t0, 6),
                        "n_spans": len(recs),
                        "spans": [r.to_dict() for r in recs]})
        return out

    def clear(self):
        with self._lock:
            self._buf.clear()


_STORE = TraceStore()


def get_store() -> TraceStore:
    return _STORE


def reset_tracing():
    """Drop all buffered spans (test isolation)."""
    _STORE.clear()


# Ambient (trace_id, span_id) of the innermost open span, or None.
_ctx: "contextvars.ContextVar[Optional[Tuple[str, str]]]" = (
    contextvars.ContextVar("zoo_tpu_trace", default=None))


def current() -> "Optional[Tuple[str, str]]":
    """The ambient ``(trace_id, span_id)`` pair, or ``None``. Capture
    this before handing work to another thread, then pass it to
    :func:`activate` or :func:`record_span` over there."""
    return _ctx.get()


# observability registers its event() here so trace/root and explicit
# record_span() records reach the JSONL event log without a circular
# import. observability.Span emits its own events and bypasses this.
_event_hook = None


def set_event_hook(hook):
    global _event_hook
    _event_hook = hook


def _emit(rec: SpanRecord):
    hook = _event_hook
    if hook is None:
        return
    try:
        hook(rec.name, trace_id=rec.trace_id, span_id=rec.span_id,
             parent_id=rec.parent_id, t_start=round(rec.t_start, 6),
             dur_s=round(rec.dur_s, 6), **rec.fields)
    except Exception:
        pass  # telemetry must never take down the traced path


class Trace:
    """Handle yielded by :func:`trace`. ``trace_id`` is ``None`` when
    tracing is disabled; :meth:`annotate` attaches fields to the root
    span record."""

    __slots__ = ("trace_id", "span_id", "fields")

    def __init__(self, trace_id: Optional[str],
                 span_id: Optional[str], fields: Dict[str, Any]):
        self.trace_id = trace_id
        self.span_id = span_id
        self.fields = fields

    def annotate(self, **fields):
        for k, v in fields.items():
            if v is not None:
                self.fields[k] = v


_NOOP = Trace(None, None, {})


@contextmanager
def trace(name: str = "trace", trace_id: Optional[str] = None,
          **fields):
    """Open a **root** span: mint (or adopt) a trace id, set the
    ambient context for the block, and record the span on exit. Yields
    a :class:`Trace`; no-op (``trace_id is None``) when disabled."""
    if not enabled():
        yield _NOOP
        return
    tid = sanitize_trace_id(trace_id) or new_trace_id()
    sid = _new_span_id()
    tok = _ctx.set((tid, sid))
    t0_wall = time.time()
    t0 = time.perf_counter()
    handle = Trace(tid, sid, dict(fields))
    try:
        yield handle
    finally:
        _ctx.reset(tok)
        rec = SpanRecord(tid, sid, None, name, t0_wall,
                         time.perf_counter() - t0,
                         threading.current_thread().name,
                         handle.fields)
        _STORE.add(rec)
        _emit(rec)


@contextmanager
def activate(ctx: "Optional[Tuple[str, str]]"):
    """Re-enter a context captured with :func:`current` on another
    thread, so spans opened inside join that trace. No-op on None."""
    if ctx is None:
        yield
        return
    tok = _ctx.set(ctx)
    try:
        yield
    finally:
        _ctx.reset(tok)


def record_span(ctx: "Optional[Tuple[str, str]]", name: str,
                t_start: float, dur_s: float, **fields):
    """Record an already-timed child span of ``ctx`` (explicit
    cross-thread form — e.g. the batcher crediting queue wait back to
    the submitting request). ``t_start`` is epoch seconds. No-op when
    ``ctx`` is None or tracing is disabled."""
    if ctx is None or not enabled():
        return
    tid, parent = ctx
    rec = SpanRecord(tid, _new_span_id(), parent, name, t_start,
                     dur_s, threading.current_thread().name, fields)
    _STORE.add(rec)
    _emit(rec)


def span_start(name: str):
    """Called by ``observability.Span.__enter__``: join the ambient
    trace as a child span. Returns an opaque token for
    :func:`span_end`, or **None** (the hot-path fast exit) when
    tracing is disabled or no trace is open."""
    if not enabled():
        return None
    cur = _ctx.get()
    if cur is None:
        return None
    tid, parent = cur
    sid = _new_span_id()
    tok = _ctx.set((tid, sid))
    return (tok, tid, sid, parent, time.time())


def span_end(token, name: str, dur_s: float,
             fields: Optional[Dict[str, Any]] = None):
    """Close a span opened by :func:`span_start` (token must be
    non-None) and buffer its record. The caller (observability.Span)
    owns event-log emission."""
    tok, tid, sid, parent, t0_wall = token
    try:
        _ctx.reset(tok)
    except ValueError:
        pass  # exited in a different context; record anyway
    _STORE.add(SpanRecord(tid, sid, parent, name, t0_wall, dur_s,
                          threading.current_thread().name,
                          dict(fields or {})))


# ---------------------------------------------------------------------------
# Perfetto / chrome-trace export
# ---------------------------------------------------------------------------

def _get(rec, key, default=None):
    if isinstance(rec, SpanRecord):
        return getattr(rec, key, default)
    return rec.get(key, default)


def chrome_events(records, source_lanes: bool = False
                  ) -> "List[dict]":
    """Render span records (:class:`SpanRecord` or plain dicts with
    the same keys, e.g. parsed event-log lines) as chrome-trace
    events: one ``ph: "X"`` complete event per span, one *process*
    per trace id, one *thread* per source thread, plus ``ph: "M"``
    metadata naming both.

    ``source_lanes=True`` assigns the process lane per the record's
    ``source`` field instead (fleet-stitched spans carry the scraped
    process's name there — `common/federation.py`), so a
    cross-process trace renders each replica process as its own
    Perfetto track group."""
    pids: "Dict[str, int]" = {}
    tids: "Dict[Tuple[int, str], int]" = {}
    events: "List[dict]" = []
    for rec in records:
        dur = _get(rec, "dur_s")
        tid_str = _get(rec, "trace_id")
        if dur is None or tid_str is None:
            continue
        t_start = _get(rec, "t_start")
        if t_start is None:
            ts = _get(rec, "ts")  # event-log lines stamp exit time
            if ts is None:
                continue
            t_start = float(ts) - float(dur)
        if source_lanes:
            lane = str(_get(rec, "source", None) or "router")
            lane_name = f"process {lane}"
        else:
            lane = tid_str
            lane_name = f"trace {tid_str}"
        if lane not in pids:
            pids[lane] = len(pids) + 1
            events.append({"ph": "M", "name": "process_name",
                           "pid": pids[lane], "tid": 0,
                           "args": {"name": lane_name}})
        pid = pids[lane]
        thread = _get(rec, "thread", "main") or "main"
        tkey = (pid, thread)
        if tkey not in tids:
            tids[tkey] = len([k for k in tids if k[0] == pid]) + 1
            events.append({"ph": "M", "name": "thread_name",
                           "pid": pid, "tid": tids[tkey],
                           "args": {"name": thread}})
        args = {"trace_id": tid_str,
                "span_id": _get(rec, "span_id"),
                "parent_id": _get(rec, "parent_id")}
        fields = _get(rec, "fields")
        if isinstance(fields, dict):
            args.update(fields)
        events.append({
            "name": _get(rec, "name") or _get(rec, "event", "span"),
            "ph": "X",
            "ts": round(float(t_start) * 1e6, 3),
            "dur": round(float(dur) * 1e6, 3),
            "pid": pid,
            "tid": tids[tkey],
            "args": {k: v for k, v in args.items() if v is not None},
        })
    return events


def to_chrome_trace(trace_ids=None) -> dict:
    """Chrome-trace JSON object for the buffered spans (optionally
    restricted to ``trace_ids``), loadable by Perfetto."""
    recs = _STORE.records()
    if trace_ids is not None:
        wanted = set(trace_ids)
        recs = [r for r in recs if r.trace_id in wanted]
    return {"traceEvents": chrome_events(recs),
            "displayTimeUnit": "ms"}
