"""Context & engine init (L1).

The reference's `init_nncontext()` creates/gets a SparkContext with the zoo
conf overlay and runs BigDL `Engine.init` to discover nodes/cores
(reference `Z/common/NNContext.scala:132-207`, `P/common/nncontext.py:21-40`).

The TPU-native equivalent discovers the accelerator topology instead: it
builds a `jax.sharding.Mesh` over the local (or multi-host) TPU slice and
registers it process-wide. Everything downstream — the Estimator's pjit'd
train step, FeatureSet's sharded host ingest, model predict — asks this
context for the mesh and shardings rather than an RDD partition count.

There is deliberately no Spark dependency in-core: data ingest accepts any
sharded-iterable (see `feature.feature_set`), which is the role RDDs played.
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from analytics_zoo_tpu.common.config import (
    MeshConf,
    ZooBuildInfo,
    ZooTpuConf,
    parse_axes,
)
from analytics_zoo_tpu.version import __version__

logger = logging.getLogger("analytics_zoo_tpu")

_lock = threading.RLock()
_current: "NNContext | None" = None
_distributed_done = False


class NNContext:
    """Process-wide engine context: mesh + config + rng root.

    Analog of SparkContext+Engine in the reference (NNContext.scala:132-146),
    with the device mesh playing the role of the cluster.
    """

    def __init__(self, conf: ZooTpuConf, mesh: Mesh):
        self.conf = conf
        self.mesh = mesh
        self._rng = jax.random.key(conf.seed)
        self._rng_lock = threading.Lock()
        self.build_info = ZooBuildInfo(
            version=__version__, jax_version=jax.__version__)

    # ---- topology ----------------------------------------------------------
    @property
    def num_devices(self) -> int:
        return self.mesh.size

    @property
    def data_axes(self) -> "tuple[str, ...]":
        """Mesh axes over which the batch dimension is sharded."""
        return tuple(a for a in self.mesh.axis_names if a in ("data", "fsdp"))

    @property
    def data_parallel_size(self) -> int:
        n = 1
        for a in self.data_axes:
            n *= self.mesh.shape[a]
        return n

    def batch_sharding(self, ndim: int = 2) -> NamedSharding:
        """Sharding for a host batch: dim0 split over the data axes."""
        spec = [None] * ndim
        spec[0] = self.data_axes or None
        return NamedSharding(self.mesh, P(*spec))

    def replicated_sharding(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    def check_batch_size(self, batch_size: int) -> int:
        """Enforce batch divisibility over the data-parallel size.

        Mirrors the reference's `batch_size % total_cores == 0` rule
        (`P/pipeline/api/net.py:741-749`), with devices standing in for
        cores.
        """
        dp = self.data_parallel_size
        if self.conf.check_batch_divisibility and batch_size % dp != 0:
            raise ValueError(
                f"batch_size ({batch_size}) must be divisible by the "
                f"data-parallel size ({dp}). Per-device batch = "
                f"batch_size // {dp}.")
        return batch_size

    # ---- rng ---------------------------------------------------------------
    def next_rng_key(self, n: Optional[int] = None):
        """Split fresh PRNG key(s) off the context root key (thread-safe)."""
        with self._rng_lock:
            if n is None:
                self._rng, out = jax.random.split(self._rng)
            else:
                keys = jax.random.split(self._rng, n + 1)
                self._rng, out = keys[0], keys[1:]
            return out

    def __repr__(self) -> str:
        return (f"NNContext(devices={self.num_devices}, "
                f"mesh={dict(self.mesh.shape)}, "
                f"platform={jax.devices()[0].platform})")


def _build_mesh(mesh_conf: MeshConf) -> Mesh:
    devices = mesh_conf.devices
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    axes = mesh_conf.resolved_axes(len(devices))
    shape = tuple(axes.values())
    names = tuple(axes.keys())
    total = int(np.prod(shape)) if shape else 1
    dev_array = np.array(devices[:total]).reshape(shape)
    return Mesh(dev_array, names)


def _maybe_init_distributed(multi_host) -> None:
    """Join the multi-host JAX cluster (the reference's
    executor-registration role, played by `jax.distributed`).

    ``multi_host=True`` forces it; ``multi_host=None`` auto-joins when
    the standard coordinator env (``JAX_COORDINATOR_ADDRESS`` /
    ``COORDINATOR_ADDRESS``) or a Cloud-TPU pod environment announces
    one. After init, ``jax.devices()`` is the GLOBAL device set and
    ``jax.process_index()`` feeds the per-host data sharding
    (`feature/rdd.py:process_shard_spec`)."""
    import os

    global _distributed_done
    if multi_host is False or _distributed_done:
        return
    announced = os.environ.get("JAX_COORDINATOR_ADDRESS") or \
        os.environ.get("COORDINATOR_ADDRESS")
    if not multi_host and not announced:
        return
    # NOTE: no jax.* probes before initialize() — touching the backend
    # (even jax.process_count()) initializes XLA and makes
    # jax.distributed.initialize() unconditionally raise
    kwargs = {}
    if announced and not os.environ.get("JAX_COORDINATOR_ADDRESS"):
        # forward the generic spelling jax doesn't read itself
        kwargs["coordinator_address"] = announced
        npz = os.environ.get("JAX_NUM_PROCESSES") or \
            os.environ.get("NUM_PROCESSES")
        pid = os.environ.get("JAX_PROCESS_ID") or \
            os.environ.get("PROCESS_ID")
        if npz is not None:
            kwargs["num_processes"] = int(npz)
        if pid is not None:
            kwargs["process_id"] = int(pid)
    try:
        jax.distributed.initialize(**kwargs)
        _distributed_done = True
        logger.info("jax.distributed initialized: process %d/%d",
                    jax.process_index(), jax.process_count())
    except RuntimeError as e:
        if "already" in str(e).lower():  # initialized elsewhere — fine
            _distributed_done = True
            return
        if multi_host:
            raise
        logger.warning("jax.distributed.initialize failed (%s); "
                       "continuing single-host", e)
    except Exception as e:  # single-host fallback stays usable
        if multi_host:
            raise
        logger.warning("jax.distributed.initialize failed (%s); "
                       "continuing single-host", e)


def init_nncontext(
    conf: "ZooTpuConf | None" = None,
    *,
    app_name: Optional[str] = None,
    tpu_mesh: "str | Mapping[str, int] | Sequence | Mesh | None" = None,
    devices: Optional[Sequence[Any]] = None,
    seed: Optional[int] = None,
    log_level: Optional[str] = None,
    multi_host: Optional[bool] = None,
) -> NNContext:
    """Create (or replace) the process-wide :class:`NNContext`.

    Analog of `init_nncontext()` (reference `P/common/nncontext.py:21-40`)
    with the north-star `tpu_mesh=` argument: instead of attaching a Spark
    cluster, attach a TPU mesh.

    Args:
      conf: full typed config; env vars ``ZOO_TPU_*`` overlay on top.
      app_name: convenience override of ``conf.app_name``.
      tpu_mesh: mesh axes spec (``"data=8"``, ``{"data": 4, "model": 2}``)
        or a prebuilt `jax.sharding.Mesh`. Default: all devices on ``data``.
      devices: explicit device list (default ``jax.devices()`` — the
        GLOBAL device set after multi-host init).
      seed: root RNG seed.
      log_level: python logging level for the zoo logger.
      multi_host: True → require `jax.distributed.initialize()` (all
        hosts of the pod run the same program); None (default) →
        auto-join when a coordinator address env is present; False →
        never.
    """
    global _current
    _maybe_init_distributed(multi_host)
    conf = ZooTpuConf.from_env(conf)
    if app_name is not None:
        conf.app_name = app_name
    if seed is not None:
        conf.seed = seed
    if log_level is not None:
        conf.log_level = log_level

    # configure only our own logger — never touch the root logger
    logger.setLevel(conf.log_level)
    if not logger.handlers:
        handler = logging.StreamHandler()
        handler.setFormatter(logging.Formatter(
            "%(asctime)s %(name)s %(levelname)s: %(message)s"))
        logger.addHandler(handler)
        logger.propagate = False

    if isinstance(tpu_mesh, Mesh):
        mesh = tpu_mesh
    else:
        if tpu_mesh is not None:
            conf.mesh = MeshConf(axes=parse_axes(tpu_mesh), devices=devices)
        elif devices is not None:
            conf.mesh.devices = devices
        mesh = _build_mesh(conf.mesh)

    ctx = NNContext(conf, mesh)
    with _lock:
        _current = ctx
    logger.info("Initialized %s", ctx)
    return ctx


def get_nncontext(create_if_missing: bool = True) -> NNContext:
    """Return the current context, creating a default one if needed
    (mirrors SparkContext.getOrCreate semantics, NNContext.scala:143)."""
    global _current
    with _lock:
        if _current is not None:
            return _current
        if not create_if_missing:
            raise RuntimeError("NNContext not initialized; "
                               "call init_nncontext() first")
        return init_nncontext()


def reset_nncontext() -> None:
    global _current
    with _lock:
        _current = None
