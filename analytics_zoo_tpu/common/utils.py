"""File/IO helpers (reference `Z/common/Utils.scala`: HDFS/S3/local
byte IO, `logUsageErrorAndThrowException`).

TPU-native redesign: the reference reached HDFS/S3 through the Hadoop
FileSystem JNI stack; here remote schemes (``hdfs://``, ``s3://``,
``gs://``, ``memory://``, ...) route through `fsspec` — the same
read/save/list surface over whatever protocol backends the deployment
installs (gcsfs, s3fs, pyarrow-HDFS). Missing backends degrade with a
clear error naming the protocol instead of a stack trace.
"""

from __future__ import annotations

import glob as _glob
import os
import shutil
from typing import List

from analytics_zoo_tpu.common.nncontext import logger

_SCHEME_ALIASES = {"s3a": "s3", "s3n": "s3"}


def _split_scheme(path: str) -> "tuple[Optional[str], str]":
    if "://" not in path:
        return None, path
    raw, rest = path.split("://", 1)
    scheme = _SCHEME_ALIASES.get(raw.lower(), raw.lower())
    if scheme == "file":
        return None, rest
    # return the path re-rooted on the NORMALIZED scheme — backends
    # like s3fs only strip the protocols they declare (s3/s3a, not s3n
    # or uppercase spellings)
    return scheme, f"{scheme}://{rest}"


def _fs_for(scheme: str):
    try:
        import fsspec
    except ImportError as e:
        raise NotImplementedError(
            f"{scheme}:// paths need fsspec (not installed): {e}"
        ) from e
    try:
        return fsspec.filesystem(scheme)
    except (ImportError, ValueError, OSError) as e:
        # missing protocol backend (s3fs/gcsfs) or an unusable one
        # (pyarrow-hdfs without a JVM)
        hint = {"gs": "gcsfs", "s3": "s3fs",
                "hdfs": "a pyarrow/Hadoop+JVM install"}.get(scheme,
                                                            scheme)
        raise NotImplementedError(
            f"{scheme}:// needs a working fsspec backend ({hint}) in "
            f"this environment: {e}") from e


def read_bytes(path: str) -> bytes:
    """(reference `Utils.readBytes` — local or any fsspec scheme)"""
    scheme, path = _split_scheme(path)
    if scheme is None:
        with open(path, "rb") as f:
            return f.read()
    with _fs_for(scheme).open(path, "rb") as f:
        return f.read()


def ceil_pool_extra(dim: int, k_eff: int, stride: int,
                    lo: int, hi: int) -> int:
    """Extra trailing padding that makes floor pooling produce
    ceil-mode's output count (torch/onnxruntime semantics: the last
    window is dropped when it starts past input + leading pad).
    Shared by the torch and ONNX importers."""
    span = dim + lo + hi - k_eff
    out_floor = span // stride + 1
    out_ceil = -(-span // stride) + 1
    if out_ceil == out_floor or (out_ceil - 1) * stride >= dim + lo:
        return 0
    return (out_ceil - 1) * stride + k_eff - (dim + lo + hi)


def parallel_map(fn, items, env_knob: str = "ZOO_TPU_DECODE_WORKERS",
                 default_workers: int = 8, min_items: int = 4):
    """Order-preserving thread-pool map for GIL-releasing per-item
    work (PIL decode/resize, numpy transforms). Serial when the knob
    is <=1, unparseable-but-small, or the batch is tiny."""
    try:
        workers = int(os.environ.get(env_knob, str(default_workers)))
    except ValueError:
        workers = default_workers
    items = list(items)
    if workers > 1 and len(items) >= min_items:
        from concurrent.futures import ThreadPoolExecutor
        with ThreadPoolExecutor(min(workers, len(items))) as ex:
            return list(ex.map(fn, items))
    return [fn(i) for i in items]


def read_bytes_many(paths) -> "dict":
    """``{path: bytes}`` for a batch of paths. Remote schemes fetch in
    ONE ``fs.cat`` call (concurrent under the hood) instead of a
    blocking round-trip per file — the difference between seconds and
    tens of minutes for a 10k-image ``gs://`` tree."""
    out: dict = {}
    by_scheme: dict = {}
    for p in paths:
        scheme, local = _split_scheme(p)
        if scheme is None:
            with open(local, "rb") as f:
                out[p] = f.read()
        else:
            by_scheme.setdefault(scheme, []).append((p, local))
    for scheme, items in by_scheme.items():
        fs = _fs_for(scheme)
        try:
            got = fs.cat([local for _, local in items])
        except Exception:
            got = None  # fall back to per-file reads below
        if isinstance(got, (bytes, bytearray)) and len(items) == 1:
            got = {fs._strip_protocol(items[0][1]): bytes(got)}
        for orig, local in items:
            key = fs._strip_protocol(local)
            if isinstance(got, dict) and key in got:
                out[orig] = got[key]
            else:
                with fs.open(local, "rb") as f:
                    out[orig] = f.read()
    return out


def save_bytes(data: bytes, path: str,
               is_overwrite: bool = False) -> None:
    """(reference `Utils.saveBytes`)"""
    scheme, path = _split_scheme(path)
    if scheme is None:
        if os.path.exists(path) and not is_overwrite:
            raise FileExistsError(
                f"{path} exists; pass is_overwrite=True")
        os.makedirs(os.path.dirname(os.path.abspath(path)),
                    exist_ok=True)
        with open(path, "wb") as f:
            f.write(data)
        return
    fs = _fs_for(scheme)
    if fs.exists(path) and not is_overwrite:
        raise FileExistsError(f"{path} exists; pass is_overwrite=True")
    with fs.open(path, "wb") as f:
        f.write(data)


def _requalify(scheme: str, names) -> List[str]:
    """fsspec strips the scheme from listing results; restore it so
    results round-trip through read_bytes etc."""
    return sorted(p if "://" in str(p) else f"{scheme}://{p}"
                  for p in names)


def list_files(pattern: str) -> List[str]:
    """Glob helper used by readers (reference `Utils.listPaths`)."""
    scheme, local = _split_scheme(pattern)
    if scheme is None:
        if os.path.isdir(local):
            return sorted(
                os.path.join(local, p) for p in os.listdir(local)
                if os.path.isfile(os.path.join(local, p)))
        return sorted(_glob.glob(local))
    pattern = local  # normalized-scheme form
    fs = _fs_for(scheme)
    if fs.isdir(pattern):
        # one listing call; filtering on the returned type info avoids
        # a per-entry stat round-trip on remote stores
        out = [e["name"] for e in fs.ls(pattern, detail=True)
               if e.get("type") == "file"]
    else:
        out = list(fs.glob(pattern))
    return _requalify(scheme, out)


def is_dir(path: str) -> bool:
    """Directory test across local and fsspec schemes."""
    scheme, local = _split_scheme(path)
    if scheme is None:
        return os.path.isdir(local)
    return bool(_fs_for(scheme).isdir(local))


def list_dirs(path: str) -> List[str]:
    """Immediate subdirectories of `path` (local or fsspec scheme),
    scheme-qualified like :func:`list_files`."""
    scheme, local = _split_scheme(path)
    if scheme is None:
        return sorted(
            os.path.join(local, d) for d in os.listdir(local)
            if os.path.isdir(os.path.join(local, d)))
    fs = _fs_for(scheme)
    out = [e["name"] for e in fs.ls(local, detail=True)
           if e.get("type") == "directory"]
    return _requalify(scheme, out)


def walk_files(path: str) -> List[str]:
    """All files under `path` recursively (reference
    `NNImageReader.scala:144-182` reads whole HDFS trees this way)."""
    scheme, local = _split_scheme(path)
    if scheme is None:
        return sorted(
            f for f in _glob.glob(os.path.join(local, "**", "*"),
                                  recursive=True)
            if os.path.isfile(f))
    fs = _fs_for(scheme)
    return _requalify(scheme, fs.find(local))


def mkdirs(path: str) -> None:
    scheme, local = _split_scheme(path)
    if scheme is None:
        os.makedirs(local, exist_ok=True)
    else:
        _fs_for(scheme).makedirs(local, exist_ok=True)


def remove(path: str, recursive: bool = False) -> None:
    scheme, local = _split_scheme(path)
    if scheme is not None:
        try:
            _fs_for(scheme).rm(local, recursive=recursive)
        except FileNotFoundError:
            pass  # match the local branch's missing-path no-op
        return
    if os.path.isdir(local):
        if not recursive:
            raise IsADirectoryError(f"{local} is a directory; pass "
                                    "recursive=True")
        shutil.rmtree(local)
    elif os.path.exists(local):
        os.remove(local)


def log_usage_error_and_throw(message: str) -> None:
    """(reference `Utils.logUsageErrorAndThrowException`)"""
    logger.error("Invalid usage: %s", message)
    raise ValueError(message)
