"""File/IO helpers (reference `Z/common/Utils.scala`: HDFS/S3/local
byte IO, `logUsageErrorAndThrowException`).

TPU-native scope: local filesystem + optional GCS via ``gs://`` when
`etils`/gcsfs-style backends are present; remote schemes degrade with a
clear error instead of a stack trace (no Hadoop in this image).
"""

from __future__ import annotations

import glob as _glob
import os
import shutil
from typing import List

from analytics_zoo_tpu.common.nncontext import logger

_REMOTE_SCHEMES = ("hdfs://", "s3://", "s3a://", "s3n://")


def _check_scheme(path: str) -> str:
    for scheme in _REMOTE_SCHEMES:
        if path.startswith(scheme):
            raise NotImplementedError(
                f"{scheme} paths need a Hadoop/S3 client that is not in "
                "this image; stage the file locally or on gs:// "
                "(reference `Utils.scala` supported these via Hadoop FS)")
    return path


def read_bytes(path: str) -> bytes:
    """(reference `Utils.readBytes`)"""
    path = _check_scheme(path)
    with open(path, "rb") as f:
        return f.read()


def save_bytes(data: bytes, path: str,
               is_overwrite: bool = False) -> None:
    """(reference `Utils.saveBytes`)"""
    path = _check_scheme(path)
    if os.path.exists(path) and not is_overwrite:
        raise FileExistsError(
            f"{path} exists; pass is_overwrite=True")
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, "wb") as f:
        f.write(data)


def list_files(pattern: str) -> List[str]:
    """Glob helper used by readers (reference `Utils.listPaths`)."""
    _check_scheme(pattern)
    if os.path.isdir(pattern):
        return sorted(
            os.path.join(pattern, p) for p in os.listdir(pattern)
            if os.path.isfile(os.path.join(pattern, p)))
    return sorted(_glob.glob(pattern))


def mkdirs(path: str) -> None:
    os.makedirs(path, exist_ok=True)


def remove(path: str, recursive: bool = False) -> None:
    if os.path.isdir(path):
        if not recursive:
            raise IsADirectoryError(f"{path} is a directory; pass "
                                    "recursive=True")
        shutil.rmtree(path)
    elif os.path.exists(path):
        os.remove(path)


def log_usage_error_and_throw(message: str) -> None:
    """(reference `Utils.logUsageErrorAndThrowException`)"""
    logger.error("Invalid usage: %s", message)
    raise ValueError(message)
