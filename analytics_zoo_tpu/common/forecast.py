"""Capacity forecasting: see exhaustion coming, not report it.

Rides the :mod:`~analytics_zoo_tpu.common.timeseries` history as a
sample listener: after every history sample it extrapolates the
recent trend of each watched resource (EWMA-smoothed least-squares
slope over ``ZOO_TPU_FORECAST_WINDOW_S``) to an exhaustion ETA —

- **kv_pages**: ``zoo_tpu_serving_gen_free_pages`` falling toward 0
  (paged-KV exhaustion → ``FleetSaturatedError``/503s);
- **queue** / **gen_queue**: ``zoo_tpu_serving_queue_depth`` /
  ``zoo_tpu_serving_gen_queue_depth`` climbing toward their
  admission limits;
- **event_log**: ``zoo_tpu_event_log_bytes`` climbing toward the
  configured rotation budget (disk).

Each resource publishes ``zoo_tpu_forecast_eta_s{resource=}``
(seconds until exhaustion at the current trend; the ``NO_ETA``
sentinel ``1e9`` means "no exhaustion in sight" — never ``inf``,
which the Prometheus renderer rejects). When a finite ETA drops
inside ``ZOO_TPU_FORECAST_HORIZON_S`` the forecaster fires ONE
*predictive* ``zoo_tpu_anomalies_total{kind="capacity_forecast"}``
anomaly (re-armed when the ETA recovers), which the shipped
``forecast`` SLO defaults in :mod:`~analytics_zoo_tpu.common.slo`
turn into burn-rate pages *before* hard saturation.

Stdlib-only; injectable clock; ``tick(now=)`` for sleepless tests.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from analytics_zoo_tpu.common import diagnostics
from analytics_zoo_tpu.common import observability as obs
from analytics_zoo_tpu.common import timeseries

__all__ = [
    "NO_ETA",
    "DEFAULT_RESOURCES",
    "Forecaster",
    "ewma",
    "linear_slope",
    "eta_to_limit",
    "enabled",
    "get_forecaster",
    "ensure_forecaster",
    "reset_forecast",
]

# Published instead of +inf when the trend never reaches the limit:
# ~31 years, finite for the text renderer, and trivially outside any
# sane SLO threshold on zoo_tpu_forecast_eta_s.
NO_ETA = 1e9

# Watched resources (pure literal; limits may be overridden or
# supplied by env). direction "down" → exhausted when the value
# falls to `limit`; "up" → when it climbs to `limit`.
DEFAULT_RESOURCES = [
    {
        "resource": "kv_pages",
        "family": "zoo_tpu_serving_gen_free_pages",
        "direction": "down",
        "limit": 0.0,
    },
    {
        "resource": "queue",
        "family": "zoo_tpu_serving_queue_depth",
        "direction": "up",
        "limit": 256.0,
        "limit_env": "ZOO_TPU_FORECAST_QUEUE_LIMIT",
    },
    {
        "resource": "gen_queue",
        "family": "zoo_tpu_serving_gen_queue_depth",
        "direction": "up",
        "limit": 256.0,
        "limit_env": "ZOO_TPU_FORECAST_GEN_QUEUE_LIMIT",
    },
    {
        "resource": "event_log",
        "family": "zoo_tpu_event_log_bytes",
        "direction": "up",
        "limit": None,
        "limit_env": "ZOO_TPU_FORECAST_EVENT_LOG_LIMIT_MB",
        "limit_scale": 1048576.0,
    },
]


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


# ---------------------------------------------------------------------------
# Trend math (pure functions — unit-tested exactly)
# ---------------------------------------------------------------------------

def ewma(values: "List[float]", alpha: float) -> "List[float]":
    """Exponentially-weighted moving average; ``alpha=1`` is the
    identity (pure linear fit on raw samples)."""
    out: "List[float]" = []
    s: Optional[float] = None
    for v in values:
        s = v if s is None else alpha * v + (1.0 - alpha) * s
        out.append(s)
    return out


def linear_slope(points: "List[tuple]") -> Optional[float]:
    """Least-squares slope of ``[(ts, value), ...]`` in units/s;
    None when fewer than 2 points or zero time spread."""
    n = len(points)
    if n < 2:
        return None
    mt = sum(p[0] for p in points) / n
    mv = sum(p[1] for p in points) / n
    den = sum((p[0] - mt) ** 2 for p in points)
    if den <= 0:
        return None
    num = sum((p[0] - mt) * (p[1] - mv) for p in points)
    return num / den


def eta_to_limit(points: "List[tuple]", limit: float,
                 direction: str,
                 alpha: float = 1.0) -> Optional[float]:
    """Seconds until the EWMA-smoothed linear trend of ``points``
    reaches ``limit`` (0.0 if already there); None when the trend
    points away from the limit or is flat/unknown."""
    if not points:
        return None
    smoothed = ewma([p[1] for p in points], alpha)
    pts = [(points[i][0], smoothed[i])
           for i in range(len(points))]
    cur = pts[-1][1]
    slope = linear_slope(pts)
    if direction == "down":
        if cur <= limit:
            return 0.0
        if slope is None or slope >= -1e-12:
            return None
        return (cur - limit) / (-slope)
    if cur >= limit:
        return 0.0
    if slope is None or slope <= 1e-12:
        return None
    return (limit - cur) / slope


# ---------------------------------------------------------------------------
# Forecaster
# ---------------------------------------------------------------------------

class Forecaster:
    """Extrapolates resource trends from a
    :class:`~analytics_zoo_tpu.common.timeseries.MetricHistory`
    into exhaustion ETAs + predictive anomalies."""

    def __init__(self, history: "timeseries.MetricHistory",
                 registry: "Optional[obs.MetricsRegistry]" = None,
                 clock: "Optional[Callable[[], float]]" = None,
                 resources: "Optional[List[dict]]" = None,
                 window_s: Optional[float] = None,
                 horizon_s: Optional[float] = None,
                 min_points: Optional[int] = None,
                 min_span_s: Optional[float] = None,
                 alpha: Optional[float] = None):
        self.history = history
        self._registry = registry or obs.get_registry()
        self._clock = clock or time.monotonic
        self._resources = [dict(r) for r in
                           (resources if resources is not None
                            else DEFAULT_RESOURCES)]
        self.window_s = (window_s if window_s is not None else
                         _env_float("ZOO_TPU_FORECAST_WINDOW_S",
                                    120.0))
        self.horizon_s = (horizon_s if horizon_s is not None else
                          _env_float("ZOO_TPU_FORECAST_HORIZON_S",
                                     600.0))
        self.min_points = max(
            min_points if min_points is not None else
            _env_int("ZOO_TPU_FORECAST_MIN_POINTS", 5), 2)
        self.min_span_s = (
            min_span_s if min_span_s is not None else
            _env_float("ZOO_TPU_FORECAST_MIN_SPAN_S", 10.0))
        a = (alpha if alpha is not None else
             _env_float("ZOO_TPU_FORECAST_EWMA", 0.3))
        self.alpha = min(max(a, 0.01), 1.0)
        self._lock = threading.Lock()
        self._pending: "Dict[str, bool]" = {}
        self._status: "Dict[str, dict]" = {}
        self._ticks = 0

    def _limit(self, spec: dict) -> Optional[float]:
        env = spec.get("limit_env")
        if env and os.environ.get(env):
            try:
                return float(os.environ[env]) * float(
                    spec.get("limit_scale", 1.0))
            except ValueError:
                pass
        limit = spec.get("limit")
        if limit is not None:
            return float(limit)
        if spec["resource"] == "event_log":
            # Default disk budget: the rotation cap times the
            # number of live segments, when rotation is on.
            max_mb = _env_float("ZOO_TPU_EVENT_LOG_MAX_MB", 0.0)
            if max_mb > 0:
                keep = _env_int("ZOO_TPU_EVENT_LOG_KEEP", 3)
                return max_mb * 1048576.0 * (keep + 1)
        return None

    def _points(self, spec: dict, now: float) -> "List[tuple]":
        """Gauge samples for the resource, summed across label
        sets at each timestamp (a family like queue depth may be
        split per batcher; capacity is the sum)."""
        ser = self.history.series(spec["family"],
                                  window_s=self.window_s,
                                  now=now)
        by_ts: "Dict[float, float]" = {}
        for s in ser.get("series", ()):
            for p in s.get("points", ()):
                if "value" in p:
                    by_ts[p["ts"]] = by_ts.get(p["ts"], 0.0) \
                        + float(p["value"])
        return sorted(by_ts.items())

    def tick(self, now: Optional[float] = None) -> dict:
        """Re-forecast every resource; called from the history's
        sample listener (so it shares the sampler's ``ts``) or
        manually with an injected ``now`` in tests."""
        t = self._clock() if now is None else float(now)
        status: "Dict[str, dict]" = {}
        with self._lock:
            for spec in self._resources:
                name = spec["resource"]
                limit = self._limit(spec)
                st: "Dict[str, Any]" = {
                    "family": spec["family"],
                    "direction": spec["direction"],
                    "limit": limit,
                }
                eta: Optional[float] = None
                if limit is not None:
                    pts = self._points(spec, t)
                    st["points"] = len(pts)
                    span = (pts[-1][0] - pts[0][0]) if pts else 0.0
                    st["span_s"] = round(span, 3)
                    st["value"] = pts[-1][1] if pts else None
                    if (len(pts) >= self.min_points
                            and span >= self.min_span_s):
                        eta = eta_to_limit(pts, limit,
                                           spec["direction"],
                                           self.alpha)
                else:
                    st["skipped"] = "no limit configured"
                st["eta_s"] = (round(eta, 3) if eta is not None
                               else None)
                self._registry.gauge(
                    "zoo_tpu_forecast_eta_s",
                    help="forecast seconds until resource "
                         "exhaustion (1e9 = none in sight)",
                    labels={"resource": name},
                ).set(round(eta, 3) if eta is not None
                      else NO_ETA)
                pending = (eta is not None
                           and eta <= self.horizon_s)
                st["pending"] = pending
                if pending and not self._pending.get(name):
                    diagnostics.anomaly(
                        "capacity_forecast",
                        resource=name,
                        eta_s=round(eta, 3),
                        limit=limit,
                        value=st.get("value"),
                        window_s=self.window_s)
                self._pending[name] = pending
                status[name] = st
            self._status = status
            self._ticks += 1
        return status

    def status(self) -> dict:
        with self._lock:
            return {"ticks": self._ticks,
                    "window_s": self.window_s,
                    "horizon_s": self.horizon_s,
                    "resources": dict(self._status)}


# ---------------------------------------------------------------------------
# Process-global forecaster, riding the global history's sampler
# ---------------------------------------------------------------------------

def enabled() -> bool:
    return os.environ.get("ZOO_TPU_FORECAST", "1") != "0"


_global_lock = threading.Lock()
_forecaster: Optional[Forecaster] = None


def _on_sample(history: "timeseries.MetricHistory", ts: float):
    f = _forecaster
    if f is None:
        return
    try:
        f.tick(now=ts)
    except Exception:
        pass  # forecasting must never break the sampler


def get_forecaster() -> Forecaster:
    """The process-global forecaster over the global history;
    created on first use (does not register the listener — use
    :func:`ensure_forecaster` for that)."""
    global _forecaster
    with _global_lock:
        if _forecaster is None:
            _forecaster = Forecaster(timeseries.get_history())
        return _forecaster


def ensure_forecaster() -> Optional[Forecaster]:
    """Idempotently wire the global forecaster onto the global
    history's sample listener; no-op when ``ZOO_TPU_FORECAST=0``."""
    if not enabled():
        return None
    f = get_forecaster()
    f.history.add_listener(_on_sample)
    return f


def reset_forecast():
    """Drop the global forecaster + listener (test isolation)."""
    global _forecaster
    with _global_lock:
        if _forecaster is not None:
            try:
                _forecaster.history.remove_listener(_on_sample)
            except Exception:
                pass
        _forecaster = None
