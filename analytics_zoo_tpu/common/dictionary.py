"""ZooDictionary: word↔index vocabulary (reference
`Z/common/ZooDictionary.scala` — used by seq2seq / chatbot pipelines).
"""

from __future__ import annotations

import json
from typing import Dict, Iterable, List, Optional, Sequence


class ZooDictionary:
    """Bidirectional word↔index mapping built from a corpus or loaded
    from saved vocab files."""

    def __init__(self, words: Optional[Iterable[str]] = None,
                 case_sensitive: bool = True):
        self._word2idx: Dict[str, int] = {}
        self._idx2word: List[str] = []
        self.case_sensitive = case_sensitive
        if words is not None:
            for w in words:
                self.add_word(w)

    # -- construction -------------------------------------------------------
    def _norm(self, word: str) -> str:
        return word if self.case_sensitive else word.lower()

    def add_word(self, word: str) -> int:
        word = self._norm(word)
        if word not in self._word2idx:
            self._word2idx[word] = len(self._idx2word)
            self._idx2word.append(word)
        return self._word2idx[word]

    @classmethod
    def from_corpus(cls, sentences: Iterable[Sequence[str]],
                    max_vocab: Optional[int] = None,
                    case_sensitive: bool = True) -> "ZooDictionary":
        """Build from tokenized sentences, most-frequent-first
        (reference constructor from a dataset of sentences)."""
        counts: Dict[str, int] = {}
        d = cls(case_sensitive=case_sensitive)
        for sent in sentences:
            for w in sent:
                w = d._norm(w)
                counts[w] = counts.get(w, 0) + 1
        ranked = sorted(counts.items(), key=lambda kv: (-kv[1], kv[0]))
        if max_vocab is not None:
            ranked = ranked[:max_vocab]
        for w, _ in ranked:
            d.add_word(w)
        return d

    # -- lookup (reference getIndex/getWord) --------------------------------
    def get_index(self, word: str, default: Optional[int] = None) -> int:
        word = self._norm(word)
        if word in self._word2idx:
            return self._word2idx[word]
        if default is not None:
            return default
        raise KeyError(f"word {word!r} not in dictionary")

    def get_word(self, index: int) -> str:
        return self._idx2word[index]

    def contains(self, word: str) -> bool:
        return self._norm(word) in self._word2idx

    def __contains__(self, word: str) -> bool:
        return self.contains(word)

    def __len__(self) -> int:
        return len(self._idx2word)

    @property
    def vocab_size(self) -> int:
        return len(self._idx2word)

    def word2idx(self) -> Dict[str, int]:
        return dict(self._word2idx)

    def idx2word(self) -> List[str]:
        return list(self._idx2word)

    # -- encode / decode ----------------------------------------------------
    def encode(self, tokens: Sequence[str],
               unk_index: Optional[int] = None) -> List[int]:
        return [self.get_index(t, default=unk_index) for t in tokens]

    def decode(self, indices: Sequence[int]) -> List[str]:
        return [self.get_word(int(i)) for i in indices]

    # -- persistence (reference save/load vocab files) ----------------------
    def save(self, path: str) -> None:
        with open(path, "w", encoding="utf-8") as f:
            json.dump({"case_sensitive": self.case_sensitive,
                       "words": self._idx2word}, f)

    @classmethod
    def load(cls, path: str) -> "ZooDictionary":
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
        d = cls(case_sensitive=data.get("case_sensitive", True))
        for w in data["words"]:
            d.add_word(w)
        return d
