"""Bounded in-process metric time-series store (observability L1.5).

Every observability surface so far — the metrics registry, tracing,
SLO burn rates, fleet federation — answers "what is happening right
now"; the only retained history was a private deque inside
`common/slo.py` that nobody else could query. This module makes
windowed history a first-class, shared plane, Monarch/Prometheus
style:

- :class:`MetricHistory` keeps a bounded raw ring of
  ``(ts, registry snapshot)`` samples plus coarser downsampled
  tiers, with a hard cap on resident bytes. It is sampled on the
  existing SLO/federation tickers (one history, one clock — the
  refactored :class:`~analytics_zoo_tpu.common.slo.SLOEngine` reads
  its windowed baselines from here), and manually tickable with an
  injected ``now`` for tests.
- :meth:`MetricHistory.series` answers windowed per-family queries
  (``GET /debug/metrics/history?family=&window=`` on both HTTP
  front-ends): counters come back as per-interval deltas + rates,
  gauges as sampled values, histograms as quantile summaries
  (q50/q90/q99 + event rate) — per label set.
- Downsampled tiers make hour/day-scale history affordable: each
  tier stores one compact point per ``step_s`` bucket (counters as
  deltas, histograms as quantile summaries — bucket arrays are NOT
  retained), so wide windows cost tier points, not raw snapshots.

Config (docs/perf_flags.md): ``ZOO_TPU_TSDB_RAW_S`` (raw ring
retention, default 900 s), ``ZOO_TPU_TSDB_RAW_MAX`` (max raw
samples, default 4096), ``ZOO_TPU_TSDB_MAX_BYTES`` (hard resident
cap, default 8 MiB), ``ZOO_TPU_TSDB_TIERS``
(``step:retention[,step:retention...]``, default
``30:3600,300:21600``).

Stdlib-only (the observability-layer constraint): importable from
serving worker threads and executor-side code; never drags in jax.
"""

from __future__ import annotations

import collections
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from analytics_zoo_tpu.common import observability as obs

__all__ = [
    "MetricHistory",
    "get_history",
    "reset_history",
]


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def _parse_tiers(raw: str) -> "List[Tuple[float, float]]":
    """``"30:3600,300:21600"`` → ``[(step_s, retention_s), ...]``
    sorted by step; malformed entries are silently dropped."""
    out = []
    for part in (raw or "").split(","):
        part = part.strip()
        if not part:
            continue
        try:
            step, ret = part.split(":")
            step_f, ret_f = float(step), float(ret)
        except ValueError:
            continue
        if step_f > 0 and ret_f > 0:
            out.append((step_f, ret_f))
    return sorted(out)


def _label_key(labels: "Optional[Dict[str, Any]]"
               ) -> "Tuple[Tuple[str, str], ...]":
    return tuple(sorted((str(k), str(v))
                        for k, v in (labels or {}).items()))


def _match(labels: "Dict[str, str]",
           want: "Optional[Dict[str, str]]") -> bool:
    return all(labels.get(k) == v for k, v in (want or {}).items())


def _approx_snapshot_bytes(snap: dict) -> int:
    """Cheap resident-size estimate of one registry snapshot —
    counted, not serialized (sampling must stay cheap)."""
    n = 0
    for name, fam in snap.items():
        n += 64 + len(name)
        for rec in fam.get("values", ()):
            n += 120
            n += 24 * len(rec.get("labels", {}))
            n += 24 * len(rec.get("buckets", {}))
    return n


def _approx_point_bytes(fams: dict) -> int:
    n = 0
    for name, fam in fams.items():
        n += 64 + len(name)
        n += 100 * len(fam.get("values", ()))
    return n


def _bucket_delta(cur_rec: dict, prev_rec: "Optional[dict]"):
    """``(finite_bounds, per_bucket_counts(+Inf tail), count_delta,
    sum_delta)`` between two cumulative histogram children
    (``prev_rec`` may be None). Deltas of cumulative counts are
    clamped monotone, so a source restart (counter reset) never
    yields negatives."""
    cb = cur_rec.get("buckets", {})
    cc = float(cur_rec.get("count", 0))
    cs = float(cur_rec.get("sum", 0.0))
    pb = (prev_rec or {}).get("buckets", {})
    pc = float((prev_rec or {}).get("count", 0))
    ps = float((prev_rec or {}).get("sum", 0.0))
    les = sorted((le for le in cb if le != "+Inf"), key=float)
    cum = [max(float(cb[le]) - float(pb.get(le, 0.0)), 0.0)
           for le in les]
    cum.append(max(float(cb.get("+Inf", cc))
                   - float(pb.get("+Inf", 0.0)), 0.0))
    per, prev_c = [], 0.0
    for c in cum:
        c = max(c, prev_c)
        per.append(c - prev_c)
        prev_c = c
    return ([float(le) for le in les], per,
            max(cc - pc, 0.0), max(cs - ps, 0.0))


def _hist_summary(les, per, count: float, dsum: float) -> dict:
    """Quantile summary of a windowed histogram delta (NaN → None
    so the payload stays strict-JSON-parseable)."""
    if count <= 0:
        return {"count": 0.0, "sum": 0.0,
                "q50": None, "q90": None, "q99": None}
    out = {"count": count, "sum": dsum}
    for name, q in (("q50", 0.5), ("q90", 0.9), ("q99", 0.99)):
        v = obs.bucket_quantile(les, per, q)
        out[name] = None if v != v else round(v, 9)
    return out


class _Tier:
    """One downsampling tier: at most one compact point per
    ``step_s`` time bucket, retained ``retention_s`` seconds."""

    __slots__ = ("step_s", "retention_s", "points", "bytes",
                 "_bucket", "_prev", "_prev_ts")

    def __init__(self, step_s: float, retention_s: float):
        self.step_s = float(step_s)
        self.retention_s = float(retention_s)
        self.points: "collections.deque" = collections.deque()
        self.bytes = 0
        self._bucket: Optional[float] = None
        # (family, labelkey) -> last cumulative value/record
        self._prev: "Dict[tuple, Any]" = {}
        self._prev_ts: Optional[float] = None

    def offer(self, ts: float, snap: dict) -> bool:
        """Downsample ``snap`` into this tier iff ``ts`` opens a new
        ``step_s`` bucket (first sample in each bucket wins)."""
        bucket = ts - (ts % self.step_s)
        if self._bucket is not None and bucket <= self._bucket:
            return False
        fams: "Dict[str, dict]" = {}
        prev = self._prev
        nxt: "Dict[tuple, Any]" = {}
        for name, fam in snap.items():
            mtype = fam.get("type")
            vals = []
            for rec in fam.get("values", ()):
                labels = dict(rec.get("labels", {}))
                lk = (name, _label_key(labels))
                if mtype == "gauge":
                    vals.append({"labels": labels,
                                 "value": float(
                                     rec.get("value", 0.0))})
                elif mtype == "counter":
                    cur = float(rec.get("value", 0.0))
                    base = prev.get(lk, 0.0)
                    vals.append({"labels": labels,
                                 "value": max(cur - base, 0.0)})
                    nxt[lk] = cur
                else:
                    les, per, dc, ds = _bucket_delta(
                        rec, prev.get(lk))
                    vals.append(dict(
                        {"labels": labels},
                        **_hist_summary(les, per, dc, ds)))
                    nxt[lk] = {
                        "buckets": dict(rec.get("buckets", {})),
                        "count": rec.get("count", 0),
                        "sum": rec.get("sum", 0.0)}
            fams[name] = {"type": mtype, "values": vals}
        dt = (ts - self._prev_ts) if self._prev_ts is not None \
            else self.step_s
        point = {"ts": ts, "dt": max(float(dt), 1e-9),
                 "fams": fams}
        self.points.append(point)
        self.bytes += _approx_point_bytes(fams)
        self._bucket = bucket
        self._prev = nxt
        self._prev_ts = ts
        horizon = ts - self.retention_s
        while self.points and self.points[0]["ts"] < horizon:
            dropped = self.points.popleft()
            self.bytes -= _approx_point_bytes(dropped["fams"])
        return True

    def clear(self):
        self.points.clear()
        self.bytes = 0
        self._bucket = None
        self._prev = {}
        self._prev_ts = None


class MetricHistory:
    """Bounded ring of registry snapshots + downsampled tiers.

    ``registry=None`` builds an append-only store (the federation
    collector feeds it merged fleet snapshots); with a registry,
    :meth:`sample`/:meth:`tick` snapshot it directly. ``clock`` is
    injectable (monotonic seconds) and every mutating entry point
    accepts an explicit ``now``/``ts`` — no test ever sleeps."""

    def __init__(self, registry: "Optional[obs.MetricsRegistry]"
                 = None,
                 clock: "Optional[Callable[[], float]]" = None,
                 raw_retention_s: Optional[float] = None,
                 raw_max: Optional[int] = None,
                 max_bytes: Optional[int] = None,
                 tiers: "Optional[List[Tuple[float, float]]]"
                 = None):
        self._registry = registry
        self._clock = clock or time.monotonic
        if raw_retention_s is None:
            raw_retention_s = _env_float("ZOO_TPU_TSDB_RAW_S",
                                         900.0)
        self.raw_retention_s = max(float(raw_retention_s), 1.0)
        if raw_max is None:
            raw_max = _env_int("ZOO_TPU_TSDB_RAW_MAX", 4096)
        self.raw_max = max(int(raw_max), 2)
        if max_bytes is None:
            max_bytes = _env_int("ZOO_TPU_TSDB_MAX_BYTES",
                                 8 * 1024 * 1024)
        self.max_bytes = max(int(max_bytes), 65536)
        if tiers is None:
            tiers = _parse_tiers(os.environ.get(
                "ZOO_TPU_TSDB_TIERS", "30:3600,300:21600"))
        self._tiers = [_Tier(s, r) for s, r in tiers]
        self._lock = threading.RLock()
        # raw ring entries: (ts, snapshot, approx_bytes)
        self._raw: "collections.deque" = collections.deque()
        self._raw_bytes = 0
        self._samples = 0
        self._evictions = 0
        self._listeners: "List[Callable]" = []

    # -- ingestion ----------------------------------------------------------
    def append(self, ts: float, snap: dict) -> dict:
        """Record one ``(ts, snapshot)`` sample: raw ring + tier
        downsampling + cap enforcement, then listener fan-out (the
        forecaster rides here). Listeners run outside the lock."""
        with self._lock:
            ts = float(ts)
            b = _approx_snapshot_bytes(snap)
            self._raw.append((ts, snap, b))
            self._raw_bytes += b
            self._samples += 1
            for tier in self._tiers:
                tier.offer(ts, snap)
            self.prune(ts)
            self._enforce_caps()
            if self._registry is not None:
                self._registry.counter(
                    "zoo_tpu_tsdb_samples_total",
                    help="metric-history samples recorded").inc()
                self._registry.gauge(
                    "zoo_tpu_tsdb_resident_bytes",
                    help="approximate resident bytes of the metric"
                         " history (raw ring + tiers)").set(
                    self._raw_bytes
                    + sum(t.bytes for t in self._tiers))
            listeners = list(self._listeners)
        for fn in listeners:
            try:
                fn(self, ts)
            except Exception:
                pass  # a bad listener must not break sampling
        return snap

    def sample(self, now: Optional[float] = None
               ) -> "Tuple[float, dict]":
        """Snapshot the bound registry and append it."""
        if self._registry is None:
            raise ValueError(
                "this MetricHistory has no registry to sample "
                "(append() only — e.g. the fleet-merged history)")
        t = self._clock() if now is None else float(now)
        snap = self._registry.snapshot()
        self.append(t, snap)
        return t, snap

    def tick(self, now: Optional[float] = None
             ) -> "Tuple[float, dict]":
        """Manual sampling tick (the injectable-``now`` convention
        of slo.py / federation.py — tests never sleep)."""
        return self.sample(now=now)

    # -- retention ----------------------------------------------------------
    def prune(self, now: float, keep_s: Optional[float] = None):
        """Drop raw entries older than the retention horizon, but
        always keep the newest entry already older than it: that
        entry is the baseline for full-width windows (the slo.py
        windows-clip-to-uptime contract)."""
        with self._lock:
            horizon = float(now) - max(float(keep_s or 0.0),
                                       self.raw_retention_s)
            raw = self._raw
            while len(raw) >= 2 and raw[1][0] <= horizon:
                self._raw_bytes -= raw.popleft()[2]

    def _enforce_caps(self):
        """Hard caps: sample count and resident bytes (down to a
        2-sample floor so windowed deltas always have a baseline).
        Evicted samples already live on in the tiers."""
        raw = self._raw
        while len(raw) > self.raw_max or (
                self._raw_bytes > self.max_bytes and len(raw) > 2):
            self._raw_bytes -= raw.popleft()[2]
            self._evictions += 1

    # -- SLO-engine seam ----------------------------------------------------
    def baseline(self, now: float, window_s: float):
        """Newest raw sample at least ``window_s`` old; the oldest
        one stands in while history is younger than the window."""
        with self._lock:
            best = None
            for ts, snap, _b in self._raw:
                if ts <= now - window_s:
                    best = (ts, snap)
                else:
                    break
            if best is None and self._raw:
                ts, snap, _b = self._raw[0]
                best = (ts, snap)
            return best

    def __len__(self) -> int:
        with self._lock:
            return len(self._raw)

    def clear(self):
        with self._lock:
            self._raw.clear()
            self._raw_bytes = 0
            for tier in self._tiers:
                tier.clear()

    # -- queries ------------------------------------------------------------
    def families(self) -> "List[dict]":
        """Known families (name + type), newest raw snapshot union
        the tiers (a family evicted from raw may persist there)."""
        with self._lock:
            out: "Dict[str, str]" = {}
            if self._raw:
                for name, fam in self._raw[-1][1].items():
                    out.setdefault(name, fam.get("type"))
            for tier in self._tiers:
                for p in tier.points:
                    for name, fam in p["fams"].items():
                        out.setdefault(name, fam.get("type"))
            return [{"family": k, "type": out[k]}
                    for k in sorted(out)]

    def series(self, family: str,
               window_s: Optional[float] = None,
               now: Optional[float] = None,
               labels: "Optional[Dict[str, str]]" = None) -> dict:
        """Windowed per-label-set series for one family.

        Raw ring when the window fits its retention, else the
        finest tier that covers it. Counters → per-interval deltas
        (``value``) + ``rate``; gauges → sampled ``value``;
        histograms → ``count``/``sum``/``q50``/``q90``/``q99`` +
        ``rate`` per interval."""
        with self._lock:
            if now is None:
                now = self._clock()
            w = float(window_s) if window_s else \
                self.raw_retention_s
            use_raw = w <= self.raw_retention_s + 1e-9
            tier = None
            if not use_raw:
                for t in self._tiers:
                    if t.retention_s + 1e-9 >= w:
                        tier = t
                        break
                if tier is None and self._tiers:
                    tier = self._tiers[-1]
                if tier is None:
                    use_raw = True
            if use_raw:
                return self._series_raw(family, w, now, labels)
            return self._series_tier(tier, family, w, now, labels)

    def _series_raw(self, family, w, now, labels) -> dict:
        start = now - w
        kept = []
        prev_entry = None
        for ts, snap, _b in self._raw:
            if ts < start:
                prev_entry = (ts, snap)
            else:
                kept.append((ts, snap))
        mtype = None
        for ts, snap, _b in reversed(self._raw):
            fam = snap.get(family)
            if fam is not None:
                mtype = fam.get("type")
                break
        out = {"family": family, "type": mtype, "window_s": w,
               "now": now, "source": "raw", "series": []}
        if mtype is None:
            return out
        keys: "Dict[tuple, dict]" = {}
        for _ts, snap in kept:
            fam = snap.get(family) or {}
            for rec in fam.get("values", ()):
                ld = dict(rec.get("labels", {}))
                if labels and not _match(ld, labels):
                    continue
                keys.setdefault(_label_key(ld), ld)
        chain = ([prev_entry] if prev_entry else []) + kept
        for lk in sorted(keys):
            ld = keys[lk]
            pts = []
            prev_rec = None
            prev_ts = None
            for ts, snap in chain:
                rec = None
                fam = snap.get(family) or {}
                for r in fam.get("values", ()):
                    if _label_key(r.get("labels", {})) == lk:
                        rec = r
                        break
                if rec is None:
                    continue
                if mtype == "gauge":
                    if ts >= start:
                        pts.append({
                            "ts": ts,
                            "value": float(rec.get("value",
                                                   0.0))})
                elif mtype == "counter":
                    if prev_rec is not None and ts >= start:
                        d = max(float(rec.get("value", 0.0))
                                - float(prev_rec.get("value",
                                                     0.0)), 0.0)
                        dt = max(ts - prev_ts, 1e-9)
                        pts.append({"ts": ts, "value": d,
                                    "rate": d / dt})
                    prev_rec, prev_ts = rec, ts
                else:
                    if prev_rec is not None and ts >= start:
                        les, per, dc, ds = _bucket_delta(
                            rec, prev_rec)
                        dt = max(ts - prev_ts, 1e-9)
                        pts.append(dict(
                            {"ts": ts, "rate": dc / dt},
                            **_hist_summary(les, per, dc, ds)))
                    prev_rec, prev_ts = rec, ts
            out["series"].append({"labels": ld, "points": pts})
        return out

    def _series_tier(self, tier, family, w, now, labels) -> dict:
        start = now - w
        out = {"family": family, "type": None, "window_s": w,
               "now": now, "source": f"tier:{int(tier.step_s)}",
               "series": []}
        keyed: "Dict[tuple, Tuple[dict, list]]" = {}
        for p in tier.points:
            if p["ts"] < start:
                continue
            fam = p["fams"].get(family)
            if fam is None:
                continue
            if out["type"] is None:
                out["type"] = fam.get("type")
            for rec in fam.get("values", ()):
                ld = dict(rec.get("labels", {}))
                if labels and not _match(ld, labels):
                    continue
                lk = _label_key(ld)
                pt = {k: v for k, v in rec.items()
                      if k != "labels"}
                pt["ts"] = p["ts"]
                if out["type"] == "counter":
                    pt["rate"] = float(pt.get("value", 0.0)) \
                        / max(p["dt"], 1e-9)
                elif out["type"] == "histogram":
                    pt["rate"] = float(pt.get("count", 0.0)) \
                        / max(p["dt"], 1e-9)
                keyed.setdefault(lk, (ld, []))[1].append(pt)
        for lk in sorted(keyed):
            ld, pts = keyed[lk]
            out["series"].append({"labels": ld, "points": pts})
        return out

    def export(self, window_s: Optional[float] = None,
               now: Optional[float] = None) -> dict:
        """Full history dump as one JSON-able document —
        ``scripts/trace_report.py --history`` and
        ``scripts/perf_sentinel.py --history`` consume this."""
        with self._lock:
            if now is None:
                now = self._clock()
            doc = {"now": float(now),
                   "window_s": (float(window_s) if window_s
                                else self.raw_retention_s),
                   "stats": self.stats(),
                   "families": {}}
            for f in self.families():
                doc["families"][f["family"]] = self.series(
                    f["family"], window_s=window_s, now=now)
            return doc

    def stats(self) -> dict:
        with self._lock:
            return {
                "raw_samples": len(self._raw),
                "raw_retention_s": self.raw_retention_s,
                "raw_max": self.raw_max,
                "resident_bytes": self._raw_bytes
                + sum(t.bytes for t in self._tiers),
                "max_bytes": self.max_bytes,
                "samples_total": self._samples,
                "evictions": self._evictions,
                "span_s": (round(self._raw[-1][0]
                                 - self._raw[0][0], 3)
                           if len(self._raw) >= 2 else 0.0),
                "tiers": [{"step_s": t.step_s,
                           "retention_s": t.retention_s,
                           "points": len(t.points)}
                          for t in self._tiers],
            }

    # -- listeners ----------------------------------------------------------
    def add_listener(self, fn: Callable):
        """Register ``fn(history, ts)`` to run after every sample
        (outside the lock). Idempotent per function object."""
        with self._lock:
            if fn not in self._listeners:
                self._listeners.append(fn)

    def remove_listener(self, fn: Callable):
        with self._lock:
            if fn in self._listeners:
                self._listeners.remove(fn)


# ---------------------------------------------------------------------------
# Process-global history (one history, one clock)
# ---------------------------------------------------------------------------

_global_lock = threading.Lock()
_history: Optional[MetricHistory] = None


def get_history() -> MetricHistory:
    """The process-global history over the global metrics registry
    — shared by the SLO engine, the forecaster and both HTTP
    front-ends; created on first use."""
    global _history
    with _global_lock:
        if _history is None:
            _history = MetricHistory(registry=obs.get_registry())
        return _history


def reset_history():
    """Drop the global history (test isolation, mirroring
    ``observability.reset_metrics``)."""
    global _history
    with _global_lock:
        _history = None
