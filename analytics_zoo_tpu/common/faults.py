"""Fault injection for chaos testing (the robustness layer).

Every resilience claim this codebase makes — sibling retry absorbs a
replica kill, a wedged dispatcher fails one batch and keeps serving,
a torn checkpoint is never loaded, canary breaches auto-roll-back —
is only as good as the failure paths that back it, and failure paths
rot unless something exercises them. This module is that something: a
registry of named **injection points** compiled into the production
code, each a guarded no-op until a test (or ``ZOO_TPU_FAULTS``) arms
it with a behavior::

    from analytics_zoo_tpu.common import faults
    _FAULT = faults.point("fleet/replica_predict")   # module scope
    ...
    def predict(self, inputs):
        _FAULT.fire(replica=self.name)               # hot path
        ...

Unarmed, :meth:`FaultPoint.fire` is a single attribute test
(``self._spec is None``) — no dict lookup, no lock, no allocation —
so shipping the hooks in the hot path costs nothing measurable
(asserted by ``tests/test_faults.py``).

Behaviors (``kind``):

``error``    raise :class:`InjectedFaultError`
``kill``     raise :class:`InjectedKillError` — semantically "the
             replica/process died"; routers treat it like any crash
``delay``    sleep ``seconds`` (straggler), then continue
``wedge``    block until disarmed (or ``seconds`` elapse, default
             30 s) — a stuck dispatcher / hung device
``corrupt``  :meth:`FaultPoint.corrupt` returns a corrupted copy of
             the value (numeric arrays are NaN-poisoned); ``fire``
             is a no-op for this kind

Arming:

- test-side: ``faults.arm("batcher/dispatch", "error", times=1)``,
  ``faults.disarm(...)`` / ``faults.disarm_all()`` (both always
  safe to call);
- env: ``ZOO_TPU_FAULTS="point=kind[:seconds][:key=val]..."``,
  ``;``-separated for multiple points (grammar in
  docs/perf_flags.md), parsed once at first arm-state query, e.g.::

      ZOO_TPU_FAULTS="fleet/replica_predict=kill:times=3:\
          where_replica=r0;batcher/dispatch=delay:0.2"

Selectors: ``times=N`` auto-disarms after N firings, ``p=0.5`` fires
probabilistically, ``where_<key>=value`` only fires when the site
passed ``fire(<key>=value)`` (e.g. target one replica by name).

Every firing increments ``zoo_tpu_faults_injected_total{point,kind}``
and appends a ``faults/injected`` event, so chaos runs are observable
through the normal telemetry (`scripts/chaos_smoke.py`,
docs/robustness.md).
"""

from __future__ import annotations

import os
import random
import threading
import time
from typing import Dict, Optional

from analytics_zoo_tpu.common import observability as obs

__all__ = [
    "FaultPoint",
    "InjectedFaultError",
    "InjectedKillError",
    "point",
    "arm",
    "disarm",
    "disarm_all",
    "armed",
    "points",
]

_KINDS = ("error", "kill", "delay", "wedge", "corrupt")


class InjectedFaultError(RuntimeError):
    """An armed ``error`` fault fired at an injection point."""

    def __init__(self, point_name: str):
        super().__init__(f"injected fault at {point_name}")
        self.point = point_name


class InjectedKillError(InjectedFaultError):
    """An armed ``kill`` fault fired — simulates the owning
    component (replica, worker) dying mid-operation."""

    def __init__(self, point_name: str):
        RuntimeError.__init__(
            self, f"injected kill at {point_name}")
        self.point = point_name


class _Spec:
    """One armed behavior: kind + selectors + firing budget."""

    def __init__(self, kind: str, seconds: float = 0.0,
                 times: Optional[int] = None, p: float = 1.0,
                 where: Optional[Dict[str, str]] = None):
        if kind not in _KINDS:
            raise ValueError(
                f"unknown fault kind {kind!r} (one of {_KINDS})")
        self.kind = kind
        self.seconds = float(seconds)
        self.times = None if times is None else int(times)
        self.p = float(p)
        self.where = dict(where) if where else None
        self.fired = 0
        self.release = threading.Event()  # unwedges on disarm

    def to_dict(self) -> dict:
        d = {"kind": self.kind, "fired": self.fired}
        if self.seconds:
            d["seconds"] = self.seconds
        if self.times is not None:
            d["times"] = self.times
        if self.p < 1.0:
            d["p"] = self.p
        if self.where:
            d["where"] = dict(self.where)
        return d


class FaultPoint:
    """A named injection point. Hold the object at module/class
    scope and call :meth:`fire` (or :meth:`corrupt` for output
    corruption) on the hot path — unarmed, both are a single
    attribute test."""

    __slots__ = ("name", "_spec")

    def __init__(self, name: str):
        self.name = name
        self._spec: Optional[_Spec] = None

    def fire(self, **ctx):
        """Execute the armed behavior, or return immediately when
        unarmed. ``ctx`` lets sites expose selectors (e.g.
        ``fire(replica=self.name)``) for ``where_*`` targeting."""
        if self._spec is None:  # the unarmed hot path: one test
            return
        self._fire_armed(ctx)

    def corrupt(self, value, **ctx):
        """Return ``value``, corrupted when an armed ``corrupt``
        fault fires (numeric numpy arrays are NaN-poisoned; integer
        arrays bit-flipped; anything else returned as-is with the
        firing still counted)."""
        if self._spec is None:
            return value
        spec = self._take(ctx, kinds=("corrupt",))
        if spec is None:
            return value
        self._count(spec)
        return _corrupt_value(value)

    # -- armed slow path -----------------------------------------------------
    def _take(self, ctx, kinds=None) -> Optional[_Spec]:
        """The armed spec iff its selectors match this firing (and
        its budget allows one more); None otherwise."""
        spec = self._spec
        if spec is None:
            return None
        if kinds is not None and spec.kind not in kinds:
            return None
        if kinds is None and spec.kind == "corrupt":
            return None  # corrupt only fires through corrupt()
        if spec.where:
            for k, v in spec.where.items():
                if str(ctx.get(k)) != v:
                    return None
        if spec.p < 1.0 and random.random() >= spec.p:
            return None
        if spec.times is not None:
            with _lock:
                if spec.times <= 0:
                    return None
                spec.times -= 1
                if spec.times == 0:
                    # budget spent: restore the no-op hot path
                    if self._spec is spec:
                        self._spec = None
                        spec.release.set()
        return spec

    def _count(self, spec: _Spec):
        spec.fired += 1
        obs.counter("zoo_tpu_faults_injected_total",
                    help="injected faults fired, by point and kind",
                    labels={"point": self.name,
                            "kind": spec.kind}).inc()
        obs.event("faults/injected", point=self.name,
                  kind=spec.kind)

    def _fire_armed(self, ctx):
        spec = self._take(ctx)
        if spec is None:
            return
        self._count(spec)
        if spec.kind == "error":
            raise InjectedFaultError(self.name)
        if spec.kind == "kill":
            raise InjectedKillError(self.name)
        if spec.kind == "delay":
            time.sleep(spec.seconds)
            return
        if spec.kind == "wedge":
            # block until disarmed (release set) or the safety cap
            spec.release.wait(timeout=spec.seconds or 30.0)
            return

    # -- introspection -------------------------------------------------------
    @property
    def armed(self) -> bool:
        return self._spec is not None

    def status(self) -> dict:
        spec = self._spec
        return {"point": self.name,
                "armed": spec.to_dict() if spec else None}

    def __repr__(self):
        return f"FaultPoint({self.name!r}, armed={self.armed})"


def _corrupt_value(value):
    import numpy as np
    try:
        arr = np.asarray(value)
    except Exception:
        return value
    if arr.dtype.kind == "f":
        return np.full_like(arr, np.nan)
    if arr.dtype.kind in "iu":
        return arr ^ np.asarray(1, arr.dtype)
    return value


_lock = threading.Lock()
_points: "Dict[str, FaultPoint]" = {}
_env_parsed = False


def point(name: str) -> FaultPoint:
    """The (process-global) injection point named ``name``; created
    on first request. Env-armed faults (``ZOO_TPU_FAULTS``) attach
    the first time their point is created."""
    with _lock:
        fp = _points.get(name)
        if fp is None:
            fp = _points[name] = FaultPoint(name)
        _parse_env_locked()
    return fp


def arm(name: str, kind: str, seconds: float = 0.0,
        times: Optional[int] = None, p: float = 1.0,
        where: Optional[Dict[str, str]] = None) -> FaultPoint:
    """Arm ``name`` with a behavior (replacing any prior arming).
    See the module docstring for kinds and selectors."""
    fp = point(name)
    spec = _Spec(kind, seconds=seconds, times=times, p=p,
                 where=where)
    with _lock:
        old = fp._spec
        fp._spec = spec
        if old is not None:
            old.release.set()
    obs.event("faults/armed", point=name, kind=kind)
    return fp


def disarm(name: str):
    """Disarm ``name`` (releasing any wedged thread). Safe when the
    point does not exist or is already unarmed."""
    with _lock:
        fp = _points.get(name)
        if fp is None:
            return
        spec = fp._spec
        fp._spec = None
    if spec is not None:
        spec.release.set()


def disarm_all():
    """Disarm every point (test teardown)."""
    with _lock:
        specs = []
        for fp in _points.values():
            if fp._spec is not None:
                specs.append(fp._spec)
                fp._spec = None
    for spec in specs:
        spec.release.set()


def armed() -> "Dict[str, dict]":
    """``{point: spec_dict}`` for every currently armed point."""
    with _lock:
        return {name: fp._spec.to_dict()
                for name, fp in _points.items()
                if fp._spec is not None}


def points() -> "Dict[str, dict]":
    """Status of every registered injection point (armed or not) —
    the failure-mode catalog's live counterpart
    (docs/robustness.md)."""
    with _lock:
        return {name: fp.status() for name, fp in _points.items()}


# -- ZOO_TPU_FAULTS grammar --------------------------------------------------

def _parse_env_locked():
    """Parse ``ZOO_TPU_FAULTS`` once per process and arm matching
    points as they are created. Grammar (docs/perf_flags.md)::

        spec      := entry (';' entry)*
        entry     := point '=' kind (':' param)*
        param     := float | 'times=' int | 'p=' float
                     | 'where_' key '=' value

    A bare float param is the behavior's ``seconds`` (delay/wedge).
    Malformed entries are skipped with a warning — a chaos flag must
    never take the process down."""
    global _env_parsed
    if _env_parsed:
        _arm_env_pending_locked()
        return
    _env_parsed = True
    raw = os.environ.get("ZOO_TPU_FAULTS", "")
    _ENV_SPECS.clear()
    if not raw:
        return
    from analytics_zoo_tpu.common.nncontext import logger
    for entry in raw.split(";"):
        entry = entry.strip()
        if not entry:
            continue
        try:
            name, rhs = entry.split("=", 1)
            parts = rhs.split(":")
            kind = parts[0].strip()
            kw: dict = {"seconds": 0.0, "times": None, "p": 1.0,
                        "where": {}}
            for param in parts[1:]:
                if param.startswith("times="):
                    kw["times"] = int(param[6:])
                elif param.startswith("p="):
                    kw["p"] = float(param[2:])
                elif param.startswith("where_"):
                    k, v = param[6:].split("=", 1)
                    kw["where"][k] = v
                else:
                    kw["seconds"] = float(param)
            _ENV_SPECS[name.strip()] = (kind, kw)
        except (ValueError, IndexError) as e:
            logger.warning(
                "ZOO_TPU_FAULTS: skipping malformed entry %r (%s)",
                entry, e)
    _arm_env_pending_locked()


_ENV_SPECS: "Dict[str, tuple]" = {}


def _arm_env_pending_locked():
    for name in list(_ENV_SPECS):
        fp = _points.get(name)
        if fp is None or fp._spec is not None:
            continue
        kind, kw = _ENV_SPECS.pop(name)
        try:
            fp._spec = _Spec(kind, seconds=kw["seconds"],
                             times=kw["times"], p=kw["p"],
                             where=kw["where"] or None)
        except ValueError:
            from analytics_zoo_tpu.common.nncontext import logger
            logger.warning(
                "ZOO_TPU_FAULTS: unknown kind %r for point %s",
                kind, name)


def reset_faults():
    """Disarm everything and forget the parsed env (test isolation —
    lets a test monkeypatch ``ZOO_TPU_FAULTS`` and re-trigger the
    parse)."""
    global _env_parsed
    disarm_all()
    with _lock:
        _env_parsed = False
        _ENV_SPECS.clear()
