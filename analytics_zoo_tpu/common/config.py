"""Typed configuration system.

The reference layers its config across a Spark conf resource file, env vars,
system properties, and per-example scopt CLIs (SURVEY.md §5 "Config / flag
system"; reference `Z/common/NNContext.scala:185-197`). Here the whole thing
collapses into one typed dataclass tree + env-var overlay, which is the
TPU-idiomatic equivalent: a single source of truth handed to `init_nncontext`.
"""

from __future__ import annotations

import dataclasses
import os
import platform
import sys
from dataclasses import dataclass, field
from typing import Any, Mapping, Sequence


_ENV_PREFIX = "ZOO_TPU_"


@dataclass(frozen=True)
class ZooBuildInfo:
    """Build/version info (analog of `ZooBuildInfo`,
    NNContext.scala:78-118)."""

    version: str
    python_version: str = field(
        default_factory=lambda: sys.version.split()[0])
    platform: str = field(default_factory=platform.platform)
    jax_version: str = ""

    def report(self) -> str:
        lines = [f"analytics_zoo_tpu version: {self.version}"]
        lines.append(f"python: {self.python_version}")
        lines.append(f"jax: {self.jax_version}")
        lines.append(f"platform: {self.platform}")
        return "\n".join(lines)


@dataclass
class MeshConf:
    """Device-mesh specification.

    ``axes`` maps axis name -> size; a size of -1 means "all remaining
    devices". Axis names follow the scaling-book convention:

    - ``data``  : pure data parallelism (batch sharded, params replicated)
    - ``fsdp``  : data parallel + ZeRO-sharded params/optimizer state
    - ``model`` : tensor parallelism (weight matrices sharded)
    - ``seq``   : sequence/context parallelism (ring attention)
    """

    axes: "dict[str, int]" = field(default_factory=lambda: {"data": -1})
    devices: Any = None  # explicit device list; None = jax.devices()
    allow_partial: bool = False  # allow leaving devices unused

    def resolved_axes(self, n_devices: int) -> "dict[str, int]":
        axes = dict(self.axes)
        fixed = 1
        wildcard = None
        for name, size in axes.items():
            if size == -1:
                if wildcard is not None:
                    raise ValueError(
                        "at most one mesh axis may have size -1, got "
                        f"{self.axes}")
                wildcard = name
            else:
                fixed *= size
        if wildcard is not None:
            if n_devices % fixed != 0:
                raise ValueError(
                    f"cannot fit wildcard axis: {n_devices} devices not "
                    f"divisible by fixed axes product {fixed}")
            axes[wildcard] = n_devices // fixed
        else:
            total = fixed
            if total > n_devices:
                raise ValueError(
                    f"mesh axes {axes} need {total} devices but only "
                    f"{n_devices} are available")
            if total < n_devices and not self.allow_partial:
                raise ValueError(
                    f"mesh axes {axes} use {total} devices but "
                    f"{n_devices} are available; set allow_partial=True to "
                    "leave devices unused")
        return axes


@dataclass
class ZooTpuConf:
    """Top-level configuration for :func:`init_nncontext`.

    Analog of the SparkConf + `spark-analytics-zoo.conf` overlay
    (reference `Z/common/NNContext.scala:132-207`): perf-relevant defaults
    live here rather than scattered through user code.
    """

    app_name: str = "analytics-zoo-tpu"
    mesh: MeshConf = field(default_factory=MeshConf)
    seed: int = 0
    # matmul/conv compute dtype. bf16 keeps the MXU fed; params stay f32.
    compute_dtype: str = "bfloat16"
    param_dtype: str = "float32"
    # batch_size must divide evenly over the data axes (the reference enforces
    # batch_size % total_cores == 0, `P/pipeline/api/net.py:741-749`).
    check_batch_divisibility: bool = True
    log_level: str = "INFO"
    version_check: bool = False
    # host data-ingest workers (FeatureSet prefetch threads)
    ingest_threads: int = 4
    # default checkpoint root
    checkpoint_dir: str = ""
    extra: "dict[str, Any]" = field(default_factory=dict)

    @staticmethod
    def from_env(base: "ZooTpuConf | None" = None) -> "ZooTpuConf":
        """Overlay ``ZOO_TPU_*`` env vars onto ``base`` (env wins).

        e.g. ``ZOO_TPU_SEED=7``, ``ZOO_TPU_COMPUTE_DTYPE=float32``.
        """
        if base is not None:
            # deep-ish copy: replace mutable sub-configs so later in-place
            # edits never write through to the caller's objects
            conf = dataclasses.replace(
                base,
                mesh=dataclasses.replace(base.mesh),
                extra=dict(base.extra))
        else:
            conf = ZooTpuConf()
        for f in dataclasses.fields(conf):
            key = _ENV_PREFIX + f.name.upper()
            if key not in os.environ:
                continue
            raw = os.environ[key]
            if f.type in ("int", int):
                setattr(conf, f.name, int(raw))
            elif f.type in ("bool", bool):
                setattr(conf, f.name, raw.lower() in ("1", "true", "yes"))
            elif f.type in ("str", str):
                setattr(conf, f.name, raw)
        return conf


def parse_axes(spec: "str | Mapping[str, int] | Sequence | None",
               ) -> "dict[str, int]":
    """Parse a mesh-axes spec: ``"data=8"``, ``"data=4,model=2"``,
    ``{"data": 8}``, or ``[("data", 8)]``."""
    if spec is None:
        return {"data": -1}
    if isinstance(spec, str):
        out: dict[str, int] = {}
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            name, _, size = part.partition("=")
            out[name.strip()] = int(size) if size else -1
        return out or {"data": -1}
    if isinstance(spec, Mapping):
        return dict(spec)
    return dict(spec)
