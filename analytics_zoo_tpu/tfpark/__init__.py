"""tfpark: TensorFlow-model integration (reference `P/tfpark/`).

- :class:`KerasModel` — train/serve a compiled `tf.keras` model on the
  TPU mesh (reference `model.py:28`).
- :class:`TFEstimator` / :class:`TFEstimatorSpec` — the
  `model_fn(features, labels, mode)` API (reference `estimator.py:82`).
- :mod:`analytics_zoo_tpu.tfpark.text` — pre-built NLP models
  (IntentEntity, NER, SequenceTagger).

TF imports are lazy: importing `analytics_zoo_tpu.tfpark` is cheap and
the text models have no TF dependency at all.
"""

__all__ = ["KerasModel", "TFEstimator", "TFEstimatorSpec", "text"]


def __getattr__(name):
    import importlib
    if name == "KerasModel":
        return importlib.import_module(
            "analytics_zoo_tpu.tfpark.model").KerasModel
    if name in ("TFEstimator", "TFEstimatorSpec"):
        mod = importlib.import_module(
            "analytics_zoo_tpu.tfpark.estimator")
        return getattr(mod, name)
    if name == "text":
        return importlib.import_module("analytics_zoo_tpu.tfpark.text")
    raise AttributeError(name)
