"""tfpark.TFEstimator: TF-Estimator-style `model_fn` API on the mesh.

Reference: `P/tfpark/estimator.py:29-238` — `model_fn(features, labels,
mode)` returns a `TFEstimatorSpec`; `train/evaluate/predict` run over
`input_fn → TFDataset`. Here the model_fn is traced per mode with a
shared variable store (standing in for TF1 graph variable reuse), the
traced graph is rewritten to explicit weights (`tf_graph`), and the
loss is minimized directly by the pjit Estimator — the reference's
IdentityCriterion trick (`TFTrainingHelper.scala:182-195`: the "loss"
is just the model's last output) maps to an identity loss function.
"""

from __future__ import annotations

from typing import Callable, Optional

import numpy as np

from analytics_zoo_tpu.common.nncontext import logger
from analytics_zoo_tpu.tfpark.tf_graph import to_jax_fn


def _tf():
    import tensorflow as tf
    return tf


class TFEstimatorSpec:
    """(reference `estimator.py:29-56`)"""

    def __init__(self, mode: str, predictions=None, loss=None):
        self.mode = mode
        self.predictions = predictions
        self.loss = loss


class _VariableStore:
    """Creates variables on the first trace, replays them (in creation
    order) on later traces — the TF2 stand-in for TF1 variable reuse."""

    def __init__(self):
        self.variables: list = []
        self._recording = True
        self._cursor = 0

    def creator(self, next_creator, **kwargs):
        if self._recording:
            var = next_creator(**kwargs)
            self.variables.append(var)
            return var
        if not self.variables:
            raise ValueError("model_fn created no variables")
        # tf.function may retrace; each trace re-creates the same
        # sequence, so replay cyclically in creation order
        var = self.variables[self._cursor % len(self.variables)]
        self._cursor += 1
        return var

    def replay(self):
        self._recording = False
        self._cursor = 0


class _TFEstimatorNet:
    """KerasNet-protocol shim: training forward returns the scalar loss
    (inputs = [features..., labels]); inference forward returns
    predictions (inputs = [features...])."""

    def __init__(self, loss_fn, pred_fn, weights, pred_perm,
                 update_spec=None):
        from analytics_zoo_tpu.tfpark.tf_graph import split_float_weights
        self._loss_fn = loss_fn
        self._pred_fn = pred_fn
        self._n = len(weights)
        self._float_idx, self._consts = split_float_weights(weights)
        self._float_values = [np.asarray(weights[i])
                              for i in self._float_idx]
        self._pred_perm = pred_perm
        # BN moving stats etc.: extra train_fn outputs → float index
        from analytics_zoo_tpu.tfpark.tf_graph import build_update_spec
        self._update_spec = build_update_spec(self._float_idx,
                                              update_spec)
        self.name = "tf_estimator_net"
        self.layers: list = []

    def init_params(self, rng=None, input_shape=None,
                    device=None):  # host numpy either way
        return {"weights": [w.copy() for w in self._float_values]}

    def init(self, rng, input_shape=None):
        return self.init_params(rng)

    def _assemble(self, float_ws):
        from analytics_zoo_tpu.tfpark.tf_graph import assemble_weights
        return assemble_weights(float_ws, self._float_idx, self._consts,
                                self._n)

    def apply(self, params, x, *, training=False, rng=None):
        from analytics_zoo_tpu.tfpark.tf_graph import fold_weight_updates
        xs = list(x) if isinstance(x, (list, tuple)) else [x]
        full = self._assemble(params["weights"])
        if training:
            loss, upd_vals = self._loss_fn(*full, *xs, rng=rng)
            if not self._update_spec:
                return loss, {}
            return loss, {"weights": fold_weight_updates(
                self._update_spec, params["weights"], upd_vals)}
        if self._pred_fn is None:
            raise RuntimeError("model_fn returned no predictions")
        wp = [full[i] for i in self._pred_perm]
        return self._pred_fn(*wp, *xs), {}

    def forward(self, params, x, *, training=False, rng=None):
        out, _ = self.apply(params, x, training=training, rng=rng)
        return out

    def regularization_loss(self, params):
        import jax.numpy as jnp
        return jnp.zeros((), jnp.float32)

    def trainable_mask(self, params):
        return {"weights": [True] * len(self._float_values)}


class TFEstimator:
    """(reference `P/tfpark/estimator.py:82`)"""

    def __init__(self, model_fn: Callable, optimizer="adam",
                 model_dir: Optional[str] = None):
        self.model_fn = model_fn
        self.optimizer = optimizer
        self.model_dir = model_dir
        self._store = _VariableStore()
        self._net: Optional[_TFEstimatorNet] = None
        self._estimator = None
        self._feature_spec = None
        self._label_spec = None
        self._eval_fn = None
        self._eval_perm: list = []

    # -- lazy build on first data ------------------------------------------
    def _specs_from_batch(self, features, labels):
        tf = _tf()
        feats = features if isinstance(features, (list, tuple)) \
            else [features]
        fspec = [tf.TensorSpec([None] + list(np.shape(f)[1:]),
                               tf.as_dtype(np.asarray(f).dtype))
                 for f in feats]
        lspec = None
        if labels is not None:
            lspec = tf.TensorSpec([None] + list(np.shape(labels)[1:]),
                                  tf.as_dtype(np.asarray(labels).dtype))
        return fspec, lspec

    def _build(self, features, labels):
        tf = _tf()
        fspec, lspec = self._specs_from_batch(features, labels)
        n_feat = len(fspec)

        def train_trace(*args):
            feats = list(args[:n_feat])
            lab = args[n_feat] if len(args) > n_feat else None
            spec = self.model_fn(
                feats if n_feat > 1 else feats[0], lab, "train")
            if spec.loss is None:
                raise ValueError("model_fn(mode='train') must set loss")
            return spec.loss

        # 1. create variables EAGERLY (tf.function forbids creation
        #    inside a trace): run model_fn once on the sample batch
        feats_e = [tf.constant(np.asarray(f)) for f in (
            features if isinstance(features, (list, tuple))
            else [features])]
        lab_e = None if labels is None else tf.constant(
            np.asarray(labels))
        with tf.variable_creator_scope(self._store.creator):
            self.model_fn(feats_e if n_feat > 1 else feats_e[0],
                          lab_e, "train")
        self._store.replay()

        sig = fspec + ([lspec] if lspec is not None else [])
        with tf.variable_creator_scope(self._store.creator):
            loss_fn, train_vars, update_spec = to_jax_fn(
                train_trace, sig, variables=self._store.variables,
                with_updates=True)

        def pred_trace(*args):
            spec = self.model_fn(
                list(args) if n_feat > 1 else args[0], None, "infer")
            out = spec.predictions
            if out is None:
                raise ValueError(
                    "model_fn(mode='infer') must set predictions")
            return out

        pred_fn, pred_vars = None, []
        with tf.variable_creator_scope(self._store.creator):
            try:
                pred_fn, pred_vars = to_jax_fn(
                    pred_trace, fspec, variables=self._store.variables)
            except ValueError as e:
                if "must set predictions" not in str(e):
                    raise  # real rewrite failure, not a mode limitation
                logger.warning("TFEstimator: no inference graph (%s)", e)
        perm = []
        for v in pred_vars:
            idx = next((i for i, t in enumerate(train_vars) if t is v),
                       None)
            if idx is None:
                raise ValueError(
                    f"inference graph reads variable {v.name} that the "
                    "training graph does not; variables must be "
                    "mode-independent")
            perm.append(idx)

        # eval-mode graph (reference ModeKeys.EVAL): dropout off etc.;
        # falls back to the train graph if model_fn only handles
        # train/infer
        def eval_trace(*args):
            feats = list(args[:n_feat])
            lab = args[n_feat] if len(args) > n_feat else None
            spec = self.model_fn(
                feats if n_feat > 1 else feats[0], lab, "eval")
            if spec.loss is None:
                raise ValueError("model_fn(mode='eval') must set loss")
            return spec.loss

        self._eval_fn, self._eval_perm = None, []
        with tf.variable_creator_scope(self._store.creator):
            try:
                eval_fn, eval_vars = to_jax_fn(
                    eval_trace, sig, variables=self._store.variables)
                self._eval_perm = []
                for v in eval_vars:
                    idx = next((i for i, t in enumerate(train_vars)
                                if t is v), None)
                    if idx is None:
                        raise ValueError(
                            f"eval graph reads variable {v.name} "
                            "unknown to the training graph")
                    self._eval_perm.append(idx)
                self._eval_fn = eval_fn
            except Exception as e:  # noqa: BLE001 — model_fn is user code
                logger.warning(
                    "TFEstimator: no eval-mode graph (%s); evaluate() "
                    "will use the training graph", e)

        self._train_vars = train_vars   # introspection/assign-back
        self._net = _TFEstimatorNet(
            loss_fn, pred_fn, [v.numpy() for v in train_vars], perm,
            update_spec=update_spec)
        from analytics_zoo_tpu.pipeline.estimator import Estimator
        import jax.numpy as jnp
        self._estimator = Estimator(
            self._net, optimizer=self.optimizer,
            loss=lambda y_true, y_pred: jnp.mean(y_pred))
        if self.model_dir:
            self._estimator.set_checkpoint(self.model_dir)

    @staticmethod
    def _first_batch(dataset):
        for xb, yb in dataset.iter_batches(
                getattr(dataset, "batch_size", 32), shuffle=False,
                drop_last=False):
            return xb, yb
        raise ValueError("empty dataset")

    # -- public API (reference estimator.py:120-238) -----------------------
    def train(self, input_fn: Callable, steps: Optional[int] = None,
              batch_size: int = 32, nb_epoch: int = 1):
        dataset = input_fn()
        xb, yb = self._first_batch(dataset)
        if self._net is None:
            self._build(xb, yb)
        # pack labels into the input tuple; the identity loss reads the
        # model's own loss output
        feats = xb if isinstance(xb, (list, tuple)) else [xb]
        packed = _PackedDataset(dataset, with_labels=yb is not None,
                                n_feat=len(feats))
        from analytics_zoo_tpu.pipeline.estimator import MaxIteration
        end = MaxIteration(steps) if steps is not None else None
        bs = getattr(dataset, "batch_size", batch_size)
        return self._estimator.train(packed, None, batch_size=bs,
                                     nb_epoch=nb_epoch, end_trigger=end)

    def evaluate(self, input_fn: Callable, batch_size: int = 32):
        dataset = input_fn()
        xb, yb = self._first_batch(dataset)
        if self._net is None:
            self._build(xb, yb)
        import jax
        loss_sum, count = 0.0, 0
        bs = getattr(dataset, "batch_size", batch_size)
        if self._eval_fn is not None:
            eval_fn, eperm = self._eval_fn, self._eval_perm

            def fwd_fn(p, x):
                full = self._net._assemble(p["weights"])
                return eval_fn(*[full[i] for i in eperm], *x)
        else:
            def fwd_fn(p, x):
                return self._net.forward(p, x, training=True)
        fwd = jax.jit(fwd_fn)
        params = (self._estimator.params or self._net.init_params())
        for xb, yb in dataset.iter_batches(bs, shuffle=False,
                                           drop_last=False):
            feats = list(xb) if isinstance(xb, (list, tuple)) else [xb]
            if yb is not None:
                feats.append(yb)
            n = feats[0].shape[0]
            # weight per-batch mean losses by batch size (tail batches
            # may be smaller; each shape compiles once)
            loss_sum += float(fwd(params, feats)) * n
            count += n
        return {"loss": loss_sum / max(count, 1)}

    def predict(self, input_fn: Callable, batch_size: int = 32):
        dataset = input_fn()
        xb, yb = self._first_batch(dataset)
        if self._net is None:
            self._build(xb, yb)
        bs = getattr(dataset, "batch_size", batch_size)
        # the Estimator's predict path shards over the mesh and handles
        # tail-batch padding
        self._estimator._ensure_initialized()
        return self._estimator.predict(dataset, batch_size=bs)


class _PackedDataset:
    """Wraps a (features, labels) dataset into features+labels-as-x with
    y=None (the training forward computes the loss internally)."""

    def __init__(self, dataset, with_labels: bool, n_feat: int):
        self._ds = dataset
        self._with_labels = with_labels
        self._n_feat = n_feat

    @property
    def num_samples(self):
        return self._ds.num_samples

    def iter_batches(self, batch_size, **kw):
        for xb, yb in self._ds.iter_batches(batch_size, **kw):
            feats = list(xb) if isinstance(xb, (list, tuple)) else [xb]
            if self._with_labels:
                if yb is None:
                    raise ValueError("dataset stopped yielding labels")
                feats.append(yb)
            yield feats, np.zeros((feats[0].shape[0], 1), np.float32)
