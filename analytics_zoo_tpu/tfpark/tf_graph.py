"""TF graph → explicit-weights XLA function.

The TPU-native analog of the reference's `export_tf` freeze + backward
generation (`P/util/tf.py:42-188`): instead of freezing variables into
constants and hand-generating a backward graph, the traced TF graph is
rewritten so every variable read becomes an explicit function INPUT.
The rewritten function is then bridged into JAX with `jax2tf.call_tf`,
where `jax.grad` differentiates straight through it (TF supplies the
local VJP, XLA compiles both directions) — no `<name>_grad` placeholder
protocol, no temp-tensor bookkeeping (`TFNet.scala:316-384`).

Rewrite steps (see `make_explicit_fn`):
1. trace `fn` to a ConcreteFunction;
2. map resource captures → the live `tf.Variable`s by handle identity;
3. in the GraphDef, swap each `ReadVariableOp` for a float Placeholder
   and drop the resource placeholders;
4. strip the control edges TF adds from reads to the output NoOp
   (they would force the now-unfed placeholders to execute);
5. strip update side effects (`AssignVariableOp` etc.) — but capture
   each plain Assign{,Add,Sub}VariableOp's VALUE tensor targeting a
   tracked variable, so callers can request them as extra outputs
   (`to_jax_fn(with_updates=True)`) and fold BatchNorm moving
   averages back after each step, matching the reference's
   all-variables round-trip (`TFTrainingHelper.scala:83-136`);
6. re-wrap with `tf.compat.v1.wrap_function`, feeding reads via
   `input_map`, with signature `(*weights, *inputs)`.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from analytics_zoo_tpu.common.nncontext import logger

_SIDE_EFFECT_OPS = {
    "AssignVariableOp", "AssignAddVariableOp", "AssignSubVariableOp",
    "ResourceApplyGradientDescent", "ResourceApplyAdam",
    "ResourceApplyMomentum",
}

# plain variable assigns whose VALUE can be captured as an extra
# function output and folded back by the caller (optimizer
# ResourceApply* ops are not: training state belongs to the zoo
# optimizer, not the bridged graph)
_ASSIGN_KINDS = {
    "AssignVariableOp": "assign",
    "AssignAddVariableOp": "add",
    "AssignSubVariableOp": "sub",
}


def _tf():
    import tensorflow as tf
    return tf


class _Rewritten:
    """Products of the variable-to-input graph rewrite."""

    def __init__(self, gd, read_map, const_reads, const_feeds,
                 input_names, output_names, used_vars, input_specs,
                 update_map=None):
        self.gd = gd
        self.read_map = read_map          # read tensor -> weight index
        self.const_reads = const_reads    # read tensor -> const value
        self.const_feeds = const_feeds    # capture tensor -> const value
        self.input_names = input_names
        self.output_names = output_names
        self.used_vars = used_vars
        self.input_specs = input_specs
        # captured variable-update side effects: the value tensor fed
        # to a stripped Assign{,Add,Sub}VariableOp targeting a tracked
        # variable — [(value_tensor_name, var_index, kind)] with kind
        # in {"assign", "add", "sub"}
        self.update_map = update_map or []


def _rewrite(fn: Callable, input_signature: Sequence,
             variables: Optional[Sequence] = None) -> _Rewritten:
    tf = _tf()
    cf = tf.function(fn).get_concrete_function(*input_signature)
    graph = cf.graph
    candidates = list(variables) if variables is not None else \
        list(graph.variables)

    # -- 2. resource captures → variables, by handle identity -------------
    ph_to_var: dict = {}      # internal placeholder op name -> var index
    ph_to_const: dict = {}    # internal placeholder op name -> value
    const_feeds: dict = {}    # internal placeholder name -> eager value
    used_vars: List = []
    for ext, internal in graph.captures:
        if ext.dtype == tf.resource:
            var = next((v for v in candidates if ext is v.handle), None)
            if var is None:  # fallback: match by handle id
                var = next(
                    (v for v in candidates
                     if getattr(ext, "_id", None) is not None and
                     getattr(v.handle, "_id", None) == ext._id), None)
            if var is None:
                raise ValueError(
                    f"could not map resource capture {internal.op.name} "
                    "to a variable; pass variables= explicitly")
            # keras-3 Variables report dtype as a string
            if not tf.as_dtype(var.dtype).is_floating:
                # int state (e.g. Keras-3 dropout seed): bake current
                # value as a constant — never a differentiable weight
                ph_to_const[internal.op.name] = var.numpy()
                continue
            if not any(var is u for u in used_vars):
                used_vars.append(var)
            ph_to_var[internal.op.name] = next(
                i for i, u in enumerate(used_vars) if u is var)
        else:
            # eagerly captured constant — bake its current value in
            const_feeds[internal.name] = ext.numpy()

    gd = graph.as_graph_def()

    # -- 2b. lower functional control flow to v1 dataflow form ------------
    # keras LSTM/GRU trace to a functional `While` whose inputs include
    # variable RESOURCES; the explicit-weights rewrite below only
    # understands ReadVariableOp chains. TF's inline/lower pass (the
    # same one freezing uses) turns While into Enter/Merge/Switch/
    # NextIteration/Exit + in-body ReadVariableOps, which the
    # graphdef_jax interpreter then collapses into lax.scan.
    _FUNCTIONAL_CTRL = {"While", "StatelessWhile", "If", "StatelessIf",
                        "Case", "StatelessCase"}
    if any(op.type in _FUNCTIONAL_CTRL
           for op in graph.get_operations()):
        from tensorflow.python.framework import (
            convert_to_constants as _ctc)
        gd = _ctc._run_inline_graph_optimization(
            cf, lower_control_flow=True, aggressive_inlining=True)

    nodes_by_name = {n.name: n for n in gd.node}
    _CHAIN_OPS = ("Identity", "Enter", "RefEnter", "Switch", "RefSwitch",
                  "Merge", "RefMerge", "NextIteration",
                  "RefNextIteration", "Exit", "RefExit")

    def _resolve_src(src: str) -> str:
        """Follow Identity/Enter/... chains back to the originating op
        name (resource values ride these into while frames)."""
        seen = set()
        while src in nodes_by_name and src not in seen:
            seen.add(src)
            node = nodes_by_name[src]
            if node.op not in _CHAIN_OPS or not node.input:
                break
            src = node.input[0].split(":")[0]
        return src

    # -- 3. swap ReadVariableOps for Placeholders; drop resource phs ------
    read_map: dict = {}     # read output tensor name -> weight index
    const_reads: dict = {}  # read output tensor name -> constant value
    update_map: list = []   # (value tensor, var index, assign kind)
    swapped = set()
    new_nodes = []

    def _weight_placeholder(name, dtype_attr, src):
        """Placeholder standing in for a variable value; records how it
        gets fed (weight arg vs baked constant)."""
        if src in ph_to_var:
            vi = ph_to_var[src]
            read_map[name + ":0"] = vi
            var_shape = used_vars[vi].shape
        else:
            const_reads[name + ":0"] = ph_to_const[src]
            var_shape = np.shape(ph_to_const[src])
        ph = tf.compat.v1.NodeDef()
        ph.name = name
        ph.op = "Placeholder"
        ph.attr["dtype"].type = dtype_attr
        ph.attr["shape"].shape.CopyFrom(
            tf.TensorShape(var_shape).as_proto())
        return ph

    # resource-carrying chain nodes (Enter/Identity wrappers riding a
    # variable resource into a while frame) get dropped with the
    # resource placeholders; gd is topologically ordered, so one pass
    # with a growing set suffices
    resource_chain: set = set()

    for node in gd.node:
        src = node.input[0].split(":")[0] if node.input else ""
        if src in resource_chain or src in ph_to_var or \
                src in ph_to_const:
            src = _resolve_src(src)
        if node.op in _CHAIN_OPS and node.input and \
                (node.input[0].split(":")[0] in resource_chain or
                 node.input[0].split(":")[0] in ph_to_var or
                 node.input[0].split(":")[0] in ph_to_const):
            resource_chain.add(node.name)
            swapped.add(node.name)
            continue
        if node.op == "ReadVariableOp" and (src in ph_to_var or
                                            src in ph_to_const):
            swapped.add(node.name)
            new_nodes.append(_weight_placeholder(
                node.name, node.attr["dtype"].type, src))
        elif node.op == "ResourceGather" and (src in ph_to_var or
                                              src in ph_to_const):
            # tf.keras Embedding: gathers FROM the resource directly.
            # Split into params-placeholder + axis const + GatherV2.
            ph_name = node.name + "/params"
            new_nodes.append(_weight_placeholder(
                ph_name, node.attr["dtype"].type, src))
            # TF semantics: ResourceGather gathers along axis=batch_dims
            bd = int(node.attr["batch_dims"].i) \
                if "batch_dims" in node.attr else 0
            axis_name = node.name + "/axis"
            axis_node = tf.compat.v1.NodeDef()
            axis_node.name = axis_name
            axis_node.op = "Const"
            axis_node.attr["dtype"].type = tf.int32.as_datatype_enum
            axis_node.attr["value"].tensor.CopyFrom(
                tf.make_tensor_proto(bd, dtype=tf.int32))
            new_nodes.append(axis_node)
            gather = tf.compat.v1.NodeDef()
            gather.name = node.name
            gather.op = "GatherV2"
            gather.input.extend([ph_name, node.input[1], axis_name])
            gather.attr["Tparams"].type = node.attr["dtype"].type
            gather.attr["Tindices"].CopyFrom(node.attr["Tindices"])
            gather.attr["Taxis"].type = tf.int32.as_datatype_enum
            if "batch_dims" in node.attr:
                gather.attr["batch_dims"].CopyFrom(
                    node.attr["batch_dims"])
            new_nodes.append(gather)
        elif node.op == "Placeholder" and (node.name in ph_to_var or
                                           node.name in ph_to_const):
            continue
        elif node.op in _SIDE_EFFECT_OPS:
            # the op itself is stripped (no resources at run time), but
            # a plain Assign* targeting a TRACKED variable is a state
            # update the caller can fold back (BatchNorm moving stats,
            # reference TFTrainingHelper.scala:83-136 round-trips ALL
            # variables): capture its value tensor as an extra output
            kind = _ASSIGN_KINDS.get(node.op)
            if kind is not None and node.input:
                res = _resolve_src(node.input[0].split(":")[0])
                if res in ph_to_var:
                    val = node.input[1]
                    if ":" not in val:
                        val = val + ":0"
                    update_map.append((val, ph_to_var[res], kind))
            swapped.add(node.name)  # strip, and strip control refs to it
            continue
        else:
            new_nodes.append(node)

    # any remaining consumer of a dropped resource placeholder is an
    # op the rewrite does not understand — fail with the op names
    # rather than a KeyError deep in the interpreter
    dropped = set(ph_to_var) | set(ph_to_const) | resource_chain
    leftovers = sorted({n.op for n in new_nodes
                        if any(x.split(":")[0] in dropped
                               for x in n.input
                               if not x.startswith("^"))})
    if leftovers:
        raise NotImplementedError(
            f"ops {leftovers} consume tf.Variable resources directly; "
            "the explicit-weights rewrite only handles ReadVariableOp "
            "and ResourceGather")

    # -- 4./5. strip control edges to swapped/stripped/dropped nodes ------
    gone = swapped | dropped
    for node in new_nodes:
        if any(i.startswith("^") for i in node.input):
            kept = [i for i in node.input
                    if not (i.startswith("^") and i[1:] in gone)]
            del node.input[:]
            node.input.extend(kept)

    gd2 = tf.compat.v1.GraphDef()
    gd2.versions.CopyFrom(gd.versions)
    gd2.library.CopyFrom(gd.library)
    gd2.node.extend(new_nodes)

    captured = set(ph_to_var) | set(ph_to_const) | {
        name.split(":")[0] for name in const_feeds}
    input_names = [t.name for t in graph.inputs
                   if t.op.name not in captured]
    output_names = [t.name for t in graph.outputs]
    input_specs = [(tuple(t.shape), t.dtype) for t in graph.inputs
                   if t.op.name not in captured]
    return _Rewritten(gd2, read_map, const_reads, const_feeds,
                      input_names, output_names, used_vars, input_specs,
                      update_map=update_map)


def make_explicit_fn(fn: Callable, input_signature: Sequence,
                     variables: Optional[Sequence] = None,
                     _rewritten: Optional[_Rewritten] = None,
                     ) -> Tuple[Callable, List]:
    """Rewrite ``fn`` (TF ops; may read `tf.Variable`s) into a pure TF
    function ``g(*weights, *inputs)`` suitable for `jax2tf.call_tf`.

    Returns ``(g, variables)`` — `variables` in the same order as the
    ``weights`` arguments, so callers can seed training from
    ``[v.numpy() for v in variables]`` and assign trained weights back
    (the reference's weights→session contract, `net.py:703-714`).
    """
    tf = _tf()
    rw = _rewritten or _rewrite(fn, input_signature, variables)
    n_w = len(rw.used_vars)

    def import_fn(*args):
        ws, xs = args[:n_w], args[n_w:]
        input_map = {}
        for name, x in zip(rw.input_names, xs):
            input_map[name] = x
        for read_out, vi in rw.read_map.items():
            input_map[read_out] = ws[vi]
        for read_out, value in rw.const_reads.items():
            input_map[read_out] = tf.constant(value)
        for name, value in rw.const_feeds.items():
            input_map[name] = tf.constant(value)
        results = tf.graph_util.import_graph_def(
            rw.gd, input_map=input_map, return_elements=rw.output_names)
        return results if len(results) > 1 else results[0]

    specs = [tf.TensorSpec(v.shape, v.dtype) for v in rw.used_vars]
    specs += [tf.TensorSpec(s, d) for s, d in rw.input_specs]
    wrapped = tf.compat.v1.wrap_function(import_fn, specs)
    return wrapped, rw.used_vars


def to_jax_fn(fn: Callable, input_signature: Sequence,
              variables: Optional[Sequence] = None,
              prefer_native: bool = True,
              with_updates: bool = False,
              max_trip_count: Optional[int] = None):
    """TF function → JAX function ``(jax_fn(*weights, *inputs), vars)``.

    Preferred path: the GraphDef→jnp interpreter (`graphdef_jax`) — the
    graph traces into ONE native XLA program, runs on TPU, and
    differentiates with `jax.grad` directly. Fallback (unsupported ops,
    e.g. `While` from keras LSTM): `jax2tf.call_tf`, which requires TF
    kernels for the backend (CPU-only in this image).

    ``with_updates=True`` returns ``(jax_fn, vars, update_spec)``:
    the stripped variable-update side effects (BatchNorm moving
    averages — Assign{,Add,Sub}VariableOp on tracked variables) become
    extra outputs, ``jax_fn`` returns ``(outputs, update_values)`` and
    ``update_spec`` is ``[(var_index, kind)]`` aligned with
    ``update_values`` (kind in {"assign", "add", "sub"}; "add"/"sub"
    values are deltas to apply to the variable). On the call_tf
    fallback the spec is empty — updates stay a documented limitation
    there.
    """
    rw = _rewrite(fn, input_signature, variables)
    upd_tensors = [t for t, _, _ in rw.update_map]
    upd_spec = [(vi, kind) for _, vi, kind in rw.update_map]
    if prefer_native:
        from analytics_zoo_tpu.tfpark.graphdef_jax import \
            GraphDefFunction
        read_names = list(rw.read_map.keys())
        read_idx = [rw.read_map[n] for n in read_names]
        feeds = dict(rw.const_reads)
        feeds.update(rw.const_feeds)
        gfn = GraphDefFunction(
            rw.gd, read_names + rw.input_names, list(rw.output_names),
            const_feeds=feeds, max_trip_count=max_trip_count)
        missing = gfn.unsupported_ops()
        if not missing and with_updates and upd_tensors:
            # updates ride along only if THEIR subgraph also
            # interprets — never degrade the main function to the
            # call_tf fallback because of an assign-value op
            gfn_full = GraphDefFunction(
                rw.gd, read_names + rw.input_names,
                list(rw.output_names) + upd_tensors, const_feeds=feeds,
                max_trip_count=max_trip_count)
            if gfn_full.unsupported_ops():
                logger.warning(
                    "to_jax_fn: ops %s in the variable-update subgraph "
                    "are not interpreted; dropping %d updates (moving "
                    "statistics will not update)",
                    gfn_full.unsupported_ops(), len(upd_tensors))
                upd_tensors, upd_spec = [], []
            else:
                gfn = gfn_full
        if not missing:
            n_w = len(rw.used_vars)
            n_out = len(rw.output_names)

            def jax_fn(*args, rng=None):
                ws, xs = args[:n_w], args[n_w:]
                res = gfn(*[ws[vi] for vi in read_idx], *xs, rng=rng)
                if not with_updates:
                    return res
                res = res if isinstance(res, (list, tuple)) else [res]
                main = res[:n_out]
                main = main[0] if n_out == 1 else tuple(main)
                return main, list(res[n_out:])

            if with_updates:
                return jax_fn, rw.used_vars, upd_spec
            return jax_fn, rw.used_vars
        logger.warning(
            "graphdef_jax: ops %s not interpreted; falling back to "
            "jax2tf.call_tf (CPU-only TF kernels)", missing)
    from jax.experimental import jax2tf
    wrapped, used_vars = make_explicit_fn(fn, input_signature, variables,
                                          _rewritten=rw)
    ctf = jax2tf.call_tf(wrapped)

    def jax_fn(*args, rng=None):
        del rng  # call_tf path: graph randomness stays baked
        out = ctf(*args)
        return (out, []) if with_updates else out

    if with_updates:
        if rw.update_map:
            logger.warning(
                "to_jax_fn: %d variable updates dropped on the "
                "call_tf fallback path (moving statistics will not "
                "update)", len(rw.update_map))
        return jax_fn, used_vars, []
    return jax_fn, used_vars


def split_float_weights(values: Sequence[np.ndarray]):
    """Split a weight list into differentiable float leaves and integer
    constants (e.g. Keras-3 dropout seed states): returns
    ``(float_indices, {index: const_value})``. `jax.grad` rejects int
    inputs, and int variables are never trainable anyway."""
    float_idx, consts = [], {}
    for i, w in enumerate(values):
        if np.issubdtype(np.asarray(w).dtype, np.floating):
            float_idx.append(i)
        else:
            consts[i] = np.asarray(w)
    return float_idx, consts


def assemble_weights(float_ws: Sequence, float_idx: Sequence[int],
                     consts: dict, total: int) -> list:
    """Inverse of `split_float_weights`: rebuild the full ordered
    weight-argument list."""
    full: list = [None] * total
    for i, w in zip(float_idx, float_ws):
        full[i] = w
    for i, c in consts.items():
        full[i] = c
    return full


def build_update_spec(float_idx, update_spec):
    """Captured-update targets → FLOAT weight-list positions, aligned
    slot-for-slot with the extra train_fn outputs. A target that is a
    tracked NON-float variable (e.g. an int step counter assigned in
    the traced graph) maps to None — it is baked as a constant, so
    there is nothing to fold back; the slot stays so alignment with
    ``upd_vals`` is preserved. One warning reports the dropped targets'
    variable indices (single copy shared by tfpark KerasModel and
    TFEstimator)."""
    spec = [(float_idx.index(vi) if vi in float_idx else None, kind)
            for vi, kind in (update_spec or [])]
    dropped = [vi for vi, _ in (update_spec or [])
               if vi not in float_idx]
    if dropped:
        logger.warning(
            "tfpark: %d captured variable update(s) target non-float "
            "variables baked as constants (indices %s); those "
            "variables will NOT advance during training",
            len(dropped), dropped)
    return spec


def fold_weight_updates(spec, weights, upd_vals):
    """Captured Assign{,Add,Sub} values → a sparse float-weight-list
    update (None = unchanged), stop-gradded, with sequential assigns
    to one variable composing in graph order. ``spec``:
    ``[(float_index, kind)]`` aligned with ``upd_vals`` (the single
    copy of the fold used by tfpark KerasModel and TFEstimator)."""
    import jax
    new_ws: list = [None] * len(weights)
    for (fi, kind), val in zip(spec, upd_vals):
        if fi is None:       # non-float target: baked const, no fold
            continue
        cur = new_ws[fi] if new_ws[fi] is not None else weights[fi]
        val = jax.lax.stop_gradient(val).astype(cur.dtype)
        if kind == "add":
            val = cur + val
        elif kind == "sub":
            val = cur - val
        new_ws[fi] = val
    return new_ws


def keras_optimizer_to_zoo(optimizer):
    """tf.keras optimizer → zoo optimizer (reference analog:
    `to_bigdl_optim_method`, `net.py:592-688`)."""
    from analytics_zoo_tpu.ops import optimizers as zoo_opt
    if optimizer is None:
        return zoo_opt.Adam()
    if isinstance(optimizer, str):
        return optimizer  # let ops.optimizers.get resolve it
    name = type(optimizer).__name__.lower()
    lr = optimizer.learning_rate
    try:
        lr = float(lr.numpy() if hasattr(lr, "numpy") else lr)
    except (TypeError, ValueError):
        # LearningRateSchedule object: a TF-graph schedule can't run
        # inside the XLA step; freeze at its step-0 value
        lr0 = float(np.asarray(lr(0)))
        logger.warning(
            "keras optimizer uses a LearningRateSchedule (%s); using "
            "its step-0 value %g — pass a zoo optimizer with an optax "
            "schedule for a decaying lr", type(lr).__name__, lr0)
        lr = lr0
    if name == "sgd":
        momentum = float(getattr(optimizer, "momentum", 0.0) or 0.0)
        return zoo_opt.SGD(lr=lr, momentum=momentum)
    if name == "adam":
        return zoo_opt.Adam(lr=lr,
                            beta_1=float(optimizer.beta_1),
                            beta_2=float(optimizer.beta_2))
    if name in ("rmsprop",):
        return zoo_opt.RMSprop(lr=lr) if hasattr(zoo_opt, "RMSprop") \
            else zoo_opt.Adam(lr=lr)
    if name in ("adagrad",):
        return zoo_opt.Adagrad(lr=lr) if hasattr(zoo_opt, "Adagrad") \
            else zoo_opt.Adam(lr=lr)
    if name in ("adadelta",):
        return zoo_opt.Adadelta(lr=lr) if hasattr(zoo_opt, "Adadelta") \
            else zoo_opt.Adam(lr=lr)
    return zoo_opt.Adam(lr=lr)


def keras_loss_to_zoo(loss):
    """tf.keras loss (instance or name) → zoo loss name/callable."""
    if loss is None:
        return "mse"
    if isinstance(loss, str):
        return loss
    name = type(loss).__name__
    table = {
        "MeanSquaredError": "mse",
        "MeanAbsoluteError": "mae",
        "BinaryCrossentropy": "binary_crossentropy",
        "CategoricalCrossentropy": "categorical_crossentropy",
        "SparseCategoricalCrossentropy":
            "sparse_categorical_crossentropy",
        "Hinge": "hinge",
        "SquaredHinge": "squared_hinge",
        "KLDivergence": "kld",
        "Poisson": "poisson",
        "CosineSimilarity": "cosine_proximity",
    }
    if name in table:
        return table[name]
    fn_name = getattr(loss, "__name__", None)
    if fn_name:
        return fn_name
    raise ValueError(f"cannot map tf.keras loss {loss!r}")
