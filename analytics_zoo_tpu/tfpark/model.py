"""tfpark.KerasModel: train/serve a `tf.keras` model on the TPU mesh.

Reference: `P/tfpark/model.py:28-366` — wraps a compiled tf.keras model
so `fit/evaluate/predict` run distributed (there: TFOptimizer on Spark;
here: the graph is rewritten to explicit weights via
`tfpark.tf_graph.make_explicit_fn`, bridged with `jax2tf.call_tf`, and
trained by the framework's pjit Estimator). After `fit`, trained
weights are assigned back into the live tf.keras model — preserving the
reference's weights→session contract (`net.py:703-714`), so
`model.save(...)`/`get_weights()` see the trained values.

BatchNorm moving averages DO update through the bridge (round 3): the
stripped `AssignSubVariableOp` values come back as extra outputs of
the training function and are folded into the tracked variables after
each step (`Estimator._merge_updates`), matching the reference's
all-variables round-trip (`TFTrainingHelper.scala:83-136`). The one
remaining gap is the `call_tf` fallback path (unsupported ops), where
updates are dropped with a warning.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from analytics_zoo_tpu.common.nncontext import logger
from analytics_zoo_tpu.tfpark.tf_graph import (
    keras_loss_to_zoo,
    keras_optimizer_to_zoo,
    to_jax_fn,
)


def _tf():
    import tensorflow as tf
    return tf


class _TFKerasNet:
    """KerasNet-protocol shim over (train_fn, infer_fn) explicit-weights
    JAX functions sharing one weight order. Non-float variables (e.g.
    Keras-3 dropout seed state) are baked as constants — `jax.grad`
    rejects int inputs and they are never trainable."""

    def __init__(self, train_fn, infer_fn, weight_values: List,
                 trainable_flags: List[bool], infer_perm: List[int],
                 update_spec: Optional[List] = None):
        from analytics_zoo_tpu.tfpark.tf_graph import split_float_weights
        self._train_fn = train_fn
        self._infer_fn = infer_fn
        self._n = len(weight_values)
        self._float_idx, self._consts = split_float_weights(weight_values)
        self._float_values = [np.asarray(weight_values[i])
                              for i in self._float_idx]
        self._trainable = [bool(trainable_flags[i])
                           for i in self._float_idx]
        self._infer_perm = infer_perm
        # variable updates (BN moving stats): see build_update_spec
        from analytics_zoo_tpu.tfpark.tf_graph import build_update_spec
        self._update_spec = build_update_spec(self._float_idx,
                                              update_spec)
        self.name = "tf_keras_net"
        self.layers: list = []

    def init_params(self, rng=None, input_shape=None,
                    device=None):  # host numpy either way
        return {"weights": [w.copy() for w in self._float_values]}

    def init(self, rng, input_shape=None):
        return self.init_params(rng)

    def _assemble(self, float_ws):
        from analytics_zoo_tpu.tfpark.tf_graph import assemble_weights
        return assemble_weights(float_ws, self._float_idx, self._consts,
                                self._n)

    def apply(self, params, x, *, training=False, rng=None):
        from analytics_zoo_tpu.tfpark.tf_graph import fold_weight_updates
        xs = list(x) if isinstance(x, (list, tuple)) else [x]
        full = self._assemble(params["weights"])
        if training:
            out, upd_vals = self._train_fn(*full, *xs, rng=rng)
            if not self._update_spec:
                return out, {}
            return out, {"weights": fold_weight_updates(
                self._update_spec, params["weights"], upd_vals)}
        wi = [full[i] for i in self._infer_perm]
        return self._infer_fn(*wi, *xs), {}

    def forward(self, params, x, *, training=False, rng=None):
        out, _ = self.apply(params, x, training=training, rng=rng)
        return out

    def regularization_loss(self, params):
        import jax.numpy as jnp
        return jnp.zeros((), jnp.float32)

    def trainable_mask(self, params):
        return {"weights": list(self._trainable)}


class KerasModel:
    """(reference `P/tfpark/model.py:28`)"""

    def __init__(self, model, optimizer=None, loss=None, metrics=None):
        tf = _tf()
        self.model = model
        if not model.inputs:
            raise ValueError(
                "the tf.keras model must be built (call it once or use "
                "Input layers) before wrapping in KerasModel")
        sig = [tf.TensorSpec([None] + list(t.shape[1:]), t.dtype)
               for t in model.inputs]
        n_in = len(sig)

        def call_train(*xs):
            return model(xs if n_in > 1 else xs[0], training=True)

        def call_infer(*xs):
            return model(xs if n_in > 1 else xs[0], training=False)

        train_fn, train_vars, update_spec = to_jax_fn(
            call_train, sig, variables=model.variables,
            with_updates=True)
        infer_fn, infer_vars = to_jax_fn(call_infer, sig,
                                         variables=model.variables)
        # second trace may order/use variables differently; permute
        perm = []
        for v in infer_vars:
            idx = next((i for i, t in enumerate(train_vars) if t is v),
                       None)
            if idx is None:
                raise ValueError(
                    f"inference graph reads variable {v.name} that the "
                    "training graph does not")
            perm.append(idx)
        trainable_ids = {id(v) for v in model.trainable_variables}
        self._vars = train_vars
        self.net = _TFKerasNet(
            train_fn, infer_fn,
            [v.numpy() for v in train_vars],
            [id(v) in trainable_ids for v in train_vars],
            perm, update_spec=update_spec)

        opt = optimizer if optimizer is not None else \
            keras_optimizer_to_zoo(getattr(model, "optimizer", None))
        lss = loss if loss is not None else \
            keras_loss_to_zoo(getattr(model, "loss", None))
        mets = metrics if metrics is not None else \
            self._metric_names(model)
        from analytics_zoo_tpu.pipeline.estimator import Estimator
        self.estimator = Estimator(self.net, optimizer=opt, loss=lss,
                                   metrics=mets)

    @staticmethod
    def _metric_names(model) -> List[str]:
        names = []
        for m in getattr(model, "metrics", []) or []:
            n = getattr(m, "name", None)
            if n in ("accuracy", "acc", "sparse_categorical_accuracy",
                     "categorical_accuracy"):
                names.append("accuracy")
            elif n in ("mae", "mean_absolute_error"):
                names.append("mae")
        return names

    # -- training surface (reference model.py:120-366) ---------------------
    def fit(self, x, y=None, batch_size: int = 32, epochs: int = 1,
            validation_data=None, distributed: bool = True, **kwargs):
        del distributed  # always mesh-parallel
        data, labels = self._unpack(x, y)
        if (isinstance(validation_data, (tuple, list))
                and len(validation_data) == 2):
            # validation features/labels follow the same named-IO
            # unpacking as the training data
            validation_data = tuple(
                self._unpack(*validation_data))
        result = self.estimator.train(
            data, labels, batch_size=batch_size, nb_epoch=epochs,
            validation_data=validation_data, **kwargs)
        self._assign_back()
        return result

    def evaluate(self, x, y=None, batch_size: int = 32,
                 distributed: bool = True):
        del distributed
        data, labels = self._unpack(x, y)
        return self.estimator.evaluate(data, labels,
                                       batch_size=batch_size)

    def predict(self, x, batch_size: int = 32,
                distributed: bool = True) -> np.ndarray:
        del distributed
        data, _ = self._unpack(x, None)
        return self.estimator.predict(data, batch_size=batch_size)

    def _unpack(self, x, y):
        from analytics_zoo_tpu.pipeline.api.net import TFDataset
        if isinstance(x, TFDataset):
            return x.feature_set, None
        if isinstance(x, dict):
            # dict features keyed by input-layer name (the tf.keras
            # named-input contract / the reference's nested
            # TensorMeta): reorder to the model's positional inputs
            names = [t.name.split(":")[0] for t in self.model.inputs]
            missing = [n for n in names if n not in x]
            if missing:
                raise KeyError(
                    f"dict features missing model input(s) {missing}; "
                    f"have {sorted(x)}")
            x = [x[n] for n in names]
        if isinstance(y, dict):
            # dict labels keyed by output name, reordered to the
            # model's positional outputs (multi-output training)
            out_names = list(getattr(self.model, "output_names", []))
            missing = [n for n in out_names if n not in y]
            if not out_names or missing:
                raise KeyError(
                    f"dict labels must name every model output "
                    f"{out_names or '?'}; have {sorted(y)}")
            y = [y[n] for n in out_names]
        return x, y

    def _assign_back(self):
        """Write trained weights into the live tf.keras variables."""
        import jax
        trained = jax.device_get(self.estimator.params)["weights"]
        for fi, w in zip(self.net._float_idx, trained):
            self._vars[fi].assign(np.asarray(w))
        logger.info("KerasModel: %d trained weights assigned back into "
                    "the tf.keras model", len(trained))

    def save_weights(self, path: str):
        self.model.save_weights(path)

    def load_weights(self, path: str):
        self.model.load_weights(path)
        self.net._float_values = [
            self._vars[i].numpy() for i in self.net._float_idx]
        # re-seed estimator params if already initialized: place on the
        # mesh and drop stale optimizer state (Adam moments belong to
        # the OLD weights)
        est = self.estimator
        if est.params is not None:
            est.params = est._place_params(self.net.init_params())
            est.opt_state = None
            est._train_step = None
