"""Native NLP model builds for tfpark.text.

Reference: `P/tfpark/text/keras/text_model.py` (`TextKerasModel` wraps
an nlp-architect keras model) and its subclasses `IntentEntity`
(`intent_entity.py`), `NER` (`ner.py`), `SequenceTagger`
(`sequence_tagger.py`). Architectures are rebuilt from the zoo's own
layer library; the reference's CRF output layer is replaced with a
per-token softmax head (XLA-friendly: no Viterbi recursion in the
train step).
"""

from __future__ import annotations

from typing import List, Optional

import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu.pipeline.api.keras import layers as L
from analytics_zoo_tpu.pipeline.api.keras.engine import Input
from analytics_zoo_tpu.pipeline.api.keras.models import Model, Sequential


def _sparse_ce(labels, logits):
    logp = jnp.log(jnp.maximum(logits, 1e-8))
    lab = labels.astype(jnp.int32)
    if lab.ndim == logp.ndim:  # (..., 1) trailing dim
        lab = lab[..., 0]
    onehot = jnp.take(jnp.eye(logp.shape[-1], dtype=logp.dtype), lab,
                      axis=0)
    return -jnp.mean(jnp.sum(onehot * logp, axis=-1))


class TextKerasModel:
    """Base wrapper: a zoo model + training glue (reference
    `TextKerasModel`, `P/tfpark/text/keras/text_model.py`)."""

    def __init__(self, model, optimizer="adam", loss=None,
                 metrics: Optional[List[str]] = None):
        self.model = model
        self.labor = model  # reference field name for the inner model
        model.compile(optimizer=optimizer,
                      loss=loss or "sparse_categorical_crossentropy",
                      metrics=metrics)

    def fit(self, x, y, batch_size: int = 32, nb_epoch: int = 1, **kw):
        return self.model.fit(x, y, batch_size=batch_size,
                              nb_epoch=nb_epoch, **kw)

    def evaluate(self, x, y, batch_size: int = 32):
        return self.model.evaluate(x, y, batch_size=batch_size)

    def predict(self, x, batch_size: int = 32):
        return self.model.predict(x, batch_size=batch_size)

    def save_model(self, path: str):
        self.model.save_weights(path)

    def load_weights(self, path: str):
        self.model.load_weights(path)


class NER(TextKerasModel):
    """Named-entity recognition: embedding → BiLSTM → per-token softmax
    (reference `P/tfpark/text/keras/ner.py`; CRF head → softmax)."""

    def __init__(self, num_entities: int, word_vocab_size: int,
                 word_length: int = 12, seq_len: int = 100,
                 embed_dim: int = 100, lstm_dim: int = 100,
                 dropout: float = 0.2, optimizer="adam"):
        del word_length  # reference char-CNN branch: not rebuilt
        self.seq_len = seq_len
        net = Sequential(name="ner")
        net.add(L.Embedding(word_vocab_size, embed_dim,
                            input_shape=(seq_len,)))
        net.add(L.Bidirectional(
            L.LSTM(lstm_dim, return_sequences=True)))
        net.add(L.Dropout(dropout))
        net.add(L.TimeDistributed(L.Dense(num_entities,
                                          activation="softmax")))
        super().__init__(net, optimizer=optimizer, loss=_sparse_ce)

    def predict_classes(self, x, batch_size: int = 32) -> np.ndarray:
        probs = self.predict(x, batch_size=batch_size)
        return np.argmax(probs, axis=-1)


class SequenceTagger(TextKerasModel):
    """POS/chunking tagger (reference
    `P/tfpark/text/keras/sequence_tagger.py`)."""

    def __init__(self, num_pos_labels: int, word_vocab_size: int,
                 seq_len: int = 100, embed_dim: int = 100,
                 lstm_dim: int = 64, num_lstm_layers: int = 2,
                 dropout: float = 0.2, optimizer="adam"):
        self.seq_len = seq_len
        net = Sequential(name="sequence_tagger")
        net.add(L.Embedding(word_vocab_size, embed_dim,
                            input_shape=(seq_len,)))
        for _ in range(num_lstm_layers):
            net.add(L.Bidirectional(
                L.LSTM(lstm_dim, return_sequences=True)))
        net.add(L.Dropout(dropout))
        net.add(L.TimeDistributed(L.Dense(num_pos_labels,
                                          activation="softmax")))
        super().__init__(net, optimizer=optimizer, loss=_sparse_ce)


class IntentEntity(TextKerasModel):
    """Joint intent classification + slot filling (reference
    `P/tfpark/text/keras/intent_entity.py`).

    Two heads over a shared BiLSTM encoder:
    - intent: final-state dense softmax over `num_intents`;
    - entities: per-token dense softmax over `num_entities`.
    Labels for `fit` are packed as ``[intent_id, tag_1..tag_T]``
    (shape ``(B, 1+seq_len)``).
    """

    def __init__(self, num_intents: int, num_entities: int,
                 word_vocab_size: int, word_length: int = 12,
                 seq_len: int = 100, embed_dim: int = 100,
                 lstm_dim: int = 100, dropout: float = 0.2,
                 optimizer="adam"):
        del word_length
        self.seq_len = seq_len
        inp = Input(shape=(seq_len,), name="tokens")
        emb = L.Embedding(word_vocab_size, embed_dim)(inp)
        enc = L.Bidirectional(L.LSTM(lstm_dim,
                                     return_sequences=True))(emb)
        enc = L.Dropout(dropout)(enc)
        last = L.Select(1, -1)(enc)
        intent = L.Dense(num_intents, activation="softmax",
                         name="intent_out")(last)
        tags = L.TimeDistributed(
            L.Dense(num_entities, activation="softmax"),
            name="entity_out")(enc)
        model = Model(inp, [intent, tags], name="intent_entity")

        def joint_loss(y_true, y_pred):
            intent_p, tag_p = y_pred
            return (_sparse_ce(y_true[:, 0], intent_p) +
                    _sparse_ce(y_true[:, 1:], tag_p))

        super().__init__(model, optimizer=optimizer, loss=joint_loss)

    @staticmethod
    def pack_labels(intent_ids: np.ndarray,
                    tag_ids: np.ndarray) -> np.ndarray:
        intent_ids = np.asarray(intent_ids).reshape(-1, 1)
        return np.concatenate(
            [intent_ids, np.asarray(tag_ids)], axis=1).astype(np.int32)
