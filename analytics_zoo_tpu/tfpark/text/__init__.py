"""tfpark.text pre-built NLP models.

Reference: `P/tfpark/text/*.py` — `IntentEntity`, `NER`,
`SequenceTagger` wrap nlp-architect tf.keras models inside
`TextKerasModel`. The TPU-native rebuild constructs the same
architectures directly from the framework's own layer library (no TF
dependency): embedding → BiLSTM stacks → per-token / per-sequence
heads, trained with the standard Estimator.
"""

from analytics_zoo_tpu.tfpark.text.models import (  # noqa: F401
    IntentEntity,
    NER,
    SequenceTagger,
    TextKerasModel,
)

__all__ = ["TextKerasModel", "IntentEntity", "NER", "SequenceTagger"]
