"""TF GraphDef → jax.numpy interpreter (the GraphDef→HLO bridge).

Why this exists: `jax2tf.call_tf` needs a TF build with XLA kernels for
the target platform; the image's CPU-only TF cannot lower to
`XLA_TPU_JIT`, so bridged graphs would be CPU-bound. This module
interprets a (rewritten, side-effect-free) GraphDef with jnp/lax ops at
JAX trace time instead — the whole TF graph becomes ONE fused XLA
program that runs natively on TPU, differentiates with `jax.grad`, and
shards under `pjit`. This is the reference's TFNet JNI-session executor
(`Z/pipeline/api/net/TFNet.scala:216-384`) re-imagined as a compiler
bridge, per SURVEY.md §2.11.1 ("a C++ GraphDef→HLO bridge is the
analog").

Coverage: the feed-forward op set traced from tf.keras models (Dense /
Conv / BN / pooling / dropout / losses / elementwise), plus v1
while-loop control flow (`Enter/Merge/Switch/NextIteration/Exit` +
`TensorList*` — the frozen form of keras LSTM/GRU): each while frame is
collapsed to `lax.scan` (static trip count ⇒ differentiable, so
imported recurrent models train on TPU); DYNAMIC trip counts lower to
a masked `lax.scan` when a `max_trip_count` bound is given (also
differentiable — data-dependent-length graphs train too), else
`lax.while_loop` (forward-only). Remaining unsupported graphs fall
back to `jax2tf.call_tf` (CPU-only).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


def _tf():
    import tensorflow as tf
    return tf


# -- attr decoding ------------------------------------------------------------

def _attr(node, name, default=None):
    if name not in node.attr:
        return default
    a = node.attr[name]
    kind = a.WhichOneof("value")
    if kind == "b":
        return bool(a.b)
    if kind == "i":
        return int(a.i)
    if kind == "f":
        return float(a.f)
    if kind == "s":
        return a.s.decode("utf-8")
    if kind == "type":
        return _tf().dtypes.as_dtype(a.type).as_numpy_dtype
    if kind == "shape":
        return [d.size for d in a.shape.dim]
    if kind == "list":
        if a.list.i:
            return [int(v) for v in a.list.i]
        if a.list.f:
            return [float(v) for v in a.list.f]
        if a.list.s:
            return [v.decode("utf-8") for v in a.list.s]
        return []
    if kind == "tensor":
        return _tf().make_ndarray(a.tensor)
    return default


def _static(v, what="operand") -> np.ndarray:
    if isinstance(v, jax.core.Tracer):
        raise ValueError(
            f"graphdef interpreter: {what} must be compile-time static")
    return np.asarray(v)


def _shape_of(x):
    return np.asarray(np.shape(x), np.int32)


# -- op table -----------------------------------------------------------------

_OPS: Dict[str, Callable] = {}


def _op(*names):
    def deco(fn):
        for n in names:
            _OPS[n] = fn
        return fn
    return deco


def _is_jax(v) -> bool:
    return isinstance(v, (jax.Array, jax.core.Tracer))


# Inside a jit trace, jnp ops on plain numpy LIFT the result into a
# tracer — which would destroy the staticness of shape/seed arithmetic
# chains. Every table op therefore dispatches: all-numpy inputs → numpy
# implementation (stays static), any jax input → jnp implementation.

# elementwise binary (TF broadcasts like numpy)
for tf_name, jfn, nfn in [
        ("AddV2", jnp.add, np.add), ("Add", jnp.add, np.add),
        ("Sub", jnp.subtract, np.subtract),
        ("Mul", jnp.multiply, np.multiply),
        ("RealDiv", jnp.divide, np.divide),
        ("Div", jnp.divide, np.divide),
        ("FloorDiv", lambda a, b: a // b, lambda a, b: a // b),
        ("FloorMod", jnp.mod, np.mod),
        ("Maximum", jnp.maximum, np.maximum),
        ("Minimum", jnp.minimum, np.minimum),
        ("Pow", jnp.power, np.power),
        ("SquaredDifference", lambda a, b: (a - b) ** 2,
         lambda a, b: (a - b) ** 2),
        ("Greater", jnp.greater, np.greater),
        ("GreaterEqual", jnp.greater_equal, np.greater_equal),
        ("Less", jnp.less, np.less),
        ("LessEqual", jnp.less_equal, np.less_equal),
        ("Equal", jnp.equal, np.equal),
        ("NotEqual", jnp.not_equal, np.not_equal),
        ("LogicalAnd", jnp.logical_and, np.logical_and),
        ("LogicalOr", jnp.logical_or, np.logical_or),
        ("Atan2", jnp.arctan2, np.arctan2)]:
    _OPS[tf_name] = (lambda jf, nf: lambda node, i:
                     nf(i[0], i[1]) if not (_is_jax(i[0]) or
                                            _is_jax(i[1]))
                     else jf(i[0], i[1]))(jfn, nfn)

# elementwise unary
for tf_name, jfn, nfn in [
        ("Relu", jax.nn.relu, lambda x: np.maximum(x, 0)),
        ("Relu6", lambda x: jnp.clip(x, 0, 6),
         lambda x: np.clip(x, 0, 6)),
        ("Elu", jax.nn.elu, None), ("Selu", jax.nn.selu, None),
        ("Sigmoid", jax.nn.sigmoid, None), ("Tanh", jnp.tanh, np.tanh),
        ("Softplus", jax.nn.softplus, None),
        ("Softsign", lambda x: x / (1 + jnp.abs(x)), None),
        ("Exp", jnp.exp, np.exp), ("Log", jnp.log, np.log),
        ("Log1p", jnp.log1p, np.log1p),
        ("Neg", jnp.negative, np.negative),
        ("Abs", jnp.abs, np.abs), ("Sign", jnp.sign, np.sign),
        ("Square", jnp.square, np.square),
        ("Sqrt", jnp.sqrt, np.sqrt),
        ("Rsqrt", lax.rsqrt, lambda x: 1.0 / np.sqrt(x)),
        ("Reciprocal", jnp.reciprocal, np.reciprocal),
        ("Floor", jnp.floor, np.floor), ("Ceil", jnp.ceil, np.ceil),
        ("Round", jnp.round, np.round),
        ("Erf", jax.scipy.special.erf, None),
        ("Sin", jnp.sin, np.sin), ("Cos", jnp.cos, np.cos),
        ("Tan", jnp.tan, np.tan),
        ("LogicalNot", jnp.logical_not, np.logical_not),
        ("Identity", lambda x: x, lambda x: x),
        ("StopGradient", lax.stop_gradient, lambda x: x),
        ("PreventGradient", lax.stop_gradient, lambda x: x),
        ("Snapshot", lambda x: x, lambda x: x),
        ("ZerosLike", jnp.zeros_like, np.zeros_like),
        ("OnesLike", jnp.ones_like, np.ones_like)]:
    _OPS[tf_name] = (lambda jf, nf: lambda node, i:
                     nf(i[0]) if nf is not None and not _is_jax(i[0])
                     else jf(i[0]))(jfn, nfn)

_OPS["LeakyRelu"] = lambda node, i: jax.nn.leaky_relu(
    i[0], _attr(node, "alpha", 0.2))
_OPS["Softmax"] = lambda node, i: jax.nn.softmax(i[0], axis=-1)
_OPS["LogSoftmax"] = lambda node, i: jax.nn.log_softmax(i[0], axis=-1)
_OPS["AddN"] = lambda node, i: sum(i[1:], i[0])
_OPS["Select"] = lambda node, i: jnp.where(i[0], i[1], i[2])
_OPS["SelectV2"] = lambda node, i: jnp.where(i[0], i[1], i[2])
_OPS["Cast"] = lambda node, i: (
    np.asarray(i[0]).astype(_attr(node, "DstT"))
    if not _is_jax(i[0])
    else i[0].astype(_attr(node, "DstT")))


@_op("MatMul")
def _matmul(node, i):
    a, b = i
    if _attr(node, "transpose_a", False):
        a = a.T
    if _attr(node, "transpose_b", False):
        b = b.T
    return a @ b


@_op("BatchMatMulV2", "BatchMatMul", "BatchMatMulV3")
def _batch_matmul(node, i):
    a, b = i
    if _attr(node, "adj_x", False):
        a = jnp.swapaxes(a, -1, -2)
    if _attr(node, "adj_y", False):
        b = jnp.swapaxes(b, -1, -2)
    return jnp.matmul(a, b)


@_op("BiasAdd")
def _bias_add(node, i):
    x, b = i
    if _attr(node, "data_format", "NHWC") == "NCHW" and np.ndim(x) > 2:
        return x + jnp.reshape(b, (1, -1) + (1,) * (np.ndim(x) - 2))
    return x + b


def _conv_padding(node, x_shape, k_shape, strides, dilations):
    padding = _attr(node, "padding", "VALID")
    if padding == "EXPLICIT":
        pads = _attr(node, "explicit_paddings", [])
        return [(pads[2 * d], pads[2 * d + 1]) for d in (1, 2)]
    return padding  # "SAME"/"VALID" understood by lax


@_op("Conv2D")
def _conv2d(node, i):
    x, w = i
    if _attr(node, "data_format", "NHWC") != "NHWC":
        raise NotImplementedError("Conv2D NCHW")
    strides = _attr(node, "strides", [1, 1, 1, 1])[1:3]
    dilations = (_attr(node, "dilations", [1, 1, 1, 1]) or
                 [1, 1, 1, 1])[1:3]
    dn = lax.conv_dimension_numbers(np.shape(x), np.shape(w),
                                    ("NHWC", "HWIO", "NHWC"))
    return lax.conv_general_dilated(
        x, w, strides, _conv_padding(node, np.shape(x), np.shape(w),
                                     strides, dilations),
        rhs_dilation=dilations, dimension_numbers=dn)


@_op("DepthwiseConv2dNative")
def _depthwise_conv(node, i):
    x, w = i  # w: (H, W, C, M)
    strides = _attr(node, "strides", [1, 1, 1, 1])[1:3]
    dilations = (_attr(node, "dilations", [1, 1, 1, 1]) or
                 [1, 1, 1, 1])[1:3]
    h, wd, c, m = np.shape(w)
    w2 = jnp.reshape(w, (h, wd, 1, c * m))
    dn = lax.conv_dimension_numbers(np.shape(x), (h, wd, 1, c * m),
                                    ("NHWC", "HWIO", "NHWC"))
    return lax.conv_general_dilated(
        x, w2, strides, _attr(node, "padding", "VALID"),
        rhs_dilation=dilations, dimension_numbers=dn,
        feature_group_count=c)


def _pool(node, i, reducer, init, average=False):
    x = i[0]
    if _attr(node, "data_format", "NHWC") != "NHWC":
        raise NotImplementedError("pooling NCHW")
    ksize = _attr(node, "ksize", [1, 1, 1, 1])
    strides = _attr(node, "strides", [1, 1, 1, 1])
    padding = _attr(node, "padding", "VALID")
    pads = lax.padtype_to_pads(np.shape(x), ksize, strides, padding)
    out = lax.reduce_window(x, init, reducer, tuple(ksize),
                            tuple(strides), pads)
    if average:
        ones = jnp.ones(np.shape(x), x.dtype)
        counts = lax.reduce_window(ones, 0.0, lax.add, tuple(ksize),
                                   tuple(strides), pads)
        out = out / counts
    return out


_OPS["MaxPool"] = lambda node, i: _pool(node, i, lax.max, -jnp.inf)
_OPS["AvgPool"] = lambda node, i: _pool(node, i, lax.add, 0.0,
                                        average=True)


@_op("FusedBatchNormV3", "FusedBatchNorm", "FusedBatchNormV2")
def _fused_bn(node, i):
    """Multi-output like TF: :0 = y, :1/:2 = batch mean/var (training
    graphs read them for the moving-average update chain), :3+ =
    reserve spaces (backward-pass intermediates; bound to mean/var so
    consumers resolve — the backward ops themselves are not run here)."""
    x, scale, offset, mean, var = i[:5]
    eps = _attr(node, "epsilon", 1e-3)
    if _attr(node, "is_training", True):
        axes = tuple(range(x.ndim - 1))
        mean = jnp.mean(x, axes)
        var = jnp.var(x, axes)
    inv = lax.rsqrt(var + eps) * scale
    y = (x - mean) * inv + offset
    return (y, mean, var, mean, var, mean)


# -- shape / indexing ---------------------------------------------------------

_OPS["Shape"] = lambda node, i: _shape_of(i[0])
_OPS["Rank"] = lambda node, i: np.asarray(np.ndim(i[0]), np.int32)
_OPS["Size"] = lambda node, i: np.asarray(np.size(i[0]), np.int32)


@_op("Reshape")
def _reshape(node, i):
    shape = [int(v) for v in _static(i[1], "Reshape shape")]
    if not _is_jax(i[0]):
        return np.reshape(i[0], shape)
    return jnp.reshape(i[0], shape)


@_op("Transpose")
def _transpose(node, i):
    perm = [int(v) for v in _static(i[1], "Transpose perm")]
    if not _is_jax(i[0]):
        return np.transpose(i[0], perm)
    return jnp.transpose(i[0], perm)


@_op("ExpandDims")
def _expand_dims(node, i):
    if not _is_jax(i[0]):
        return np.expand_dims(i[0], int(_static(i[1])))
    return jnp.expand_dims(i[0], int(_static(i[1])))


@_op("Squeeze")
def _squeeze(node, i):
    dims = _attr(node, "squeeze_dims", None) or _attr(node, "axis", None)
    if not _is_jax(i[0]):
        return np.squeeze(i[0], tuple(dims) if dims else None)
    return jnp.squeeze(i[0], tuple(dims) if dims else None)


@_op("Pack")
def _pack(node, i):
    axis = _attr(node, "axis", 0)
    if all(not isinstance(v, (jax.Array, jax.core.Tracer)) for v in i):
        return np.stack([np.asarray(v) for v in i], axis=axis)
    return jnp.stack(i, axis=axis)


@_op("Unpack")
def _unpack(node, i):
    axis = _attr(node, "axis", 0)
    num = _attr(node, "num")
    return tuple(jnp.squeeze(s, axis) for s in
                 jnp.split(i[0], num, axis=axis))


@_op("ConcatV2")
def _concat(node, i):
    axis = int(_static(i[-1], "Concat axis"))
    vals = i[:-1]
    if all(not isinstance(v, (jax.Array, jax.core.Tracer))
           for v in vals):
        return np.concatenate([np.asarray(v) for v in vals], axis=axis)
    return jnp.concatenate(vals, axis=axis)


@_op("Split")
def _tf_split(node, i):
    axis = int(_static(i[0], "Split axis"))
    num = _attr(node, "num_split")
    return tuple(jnp.split(i[1], num, axis=axis))


@_op("SplitV")
def _tf_splitv(node, i):
    sizes = [int(v) for v in _static(i[1], "SplitV sizes")]
    axis = int(_static(i[2], "SplitV axis"))
    offs = np.cumsum([0] + sizes)
    return tuple(lax.slice_in_dim(i[0], int(offs[k]), int(offs[k + 1]),
                                  axis=axis)
                 for k in range(len(sizes)))


@_op("GatherV2", "Gather", "ResourceGather")
def _gather(node, i):
    axis = int(_static(i[2])) if len(i) > 2 else 0
    batch_dims = _attr(node, "batch_dims", 0) or 0
    idx = i[1]
    if _is_jax(idx):
        idx = idx.astype(jnp.int32)
    else:
        idx = np.asarray(idx).astype(np.int32)
    if batch_dims:
        if axis < 0:
            axis += np.ndim(i[0])
        fn = lambda p, ix: jnp.take(p, ix, axis=axis - batch_dims)  # noqa: E731
        for _ in range(batch_dims):
            fn = jax.vmap(fn)
        return fn(jnp.asarray(i[0]), idx)
    if not _is_jax(i[0]) and not _is_jax(idx):
        return np.take(i[0], idx, axis=axis)
    return jnp.take(i[0], idx, axis=axis)


@_op("Slice")
def _tf_slice(node, i):
    begin = [int(v) for v in _static(i[1], "Slice begin")]
    size = [int(v) for v in _static(i[2], "Slice size")]
    x = i[0]
    lims = [b + (s if s != -1 else np.shape(x)[d] - b)
            for d, (b, s) in enumerate(zip(begin, size))]
    return lax.slice(x, begin, lims)


def _dynamic_strided_slice(node, x, begin_raw):
    """StridedSlice with a TRACED begin (e.g. ``x[:, i, :]`` on a
    while-loop counter inside a dynamic frame): lowered to
    `lax.dynamic_slice`. Supported spec: unit strides, each dim either
    fully-masked (whole extent) or shrink (one dynamic index) — the
    form TF emits for per-step sequence indexing."""
    bm = _attr(node, "begin_mask", 0)
    em = _attr(node, "end_mask", 0)
    shrink_mask = _attr(node, "shrink_axis_mask", 0)
    if _attr(node, "ellipsis_mask", 0) or _attr(node, "new_axis_mask", 0):
        raise ValueError(
            "graphdef interpreter: dynamic StridedSlice supports no "
            "ellipsis/new-axis")
    xj = jnp.asarray(x)
    bvec = jnp.asarray(begin_raw).reshape(-1)
    n_spec = int(bvec.shape[0])
    starts, sizes, squeeze = [], [], []
    for k in range(n_spec):
        if shrink_mask & (1 << k):
            starts.append(bvec[k].astype(jnp.int32))
            sizes.append(1)
            squeeze.append(k)
        elif (bm & (1 << k)) and (em & (1 << k)):
            starts.append(jnp.int32(0))
            sizes.append(xj.shape[k])
        else:
            raise ValueError(
                "graphdef interpreter: dynamic StridedSlice dims must "
                "be fully-masked or shrink")
    for k in range(n_spec, xj.ndim):
        starts.append(jnp.int32(0))
        sizes.append(xj.shape[k])
    out = lax.dynamic_slice(xj, starts, sizes)
    return jnp.squeeze(out, axis=tuple(squeeze)) if squeeze else out


@_op("StridedSlice")
def _strided_slice(node, i):
    x = i[0]
    if isinstance(i[1], jax.core.Tracer):
        strides = [int(v) for v in
                   _static(i[3], "StridedSlice strides")]
        if any(s != 1 for s in strides):
            raise ValueError("graphdef interpreter: dynamic "
                             "StridedSlice needs unit strides")
        return _dynamic_strided_slice(node, x, i[1])
    begin = [int(v) for v in _static(i[1], "StridedSlice begin")]
    end = [int(v) for v in _static(i[2], "StridedSlice end")]
    strides = [int(v) for v in _static(i[3], "StridedSlice strides")]
    bm = _attr(node, "begin_mask", 0)
    em = _attr(node, "end_mask", 0)
    ellipsis_mask = _attr(node, "ellipsis_mask", 0)
    new_axis_mask = _attr(node, "new_axis_mask", 0)
    shrink_mask = _attr(node, "shrink_axis_mask", 0)
    spec: list = []
    n_spec = len(begin)
    n_new = bin(new_axis_mask).count("1")
    ndim = np.ndim(x)
    for k in range(n_spec):
        if ellipsis_mask & (1 << k):
            n_explicit = n_spec - 1 - n_new
            spec.extend([slice(None)] * (ndim - n_explicit))
        elif new_axis_mask & (1 << k):
            spec.append(None)
        elif shrink_mask & (1 << k):
            spec.append(begin[k])
        else:
            b = None if bm & (1 << k) else begin[k]
            e = None if em & (1 << k) else end[k]
            spec.append(slice(b, e, strides[k]))
    if isinstance(x, (jax.Array, jax.core.Tracer)):
        return x[tuple(spec)]
    return np.asarray(x)[tuple(spec)]


@_op("Fill")
def _fill(node, i):
    shape = [int(v) for v in _static(i[0], "Fill shape")]
    if not _is_jax(i[1]):
        return np.full(shape, i[1])
    return jnp.full(shape, i[1])


@_op("BroadcastTo")
def _broadcast_to(node, i):
    shape = [int(v) for v in _static(i[1], "BroadcastTo shape")]
    if not _is_jax(i[0]):
        return np.broadcast_to(i[0], shape)
    return jnp.broadcast_to(i[0], shape)


@_op("Tile")
def _tile(node, i):
    reps = [int(v) for v in _static(i[1], "Tile reps")]
    if not _is_jax(i[0]):
        return np.tile(i[0], reps)
    return jnp.tile(i[0], reps)


@_op("Pad", "PadV2")
def _tf_pad(node, i):
    pads = [(int(a), int(b)) for a, b in _static(i[1], "Pad paddings")]
    value = float(_static(i[2])) if len(i) > 2 else 0.0
    return jnp.pad(i[0], pads, constant_values=value)


@_op("MirrorPad")
def _mirror_pad(node, i):
    pads = [(int(a), int(b)) for a, b in _static(i[1], "Pad paddings")]
    mode = {"REFLECT": "reflect", "SYMMETRIC": "symmetric"}[
        _attr(node, "mode", "REFLECT")]
    return jnp.pad(i[0], pads, mode=mode)


@_op("Range")
def _range(node, i):
    start, limit, delta = (int(_static(v)) for v in i[:3])
    return np.arange(start, limit, delta, dtype=np.int32)


# -- reductions ---------------------------------------------------------------

def _reduction(jnp_fn, np_fn):
    def fn(node, i):
        axes = _static(i[1], "reduction axes").reshape(-1)
        kd = _attr(node, "keep_dims", _attr(node, "keepdims", False))
        f = np_fn if not _is_jax(i[0]) else jnp_fn
        return f(i[0], axis=tuple(int(a) for a in axes),
                 keepdims=bool(kd))
    return fn


_OPS["Mean"] = _reduction(jnp.mean, np.mean)
_OPS["Sum"] = _reduction(jnp.sum, np.sum)
_OPS["Max"] = _reduction(jnp.max, np.max)
_OPS["Min"] = _reduction(jnp.min, np.min)
_OPS["Prod"] = _reduction(jnp.prod, np.prod)
_OPS["All"] = _reduction(jnp.all, np.all)
_OPS["Any"] = _reduction(jnp.any, np.any)
_OPS["ArgMax"] = lambda node, i: jnp.argmax(
    i[0], axis=int(_static(i[1]))).astype(
        _attr(node, "output_type", np.int64))
_OPS["ArgMin"] = lambda node, i: jnp.argmin(
    i[0], axis=int(_static(i[1]))).astype(
        _attr(node, "output_type", np.int64))


# -- stateless randomness (keras-3 dropout) -----------------------------------

@_op("StatelessRandomGetKeyCounter")
def _get_key_counter(node, i):
    seed = _static(i[0], "random seed").astype(np.int64).reshape(-1)
    # surrogate: carry the seed through as (key, counter)
    key = np.asarray([seed[0] & 0x7FFFFFFF], np.uint64)
    counter = np.asarray([seed[-1] & 0x7FFFFFFF, 0], np.uint64)
    return (key, counter)


@_op("StatelessRandomUniformV2")
def _stateless_uniform(node, i):
    shape = [int(v) for v in _static(i[0], "random shape")]
    key = _static(i[1], "random key").reshape(-1)
    counter = _static(i[2], "random counter").reshape(-1)
    rng = jax.random.PRNGKey(int(key[0]) ^ int(counter[0]))
    return jax.random.uniform(rng, shape,
                              dtype=_attr(node, "dtype", np.float32))


@_op("StatelessRandomNormalV2")
def _stateless_normal(node, i):
    shape = [int(v) for v in _static(i[0], "random shape")]
    key = _static(i[1], "random key").reshape(-1)
    counter = _static(i[2], "random counter").reshape(-1)
    rng = jax.random.PRNGKey(int(key[0]) ^ int(counter[0]))
    return jax.random.normal(rng, shape,
                             dtype=_attr(node, "dtype", np.float32))


# -- TensorList (TensorArray v2) ----------------------------------------------
# A TensorList is represented as a dense stacked array with the list
# index as axis 0 (keras RNNs transpose to time-major before
# TensorListFromTensor, so axis 0 is already time). Static shapes only —
# the XLA-friendly representation; dynamically-shaped lists raise and
# the caller falls back to call_tf.

@_op("TensorListFromTensor")
def _tl_from_tensor(node, i):
    return i[0]


@_op("TensorListStack")
def _tl_stack(node, i):
    if isinstance(i[0], _PendingTensorList):
        raise NotImplementedError(
            "TensorListStack of a never-written TensorList")
    return i[0]


@_op("TensorListLength")
def _tl_length(node, i):
    if isinstance(i[0], _PendingTensorList):
        return np.int32(i[0].num)
    return np.int32(np.shape(i[0])[0])


@_op("TensorListElementShape")
def _tl_element_shape(node, i):
    if isinstance(i[0], _PendingTensorList):
        raise NotImplementedError(
            "TensorListElementShape of a never-written TensorList "
            "(unknown element shape)")
    return np.asarray(np.shape(i[0])[1:], np.int32)


class _PendingTensorList:
    """A TensorListReserve whose element shape has unknown dims: XLA
    needs static shapes, so materialization is deferred to the first
    SetItem (whose item fixes the open dims)."""

    def __init__(self, num: int, shape, dtype):
        self.num = num
        self.shape = [int(d) for d in shape]
        self.dtype = dtype

    def materialize_like(self, item):
        got = list(np.shape(item))
        if len(got) != len(self.shape):
            raise NotImplementedError(
                f"TensorList element rank mismatch: reserved "
                f"{self.shape}, wrote {got}")
        shape = [s if s >= 0 else g for s, g in zip(self.shape, got)]
        return jnp.zeros((self.num, *shape), self.dtype)


@_op("TensorListReserve")
def _tl_reserve(node, i):
    shape = _static(i[0], "TensorListReserve element_shape").reshape(-1)
    num = int(_static(i[1], "TensorListReserve num_elements"))
    dtype = _attr(node, "element_dtype", np.float32)
    if any(int(d) < 0 for d in shape):
        return _PendingTensorList(num, shape, dtype)
    return np.zeros((num,) + tuple(int(d) for d in shape), dtype)


@_op("TensorListGetItem")
def _tl_get(node, i):
    arr, idx = i[0], i[1]
    if isinstance(arr, _PendingTensorList):
        raise NotImplementedError(
            "TensorList read before first write (unknown element shape)")
    if not _is_jax(idx):
        return arr[int(np.asarray(idx))]
    return lax.dynamic_index_in_dim(jnp.asarray(arr), idx, axis=0,
                                    keepdims=False)


@_op("TensorListSetItem")
def _tl_set(node, i):
    arr, idx, item = i[0], i[1], i[2]
    if isinstance(arr, _PendingTensorList):
        arr = arr.materialize_like(item)
    arr = jnp.asarray(arr)
    item = jnp.asarray(item, arr.dtype)
    if not _is_jax(idx):
        idx = int(np.asarray(idx))
    return lax.dynamic_update_index_in_dim(arr, item, idx, axis=0)


# -- v1 while-loop control flow -----------------------------------------------
# TF freezes tf.function while loops (keras LSTM/GRU) into v1 dataflow
# control flow: Enter/Merge/Switch/NextIteration/Exit per loop variable,
# one LoopCond per frame. The interpreter collapses each frame into ONE
# XLA loop: `lax.scan` when the trip count is compile-time static (the
# keras-RNN case — scan is reverse-mode differentiable, so imported
# recurrent models TRAIN on TPU), else `lax.while_loop` (inference).
# Reference behavior being replaced: TFNet runs these graphs via the TF
# JNI session (`Z/pipeline/api/net/TFNet.scala:216-296`).

_CTRL_OPS = {"Enter", "RefEnter", "Exit", "RefExit", "Merge", "RefMerge",
             "Switch", "RefSwitch", "NextIteration", "RefNextIteration",
             "LoopCond"}


# -- interpreter --------------------------------------------------------------

class GraphDefFunction:
    """A side-effect-free GraphDef as a pure python/JAX callable.

    ``input_names`` are tensor names ("node:idx") fed positionally;
    ``output_names`` are fetched. Constant feeds are baked in. The
    function evaluates lazily with memoization, so only the subgraph
    reachable from the outputs runs.
    """

    def __init__(self, graph_def, input_names: Sequence[str],
                 output_names: Sequence[str],
                 const_feeds: Optional[Dict[str, np.ndarray]] = None,
                 max_trip_count: Optional[int] = None):
        """``max_trip_count``: upper bound for DYNAMIC v1 while loops
        (predicate depends on runtime values). With a bound, such
        loops lower to a masked `lax.scan` — reverse-mode
        differentiable, so data-dependent-length imported graphs
        TRAIN on TPU (VERDICT r3 missing #4; the reference TFNet
        backward runs any graph via the TF runtime,
        `Z/pipeline/api/net/TFNet.scala:316-384`). The bound must be
        ≥ the actual trip count: iterations past the predicate's
        first False are masked no-ops, but a loop that would run
        LONGER than the bound is silently truncated. Defaults to the
        ``ZOO_TPU_TF_MAX_TRIP`` env var; unset ⇒ dynamic loops use
        `lax.while_loop` (forward-only)."""
        import os
        self.gd = graph_def
        self.input_names = [self._norm(n) for n in input_names]
        self.output_names = [self._norm(n) for n in output_names]
        self.const_feeds = {self._norm(k): np.asarray(v)
                            for k, v in (const_feeds or {}).items()}
        if max_trip_count is None:
            env = os.environ.get("ZOO_TPU_TF_MAX_TRIP")
            max_trip_count = int(env) if env else None
        if max_trip_count is not None and max_trip_count <= 0:
            max_trip_count = None    # 0/negative = unset (the repo's
        self.max_trip_count = max_trip_count  # "0 = off" convention)
        self._nodes = {n.name: n for n in graph_def.node}
        self._consts: Dict[str, np.ndarray] = {}
        for n in graph_def.node:
            if n.op == "Const":
                self._consts[n.name + ":0"] = _attr(n, "value")
        self._frame_list: Optional[List[dict]] = None
        self._member_frame: Dict[str, dict] = {}

    @staticmethod
    def _norm(name: str) -> str:
        return name if ":" in name else name + ":0"

    def unsupported_ops(self) -> List[str]:
        """Uninterpreted ops among the nodes actually REACHABLE from the
        outputs (dead subgraphs never run, so they don't force the
        call_tf fallback). v1 while-loop control flow counts as
        supported when the frame structure is regular enough to lower
        (see `_frames`)."""
        fed = {n.split(":")[0] for n in self.input_names}
        fed |= {n.split(":")[0] for n in self.const_feeds}
        out = set()
        has_ctrl = False
        for name in self._reachable(fed):
            node = self._nodes[name]
            if node.op in ("Const", "Placeholder", "NoOp"):
                continue
            if node.op in _CTRL_OPS:
                has_ctrl = True
                continue
            if node.op not in _OPS:
                out.add(node.op)
        if has_ctrl:
            try:
                self._frames()
                for name in self._reachable(fed):
                    node = self._nodes[name]
                    if node.op in _CTRL_OPS and \
                            name not in self._member_frame:
                        # e.g. Switch/Merge from a lowered If — no
                        # Enter ancestry, so not lowerable as a loop
                        out.add(f"{node.op}[non-while]")
            except NotImplementedError as e:
                out.add(f"WhileLoopV1[{e}]")
        return sorted(out)

    # -- while-frame extraction -------------------------------------------

    def _frames(self) -> List[dict]:
        """Group v1 control-flow nodes into while frames and validate
        the structure this interpreter can lower (single-level frames,
        one LoopCond, regular Merge/Enter/NextIteration/Switch/Exit
        wiring). Raises NotImplementedError otherwise."""
        if self._frame_list is not None:
            return self._frame_list
        consumers: Dict[str, List[str]] = {}
        for n in self.gd.node:
            for x in n.input:
                if not x.startswith("^"):
                    consumers.setdefault(x.split(":")[0], []).append(n.name)
        by_frame: Dict[str, List] = {}
        for n in self.gd.node:
            if n.op in ("Enter", "RefEnter"):
                by_frame.setdefault(_attr(n, "frame_name"), []).append(n)
        frame_list: List[dict] = []
        member_frame: Dict[str, dict] = {}
        for fname, enters in by_frame.items():
            members = {e.name for e in enters}
            stack = [e.name for e in enters]
            while stack:
                nm = stack.pop()
                if self._nodes[nm].op in ("Exit", "RefExit"):
                    continue  # Exit output lives outside the frame
                for c in consumers.get(nm, ()):
                    if c in members:
                        continue
                    cn = self._nodes[c]
                    if cn.op in ("Enter", "RefEnter"):
                        raise NotImplementedError(
                            f"nested while frames ({fname} feeds "
                            f"{_attr(cn, 'frame_name')})")
                    members.add(c)
                    stack.append(c)
            merges = [n for n in self.gd.node if n.name in members
                      and n.op in ("Merge", "RefMerge")]
            loopconds = [self._nodes[m] for m in members
                         if self._nodes[m].op == "LoopCond"]
            if len(loopconds) != 1:
                raise NotImplementedError(
                    f"while frame {fname} has {len(loopconds)} LoopCond "
                    "nodes (expected 1)")
            merge_enter, merge_next, merge_index = {}, {}, {}
            for i, m in enumerate(merges):
                ins = [self._nodes[x.split(":")[0]] for x in m.input
                       if not x.startswith("^")]
                ent = [n for n in ins if n.op in ("Enter", "RefEnter")]
                nxt = [n for n in ins
                       if n.op in ("NextIteration", "RefNextIteration")]
                if len(ent) != 1 or len(nxt) != 1:
                    raise NotImplementedError(
                        f"irregular Merge {m.name} in while frame")
                merge_enter[m.name] = ent[0]
                merge_next[m.name] = nxt[0]
                merge_index[m.name] = i
            exits = [self._nodes[m] for m in members
                     if self._nodes[m].op in ("Exit", "RefExit")]
            exit_var = {}
            for ex in exits:
                sw = self._nodes[ex.input[0].split(":")[0]]
                if sw.op not in ("Switch", "RefSwitch") or \
                        sw.input[0].split(":")[0] not in merge_index:
                    raise NotImplementedError(
                        f"Exit {ex.name} not wired Switch(Merge, ...)")
                exit_var[ex.name] = merge_index[sw.input[0].split(":")[0]]
            fr = dict(name=fname, enters=enters, merges=merges,
                      loopcond=loopconds[0], merge_enter=merge_enter,
                      merge_next=merge_next, merge_index=merge_index,
                      exits=exits, exit_var=exit_var, members=members)
            frame_list.append(fr)
            for m in members:
                member_frame[m] = fr
        self._frame_list = frame_list
        self._member_frame = member_frame
        return frame_list

    def _frame_eval(self, fr: dict, target: str, env2: Dict[str, Any],
                    env: Dict[str, Any], rng=None):
        """Memoized iterative eval of a frame-internal tensor given a
        seeded env2 (merge values + invariant Enters); falls through to
        the outer env for non-member producers (consts)."""
        members = fr["members"]
        stack = [self._norm(target)]
        while stack:
            t = stack[-1]
            if t in env2:
                stack.pop()
                continue
            name = t.split(":")[0]
            if name not in members:
                # loop-invariant outer tensor (const-derived chains are
                # not frame members — anything touching a member would
                # be one); evaluate into the OUTER env, memoized
                if t in env:
                    env2[t] = env[t]
                    stack.pop()
                    continue
                node = self._nodes.get(name)
                if node is None:
                    raise KeyError(f"no node named {name}")
                if node.op == "Placeholder":
                    raise ValueError(f"unfed placeholder {name}")
                if node.op not in _OPS:
                    raise NotImplementedError(
                        f"TF op {node.op} (node {name}); use the "
                        "call_tf fallback for this graph")
                deps = [self._norm(x) for x in node.input
                        if not x.startswith("^")]
                missing = [d for d in deps if d not in env]
                if missing:
                    stack.extend(missing)
                    continue
                stack.pop()
                args = [env[self._norm(x)] for x in node.input
                        if not x.startswith("^")]
                out = self._apply_node(node, args, None)
                if isinstance(out, tuple):
                    for k, v in enumerate(out):
                        env[f"{name}:{k}"] = v
                else:
                    env[name + ":0"] = out
                if t not in env:
                    raise KeyError(
                        f"node {name} produced no output {t}")
                env2[t] = env[t]
                continue
            node = self._nodes[name]
            if node.op in ("Switch", "RefSwitch"):
                deps = [self._norm(node.input[0])]
            elif node.op in _CTRL_OPS:
                raise NotImplementedError(
                    f"unexpected control op {node.op} inside while body")
            elif node.op not in _OPS:
                raise NotImplementedError(
                    f"TF op {node.op} inside while body")
            else:
                deps = [self._norm(x) for x in node.input
                        if not x.startswith("^")]
            missing = [d for d in deps if d not in env2]
            if missing:
                stack.extend(missing)
                continue
            stack.pop()
            if node.op in ("Switch", "RefSwitch"):
                v = env2[self._norm(node.input[0])]
                env2[name + ":0"] = v  # false/exit arm == current value
                env2[name + ":1"] = v  # true/body arm
                continue
            args = [env2[self._norm(x)] for x in node.input
                    if not x.startswith("^")]
            out = self._apply_node(node, args, rng)
            if isinstance(out, tuple):
                for k, v in enumerate(out):
                    env2[f"{name}:{k}"] = v
            else:
                env2[name + ":0"] = out
        return env2[self._norm(target)]

    def _seed_frame_env(self, fr: dict, var_vals,
                        enter_vals: Dict[str, Any]) -> Dict[str, Any]:
        env2: Dict[str, Any] = {}
        for e in fr["enters"]:
            if _attr(e, "is_constant", False):
                env2[e.name + ":0"] = enter_vals[e.name]
        for m, v in zip(fr["merges"], var_vals):
            env2[m.name + ":0"] = v
            env2[m.name + ":1"] = np.int32(0)  # Merge value_index
        return env2

    def _merges_read(self, fr: dict, target: str) -> set:
        """Names of this frame's Merge nodes that `target` transitively
        reads (via Switch data inputs)."""
        out, seen = set(), set()
        stack = [target.split(":")[0]]
        while stack:
            nm = stack.pop()
            if nm in seen or nm not in fr["members"]:
                continue
            seen.add(nm)
            node = self._nodes[nm]
            if node.op in ("Merge", "RefMerge"):
                out.add(nm)
            elif node.op in ("Switch", "RefSwitch"):
                stack.append(node.input[0].split(":")[0])
            else:
                stack.extend(x.split(":")[0] for x in node.input
                             if not x.startswith("^"))
        return out

    def _static_trip_count(self, fr: dict, init: list,
                           env: Dict[str, Any],
                           enter_vals: Dict[str, Any]) -> Optional[int]:
        """Trip count when the loop predicate depends only on
        compile-time-static loop vars (keras RNN counters); simulated
        with numpy. None ⇒ dynamic (lower to while_loop)."""
        needed = self._merges_read(fr, fr["loopcond"].input[0])
        for _ in range(len(fr["merges"]) + 1):
            extra = set()
            for mn in needed:
                extra |= self._merges_read(
                    fr, fr["merge_next"][mn].input[0])
            if extra <= needed:
                break
            needed |= extra
        idx = fr["merge_index"]
        vals = {mn: init[idx[mn]] for mn in needed}
        if any(_is_jax(v) for v in vals.values()):
            return None
        try:
            for trips in range(32_768):
                env2 = self._seed_frame_env(
                    fr, [vals.get(m.name) for m in fr["merges"]],
                    enter_vals)
                # unrelated merges seeded None: touching one raises
                env2 = {k: v for k, v in env2.items() if v is not None}
                pred = self._frame_eval(fr, fr["loopcond"].input[0],
                                        env2, env)
                if _is_jax(pred):
                    return None
                if not bool(np.asarray(pred)):
                    return trips
                nxt = {}
                for mn in needed:
                    v = self._frame_eval(
                        fr, fr["merge_next"][mn].input[0], env2, env)
                    if _is_jax(v):
                        return None
                    nxt[mn] = v
                vals = nxt
        except (KeyError, NotImplementedError, ValueError):
            return None
        return None

    def _eval_frame(self, fr: dict, env: Dict[str, Any], rng) -> None:
        """Lower one while frame to lax.scan/while_loop and bind its
        Exit outputs into env."""
        merges = fr["merges"]
        init = [env[self._norm(fr["merge_enter"][m.name].input[0])]
                for m in merges]
        enter_vals = {
            e.name: env[self._norm(e.input[0])] for e in fr["enters"]}

        def body_vals(var_vals):
            env2 = self._seed_frame_env(fr, var_vals, enter_vals)
            return tuple(
                self._frame_eval(fr, fr["merge_next"][m.name].input[0],
                                 env2, env, rng)
                for m in merges)

        def cond_fn(var_vals):
            env2 = self._seed_frame_env(fr, var_vals, enter_vals)
            pred = self._frame_eval(fr, fr["loopcond"].input[0],
                                    env2, env)
            return jnp.reshape(jnp.asarray(pred), ())

        if any(isinstance(v, _PendingTensorList) for v in init):
            # probe one body step to learn the deferred TensorList
            # shapes (under jit this only adds dead traced ops; XLA
            # DCEs them), then enter the loop fully materialized
            probe = body_vals(init)
            init = [jnp.zeros(jnp.asarray(p).shape, jnp.asarray(p).dtype)
                    if isinstance(v, _PendingTensorList) else v
                    for v, p in zip(init, probe)]

        trip = self._static_trip_count(fr, init, env, enter_vals)
        init_t = tuple(jnp.asarray(v) for v in init)
        if trip is not None:
            # static trip count ⇒ scan: differentiable, unrollable
            finals, _ = lax.scan(lambda vs, _: (body_vals(vs), None),
                                 init_t, None, length=trip)
        elif self.max_trip_count is not None:
            # dynamic trip count with a user bound ⇒ MASKED scan:
            # the predicate re-evaluates each iteration, iterations
            # past its first False freeze the carry, and reverse-mode
            # AD works (lax.while_loop is forward-only)
            def masked_step(carry, _):
                vals, active = carry
                act = jnp.logical_and(active, cond_fn(vals))
                new_vals = body_vals(vals)
                merged = tuple(
                    jnp.where(act, jnp.asarray(n), v)
                    for n, v in zip(new_vals, vals))
                return (merged, act), None
            (finals, _), _ = lax.scan(
                masked_step, (init_t, jnp.asarray(True)), None,
                length=int(self.max_trip_count))
        else:
            finals = lax.while_loop(cond_fn, body_vals, init_t)
        for ex in fr["exits"]:
            env[ex.name + ":0"] = finals[fr["exit_var"][ex.name]]

    def _reachable(self, fed: set) -> List[str]:
        """Node names reachable from the outputs, stopping at fed
        tensors (iterative DFS — graphs can be 1000s of nodes deep)."""
        seen: set = set()
        stack = [n.split(":")[0] for n in self.output_names]
        while stack:
            name = stack.pop()
            if name in seen or name in fed:
                continue
            seen.add(name)
            node = self._nodes.get(name)
            if node is None:
                raise KeyError(f"no node named {name}")
            for x in node.input:
                if not x.startswith("^"):
                    stack.append(x.split(":")[0])
        return [n.name for n in self.gd.node if n.name in seen]

    def _apply_node(self, node, args, rng):
        """Evaluate one (non-control) node. ``rng`` overrides baked
        stateless-random seeds (per-step dropout masks)."""
        if rng is not None and node.op in (
                "StatelessRandomUniformV2", "StatelessRandomNormalV2"):
            import zlib
            shape = [int(v) for v in _static(args[0], "random shape")]
            sub = jax.random.fold_in(
                rng, zlib.crc32(node.name.encode()) & 0x7FFFFFFF)
            sampler = (jax.random.uniform
                       if node.op == "StatelessRandomUniformV2"
                       else jax.random.normal)
            return sampler(sub, shape,
                           dtype=_attr(node, "dtype", np.float32))
        return _OPS[node.op](node, args)

    def __call__(self, *inputs, rng=None):
        """Evaluate (demand-driven, memoized, iterative — only the
        subgraph reachable from the outputs runs; while frames are
        evaluated as single lax.scan/while_loop units). ``rng`` (a JAX
        PRNG key) overrides the graph's baked stateless-random seeds so
        dropout masks differ per step — the stripped seed-increment side
        effect (`tf_graph` step 5) would otherwise freeze the mask."""
        if len(inputs) != len(self.input_names):
            raise ValueError(
                f"expected {len(self.input_names)} inputs, "
                f"got {len(inputs)}")
        env: Dict[str, Any] = dict(self._consts)
        env.update(self.const_feeds)
        env.update(zip(self.input_names, inputs))
        self._frames()
        done_frames: set = set()
        stack = [self._norm(n) for n in self.output_names]
        budget = 1000 + 50 * sum(
            len(n.input) + 1 for n in self.gd.node)
        while stack:
            budget -= 1
            if budget < 0:
                raise RuntimeError(
                    "graphdef evaluation did not converge (cyclic "
                    "non-frame graph?)")
            t = stack[-1]
            if t in env:
                stack.pop()
                continue
            name = t.split(":")[0]
            node = self._nodes.get(name)
            if node is None:
                raise KeyError(f"no node named {name}")
            fr = self._member_frame.get(name)
            if fr is not None:
                deps = [self._norm(e.input[0]) for e in fr["enters"]]
            elif node.op == "Placeholder":
                raise ValueError(
                    f"unfed placeholder {name} (feed it via "
                    "input_names or const_feeds)")
            elif node.op not in _OPS:
                raise NotImplementedError(
                    f"TF op {node.op} (node {name}); use the "
                    "call_tf fallback for this graph")
            else:
                deps = [self._norm(x) for x in node.input
                        if not x.startswith("^")]
            missing = [d for d in deps if d not in env]
            if missing:
                stack.extend(missing)
                continue
            stack.pop()
            if fr is not None:
                if fr["name"] not in done_frames:
                    self._eval_frame(fr, env, rng)
                    done_frames.add(fr["name"])
                if t not in env:
                    raise NotImplementedError(
                        f"tensor {t} of while frame {fr['name']} is "
                        "consumed outside the loop (only Exit outputs "
                        "may be)")
                continue
            args = [env[self._norm(x)] for x in node.input
                    if not x.startswith("^")]
            out = self._apply_node(node, args, rng)
            if isinstance(out, tuple):
                for k, v in enumerate(out):
                    env[f"{name}:{k}"] = v
            else:
                env[name + ":0"] = out
        outs = [env[n] for n in self.output_names]
        return outs if len(outs) > 1 else outs[0]
