"""analytics_zoo_tpu — a TPU-native analytics + AI framework.

A ground-up JAX/XLA/Pallas re-design with the capabilities of Analytics Zoo
(reference: pgargesa/analytics-zoo): a unified platform where one driver
program does data wrangling, Keras-style model definition, and distributed
training/inference — except the execution engine is XLA on TPU meshes
(GSPMD data/tensor/sequence parallelism over ICI) instead of BigDL's
MKL-on-Spark engine.

Top-level surface (mirrors the capability map in SURVEY.md §1):

- ``analytics_zoo_tpu.common``    — context & engine init (L1)
- ``analytics_zoo_tpu.feature``   — FeatureSet / ImageSet / TextSet (L2)
- ``analytics_zoo_tpu.pipeline``  — autograd, keras API, estimator, nnframes,
                                    inference (L3/L4/L7/L8/L9)
- ``analytics_zoo_tpu.models``    — built-in model zoo (L6)
- ``analytics_zoo_tpu.parallel``  — mesh / sharding / collectives / ring
                                    attention (replaces §2.10's Spark
                                    parameter-manager all-reduce)
- ``analytics_zoo_tpu.ops``       — losses, metrics, optimizers, pallas kernels
"""

import os as _os

# Honor JAX_PLATFORMS authoritatively at import: plugin backends (the
# axon TPU tunnel) register regardless of the env var, so without this
# a documented `JAX_PLATFORMS=cpu python ...` run can hang device init
# on an unreachable tunnel. No-op when unset; best-effort if a backend
# is already initialized.
if _os.environ.get("JAX_PLATFORMS"):
    import jax as _jax
    try:
        _jax.config.update("jax_platforms",
                           _os.environ["JAX_PLATFORMS"])
    except Exception as _e:  # pin failed: surface it — a silent miss
        import warnings as _warnings  # would revive the tunnel hang
        _warnings.warn(f"could not pin jax_platforms from "
                       f"JAX_PLATFORMS: {_e}")

from analytics_zoo_tpu.version import __version__
from analytics_zoo_tpu.common.nncontext import (
    init_nncontext,
    get_nncontext,
    NNContext,
    ZooTpuConf,
)

__all__ = [
    "__version__",
    "init_nncontext",
    "get_nncontext",
    "NNContext",
    "ZooTpuConf",
    "Net",
]


def __getattr__(name):
    if name == "Net":  # lazy: pulls in jax/layer machinery
        from analytics_zoo_tpu.pipeline.api.net_load import Net
        return Net
    raise AttributeError(name)
