"""analytics_zoo_tpu — a TPU-native analytics + AI framework.

A ground-up JAX/XLA/Pallas re-design with the capabilities of Analytics Zoo
(reference: pgargesa/analytics-zoo): a unified platform where one driver
program does data wrangling, Keras-style model definition, and distributed
training/inference — except the execution engine is XLA on TPU meshes
(GSPMD data/tensor/sequence parallelism over ICI) instead of BigDL's
MKL-on-Spark engine.

Top-level surface (mirrors the capability map in SURVEY.md §1):

- ``analytics_zoo_tpu.common``    — context & engine init (L1)
- ``analytics_zoo_tpu.feature``   — FeatureSet / ImageSet / TextSet (L2)
- ``analytics_zoo_tpu.pipeline``  — autograd, keras API, estimator, nnframes,
                                    inference (L3/L4/L7/L8/L9)
- ``analytics_zoo_tpu.models``    — built-in model zoo (L6)
- ``analytics_zoo_tpu.parallel``  — mesh / sharding / collectives / ring
                                    attention (replaces §2.10's Spark
                                    parameter-manager all-reduce)
- ``analytics_zoo_tpu.ops``       — losses, metrics, optimizers, pallas kernels
"""

import os as _os

# Honor JAX_PLATFORMS at import: plugin backends (the axon TPU
# tunnel) clobber the env var's selection with a startup
# `jax.config.update("jax_platforms", "axon,cpu")` from their
# sitecustomize, so without this a documented
# `JAX_PLATFORMS=cpu python ...` run can hang device init on an
# unreachable tunnel. Restore the env's choice ONLY when the current
# config value is still that plugin clobber (or already the env
# value): a program that pinned a platform via jax.config.update
# AFTER the clobber (e.g. bench.py's dead-tunnel CPU fallback child,
# running under a driver env of JAX_PLATFORMS=axon) must keep its
# pin — re-pinning from env here is what hung round 4's fallback on
# the dead tunnel. No-op when unset.
if _os.environ.get("JAX_PLATFORMS"):
    import jax as _jax
    try:
        _env_p = _os.environ["JAX_PLATFORMS"]
        _cur = getattr(_jax.config, "jax_platforms", None)
        # "plugin clobber" = any current selection that contains the
        # axon backend while the env selection does NOT — the plugin's
        # sitecustomize inserted it (whatever it packed around it:
        # "axon,cpu", "axon", future "axon,tpu,cpu", ...); a selection
        # without axon that differs from the env was chosen by the
        # program and stays.
        _is_clobber = bool(_cur) and \
            "axon" in _cur.split(",") and \
            "axon" not in _env_p.split(",")
        if _cur in (None, "", _env_p) or _is_clobber:
            if _cur != _env_p:
                _jax.config.update("jax_platforms", _env_p)
        elif _cur != _env_p:
            # programmatic pin kept — say so, because a user staring
            # at JAX_PLATFORMS=cpu while devices init on another
            # backend otherwise has nothing to go on
            import logging as _logging
            _logging.getLogger(__name__).info(
                "JAX_PLATFORMS=%r not re-pinned: jax_platforms=%r "
                "was set programmatically (not an axon plugin "
                "clobber) and takes precedence", _env_p, _cur)
    except Exception as _e:  # pin failed: surface it — a silent miss
        import warnings as _warnings  # would revive the tunnel hang
        _warnings.warn(f"could not pin jax_platforms from "
                       f"JAX_PLATFORMS: {_e}")

from analytics_zoo_tpu.version import __version__
from analytics_zoo_tpu.common.nncontext import (
    init_nncontext,
    get_nncontext,
    NNContext,
    ZooTpuConf,
)

__all__ = [
    "__version__",
    "init_nncontext",
    "get_nncontext",
    "NNContext",
    "ZooTpuConf",
    "Net",
]


def __getattr__(name):
    if name == "Net":  # lazy: pulls in jax/layer machinery
        from analytics_zoo_tpu.pipeline.api.net_load import Net
        return Net
    raise AttributeError(name)
