"""Shared timing harness for the secondary benchmarks (bench_ncf,
bench_bert; bench.py's multi-variant supervisor keeps its own copy of
the same methodology).

Measurement recipe (PERF.md): ONE compiled lax.scan chain per
workload, one scalar host fetch per run, the constant dispatch/round-
trip overhead (min of 5 tiny-jit samples — a single transient RPC
spike must not inflate throughput) subtracted from the best of
``reps`` runs.

Artifact schema (the JSON lines bench.py prints; each line is a
self-contained best-so-far record — the last is the most complete):

- ``metric``/``unit``: what the headline measures
  (``resnet50_train_images_per_sec_per_chip``, images/sec).
- ``value``: the CHIP headline. ``null`` whenever the chip was
  unreachable (dead tunnel / zero-signal child) — a null headline can
  never be mistaken for chip perf. While a live run is in flight it
  is the best-so-far chip number (0.0 until the first measurement).
- ``vs_baseline``: achieved model-MFU / 0.45; ``null`` with a null
  headline.
- ``cpu_fallback_value``: host-CPU img/s from the fallback resnet
  stage — present ONLY when the chip was unreachable; explicitly
  NOT chip perf (``fallback`` carries its config label).
- ``extra_metrics``: list of per-stage/per-workload records
  (ncf/bert/conformance/resnet fallback stages, each with its own
  metric/value/unit).
- ``diag``/``stage_errors``: what went wrong, per stage.
- ``probe_latency_s``/``probe_failure``: how long the backend probe
  took and, on failure, its kind (``timeout``/``probe_rc``/
  ``no_probe_ok``).
- ``telemetry``: process-global metrics snapshot
  (`attach_metrics_snapshot`).
- ``goodput``: recent per-epoch goodput/MFU summaries from
  `analytics_zoo_tpu.perf.goodput` when an Estimator fit ran in this
  process (docs/observability.md).
- ``autotune``: ``{enabled, cache_hits, cache_misses, sweeps,
  source}`` provenance from `analytics_zoo_tpu.perf.autotune` —
  scripts/perf_sentinel.py splits tuned runs into their own ``-tuned``
  lineages keyed on ``enabled``.
- ``build_info``: package/jax versions, device kind, and the active
  ``ZOO_TPU_*`` flag fingerprint (`common/diagnostics.build_info` —
  the same record the ``zoo_tpu_build_info`` gauge exposes).

Exit code 0 iff real signal was banked (chip headline or at least one
fallback stage record).
"""

from __future__ import annotations

import time

import numpy as np


RTT_BOUND_NOTE = ("rtt_bound: the constant dispatch round-trip "
                  "dominates this chain; treat as a lower-confidence "
                  "number")


def flag_rtt_bound(rec: dict, rtt_bound: bool) -> dict:
    """Attach the shared quality note to a metric record when the
    measurement was round-trip-dominated (see time_chain)."""
    if rtt_bound:
        rec["quality"] = RTT_BOUND_NOTE
    return rec


def attach_metrics_snapshot(rec: dict) -> dict:
    """Embed the process-global telemetry snapshot
    (`common/observability.py`) in a bench JSON artifact under
    ``"telemetry"`` — so a bench run's step/ingest/serving metrics
    ride along with its headline number. No-op when nothing was
    recorded (raw jit chains bypass the instrumented layers)."""
    from analytics_zoo_tpu.common.observability import snapshot
    snap = snapshot()
    if snap:
        rec["telemetry"] = snap
    try:
        from analytics_zoo_tpu.perf.goodput import recent_summaries
        summaries = recent_summaries()
        if summaries:
            rec["goodput"] = summaries
    except Exception:
        pass  # goodput is optional decoration on the artifact
    try:
        # provenance: was this run tuned? perf_sentinel keys its
        # tuned-vs-heuristic lineage split on autotune.enabled, so a
        # tuned run can never masquerade as a heuristic-config win
        from analytics_zoo_tpu.perf import autotune
        rec["autotune"] = autotune.stats()
    except Exception:
        pass
    try:
        # provenance: package/jax versions, device kind, and the
        # ZOO_TPU_* flag fingerprint this run executed under — the
        # same record the zoo_tpu_build_info gauge exposes
        from analytics_zoo_tpu.common import diagnostics
        rec["build_info"] = diagnostics.build_info()
    except Exception:
        pass
    return rec


def dispatch_overhead(samples: int = 5) -> float:
    """Constant per-dispatch round-trip cost, min over ``samples``."""
    import jax
    import jax.numpy as jnp

    tiny = jax.jit(lambda a: a + 1.0).lower(
        jnp.zeros((), jnp.float32)).compile()
    float(np.asarray(tiny(jnp.zeros((), jnp.float32))))  # warm
    overhead = float("inf")
    for _ in range(samples):
        t0 = time.perf_counter()
        float(np.asarray(tiny(jnp.zeros((), jnp.float32))))
        overhead = min(overhead, time.perf_counter() - t0)
    return overhead


def time_chain(compiled, args, reps: int = 3,
               with_quality: bool = False):
    """Best wall time of ``compiled(*args)`` (last output = scalar
    loss fetched to host as the sync point) minus the dispatch
    overhead. Returns ``(dt_seconds, last_loss)`` — or with
    ``with_quality=True``, ``(dt, loss, rtt_bound)`` where
    ``rtt_bound`` flags a measurement the constant round-trip
    overhead dominates (dt after subtraction is under half the raw
    wall time — e.g. a sub-10ms chain over the ~66ms axon tunnel):
    such numbers are jitter, not throughput, and callers should
    label them or lengthen the chain."""
    def timed():
        t0 = time.perf_counter()
        out = compiled(*args)
        loss = out[-1] if isinstance(out, (list, tuple)) else out
        # the host fetch IS the sync point — it must complete before
        # the clock stops (a `return elapsed, fetch()` tuple evaluates
        # the elapsed time first and times only the async dispatch)
        loss_val = float(np.asarray(loss))
        return time.perf_counter() - t0, loss_val

    timed()                                   # warmup run
    overhead = dispatch_overhead()
    best_dt, loss = None, float("nan")
    for _ in range(reps):
        dt_i, loss = timed()
        best_dt = dt_i if best_dt is None else min(best_dt, dt_i)
    dt = max(best_dt - overhead, 1e-9)
    if with_quality:
        return dt, loss, dt < 0.5 * best_dt
    return dt, loss
