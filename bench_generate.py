"""Generation-path benchmark: continuous batching vs sequential decode.

Closed-loop multi-client harness over the decode fast path
(`pipeline/inference/generation.py` + `ContinuousBatcher`): N client
threads each submit generation requests (mixed prompt lengths and
decode budgets) as fast as results return, for a fixed wall-clock
window. Run twice:

- **continuous** — every client submits into the live
  `ContinuousBatcher`; sequences share ONE compiled decode step and
  join/leave at token boundaries (ORCA-style iteration scheduling);
- **continuous+levers** (only when a lever flag is set) — the same
  harness on a second engine with the requested capacity levers,
  so the artifact carries a levers-off/levers-on A/B on identical
  traffic;
- **sequential** — the per-request baseline: one compiled whole-loop
  `generate` at a time (`InferenceModel.generate`, batch 1),
  serialized the way per-request decode actually serializes.

Reports tokens/sec, request latency p50/p99, and mean time-to-first-
token for every mode. The levered window (or the plain continuous
one when no levers are set) also runs a small pool of closed-loop
TTFT probe clients: alternating short and LONG single-token requests
whose per-request latencies give `ttft_{short,long}_p{50,99}_ms` —
the chunked-prefill acceptance signal is long-prompt TTFT p99
staying within 1.5x of short-prompt p99 while decode traffic flows
(several probe clients so each shape's p99 rests on hundreds of
samples taken at realistic slot occupancy, not the max of a hundred
lightly-loaded ones). Note the CPU host
under-reports the levered mode's throughput: per-iteration dispatch
overhead dominates the tiny toy model, so speculation's extra
tokens/step (~9.7 vs ~5.6 levers-off in the committed artifact) do
not translate into CPU tokens/s the way they do on a
bandwidth-bound accelerator decode.

``--disagg`` adds the disaggregated-serving A/B on top (ISSUE 19 /
docs/serving.md §Disaggregation): the same closed-loop mix — sized
up so the decode pool saturates — through a ``DisaggRouter`` over
1 prefill + 2 decode replicas, measured twice: **disagg-inproc**
(blob hands off as a host dict) and **disagg-http** (the same
warmed pool engines behind stdlib HTTP front-ends, pages base64 on
the wire). Both windows run the TTFT probe: with prefill on its own
pool the long/short p99 ratio stays ≈1 even while every decode slot
is busy — the contention case a monolithic engine cannot shield —
and the artifact's ``disagg{...}`` block records the ratio plus the
per-window handoff latency quantiles from
``zoo_tpu_serving_gen_handoff_seconds``.

The capacity levers are A/B'd from the command line and recorded in
the artifact's sentinel key block: ``--prefill-chunk N`` (chunked
prefill), ``--kv-dtype f32|bf16|int8`` (paged-cache storage), and
``--spec-k N`` (speculative decoding with a half-width drafter; the
continuous record then carries ``spec_accept_rate`` and the realized
``tokens_per_step``). Prints ONE JSON line in the bench_common
artifact schema and ALSO writes it to ``BENCH_generate.json``:

    {"metric": "generate_throughput_tokens_per_sec",
     "unit": "tokens/sec", "value": N, "vs_baseline": null,
     "generate": {...}, "extra_metrics": [...], "telemetry": {...}}

The ``"generate"`` block (slots, page_size, max_context, clients) is
what `scripts/perf_sentinel.py` keys on to give generation runs their
own lineage — decode tokens/s is never compared against predict-path
rows/s. With ``--cpu-fallback`` the headline ``value`` is null and
the measured number moves to ``cpu_fallback_value`` (the schema's
rule: a null headline can never be mistaken for chip perf). The
acceptance gate is continuous >= sequential tokens/s at >= 4
concurrent clients.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

import numpy as np

_t_start = time.perf_counter()

# mixed workload, cycled per client: (prompt_len, max_new_tokens) —
# varied on both axes so admission is genuinely staggered and the
# prompt-bucket ladder is exercised past one shape
# short conversational shapes plus two long-prompt entries so the
# background mix actually exercises chunked prefill (PR 17): under
# monolithic prefill the long prompts inflate every neighbour's
# latency; under chunking they amortize one chunk per iteration
WORK_MIX = ((4, 16), (9, 24), (17, 8), (6, 32), (12, 16), (27, 12),
            (72, 8), (100, 6))

SLOTS = 8
SEQ_LEN = 128
VOCAB = 256

# TTFT probe shapes: single-token requests whose request latency IS
# the time to first token; the long one spans many prefill chunks.
# Several closed-loop probe clients run at once so the per-shape p99
# rests on hundreds of samples at realistic slot occupancy instead of
# being the max of ~100 lightly-loaded ones.
PROBE_SHORT, PROBE_LONG = 4, 100
PROBE_CLIENTS = 3


def _build_engine(prefill_chunk=0, spec_k=0, kv_dtype="f32",
                  slots=SLOTS, role="both"):
    from analytics_zoo_tpu import init_nncontext
    from analytics_zoo_tpu.pipeline.api.keras.layers.transformer \
        import TransformerLayer
    from analytics_zoo_tpu.pipeline.inference import InferenceModel

    from analytics_zoo_tpu.common import diagnostics

    init_nncontext(seed=0, log_level="WARNING")
    import jax
    # enough width that a decode step has real matmul traffic, small
    # enough that the CPU host finishes the window in seconds
    net = TransformerLayer(n_block=2, hidden_size=128, n_head=4,
                           seq_len=SEQ_LEN, vocab=VOCAB,
                           hidden_p_drop=0.0, attn_p_drop=0.0,
                           embed_p_drop=0.0)
    # param-init and loader compiles are deliberate bench setup, not
    # a storm (the engine excuses its own warm() internally)
    with diagnostics.expected_compiles():
        params = net.build(jax.random.key(0), (SEQ_LEN,))
        kw = dict(max_slots=slots, max_context=SEQ_LEN, page_size=16,
                  prefill_chunk=prefill_chunk, spec_k=spec_k,
                  cache_dtype=kv_dtype, role=role)
        if spec_k > 0:
            # half-width, half-depth drafter sharing the vocabulary
            drafter = TransformerLayer(n_block=1, hidden_size=64,
                                       n_head=4, seq_len=SEQ_LEN,
                                       vocab=VOCAB, hidden_p_drop=0.0,
                                       attn_p_drop=0.0,
                                       embed_p_drop=0.0)
            kw["drafter"] = drafter
            kw["drafter_params"] = drafter.build(jax.random.key(1),
                                                 (SEQ_LEN,))
        im = InferenceModel()
        im.load_generator(net, params, **kw)
    return im


def _ttft_mean_ms(before: "tuple[float, float]") -> "float | None":
    """Mean time-to-first-token over the window, from the serving
    histogram's (sum, count) delta. None when nothing was observed."""
    from analytics_zoo_tpu.common import observability as obs
    h = obs.histogram("zoo_tpu_serving_gen_ttft_seconds",
                      help="time from submit to first generated token")
    ds, dc = h.sum - before[0], h.count - before[1]
    return round(ds / dc * 1e3, 2) if dc else None


def _ttft_state() -> "tuple[float, float]":
    from analytics_zoo_tpu.common import observability as obs
    h = obs.histogram("zoo_tpu_serving_gen_ttft_seconds",
                      help="time from submit to first generated token")
    return h.sum, h.count


def _run_clients(submit, clients: int, duration_s: float):
    """Closed loop: every client submits back-to-back until the
    window closes. ``submit(prompt, max_new) -> token array``.
    Returns (tokens_done, request_latencies_s, errors)."""
    rs = np.random.RandomState(7)
    prompts = {n: rs.randint(1, VOCAB, size=n).tolist()
               for n, _ in WORK_MIX}
    stop_at = time.perf_counter() + duration_s
    lock = threading.Lock()
    lat, toks, errors = [], [0], [0]

    def client(cid: int):
        i = cid  # stagger the mix across clients
        while time.perf_counter() < stop_at:
            n, max_new = WORK_MIX[i % len(WORK_MIX)]
            i += 1
            t0 = time.perf_counter()
            try:
                out = submit(prompts[n], max_new)
            except Exception:
                with lock:
                    errors[0] += 1
                continue
            dt = time.perf_counter() - t0
            with lock:
                lat.append(dt)
                toks[0] += len(out)

    ts = [threading.Thread(target=client, args=(c,))
          for c in range(clients)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    return toks[0], lat, errors[0]


def _run_ttft_probe(submit, duration_s: float) -> dict:
    """PROBE_CLIENTS extra closed-loop clients alternating short/long
    single-token prompts while the mix clients keep the decode batch
    busy: each request's latency IS its TTFT. Returns per-shape
    p50/p99 (ms) and the long/short p99 ratio the chunked-prefill
    acceptance gate reads."""
    rs = np.random.RandomState(11)
    prompts = {n: rs.randint(1, VOCAB, size=n).tolist()
               for n in (PROBE_SHORT, PROBE_LONG)}
    samples = {PROBE_SHORT: [], PROBE_LONG: []}
    lock = threading.Lock()
    stop_at = time.perf_counter() + duration_s
    shapes = (PROBE_SHORT, PROBE_LONG)

    def client(cid: int):
        i = cid  # offset so clients interleave shapes
        while time.perf_counter() < stop_at:
            n = shapes[i % 2]
            i += 1
            t0 = time.perf_counter()
            try:
                submit(prompts[n], 1)
            except Exception:
                continue
            dt = (time.perf_counter() - t0) * 1e3
            with lock:
                samples[n].append(dt)

    ts = [threading.Thread(target=client, args=(c,))
          for c in range(PROBE_CLIENTS)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    out = {}
    for n, name in ((PROBE_SHORT, "short"), (PROBE_LONG, "long")):
        arr = np.asarray(samples[n]) if samples[n] else np.zeros((1,))
        out[f"ttft_{name}_p50_ms"] = round(
            float(np.percentile(arr, 50)), 2)
        out[f"ttft_{name}_p99_ms"] = round(
            float(np.percentile(arr, 99)), 2)
        out[f"ttft_{name}_samples"] = len(samples[n])
    p99s, p99l = out["ttft_short_p99_ms"], out["ttft_long_p99_ms"]
    out["ttft_long_vs_short_p99"] = (
        round(p99l / p99s, 2) if p99s else None)
    return out


def _counter_value(name: str) -> float:
    from analytics_zoo_tpu.common import observability as obs
    return obs.counter(name, help=name).value


def _handoff_hist():
    from analytics_zoo_tpu.common import observability as obs
    return obs.histogram(
        "zoo_tpu_serving_gen_handoff_seconds",
        help="prefill-pool export to decode-pool admission latency")


def _hist_counts(h) -> "list[int]":
    """Per-bucket counts (last = +Inf overflow) from the public
    cumulative exposition, so window deltas can be quantiled."""
    cum = [c for _, c in h.cumulative()]
    return [cum[0]] + [b - a for a, b in zip(cum, cum[1:])]


def _hist_window_quantiles(h, before: "list[int]") -> dict:
    """p50/p99 (ms) + count of the observations since ``before``
    (a `_hist_counts` snapshot) — per-mode handoff latency even
    though the histogram accumulates across the whole bench."""
    from analytics_zoo_tpu.common.observability import bucket_quantile
    delta = [b - a for a, b in zip(before, _hist_counts(h))]
    n = sum(delta)
    if not n:
        return {"handoffs": 0}
    return {
        "handoffs": n,
        "handoff_p50_ms": round(
            bucket_quantile(h.buckets, delta, 0.5) * 1e3, 2),
        "handoff_p99_ms": round(
            bucket_quantile(h.buckets, delta, 0.99) * 1e3, 2),
    }


def _measure_disagg(mode: str, router, im, clients: int,
                    duration_s: float) -> dict:
    """One disagg window: the standard closed-loop mix (sized to
    saturate the decode pool) + the TTFT probe, annotated with the
    window's handoff latency quantiles."""
    h = _handoff_hist()
    before = _hist_counts(h)
    rec = measure(mode, im, clients, duration_s, probe_ttft=True,
                  router=router)
    rec.update(_hist_window_quantiles(h, before))
    return rec


def measure(mode: str, im, clients: int, duration_s: float,
            probe_ttft: bool = False, router=None) -> dict:
    from analytics_zoo_tpu.pipeline.inference import ContinuousBatcher

    engine = im.generator
    cb = None
    if router is not None:
        # disaggregated path: the router fans prompts to the prefill
        # pool and ships KV pages to the decode pool (caller owns the
        # router's lifecycle — pools warm at router.start())
        def submit(prompt, max_new):
            return router.submit(prompt,
                                 max_new_tokens=max_new).result(120)
    elif mode.startswith("continuous"):
        cb = ContinuousBatcher(engine, queue_depth=512).start()

        def submit(prompt, max_new):
            return cb.submit(prompt,
                             max_new_tokens=max_new).result(120)
    else:
        # sequential per-request decode: whole-loop generate, batch 1,
        # one at a time — the engine is single-driver by contract, and
        # that serialization IS the baseline being measured
        seq_lock = threading.Lock()

        def submit(prompt, max_new):
            with seq_lock:
                return im.generate(prompt,
                                   max_new_tokens=max_new)[0]
    stream = cb is not None or router is not None
    probe_rec = {}
    try:
        # warmup outside the window: every (bucket, budget) shape in
        # the mix compiles here, not inside the measurement. The
        # sequential path compiles on THIS thread (the continuous
        # one brackets its own warm()), so excuse the burst from the
        # recompile-storm detector — it is deliberate.
        from analytics_zoo_tpu.common import diagnostics
        with diagnostics.expected_compiles():
            for n, max_new in WORK_MIX:
                submit(list(range(1, n + 1)), max_new)
            if stream:
                submit(list(range(1, PROBE_LONG + 1)), 1)  # probe
                submit(list(range(1, PROBE_SHORT + 1)), 1)
        ttft0 = _ttft_state()
        tok0 = _counter_value("zoo_tpu_serving_gen_tokens_total")
        step0 = _counter_value("zoo_tpu_serving_gen_steps_total")
        spec0 = (engine.spec_proposed, engine.spec_accepted) \
            if getattr(engine, "spec_k", 0) else None
        t0 = time.perf_counter()
        if stream and probe_ttft:
            probe = {}
            pt = threading.Thread(target=lambda: probe.update(
                _run_ttft_probe(submit, duration_s)))
            pt.start()
        tokens, lat, errors = _run_clients(submit, clients,
                                           duration_s)
        if stream and probe_ttft:
            pt.join()
            probe_rec = probe
        window = time.perf_counter() - t0
        d_tok = _counter_value(
            "zoo_tpu_serving_gen_tokens_total") - tok0
        d_step = _counter_value(
            "zoo_tpu_serving_gen_steps_total") - step0
    finally:
        if cb is not None:
            cb.stop()
    lat_ms = np.asarray(lat) * 1e3 if lat else np.zeros((1,))
    rec = {
        "mode": mode,
        "clients": clients,
        "window_s": round(window, 2),
        "requests": len(lat),
        "tokens_per_sec": round(tokens / window, 1),
        "requests_per_sec": round(len(lat) / window, 1),
        "latency_p50_ms": round(float(np.percentile(lat_ms, 50)), 2),
        "latency_p99_ms": round(float(np.percentile(lat_ms, 99)), 2),
        "errors": errors,
    }
    ttft = _ttft_mean_ms(ttft0)
    # sequential has no streaming boundary: first token arrives with
    # the rest, so mean latency IS its time-to-first-token
    rec["ttft_mean_ms"] = (ttft if stream
                           else round(float(np.mean(lat_ms)), 2))
    if stream:
        rec.update(probe_rec)
        # realized tokens per decode iteration: > 1 only when
        # speculation lands multi-token rounds
        rec["tokens_per_step"] = (round(d_tok / d_step, 2)
                                  if d_step else None)
        if spec0 is not None:
            dp = engine.spec_proposed - spec0[0]
            da = engine.spec_accepted - spec0[1]
            rec["spec_proposed"] = int(dp)
            rec["spec_accepted"] = int(da)
            rec["spec_accept_rate"] = (round(da / dp, 3)
                                       if dp else None)
    print(f"# [{mode}] {rec['tokens_per_sec']} tok/s "
          f"{rec['requests_per_sec']} req/s "
          f"p50={rec['latency_p50_ms']}ms "
          f"p99={rec['latency_p99_ms']}ms "
          f"ttft={rec['ttft_mean_ms']}ms errors={errors}",
          file=sys.stderr, flush=True)
    return rec


def main():
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--clients", type=int, default=int(os.environ.get(
        "ZOO_TPU_BENCH_GEN_CLIENTS", "6")))
    ap.add_argument("--duration", type=float,
                    default=float(os.environ.get(
                        "ZOO_TPU_BENCH_GEN_DURATION", "6")))
    ap.add_argument("--prefill-chunk", type=int, default=int(
        os.environ.get("ZOO_TPU_PREFILL_CHUNK", "0")),
        help="chunked prefill: prompt tokens written per batcher "
        "iteration (0 = whole-prompt bucketed prefill)")
    ap.add_argument("--spec-k", type=int, default=int(
        os.environ.get("ZOO_TPU_SPEC_K", "0")),
        help="speculative decoding: draft tokens per verify round "
        "(0 = off); the drafter is a half-width half-depth stack")
    ap.add_argument("--kv-dtype", default=os.environ.get(
        "ZOO_TPU_KV_DTYPE", "f32"),
        choices=("f32", "bf16", "int8"),
        help="paged KV cache storage dtype")
    ap.add_argument("--disagg", action="store_true",
                    help="add the disaggregated-serving A/B: the "
                    "same mix through a DisaggRouter (1 prefill + 2 "
                    "decode replicas) in-process AND over an HTTP "
                    "hop, with the decode pool saturated; the "
                    "artifact gains a disagg{...} block and its own "
                    "perf_sentinel lineage")
    ap.add_argument("--cpu-fallback", action="store_true",
                    help="pin the run to the host CPU backend; the "
                    "measurement lands in cpu_fallback_value and the "
                    "chip headline stays null")
    args = ap.parse_args()
    if args.disagg and args.spec_k > 0:
        ap.error("--disagg is incompatible with --spec-k (the "
                 "verify step needs prefill+decode on one engine)")

    import jax
    if args.cpu_fallback:
        jax.config.update("jax_platforms", "cpu")
    devices = jax.devices()
    print(f"# backend={devices[0].platform} "
          f"n_devices={len(devices)} clients={args.clients} "
          f"duration={args.duration}s slots={SLOTS} "
          f"prefill_chunk={args.prefill_chunk} "
          f"spec_k={args.spec_k} kv_dtype={args.kv_dtype}",
          file=sys.stderr, flush=True)

    levers_on = (args.prefill_chunk > 0 or args.spec_k > 0
                 or args.kv_dtype != "f32")
    # the A/B: the baseline (levers off) keeps the tokens/s lineage
    # comparable across PRs — continuous vs sequential on identical
    # engines — while the levered run carries the TTFT probe,
    # acceptance-rate and tokens/step fields the PR 17 gate reads
    im = _build_engine()
    continuous = measure("continuous", im, args.clients,
                         args.duration, probe_ttft=not levers_on)
    levered = None
    if levers_on:
        im_lev = _build_engine(prefill_chunk=args.prefill_chunk,
                               spec_k=args.spec_k,
                               kv_dtype=args.kv_dtype)
        levered = measure("continuous+levers", im_lev, args.clients,
                          args.duration, probe_ttft=True)
    sequential = measure("sequential", im, args.clients,
                         args.duration)
    speedup = (continuous["tokens_per_sec"]
               / sequential["tokens_per_sec"]
               if sequential["tokens_per_sec"] else float("inf"))
    print(f"# continuous speedup={speedup:.2f}x over sequential "
          f"per-request decode ({args.clients} clients)",
          file=sys.stderr, flush=True)

    disagg_inproc = disagg_http = disagg_block = None
    if args.disagg:
        from analytics_zoo_tpu.pipeline.inference import \
            ContinuousBatcher
        from analytics_zoo_tpu.pipeline.inference.fleet import (
            DisaggRouter, HttpDisaggReplica)
        from analytics_zoo_tpu.pipeline.inference.serving import \
            InferenceServer
        # small per-replica pools so the closed-loop mix actually
        # saturates the decode pool (the gate's contention case);
        # the prefill pool runs whole-prompt bucketed prefill —
        # chunking exists to protect co-resident decode, which
        # disaggregation removes
        d_slots = 4
        n_prefill, n_decode = 1, 2
        d_clients = max(args.clients, n_decode * d_slots + 2)
        im_d = _build_engine(kv_dtype=args.kv_dtype, slots=d_slots)
        router = DisaggRouter.for_engine(
            im_d.generator, n_prefill=n_prefill, n_decode=n_decode)
        router.start()
        disagg_inproc = _measure_disagg(
            "disagg-inproc", router, im_d, d_clients, args.duration)
        router.drain()
        pool = [(r.engine, r.role)
                for r in router.prefill + router.decode]
        router.stop()
        # HTTP hop: the SAME warmed pool engines behind stdlib HTTP
        # front-ends — the delta vs in-process is pure wire cost
        # (base64 pages + two request hops), no new compiles
        servers, reps = [], {"prefill": [], "decode": []}
        for i, (eng, role) in enumerate(pool):
            srv = InferenceServer(im_d, port=0, batcher=None,
                                  gen_batcher=ContinuousBatcher(eng))
            srv.start()
            servers.append(srv)
            reps[role].append(HttpDisaggReplica(
                f"http://127.0.0.1:{srv.port}", role,
                name=f"http-{role}{i}"))
        router2 = DisaggRouter(reps["prefill"], reps["decode"])
        router2.start()
        disagg_http = _measure_disagg(
            "disagg-http", router2, im_d, d_clients, args.duration)
        router2.stop()
        for srv in servers:
            srv.stop()
        ratio = disagg_inproc.get("ttft_long_vs_short_p99")
        disagg_block = {
            "prefill_replicas": n_prefill,
            "decode_replicas": n_decode,
            "slots_per_replica": d_slots,
            "page_size": 16,
            "kv_dtype": args.kv_dtype,
            "mix_clients": d_clients,
            "decode_slots": n_decode * d_slots,
            "ttft_long_vs_short_p99": ratio,
            "handoff_p50_ms": disagg_inproc.get("handoff_p50_ms"),
            "handoff_p99_ms": disagg_inproc.get("handoff_p99_ms"),
            "handoff_http_p50_ms": disagg_http.get(
                "handoff_p50_ms"),
            "handoff_http_p99_ms": disagg_http.get(
                "handoff_p99_ms"),
        }
        print(f"# disagg TTFT long/short p99 ratio={ratio} "
              f"(gate: <= 1.1 with the decode pool saturated); "
              f"handoff p99 in-proc="
              f"{disagg_block['handoff_p99_ms']}ms http="
              f"{disagg_block['handoff_http_p99_ms']}ms",
              file=sys.stderr, flush=True)

    headline = continuous["tokens_per_sec"]
    rec = {
        "metric": "generate_throughput_tokens_per_sec",
        "unit": "tokens/sec",
        "value": None if args.cpu_fallback else headline,
        "vs_baseline": None,
        # the sentinel keys on this block: generation runs are their
        # own lineage, never compared against predict-path rows
        "generate": {
            "slots": SLOTS,
            "page_size": 16,
            "max_context": SEQ_LEN,
            "clients": args.clients,
            "prefill_chunk": args.prefill_chunk,
            "spec_k": args.spec_k,
            "kv_dtype": args.kv_dtype,
        },
        "extra_metrics": [
            continuous,
            *([levered] if levered else []),
            sequential,
            *([disagg_inproc] if disagg_inproc else []),
            *([disagg_http] if disagg_http else []),
            {"metric": "generate_continuous_speedup",
             "value": round(speedup, 2), "unit": "x"},
        ],
    }
    if disagg_block is not None:
        # perf_sentinel keys on this block: disagg runs are their own
        # lineage, never compared against monolithic decode rows
        rec["disagg"] = disagg_block
    if args.cpu_fallback:
        rec["cpu_fallback_value"] = headline
        rec["fallback"] = (f"cpu clients={args.clients} "
                           f"duration={args.duration}s")
    from bench_common import attach_metrics_snapshot
    rec = attach_metrics_snapshot(rec)
    out_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "BENCH_generate.json")
    with open(out_path, "w", encoding="utf-8") as fh:
        json.dump(rec, fh)
        fh.write("\n")
    print(json.dumps(rec), flush=True)
    print(f"# wrote {out_path}", file=sys.stderr)
    print(f"# total={time.perf_counter() - _t_start:.1f}s",
          file=sys.stderr)


if __name__ == "__main__":
    main()
