"""Generation-path benchmark: continuous batching vs sequential decode.

Closed-loop multi-client harness over the decode fast path
(`pipeline/inference/generation.py` + `ContinuousBatcher`): N client
threads each submit generation requests (mixed prompt lengths and
decode budgets) as fast as results return, for a fixed wall-clock
window. Run twice:

- **continuous** — every client submits into the live
  `ContinuousBatcher`; sequences share ONE compiled decode step and
  join/leave at token boundaries (ORCA-style iteration scheduling);
- **sequential** — the per-request baseline: one compiled whole-loop
  `generate` at a time (`InferenceModel.generate`, batch 1),
  serialized the way per-request decode actually serializes.

Reports tokens/sec, request latency p50/p99, and mean time-to-first-
token for both modes. Prints ONE JSON line in the bench_common
artifact schema and ALSO writes it to ``BENCH_generate.json``:

    {"metric": "generate_throughput_tokens_per_sec",
     "unit": "tokens/sec", "value": N, "vs_baseline": null,
     "generate": {...}, "extra_metrics": [...], "telemetry": {...}}

The ``"generate"`` block (slots, page_size, max_context, clients) is
what `scripts/perf_sentinel.py` keys on to give generation runs their
own lineage — decode tokens/s is never compared against predict-path
rows/s. With ``--cpu-fallback`` the headline ``value`` is null and
the measured number moves to ``cpu_fallback_value`` (the schema's
rule: a null headline can never be mistaken for chip perf). The
acceptance gate is continuous >= sequential tokens/s at >= 4
concurrent clients.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

import numpy as np

_t_start = time.perf_counter()

# mixed workload, cycled per client: (prompt_len, max_new_tokens) —
# varied on both axes so admission is genuinely staggered and the
# prompt-bucket ladder is exercised past one shape
WORK_MIX = ((4, 16), (9, 24), (17, 8), (6, 32), (12, 16), (27, 12))

SLOTS = 8
SEQ_LEN = 128
VOCAB = 256


def _build_engine():
    from analytics_zoo_tpu import init_nncontext
    from analytics_zoo_tpu.pipeline.api.keras.layers.transformer \
        import TransformerLayer
    from analytics_zoo_tpu.pipeline.inference import InferenceModel

    init_nncontext(seed=0, log_level="WARNING")
    import jax
    # enough width that a decode step has real matmul traffic, small
    # enough that the CPU host finishes the window in seconds
    net = TransformerLayer(n_block=2, hidden_size=128, n_head=4,
                           seq_len=SEQ_LEN, vocab=VOCAB,
                           hidden_p_drop=0.0, attn_p_drop=0.0,
                           embed_p_drop=0.0)
    params = net.build(jax.random.key(0), (SEQ_LEN,))
    im = InferenceModel()
    im.load_generator(net, params, max_slots=SLOTS,
                      max_context=SEQ_LEN, page_size=16)
    return im


def _ttft_mean_ms(before: "tuple[float, float]") -> "float | None":
    """Mean time-to-first-token over the window, from the serving
    histogram's (sum, count) delta. None when nothing was observed."""
    from analytics_zoo_tpu.common import observability as obs
    h = obs.histogram("zoo_tpu_serving_gen_ttft_seconds",
                      help="time from submit to first generated token")
    ds, dc = h.sum - before[0], h.count - before[1]
    return round(ds / dc * 1e3, 2) if dc else None


def _ttft_state() -> "tuple[float, float]":
    from analytics_zoo_tpu.common import observability as obs
    h = obs.histogram("zoo_tpu_serving_gen_ttft_seconds",
                      help="time from submit to first generated token")
    return h.sum, h.count


def _run_clients(submit, clients: int, duration_s: float):
    """Closed loop: every client submits back-to-back until the
    window closes. ``submit(prompt, max_new) -> token array``.
    Returns (tokens_done, request_latencies_s, errors)."""
    rs = np.random.RandomState(7)
    prompts = {n: rs.randint(1, VOCAB, size=n).tolist()
               for n, _ in WORK_MIX}
    stop_at = time.perf_counter() + duration_s
    lock = threading.Lock()
    lat, toks, errors = [], [0], [0]

    def client(cid: int):
        i = cid  # stagger the mix across clients
        while time.perf_counter() < stop_at:
            n, max_new = WORK_MIX[i % len(WORK_MIX)]
            i += 1
            t0 = time.perf_counter()
            try:
                out = submit(prompts[n], max_new)
            except Exception:
                with lock:
                    errors[0] += 1
                continue
            dt = time.perf_counter() - t0
            with lock:
                lat.append(dt)
                toks[0] += len(out)

    ts = [threading.Thread(target=client, args=(c,))
          for c in range(clients)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    return toks[0], lat, errors[0]


def measure(mode: str, im, clients: int, duration_s: float) -> dict:
    from analytics_zoo_tpu.pipeline.inference import ContinuousBatcher

    engine = im.generator
    cb = None
    if mode == "continuous":
        cb = ContinuousBatcher(engine, queue_depth=512).start()

        def submit(prompt, max_new):
            return cb.submit(prompt,
                             max_new_tokens=max_new).result(120)
    else:
        # sequential per-request decode: whole-loop generate, batch 1,
        # one at a time — the engine is single-driver by contract, and
        # that serialization IS the baseline being measured
        seq_lock = threading.Lock()

        def submit(prompt, max_new):
            with seq_lock:
                return im.generate(prompt,
                                   max_new_tokens=max_new)[0]
    try:
        # warmup outside the window: every (bucket, budget) shape in
        # the mix compiles here, not inside the measurement
        for n, max_new in WORK_MIX:
            submit(list(range(1, n + 1)), max_new)
        ttft0 = _ttft_state()
        t0 = time.perf_counter()
        tokens, lat, errors = _run_clients(submit, clients,
                                           duration_s)
        window = time.perf_counter() - t0
    finally:
        if cb is not None:
            cb.stop()
    lat_ms = np.asarray(lat) * 1e3 if lat else np.zeros((1,))
    rec = {
        "mode": mode,
        "clients": clients,
        "window_s": round(window, 2),
        "requests": len(lat),
        "tokens_per_sec": round(tokens / window, 1),
        "requests_per_sec": round(len(lat) / window, 1),
        "latency_p50_ms": round(float(np.percentile(lat_ms, 50)), 2),
        "latency_p99_ms": round(float(np.percentile(lat_ms, 99)), 2),
        "errors": errors,
    }
    ttft = _ttft_mean_ms(ttft0)
    # sequential has no streaming boundary: first token arrives with
    # the rest, so mean latency IS its time-to-first-token
    rec["ttft_mean_ms"] = (ttft if mode == "continuous"
                           else round(float(np.mean(lat_ms)), 2))
    print(f"# [{mode}] {rec['tokens_per_sec']} tok/s "
          f"{rec['requests_per_sec']} req/s "
          f"p50={rec['latency_p50_ms']}ms "
          f"p99={rec['latency_p99_ms']}ms "
          f"ttft={rec['ttft_mean_ms']}ms errors={errors}",
          file=sys.stderr, flush=True)
    return rec


def main():
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--clients", type=int, default=int(os.environ.get(
        "ZOO_TPU_BENCH_GEN_CLIENTS", "6")))
    ap.add_argument("--duration", type=float,
                    default=float(os.environ.get(
                        "ZOO_TPU_BENCH_GEN_DURATION", "6")))
    ap.add_argument("--cpu-fallback", action="store_true",
                    help="pin the run to the host CPU backend; the "
                    "measurement lands in cpu_fallback_value and the "
                    "chip headline stays null")
    args = ap.parse_args()

    import jax
    if args.cpu_fallback:
        jax.config.update("jax_platforms", "cpu")
    devices = jax.devices()
    print(f"# backend={devices[0].platform} "
          f"n_devices={len(devices)} clients={args.clients} "
          f"duration={args.duration}s slots={SLOTS}",
          file=sys.stderr, flush=True)

    im = _build_engine()
    continuous = measure("continuous", im, args.clients,
                         args.duration)
    sequential = measure("sequential", im, args.clients,
                         args.duration)
    speedup = (continuous["tokens_per_sec"]
               / sequential["tokens_per_sec"]
               if sequential["tokens_per_sec"] else float("inf"))
    print(f"# continuous speedup={speedup:.2f}x over sequential "
          f"per-request decode ({args.clients} clients)",
          file=sys.stderr, flush=True)

    headline = continuous["tokens_per_sec"]
    rec = {
        "metric": "generate_throughput_tokens_per_sec",
        "unit": "tokens/sec",
        "value": None if args.cpu_fallback else headline,
        "vs_baseline": None,
        # the sentinel keys on this block: generation runs are their
        # own lineage, never compared against predict-path rows
        "generate": {
            "slots": SLOTS,
            "page_size": 16,
            "max_context": SEQ_LEN,
            "clients": args.clients,
        },
        "extra_metrics": [
            continuous, sequential,
            {"metric": "generate_continuous_speedup",
             "value": round(speedup, 2), "unit": "x"},
        ],
    }
    if args.cpu_fallback:
        rec["cpu_fallback_value"] = headline
        rec["fallback"] = (f"cpu clients={args.clients} "
                           f"duration={args.duration}s")
    from bench_common import attach_metrics_snapshot
    rec = attach_metrics_snapshot(rec)
    out_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "BENCH_generate.json")
    with open(out_path, "w", encoding="utf-8") as fh:
        json.dump(rec, fh)
        fh.write("\n")
    print(json.dumps(rec), flush=True)
    print(f"# wrote {out_path}", file=sys.stderr)
    print(f"# total={time.perf_counter() - _t_start:.1f}s",
          file=sys.stderr)


if __name__ == "__main__":
    main()
