# Developer entry points. The sandbox CI has no package egress, so the
# three real-pyspark `local[4]` tests importorskip there; the docker
# image installs pyspark at build time (network available), and
# `make docker-test` is where they run for real — 0 pyspark skips.

IMAGE ?= analytics-zoo-tpu

.PHONY: test docker-build docker-test docker-test-spark dist docs \
    lint obs-smoke fused-conformance flops-audit serving-smoke \
    bench-serving bench-serving-fleet trace-smoke trace-report \
    slo-smoke perf-sentinel fleet-smoke generate-smoke \
    bench-generate chaos-smoke autotune autotune-smoke \
    dashboard-smoke

# unit tests plus the end-to-end telemetry smokes (metrics
# exposition, tracing, SLO control loop), so `make test` proves the
# observability stack, not just the library; the perf sentinel runs
# advisory here so every test run prints the bench trajectory
test:
	python -m pytest tests/ -x -q
	$(MAKE) obs-smoke
	$(MAKE) trace-smoke
	$(MAKE) slo-smoke
	$(MAKE) fleet-smoke
	$(MAKE) generate-smoke
	$(MAKE) chaos-smoke
	$(MAKE) autotune-smoke
	$(MAKE) dashboard-smoke
	python scripts/perf_sentinel.py --advisory

# conv+BN (+ residual-epilogue) conformance: the exact Pallas kernel
# code paths the fused ResNet runs on chip, exercised under the
# interpreter on the host CPU — values, gradients (Pallas vs XLA
# backward), moving state, bf16, padded grids, DP sharding. Tier-1
# safe; documented next to the MFU roofline in PERF.md.
fused-conformance:
	JAX_PLATFORMS=cpu python -m pytest tests/test_conv_bn.py -q

# telemetry end-to-end: 2 train steps + 1 served request, then assert
# the /metrics exposition carries every layer (docs/observability.md)
obs-smoke:
	JAX_PLATFORMS=cpu python scripts/obs_smoke.py

# tracing end-to-end: 3 train steps + 1 traced request (X-Zoo-Trace-Id
# echo, /debug/traces, chrome-trace export) — docs/observability.md
trace-smoke:
	JAX_PLATFORMS=cpu python scripts/trace_smoke.py

# SLO control loop end-to-end: shipped serving objectives on
# /debug/slo, a driven error burst trips the error-rate breach and
# the breach/anomaly counters increment (docs/slo.md)
slo-smoke:
	JAX_PLATFORMS=cpu python scripts/slo_smoke.py

# perf-regression sentinel over BENCH_r*.json / BENCH_serving.json:
# trajectory table + exit 1 when the newest round regressed >10%
# vs the best comparable (same-lineage) prior value (docs/slo.md)
perf-sentinel:
	python scripts/perf_sentinel.py

# offline report over a ZOO_TPU_EVENT_LOG JSONL: per-step timeline,
# top-N slowest requests, anomaly digest, optional Perfetto export
EVENTS ?= /tmp/zoo_tpu_trace_smoke.events.jsonl
trace-report:
	python scripts/trace_report.py --events $(EVENTS)

# executed-FLOPs audit of the ResNet-50 train step, phase backward
# off vs on (lowering only — CPU-safe, no chip; docs/perf_flags.md)
flops-audit:
	JAX_PLATFORMS=cpu python scripts/flops_audit.py --image 96

# dynamic-batching end-to-end: batched server (default front-end),
# mixed-size concurrent requests, exact outputs, warmed buckets,
# queue metrics on /metrics (docs/serving.md)
serving-smoke:
	JAX_PLATFORMS=cpu python scripts/serving_smoke.py

# batched-vs-unbatched serving throughput on the host CPU backend
# (the chip headline stays null; see bench_serving.py)
bench-serving:
	JAX_PLATFORMS=cpu python bench_serving.py --cpu-fallback

# fleet A/B sweep: 1 replica vs N replicas behind the router, writes
# BENCH_serving_fleet.json (its own perf-sentinel lineage — never
# compared against single-process serving rows)
bench-serving-fleet:
	JAX_PLATFORMS=cpu python bench_serving.py --cpu-fallback \
	    --replicas 4

# decode fast path end-to-end: compiled generate loop must EXACTLY
# match a naive uncached re-forward reference, then mixed concurrent
# /generate requests through the continuous batcher (docs/serving.md)
generate-smoke:
	JAX_PLATFORMS=cpu python scripts/generate_smoke.py

# continuous batching vs sequential per-request decode on the host
# CPU backend; writes BENCH_generate.json (its own perf-sentinel
# lineage — decode tokens/s is never compared against predict rows/s).
# Capacity levers on: chunked prefill (chunk sized to ~one decode
# iteration's compute on this backend) + speculative decoding, so the
# artifact carries the TTFT short/long probe and acceptance-rate
# fields the PR 17 gate reads.
bench-generate:
	JAX_PLATFORMS=cpu python bench_generate.py --cpu-fallback \
	    --prefill-chunk 64 --spec-k 2

# chaos end-to-end: injected kill/straggler/queue-wedge faults under
# concurrent load (zero lost acked requests), then a canary rollout
# auto-rolled-back by an injected error burst and a clean re-roll
# promoted, all observable on /debug/rollout (docs/robustness.md)
chaos-smoke:
	JAX_PLATFORMS=cpu python scripts/chaos_smoke.py

# replicated-fleet end-to-end: 2-replica CPU fleet, mixed concurrent
# load with exact outputs, one replica killed mid-load (zero lost
# acked requests), ejected, healed, re-admitted (docs/serving.md)
fleet-smoke:
	JAX_PLATFORMS=cpu python scripts/fleet_smoke.py

# populate the persistent autotune cache for the bench shapes
# (ZOO_TPU_AUTOTUNE=1 sweeps on first sight; docs/autotune.md), then
# print the decision table. chip_session.sh runs this before the
# benches and commits the refreshed v5e defaults table.
autotune:
	ZOO_TPU_AUTOTUNE=1 python scripts/autotune_report.py --sweep

# autotuner lifecycle end-to-end on CPU: sweep two shapes
# (interpret-guarded), persist, reload in a FRESH process as pure
# cache hits (zero sweeps, counter-asserted), report renders
autotune-smoke:
	JAX_PLATFORMS=cpu python scripts/autotune_smoke.py

# metric-history plane end-to-end: MetricHistory sampling cost under
# a byte cap, capacity_forecast firing with a finite KV-page ETA
# BEFORE saturation, /debug/metrics/history + /debug/dashboard on
# both HTTP front-ends, fleet-merged series (docs/observability.md)
dashboard-smoke:
	JAX_PLATFORMS=cpu python scripts/dashboard_smoke.py

docker-build:
	docker build -t $(IMAGE) -f docker/Dockerfile .

# full suite inside the image (CPU mesh; includes the pyspark tier)
docker-test: docker-build
	docker run --rm -e JAX_PLATFORMS=cpu \
	    -e XLA_FLAGS=--xla_force_host_platform_device_count=8 \
	    $(IMAGE) python -m pytest tests -q

# just the three environment-bound pyspark tests, verbose — proves
# the suite runs with 0 pyspark skips where pyspark is installable
docker-test-spark: docker-build
	docker run --rm -e JAX_PLATFORMS=cpu \
	    -e XLA_FLAGS=--xla_force_host_platform_device_count=8 \
	    $(IMAGE) python -m pytest tests/test_spark_ingest.py \
	    tests/test_nnframes.py -q -rs

docs:
	JAX_PLATFORMS=cpu python scripts/gen_api_docs.py

dist:
	bash scripts/make-dist.sh

lint:
	python scripts/lint.py
