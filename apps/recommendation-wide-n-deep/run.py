"""Wide&Deep recommendation app (reference
`apps/recommendation-wide-n-deep/wide_n_deep.ipynb`): the ml-1m
workflow — feature assembly (wide base/cross, indicators, id
embeddings, continuous age), `WideAndDeep` training with Adam +
class_nll, then `predict_user_item_pair` / `recommend_for_user` /
`recommend_for_item`.

The full recipe lives in
`analytics_zoo_tpu/examples/wide_and_deep.py` (reference
`Ml1mWideAndDeep.scala`); this app drives it at tutorial scale and
reports the ranking surfaces, with every knob exposed."""

from __future__ import annotations

import sys


def main(argv=None):
    from analytics_zoo_tpu.examples.wide_and_deep import main as run
    return run(argv if argv is not None else sys.argv[1:])


if __name__ == "__main__":
    main()
