"""Image-augmentation app (reference
`apps/image-augmentation/image-augmentation.ipynb`): the notebook
walks every image transformer over one test image and displays each
result; this runs the same gallery through `feature.image` —
ImageSet.read → transformer → written PNG per step — plus the chained
random pipeline the training recipes use.

Pass ``--image`` for a real photo; omitted, a synthetic scene is
generated so the app runs offline. Outputs land in ``--out-dir``.
"""

from __future__ import annotations

import argparse
import os
import tempfile

import numpy as np


def synth_image(path: str, rng) -> None:
    from PIL import Image
    h, w = 240, 320
    yy, xx = np.mgrid[0:h, 0:w].astype(np.float32)
    img = np.stack([
        120 + 80 * np.sin(2 * np.pi * xx / w),
        100 + 60 * np.cos(2 * np.pi * yy / h),
        140 + 50 * np.sin(2 * np.pi * (xx + yy) / (h + w)),
    ], -1) + rng.randn(h, w, 3) * 8
    Image.fromarray(np.clip(img, 0, 255).astype(np.uint8)).save(path)


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--image", default=None,
                   help="input image path (local or fsspec scheme); "
                        "omit for a synthetic test image")
    p.add_argument("--out-dir", default=None,
                   help="where the per-transformer PNGs go "
                        "(default: a temp dir)")
    args = p.parse_args(argv)

    from PIL import Image

    from analytics_zoo_tpu import init_nncontext
    from analytics_zoo_tpu.feature.common import ChainedPreprocessing
    from analytics_zoo_tpu.feature.image import ImageSet
    from analytics_zoo_tpu.feature.image import transforms as T

    init_nncontext(seed=0)
    rng = np.random.RandomState(0)
    path = args.image
    if path is None:
        path = os.path.join(tempfile.mkdtemp(prefix="aug_"),
                            "test.png")
        synth_image(path, rng)
    out_dir = args.out_dir or tempfile.mkdtemp(prefix="aug_out_")
    os.makedirs(out_dir, exist_ok=True)

    # the notebook's gallery, one transformer at a time
    gallery = [
        ("brightness", T.ImageBrightness(0.0, 32.0, seed=0)),
        ("hue", T.ImageHue(-18.0, 18.0, seed=0)),
        ("saturation", T.ImageSaturation(10.0, 20.0, seed=0)),
        ("channel_order", T.ImageChannelOrder()),
        ("color_jitter", T.ImageColorJitter(seed=0)),
        ("resize", T.ImageResize(300, 300)),
        ("aspect_scale", T.ImageAspectScale(200, max_size=3000)),
        ("random_aspect_scale",
         T.ImageRandomAspectScale([100, 300], max_size=3000, seed=0)),
        ("channel_normalize",
         T.ImageChannelNormalize(20.0, 30.0, 40.0, 2.0, 3.0, 4.0)),
        ("center_crop", T.ImageCenterCrop(200, 200)),
        ("random_crop", T.ImageRandomCrop(200, 200, seed=0)),
        ("fixed_crop", T.ImageFixedCrop(0.0, 0.0, 200.0, 200.0,
                                        normalized=False)),
        ("filler", T.ImageFiller(0.0, 0.0, 0.5, 0.5, 255)),
        ("expand", T.ImageExpand(means=(123, 117, 104),
                                 max_expand_ratio=2.0, seed=0)),
        ("hflip", T.ImageHFlip()),
    ]
    written = []
    for name, tr in gallery:
        iset = ImageSet.read(path).transform(tr)
        img = np.asarray(iset.features[0].image)
        if img.dtype != np.uint8:      # normalized outputs: rescale
            lo, hi = float(img.min()), float(img.max())
            img = ((img - lo) / (hi - lo + 1e-8) * 255).astype(
                np.uint8)
        dest = os.path.join(out_dir, f"{name}.png")
        Image.fromarray(img).save(dest)
        written.append((name, img.shape))
        print(f"{name:22s} -> {img.shape}")

    # the chained random pipeline (what a training recipe composes)
    chain = ChainedPreprocessing([
        T.ImageBrightness(seed=0), T.ImageHFlip(),
        T.ImageResize(256, 256), T.ImageRandomCrop(224, 224, seed=0),
    ])
    out = ImageSet.read(path).transform(chain)
    shape = np.asarray(out.features[0].image).shape
    print(f"{'chained pipeline':22s} -> {shape}")
    assert shape[:2] == (224, 224)
    assert len(written) == len(gallery)
    print(f"{len(written) + 1} outputs in {out_dir}")
    return out_dir


if __name__ == "__main__":
    main()
