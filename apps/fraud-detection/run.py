"""Credit-card fraud-detection app (reference
`apps/fraud-detection/fraud-detection.ipynb`): imbalanced tabular
binary classification through the nnframes ML-pipeline surface.

The reference recipe on the Kaggle `creditcard.csv` schema
(Time, V1..V28, Amount, Class):
  1. assemble V1..V28 + Amount into a 29-feature vector, standardize;
  2. time-based 70/30 split (`approxQuantile("Time", 0.7)`);
  3. `DLClassifier(Sequential(Linear(29,10), Linear(10,2),
     LogSoftMax), ClassNLL)`;
  4. evaluate precision/recall/areaUnderROC on the validation window;
  5. fight the ~0.17% positive-class imbalance with a bagging
     ensemble over stratified bootstrap samples (fraud oversampled
     10x, majority downsampled to 5%) and a vote threshold.

This app runs the same workflow TPU-natively: NNClassifier over a
pandas (or Spark) DataFrame, softmax head + sparse CE (the log-prob
head pairing), and the same stratified-bagging ensemble with a vote
threshold swept on validation recall/precision. With no Kaggle
download in this environment, `--csv` reads a real creditcard.csv;
omitted, a synthetic generator reproduces the shape: two Gaussian
clusters in V-space at the published 0.17% fraud rate with
time-drifting means (so the time-based split matters).
"""

from __future__ import annotations

import argparse

import numpy as np
import pandas as pd


def synth_creditcard(n: int, fraud_rate: float, rng) -> pd.DataFrame:
    """creditcard.csv-shaped frame: Time, V1..V28, Amount, Class."""
    n_fraud = max(int(n * fraud_rate), 8)
    n_ok = n - n_fraud
    t = np.sort(rng.uniform(0, 172800, size=n))  # 2 days of seconds
    is_fraud = np.zeros(n, bool)
    is_fraud[rng.choice(n, size=n_fraud, replace=False)] = True
    drift = (t / 172800.0)[:, None]              # legit cluster drifts
    v = rng.randn(n, 28) * 1.2 + drift
    centre = np.linspace(1.8, -1.8, 28)          # fraud cluster offset
    v[is_fraud] += centre[None, :]
    amount = np.where(is_fraud,
                      rng.lognormal(4.5, 1.0, n),
                      rng.lognormal(3.0, 1.2, n))
    df = pd.DataFrame(v, columns=[f"V{i}" for i in range(1, 29)])
    df.insert(0, "Time", t)
    df["Amount"] = amount
    df["Class"] = is_fraud.astype(np.int64)
    return df


def to_features(df: pd.DataFrame, mean=None, std=None):
    """VectorAssembler(V1..V28, Amount) + StandardScaler analog."""
    cols = [f"V{i}" for i in range(1, 29)] + ["Amount"]
    x = df[cols].to_numpy(np.float32)
    if mean is None:
        mean, std = x.mean(0), x.std(0) + 1e-8
    x = (x - mean) / std
    out = pd.DataFrame({"features": [row for row in x],
                        "label": df["Class"].to_numpy(np.int64)})
    return out, mean, std


def build_classifier(lr: float, batch: int, epochs: int):
    from analytics_zoo_tpu.feature.common import SeqToTensor
    from analytics_zoo_tpu.pipeline.api.keras import Sequential, \
        layers as L
    from analytics_zoo_tpu.pipeline.nnframes import NNClassifier
    m = Sequential()
    m.add(L.Dense(10, input_shape=(29,)))
    m.add(L.Dense(2, activation="softmax"))  # reference: LogSoftMax
    return (NNClassifier(m, "sparse_categorical_crossentropy",
                         SeqToTensor((29,)))
            .set_batch_size(batch).set_max_epoch(epochs)
            .set_learning_rate(lr))


def stratified_bootstrap(df: pd.DataFrame, rng,
                         fraud_mult: float = 10.0,
                         ok_ratio: float = 3.0) -> pd.DataFrame:
    """Reference `StratifiedSampler(Map(fraud -> 10, ok -> 0.05))`:
    oversample fraud with replacement, downsample the majority. On
    the reference's 284k-row dataset those rates leave ~3 legit rows
    per oversampled fraud row; expressing the majority sample as that
    RATIO keeps the bootstrap balance at any dataset size."""
    fraud = df[df["label"] == 1]
    ok = df[df["label"] == 0]
    fraud_s = fraud.sample(n=int(len(fraud) * fraud_mult),
                           replace=True, random_state=rng)
    ok_s = ok.sample(n=min(len(ok), int(len(fraud_s) * ok_ratio)),
                     random_state=rng)
    return pd.concat([fraud_s, ok_s]).sample(
        frac=1.0, random_state=rng).reset_index(drop=True)


def evaluate(y_true, scores, preds):
    """precision / recall / ROC-AUC like the reference's
    Binary+MulticlassClassificationEvaluator cell."""
    from analytics_zoo_tpu.ops.metrics import AUC
    tp = int(((preds == 1) & (y_true == 1)).sum())
    fp = int(((preds == 1) & (y_true == 0)).sum())
    fn = int(((preds == 0) & (y_true == 1)).sum())
    precision = tp / (tp + fp) if tp + fp else 0.0
    recall = tp / (tp + fn) if tp + fn else 0.0
    auc_m = AUC()
    stats = auc_m.batch_stats(y_true.astype(np.float32),
                              scores.astype(np.float32))
    auc = float(auc_m.aggregate(
        {k: np.asarray(v) for k, v in stats.items()}))
    return precision, recall, auc


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--csv", default=None,
                   help="path to a real creditcard.csv; omit for "
                        "synthetic data with the same schema")
    p.add_argument("--rows", type=int, default=20000,
                   help="synthetic row count")
    p.add_argument("--fraud-rate", type=float, default=0.0017)
    p.add_argument("--epochs", type=int, default=12)
    p.add_argument("--batch-size", type=int, default=1024)
    p.add_argument("--lr", type=float, default=3e-2)
    p.add_argument("--models", type=int, default=5,
                   help="bagging ensemble size (reference: 10)")
    args = p.parse_args(argv)

    from analytics_zoo_tpu import init_nncontext
    init_nncontext(seed=0)
    rng = np.random.RandomState(0)

    if args.csv:
        data = pd.read_csv(args.csv)
    else:
        data = synth_creditcard(args.rows, args.fraud_rate, rng)
        print(f"synthetic creditcard data: {len(data)} rows, "
              f"{int(data['Class'].sum())} fraud")

    # time-based split at the 0.7 quantile (reference approxQuantile)
    split_t = float(data["Time"].quantile(0.7))
    train_raw = data[data["Time"] < split_t]
    valid_raw = data[data["Time"] >= split_t]
    print(f"training records: {len(train_raw)}  "
          f"validation records: {len(valid_raw)}")

    train_df, mean, std = to_features(train_raw)
    valid_df, _, _ = to_features(valid_raw, mean, std)
    y_valid = valid_df["label"].to_numpy()

    # ---- single model on the raw (imbalanced) training window ------
    clf = build_classifier(args.lr, args.batch_size, args.epochs)
    model = clf.fit(train_df)
    scores = model.estimator.predict(
        np.stack(valid_df["features"]))[:, 1]
    preds = model.transform(valid_df)["prediction"].to_numpy()
    prec, rec, auc = evaluate(y_valid, scores, preds)
    print(f"single model: precision={prec:.3f} recall={rec:.3f} "
          f"AUC={auc:.3f}")

    # ---- bagging over stratified bootstrap samples -----------------
    votes = np.zeros(len(valid_df))
    for i in range(args.models):
        boot = stratified_bootstrap(train_df,
                                    np.random.RandomState(100 + i))
        m_i = build_classifier(args.lr, args.batch_size,
                               args.epochs).fit(boot)
        votes += m_i.transform(valid_df)["prediction"].to_numpy()
    # vote-threshold sweep (reference fixes threshold=15 of 20; with
    # an adjustable ensemble size, sweep and report the best-F1 row)
    best = None
    for thr in range(1, args.models + 1):
        preds_t = (votes >= thr).astype(np.int64)
        prec, rec, auc_t = evaluate(y_valid, votes / args.models,
                                    preds_t)
        f1 = 2 * prec * rec / (prec + rec) if prec + rec else 0.0
        print(f"bagging threshold {thr}/{args.models}: "
              f"precision={prec:.3f} recall={rec:.3f} f1={f1:.3f}")
        if best is None or f1 > best[0]:
            best = (f1, thr, prec, rec)
    f1, thr, prec, rec = best
    print(f"best ensemble: threshold={thr} precision={prec:.3f} "
          f"recall={rec:.3f} f1={f1:.3f}")
    if not args.csv and (prec + rec):
        assert rec >= 0.5, "ensemble failed to learn the fraud class"


if __name__ == "__main__":
    main()
