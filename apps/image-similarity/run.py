"""Image-similarity app (reference
`apps/image-similarity/image-similarity.ipynb`): real-estate-style
scene search combining a SEMANTIC model (scene classification) with a
VISUAL model (deep-feature embeddings + cosine similarity).

The reference workflow:
  1. `NNImageReader.readImages` + a path→label UDF builds a labeled
     scene DataFrame;
  2. semantic model: pretrained GoogLeNet-places365 cut at
     `pool5/drop_7x7_s1` via `Net.new_graph`, frozen, + Linear head →
     trained as an `NNClassifier` pipeline;
  3. visual model: VGG-16-places365 cut at `pool5`, `View(25088)` +
     L2 `Normalize` → an `NNModel` that adds an embedding column;
  4. query: `score = 0.3·classMatch + 0.7·cosine(query, candidate)`,
     top-k via `heapq.nlargest`.

This app runs the same four stages TPU-natively: a keras-API graph
backbone cut with `Model.new_graph` + `freeze_up_to` (the same
transfer-learning surgery surface), NNClassifier training, an
embedding extractor sharing the trained backbone with post-hoc L2
normalization, and the reference's exact scoring formula. Offline it
synthesizes a 4-class scene folder (distinct color/texture
statistics per class); pass `--folder` with `class_name/xxx.jpg`
subdirs to run on real data.
"""

from __future__ import annotations

import argparse
import os
import tempfile
from heapq import nlargest

import numpy as np
import pandas as pd

CLASSES = ["bathroom", "bedroom", "house", "kitchen"]


def synth_scene_folder(root: str, per_class: int, size: int,
                       rng) -> None:
    """Scene-shaped classes: per-class base color + stripe texture
    frequency, so both the classifier and the embedding have real
    (but learnable-offline) structure."""
    from PIL import Image
    bases = [(200, 220, 235), (180, 150, 120),
             (120, 170, 110), (235, 200, 160)]
    for ci, cls in enumerate(CLASSES):
        os.makedirs(os.path.join(root, cls), exist_ok=True)
        base = np.array(bases[ci], np.float32)
        for i in range(per_class):
            yy = np.arange(size)[:, None, None]
            stripes = 25.0 * np.sin(2 * np.pi * (ci + 1) * yy / size)
            img = base[None, None, :] + stripes + \
                rng.randn(size, size, 3) * 12.0
            Image.fromarray(
                np.clip(img, 0, 255).astype(np.uint8)).save(
                os.path.join(root, cls, f"{i}.png"))


def build_backbone(size: int):
    """Small conv graph with NAMED nodes so `new_graph("pool5")` /
    `freeze_up_to` work exactly like the reference's Net surgery."""
    from analytics_zoo_tpu.pipeline.api.keras import (
        Input, Model, layers as L)
    inp = Input(shape=(size, size, 3), name="image")
    x = L.Convolution2D(16, 3, 3, activation="relu",
                        border_mode="same", name="conv1")(inp)
    x = L.MaxPooling2D((2, 2), name="pool1")(x)
    x = L.Convolution2D(32, 3, 3, activation="relu",
                        border_mode="same", name="conv2")(x)
    x = L.MaxPooling2D((2, 2), name="pool2")(x)
    x = L.GlobalAveragePooling2D(name="pool5")(x)
    out = L.Dense(len(CLASSES), activation="softmax", name="head")(x)
    return Model(inp, out)


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--folder", default=None,
                   help="scene folder with class_name/xxx.jpg subdirs "
                        "(local or fsspec scheme); omit for synthetic")
    p.add_argument("--image-size", type=int, default=32)
    p.add_argument("--per-class", type=int, default=24)
    p.add_argument("--epochs", type=int, default=30)
    p.add_argument("--batch-size", type=int, default=16)
    p.add_argument("--top-k", type=int, default=3)
    p.add_argument("--class-weight", type=float, default=0.3,
                   help="semantic weight in the reference score "
                        "0.3*classMatch + 0.7*cosine")
    args = p.parse_args(argv)

    from analytics_zoo_tpu import init_nncontext
    from analytics_zoo_tpu.feature.common import SeqToTensor
    from analytics_zoo_tpu.feature.image import ImageSet
    from analytics_zoo_tpu.ops.optimizers import Adam
    from analytics_zoo_tpu.pipeline.nnframes import NNClassifier
    init_nncontext(seed=0)
    rng = np.random.RandomState(0)

    folder = args.folder
    if folder is None:
        folder = tempfile.mkdtemp(prefix="scenes_")
        synth_scene_folder(folder, args.per_class, args.image_size,
                           rng)

    # 1. labeled scene DataFrame (reference: readImages + label UDF)
    iset = ImageSet.read(folder, with_label_from_dirs=True)
    size = args.image_size
    from PIL import Image as PILImage
    feats, labels, origins = [], [], []
    for f in iset.features:
        arr = np.asarray(
            PILImage.fromarray(f.image).resize((size, size)),
            np.float32) / 255.0
        feats.append(arr)
        labels.append(float(f.label[0]))
        origins.append(f.get(f.URI))
    df = pd.DataFrame({"features": feats, "label": labels,
                       "origin": origins})
    print(f"scene DataFrame: {len(df)} images, "
          f"{len(set(labels))} classes")

    # 2. semantic model: backbone surgery + frozen transfer head.
    # (The reference cuts a PRETRAINED net; offline the backbone
    # trains end-to-end first, then the same new_graph/freeze_up_to
    # surgery produces the deployment classifier.)
    net = build_backbone(size)
    clf = (NNClassifier(net, "sparse_categorical_crossentropy",
                        SeqToTensor((size, size, 3)))
           .set_batch_size(args.batch_size)
           .set_max_epoch(args.epochs)
           .set_optim_method(Adam(lr=1e-2)))
    scene_model = clf.fit(df)
    out = scene_model.transform(df)
    acc = float((out["prediction"] == out["label"]).mean())
    print(f"scene classification train accuracy: {acc:.3f}")

    # the reference's surgery surface, on the trained graph: cut at
    # pool5 and freeze everything below it
    part = net.new_graph(["pool5"])
    part.freeze_up_to("pool5")
    n_frozen = sum(1 for lyr in part.layers if not lyr.trainable)
    print(f"new_graph(pool5): {len(part.layers)} layers, "
          f"{n_frozen} frozen")

    # 3. visual model: the pool5 activations, L2-normalized
    # (reference: new_graph("pool5") + View + Normalize(2.0))
    emb_params = {k: v for k, v in
                  scene_model.estimator.params.items()}
    x_all = np.stack(df["features"]).astype(np.float32)
    import jax
    emb = np.asarray(jax.jit(
        lambda p, x: part.call(p, x))(emb_params, x_all))
    emb = emb.reshape(len(df), -1)
    emb = emb / (np.linalg.norm(emb, axis=1, keepdims=True) + 1e-8)
    classes = out["prediction"].to_numpy()
    print(f"embeddings: {emb.shape}")

    # 4. query: reference score = w·classMatch + (1-w)·cosine
    qi = int(rng.randint(len(df)))
    q_cls, q_emb = classes[qi], emb[qi]

    def score(i):
        class_match = 1.0 if classes[i] == q_cls else 0.0
        cosine = float(q_emb @ emb[i])
        return args.class_weight * class_match + \
            (1 - args.class_weight) * cosine

    ranked = nlargest(args.top_k + 1, range(len(df)), key=score)
    ranked = [i for i in ranked if i != qi][:args.top_k]
    print(f"query: {df['origin'][qi]} (class {int(labels[qi])})")
    for r, i in enumerate(ranked):
        print(f"  top-{r + 1}: {df['origin'][i]} "
              f"(class {int(labels[i])}, score={score(i):.3f})")
    if args.folder is None:
        top1_same = labels[ranked[0]] == labels[qi]
        assert top1_same, "top-1 similar image is from another scene"
    return acc


if __name__ == "__main__":
    main()
