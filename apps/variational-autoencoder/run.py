"""Variational-autoencoder app (reference `apps/variational-autoencoder/
using_variational_autoencoder_to_generate_digital_numbers.ipynb` and
`..._to_generate_faces.ipynb`): a conv VAE — conv encoder →
GaussianSampler (reparameterized z) → deconv decoder — trained with
KLD + reconstruction criteria, generating an image grid after every
epoch.

TPU-natively the whole ELBO is ONE autograd graph (`pipeline.api
.autograd`: the reparameterization, KL term, and BCE reconstruction
compose as Variables and jit into a single XLA program — the
reference wires GaussianSampler/KLDCriterion/BCECriterion as separate
BigDL modules). Offline it trains on synthetic face-shaped blobs
(pass ``--mnist`` to use the bundled MNIST loader instead); generated
grids land in ``--out-dir``.
"""

from __future__ import annotations

import argparse
import os
import tempfile

import numpy as np

LATENT = 8


def synth_faces(n, size, rng):
    """Face-shaped blobs: oval + two eyes + mouth with jittered
    geometry, normalized to [0, 1]."""
    yy, xx = np.mgrid[0:size, 0:size].astype(np.float32) / size
    imgs = np.zeros((n, size, size), np.float32)
    for i in range(n):
        cy, cx = 0.5 + rng.randn() * 0.04, 0.5 + rng.randn() * 0.04
        ry, rx = 0.36 + rng.rand() * 0.08, 0.28 + rng.rand() * 0.08
        face = np.exp(-(((yy - cy) / ry) ** 2 +
                        ((xx - cx) / rx) ** 2) ** 2)
        for ex in (-0.12, 0.12):
            face -= 0.8 * np.exp(-(((yy - cy + 0.1) / 0.05) ** 2 +
                                   ((xx - cx - ex) / 0.05) ** 2))
        face -= 0.6 * np.exp(-(((yy - cy - 0.15) / 0.04) ** 2 +
                               ((xx - cx) / (0.1 + rng.rand() * 0.05))
                               ** 2))
        imgs[i] = np.clip(face, 0, 1)
    return imgs[..., None]


def build_vae(size):
    from analytics_zoo_tpu.pipeline.api import autograd as A
    from analytics_zoo_tpu.pipeline.api.keras.engine import Input
    from analytics_zoo_tpu.pipeline.api.keras.layers import (
        Convolution2D, Dense, Flatten, Reshape, UpSampling2D)
    from analytics_zoo_tpu.pipeline.api.keras.models import Model

    s4 = size // 4
    x_in = Input((size, size, 1), name="image")
    eps_in = Input((LATENT,), name="eps")
    # encoder (the notebook's conv_bn_lrelu stack, LeakyReLU→relu)
    h = Convolution2D(16, 3, 3, subsample=(2, 2), activation="relu",
                      border_mode="same", name="enc_c1")(x_in)
    h = Convolution2D(32, 3, 3, subsample=(2, 2), activation="relu",
                      border_mode="same", name="enc_c2")(h)
    h = Flatten()(h)
    z_mean = Dense(LATENT, name="enc_mean")(h)
    z_logvar = Dense(LATENT, name="enc_logvar")(h)
    # GaussianSampler, as plain autograd
    z = z_mean + A.exp(z_logvar * 0.5) * eps_in
    # decoder (Linear → reshape → upsample+conv — the notebook's
    # ResizeBilinear+conv decoder shape)
    dec = [Dense(s4 * s4 * 32, activation="relu", name="dec_fc"),
           Reshape((s4, s4, 32)),
           UpSampling2D((2, 2)),
           Convolution2D(16, 3, 3, activation="relu",
                         border_mode="same", name="dec_c1"),
           UpSampling2D((2, 2)),
           Convolution2D(1, 3, 3, activation="sigmoid",
                         border_mode="same", name="dec_c2")]

    def decode(v):
        for lyr in dec:
            v = lyr(v)
        return v

    recon = A.clip(decode(z), 1e-6, 1.0 - 1e-6)
    flat_x = Flatten()(x_in)
    flat_r = Flatten()(recon)
    bce = -A.sum(flat_x * A.log(flat_r) +
                 (1.0 - flat_x) * A.log(1.0 - flat_r),
                 axis=1, keepdims=True)
    kl = A.sum(A.square(z_mean) + A.exp(z_logvar) - z_logvar - 1.0,
               axis=1, keepdims=True) * 0.5
    vae = Model([x_in, eps_in], bce + kl, name="vae")

    # standalone decoder sharing the SAME layer objects (the
    # reference's decoder.forward for generation)
    z_in = Input((LATENT,), name="z")
    decoder = Model(z_in, decode(z_in), name="decoder")
    return vae, decoder


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--mnist", action="store_true",
                   help="train on the bundled MNIST loader instead "
                        "of synthetic faces")
    p.add_argument("--samples", type=int, default=512)
    p.add_argument("--image-size", type=int, default=28)
    p.add_argument("--epochs", type=int, default=3)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--out-dir", default=None)
    args = p.parse_args(argv)

    from PIL import Image

    from analytics_zoo_tpu import init_nncontext
    from analytics_zoo_tpu.ops.optimizers import Adam
    from analytics_zoo_tpu.pipeline.api.autograd import CustomLoss

    init_nncontext(seed=0)
    rng = np.random.RandomState(0)
    size = args.image_size
    if args.mnist:
        from analytics_zoo_tpu.pipeline.api.keras.datasets import mnist
        (xt, _), _ = mnist.load_data()
        x = (xt[:args.samples, :, :, None] / 255.0).astype(np.float32)
        size = x.shape[1]
    else:
        x = synth_faces(args.samples, size, rng)
    eps = rng.randn(len(x), LATENT).astype(np.float32)
    out_dir = args.out_dir or tempfile.mkdtemp(prefix="vae_")
    os.makedirs(out_dir, exist_ok=True)

    vae, decoder = build_vae(size)
    # the ELBO is the model output; Adam(0.001, beta1=0.5) like the
    # notebook
    vae.compile(optimizer=Adam(lr=1e-3, beta_1=0.5),
                loss=CustomLoss(
                    lambda y_true, y_pred: y_pred + y_true * 0.0,
                    y_pred_shape=(1,)))
    dummy_y = np.zeros((len(x), 1), np.float32)
    # the decoder is a separate Model over the SAME layer objects;
    # its estimator keeps its own params, so sync the trained
    # dec_* weights from the VAE by layer name before generating
    decoder.compile("adam", "mse")

    def gen_image_row():
        decoder.copy_weights_from(vae)
        zs = rng.randn(8, LATENT).astype(np.float32)
        imgs = decoder.predict(zs, batch_size=8)
        return np.column_stack([im[..., 0] for im in imgs])

    losses = []
    for epoch in range(1, args.epochs + 1):
        res = vae.fit([x, eps], dummy_y,
                      batch_size=args.batch_size, nb_epoch=1)
        row = np.vstack([gen_image_row() for _ in range(4)])
        dest = os.path.join(out_dir, f"epoch_{epoch}.png")
        Image.fromarray(
            np.clip(row * 255, 0, 255).astype(np.uint8)).save(dest)
        loss = float(res.history[-1]["loss"])
        losses.append(loss)
        print(f"epoch {epoch}: elbo-loss={loss:.1f} grid -> {dest}")
    if len(losses) > 1 and np.isfinite(losses[0]):
        assert losses[-1] < losses[0], "ELBO did not improve"
    print(f"{args.epochs} grids in {out_dir}")
    return losses


if __name__ == "__main__":
    main()
