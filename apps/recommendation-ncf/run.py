"""Recommendation-NCF app (reference `apps/recommendation-ncf`): see
README.md alongside this file for the narrated walkthrough."""

from __future__ import annotations

import argparse

import numpy as np


def load_ratings(path: "str | None", n_users: int, n_items: int,
                 n_samples: int, rng):
    """(user, item, rating 1..5) int arrays — ml-1m ratings.dat or a
    synthetic set with latent structure."""
    if path:
        from analytics_zoo_tpu.common.utils import read_bytes
        rows = []
        for line in read_bytes(path).decode().splitlines():
            parts = line.strip().split("::")
            if len(parts) >= 3:
                rows.append((int(parts[0]) - 1, int(parts[1]) - 1,
                             int(parts[2])))
        if not rows:
            raise ValueError(
                f"no ratings parsed from {path} (expected ml-1m "
                f"'user::item::rating::ts' lines)")
        arr = np.asarray(rows, np.int64)
        return arr[:, 0], arr[:, 1], arr[:, 2].astype(np.int32)
    # synthetic with learnable latent affinity
    users = rng.randint(0, n_users, n_samples)
    items = rng.randint(0, n_items, n_samples)
    u_lat = rng.randn(n_users, 4)
    i_lat = rng.randn(n_items, 4)
    affinity = np.sum(u_lat[users] * i_lat[items], axis=1)
    rating = np.clip(np.round(3 + affinity), 1, 5).astype(np.int32)
    return users, items, rating


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--ratings", default=None,
                   help="ml-1m ratings.dat (user::item::rating::ts); "
                        "omit for synthetic data")
    p.add_argument("--users", type=int, default=600)
    p.add_argument("--items", type=int, default=370)
    p.add_argument("--samples", type=int, default=20000)
    p.add_argument("--batch-size", type=int, default=2048)
    p.add_argument("--epochs", type=int, default=5)
    args = p.parse_args(argv)

    from analytics_zoo_tpu import init_nncontext
    from analytics_zoo_tpu.models.recommendation import (NeuralCF,
                                                         UserItemFeature)

    init_nncontext()
    rng = np.random.RandomState(0)
    users, items, rating = load_ratings(args.ratings, args.users,
                                        args.items, args.samples, rng)
    n_users = int(users.max()) + 1
    n_items = int(items.max()) + 1

    x = np.stack([users, items], axis=1).astype(np.int32)
    y = (rating - 1).reshape(-1, 1)          # classes 0..4
    idx = rng.permutation(len(x))
    split = int(len(x) * 0.9)
    tr, te = idx[:split], idx[split:]

    ncf = NeuralCF(user_count=n_users, item_count=n_items, num_classes=5,
                   user_embed=20, item_embed=20,
                   hidden_layers=(40, 20, 10), mf_embed=20)
    # class_nll: NeuralCF ends in log_softmax (the reference's
    # LogSoftMax + ClassNLLCriterion pairing) — a probability-space
    # loss would clip the log-probs and train nothing
    ncf.compile(optimizer="adam", loss="class_nll",
                metrics=["accuracy"])
    ncf.fit(x[tr], y[tr], batch_size=args.batch_size,
            nb_epoch=args.epochs)
    metrics = ncf.evaluate(x[te], y[te], batch_size=args.batch_size)
    print("test:", {k: round(float(v), 4) for k, v in metrics.items()})

    pairs = [UserItemFeature(user_id=int(u), item_id=int(i),
                             feature=np.array([u, i], np.int32))
             for u, i in zip(users[te][:200], items[te][:200])]
    for r in ncf.recommend_for_user(pairs, max_items=3)[:5]:
        print(f"user {r.user_id}: item {r.item_id} rated "
              f"{r.prediction + 1} (p={r.probability:.3f})")
    for r in ncf.recommend_for_item(pairs, max_users=3)[:5]:
        print(f"item {r.item_id}: user {r.user_id} rated "
              f"{r.prediction + 1} (p={r.probability:.3f})")
    return metrics


if __name__ == "__main__":
    main()
