"""Dogs-vs-cats transfer-learning app (reference `apps/dogs-vs-cats`,
BASELINE config #2): see README.md alongside this file."""

from __future__ import annotations

import argparse
import os
import tempfile

import numpy as np
import pandas as pd


def synth_folder(root: str, per_class: int, size: int, rng) -> None:
    """cats/dogs-shaped folder: brightness-biased classes so a frozen
    random backbone + linear head can still learn offline."""
    from PIL import Image
    for cls, lo, hi in (("cat", 0, 128), ("dog", 128, 255)):
        os.makedirs(os.path.join(root, cls), exist_ok=True)
        for i in range(per_class):
            img = rng.randint(lo, hi, (size, size, 3)).astype(np.uint8)
            Image.fromarray(img).save(
                os.path.join(root, cls, f"{i}.png"))


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--folder", default=None,
                   help="cat/... dog/... image folder (local or "
                        "fsspec scheme); omit for synthetic data")
    p.add_argument("--arch", default="lenet-5",
                   help="backbone architecture. The reference app "
                        "uses inception-v1 WITH pretrained weights "
                        "(--weights); without weights a deep "
                        "backbone's random features vanish (or its "
                        "BatchNorm train/eval stats mismatch), so "
                        "the offline demo defaults to the shallow "
                        "BN-free lenet-5")
    p.add_argument("--weights", default=None,
                   help="backbone weights (.npz) for real transfer "
                        "learning")
    p.add_argument("--image-size", type=int, default=28)
    p.add_argument("--per-class", type=int, default=32)
    p.add_argument("--epochs", type=int, default=20)
    p.add_argument("--batch-size", type=int, default=32)
    args = p.parse_args(argv)

    from analytics_zoo_tpu import init_nncontext
    from analytics_zoo_tpu.feature.common import SeqToTensor
    from analytics_zoo_tpu.feature.image import ImageSet
    from analytics_zoo_tpu.models.image.imageclassification import \
        ImageClassifier
    from analytics_zoo_tpu.ops.optimizers import Adam
    from analytics_zoo_tpu.pipeline.nnframes import NNClassifier

    init_nncontext()
    rng = np.random.RandomState(0)
    folder = args.folder
    if folder is None:
        folder = tempfile.mkdtemp(prefix="dogs_cats_")
        synth_folder(folder, args.per_class, args.image_size, rng)

    # 1. images + labels from the class-dir layout
    iset = ImageSet.read(folder, with_label_from_dirs=True)
    size = args.image_size
    channels = 1 if args.arch == "lenet-5" else 3
    feats, labels = [], []
    for f in iset.features:
        from PIL import Image
        arr = np.asarray(Image.fromarray(f.image).resize((size, size)),
                         np.float32) / 255.0
        if channels == 1:
            arr = arr.mean(axis=-1, keepdims=True)
        feats.append(arr)
        # 0-based class ids: the TPU losses/argmax are 0-based
        # (divergence from BigDL's 1-based ClassNLL convention)
        labels.append(float(f.label[0]))
    df = pd.DataFrame({"features": feats, "label": labels})

    # 2. backbone + freeze (the reference's freezeUpTo): everything
    # but the classification head stays fixed
    backbone = ImageClassifier(args.arch,
                               input_shape=(size, size, channels),
                               classes=2)
    backbone.compile()            # builds params so weights can load
    if args.weights:
        backbone.load_weights(args.weights)
    net = backbone.model
    net.freeze(*[l.name for l in net.layers[:-1]])
    n_frozen = sum(1 for l in net.layers if not l.trainable)
    print(f"backbone {args.arch}: {len(net.layers)} layers, "
          f"{n_frozen} frozen, head trains")

    # 3. Spark-ML-style training + scoring. The loss must match the
    # head: lenet-5 ends in softmax (probability-space loss), the
    # other registry backbones end in raw logits (softmax CE) — the
    # wrong pairing clips/squashes gradients and learns nothing
    loss = ("sparse_categorical_crossentropy"
            if args.arch == "lenet-5" else "softmax_cross_entropy")
    clf = (NNClassifier(net, loss,
                        SeqToTensor((size, size, channels)))
           .set_batch_size(args.batch_size)
           .set_max_epoch(args.epochs)
           .set_optim_method(Adam(lr=1e-2)))
    model = clf.fit(df)
    out = model.transform(df)
    acc = float((out["prediction"] == out["label"]).mean())
    print(f"train accuracy: {acc:.3f} over {len(df)} images")
    return acc


if __name__ == "__main__":
    main()
