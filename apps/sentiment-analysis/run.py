"""Sentiment-analysis app (reference `apps/sentiment-analysis`): see
README.md alongside this file."""

from __future__ import annotations

import argparse

import numpy as np


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--csv", default=None,
                   help="CSV with text,label columns — runs the raw "
                        "TextSet pipeline on your data")
    p.add_argument("--imdb", action="store_true",
                   help="use the keras.datasets.imdb loader (real "
                        "reviews when ~/.zoo/dataset/imdb_full.pkl "
                        "is present; its offline stand-in has RANDOM "
                        "labels, so accuracy stays ~0.5 by design)")
    p.add_argument("--encoder", default="cnn",
                   choices=["cnn", "lstm", "gru"])
    p.add_argument("--sequence-length", type=int, default=64)
    p.add_argument("--token-length", type=int, default=32)
    p.add_argument("--nb-words", type=int, default=4000)
    p.add_argument("--samples", type=int, default=512)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--epochs", type=int, default=4)
    args = p.parse_args(argv)

    from analytics_zoo_tpu import init_nncontext
    from analytics_zoo_tpu.models.textclassification import TextClassifier
    from analytics_zoo_tpu.pipeline.api.keras.layers import Embedding

    init_nncontext()
    seq = args.sequence_length

    from analytics_zoo_tpu.feature.text import TextSet

    def pipeline(texts, labels):
        ts = TextSet.from_texts(texts, labels)
        ts = (ts.tokenize().word2idx()
              .shape_sequence(seq).generate_sample())
        x, y = ts.to_arrays()
        return x, y, int(x.max()) + 1

    if args.csv:
        import io

        import pandas as pd

        from analytics_zoo_tpu.common.utils import read_bytes
        df = pd.read_csv(io.BytesIO(read_bytes(args.csv)))
        # label-sorted exports are common: shuffle before the split;
        # string labels ("pos"/"neg") map to 0-based ids
        df = df.sample(frac=1, random_state=0).reset_index(drop=True)
        labels = df["label"]
        if not np.issubdtype(np.asarray(labels).dtype, np.number):
            codes, classes = pd.factorize(labels)
            print("label mapping:",
                  {c: i for i, c in enumerate(classes)})
            labels = codes
        x, y, vocab = pipeline(list(df["text"]),
                               [int(v) for v in labels])
    elif args.imdb:
        from analytics_zoo_tpu.pipeline.api.keras.datasets import imdb
        (xs, ys), _ = imdb.load_data(nb_words=args.nb_words)
        xs, ys = xs[:args.samples], ys[:args.samples]
        x = np.zeros((len(xs), seq), np.int32)
        for i, s in enumerate(xs):                 # pad/truncate
            s = list(s)[:seq]
            x[i, :len(s)] = s
        y = np.asarray(ys, np.int32).reshape(-1, 1)
        vocab = args.nb_words
    else:
        # offline demo: review-shaped synthetic corpus with real
        # sentiment signal, through the FULL TextSet pipeline
        rng = np.random.RandomState(0)
        pos = ("great wonderful loved brilliant superb charming "
               "delightful masterpiece moving excellent").split()
        neg = ("awful boring terrible dull waste disappointing "
               "mess lifeless tedious poor").split()
        filler = ("movie film plot actor scene story the a was and "
                  "it of with director ending music").split()
        texts, labels = [], []
        for i in range(args.samples):
            lbl = i % 2
            strong = pos if lbl else neg
            n = rng.randint(10, seq)
            words = [(rng.choice(strong) if rng.rand() < 0.3
                      else rng.choice(filler)) for _ in range(n)]
            texts.append(" ".join(words))
            labels.append(lbl)
        order = rng.permutation(len(texts))
        x, y, vocab = pipeline([texts[i] for i in order],
                               [labels[i] for i in order])

    split = int(len(x) * 0.8)
    clf = TextClassifier(
        class_num=int(y.max()) + 1,
        token_length=args.token_length, sequence_length=seq,
        encoder=args.encoder, encoder_output_dim=64,
        embedding=Embedding(vocab, args.token_length,
                            input_shape=(seq,)))
    # probability-space loss: TextClassifier ends in softmax
    clf.compile(optimizer="adam",
                loss="sparse_categorical_crossentropy",
                metrics=["accuracy"])
    clf.fit(x[:split], y[:split], batch_size=args.batch_size,
            nb_epoch=args.epochs)
    metrics = clf.evaluate(x[split:], y[split:],
                           batch_size=args.batch_size)
    print("test:", {k: round(float(v), 4) for k, v in metrics.items()})
    return metrics


if __name__ == "__main__":
    main()
