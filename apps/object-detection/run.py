"""Object-detection app (reference `apps/object-detection/
object-detection.ipynb`): load an SSD detector from the model zoo,
run batched detection over an image set, and write box-annotated
images with the `Visualizer` (the notebook's visualize cells).

Random weights + synthetic images by default so the app runs offline
(no pretrained-zoo download here); point ``--weights`` at a trained
checkpoint and raise ``--conf`` for real detections. The detection
recipe itself mirrors `analytics_zoo_tpu/examples/
object_detection.py` (reference `pyzoo/zoo/examples/objectdetection/
predict.py`)."""

from __future__ import annotations

import argparse
import os
import tempfile

import numpy as np

VOC_CLASSES = [
    "aeroplane", "bicycle", "bird", "boat", "bottle", "bus", "car",
    "cat", "chair", "cow", "diningtable", "dog", "horse", "motorbike",
    "person", "pottedplant", "sheep", "sofa", "train", "tvmonitor"]


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--model", default="ssd-vgg16-300x300")
    p.add_argument("--weights", default=None,
                   help="trained .model checkpoint")
    p.add_argument("--images", type=int, default=2)
    p.add_argument("--conf", type=float, default=0.05,
                   help="random weights score low; raise for a "
                        "trained checkpoint")
    p.add_argument("--out-dir", default=None)
    args = p.parse_args(argv)

    from PIL import Image

    from analytics_zoo_tpu import init_nncontext
    from analytics_zoo_tpu.models.image.objectdetection import (
        ObjectDetector, Visualizer)

    init_nncontext(seed=0)
    rng = np.random.RandomState(0)
    out_dir = args.out_dir or tempfile.mkdtemp(prefix="objdet_")
    os.makedirs(out_dir, exist_ok=True)

    detector = ObjectDetector(args.model)
    if args.weights:
        detector.model.load_weights(args.weights)
    else:
        detector.compile()   # random weights: demonstrates the flow
    size = detector.img_size
    images = rng.rand(args.images, size, size, 3).astype(np.float32)
    results = detector.detect(images, batch_size=args.images,
                              conf_threshold=args.conf)

    viz = Visualizer(VOC_CLASSES, score_threshold=args.conf)
    n_boxes = 0
    for i, dets in enumerate(results):
        annotated = viz.draw(
            (images[i] * 255).astype(np.uint8), dets)
        dest = os.path.join(out_dir, f"det_{i}.png")
        Image.fromarray(annotated).save(dest)
        n_boxes += len(dets)
        print(f"image {i}: {len(dets)} detections -> {dest}")
        for d in dets[:3]:
            name = (VOC_CLASSES[d.class_id]
                    if d.class_id < len(VOC_CLASSES)
                    else str(d.class_id))
            print(f"  {name} score={d.score:.3f} "
                  f"box={np.round(d.box, 3).tolist()}")
    print(f"{n_boxes} boxes over {args.images} images in {out_dir}")
    return n_boxes


if __name__ == "__main__":
    main()
