"""Web-service app (reference `apps/web-service-sample`): see
README.md alongside this file for the narrated walkthrough."""

from __future__ import annotations

import argparse
import json
import threading
import urllib.request

import numpy as np


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--concurrency", type=int, default=4)
    p.add_argument("--requests", type=int, default=16)
    args = p.parse_args(argv)

    from analytics_zoo_tpu import init_nncontext
    from analytics_zoo_tpu.pipeline.api.keras import Sequential, \
        layers as L
    from analytics_zoo_tpu.pipeline.inference import InferenceModel
    from analytics_zoo_tpu.pipeline.inference.serving import \
        make_inference_server

    init_nncontext()
    net = Sequential()
    net.add(L.Dense(32, input_shape=(8,), activation="relu"))
    net.add(L.Dense(3, activation="softmax"))
    net.compile(optimizer="adam",
                loss="sparse_categorical_crossentropy")

    model = InferenceModel(supported_concurrent_num=args.concurrency)
    model.load_keras_net(net)
    server = make_inference_server(model)    # native C++ when built
    server.start()
    base = f"http://127.0.0.1:{server.port}"
    print(f"serving on {base} via {type(server).__name__}")

    with urllib.request.urlopen(f"{base}/health", timeout=10) as r:
        print("health:", json.loads(r.read()))

    # payloads generated up front: RandomState is not thread-safe
    rng = np.random.RandomState(0)
    payloads = [rng.rand(2, 8).astype(np.float32).tolist()
                for _ in range(args.requests)]
    errors: "list[str]" = []

    def client(i: int):
        x = payloads[i]
        req = urllib.request.Request(
            f"{base}/predict",
            data=json.dumps({"inputs": x}).encode(),
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=30) as r:
                preds = json.loads(r.read())["outputs"]
            rows = np.asarray(preds, np.float32)
            if rows.shape != (2, 3) or not np.allclose(
                    rows.sum(-1), 1.0, atol=1e-3):
                errors.append(f"request {i}: bad payload {rows!r}")
        except Exception as e:
            errors.append(f"request {i}: {e}")

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(args.requests)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    server.stop()
    if errors:
        raise SystemExit("FAILED:\n" + "\n".join(errors[:5]))
    print(f"{args.requests} concurrent requests served OK "
          f"({args.concurrency}-way pool)")


if __name__ == "__main__":
    main()
