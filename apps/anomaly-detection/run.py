"""Anomaly-detection app (reference `apps/anomaly-detection`): see
README.md alongside this file for the narrated walkthrough."""

from __future__ import annotations

import argparse

import numpy as np


def load_series(csv: "str | None", points: int, rng) -> np.ndarray:
    if csv:
        import pandas as pd

        from analytics_zoo_tpu.common.utils import read_bytes
        import io
        df = pd.read_csv(io.BytesIO(read_bytes(csv)))
        col = "value" if "value" in df.columns else df.columns[-1]
        return df[col].to_numpy(np.float32)
    # taxi-shaped synthetic: daily + weekly seasonality + noise + spikes
    t = np.arange(points)
    series = (10.0 + 2.0 * np.sin(t / 48 * 2 * np.pi)
              + 1.0 * np.sin(t / (48 * 7) * 2 * np.pi)
              + 0.2 * rng.randn(points)).astype(np.float32)
    spikes = rng.choice(points, 5, replace=False)
    series[spikes] += 6.0
    return series


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--csv", default=None,
                   help="CSV with a 'value' column (local or fsspec "
                        "scheme); omit for synthetic data")
    p.add_argument("--points", type=int, default=2000)
    p.add_argument("--unroll", type=int, default=24)
    p.add_argument("--epochs", type=int, default=5)
    p.add_argument("--batch-size", type=int, default=256)
    p.add_argument("--anomalies", type=int, default=5)
    args = p.parse_args(argv)

    from analytics_zoo_tpu import init_nncontext
    from analytics_zoo_tpu.models.anomalydetection import AnomalyDetector

    init_nncontext()
    rng = np.random.RandomState(0)
    series = load_series(args.csv, args.points, rng)
    # standardise like the reference notebook
    series = (series - series.mean()) / (series.std() + 1e-8)

    indexed = AnomalyDetector.unroll(series[:, None], args.unroll)
    x, y = AnomalyDetector.to_arrays(indexed)
    split = int(len(x) * 0.8)
    x_train, y_train, x_test, y_test = (x[:split], y[:split],
                                        x[split:], y[split:])

    ad = AnomalyDetector(feature_shape=(args.unroll, 1),
                         hidden_layers=(8, 32, 15),
                         dropouts=(0.2, 0.2, 0.2))
    ad.compile(optimizer="adam", loss="mse")
    ad.fit(x_train, y_train, batch_size=args.batch_size,
           nb_epoch=args.epochs)

    y_pred = ad.predict(x_test, batch_size=args.batch_size).reshape(-1)
    mse = float(np.mean((y_pred - y_test.reshape(-1)) ** 2))
    flagged, threshold = AnomalyDetector.detect_anomalies(
        y_test.reshape(-1), y_pred, anomaly_size=args.anomalies)
    print(f"test mse={mse:.4f}; flagged {len(flagged)} anomalies "
          f"(|error| > {threshold:.3f}) at test indices "
          f"{sorted(flagged.tolist())}")
    return flagged


if __name__ == "__main__":
    main()
