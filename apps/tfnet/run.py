"""TFNet app (reference `apps/tfnet/image_classification_inference.ipynb`):
the notebook exports a pretrained slim Inception-v1 with `export_tf`,
wraps it in `TFNet` for distributed inference, then re-exports the
graph CUT AT THE POOLING LAYER and trains a new classifier head on
those embeddings (DLClassifier pipeline).

This app runs the same three stages offline and TPU-natively:
  1. a TF-authored CNN (stand-in for the slim checkpoint) is trained
     briefly in TF on synthetic data, frozen to a GraphDef;
  2. `TFNet.from_frozen_graph` executes it — the graph becomes one
     XLA program — and its predictions must agree with TF eager;
  3. the frozen graph cut at the pool layer yields embeddings, and an
     `NNClassifier` trains a new head on them (the transfer-learning
     workflow).
"""

from __future__ import annotations

import argparse
import os
import tempfile

import numpy as np


def synth_images(n, size, rng):
    """Two classes separated by color statistics + stripe frequency."""
    y = rng.randint(0, 2, n)
    base = np.where(y[:, None, None, None] == 0, 0.3, 0.7)
    yy = np.arange(size)[None, :, None, None]
    stripes = 0.2 * np.sin(2 * np.pi * (y[:, None, None, None] + 1) *
                           yy / size)
    x = base + stripes + rng.randn(n, size, size, 3) * 0.05
    return x.astype(np.float32), y.astype(np.int64)


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--samples", type=int, default=256)
    p.add_argument("--image-size", type=int, default=24)
    p.add_argument("--tf-epochs", type=int, default=3)
    p.add_argument("--head-epochs", type=int, default=10)
    p.add_argument("--batch-size", type=int, default=32)
    args = p.parse_args(argv)

    import tensorflow as tf

    from analytics_zoo_tpu import init_nncontext
    from analytics_zoo_tpu.feature.common import SeqToTensor
    from analytics_zoo_tpu.pipeline.api.net import TFNet
    from analytics_zoo_tpu.pipeline.nnframes import NNClassifier

    init_nncontext(seed=0)
    rng = np.random.RandomState(0)
    size = args.image_size
    x, y = synth_images(args.samples, size, rng)

    # -- 1. the "pretrained" TF model (trained here since no download)
    tf.keras.utils.set_random_seed(0)
    backbone = tf.keras.Sequential([
        tf.keras.layers.Conv2D(16, 3, activation="relu",
                               padding="same",
                               input_shape=(size, size, 3)),
        tf.keras.layers.MaxPooling2D(2),
        tf.keras.layers.Conv2D(32, 3, activation="relu",
                               padding="same"),
        tf.keras.layers.GlobalAveragePooling2D(name="pool"),
    ])
    model = tf.keras.Sequential(
        [backbone, tf.keras.layers.Dense(2, name="logits")])
    model.compile(optimizer="adam",
                  loss=tf.keras.losses.SparseCategoricalCrossentropy(
                      from_logits=True))
    model.fit(x, y, batch_size=args.batch_size,
              epochs=args.tf_epochs, verbose=0)

    # freeze (the notebook's export_tf) to a .pb
    from tensorflow.python.framework.convert_to_constants import (
        convert_variables_to_constants_v2)
    f = tf.function(lambda img: model(img, training=False))
    cf = f.get_concrete_function(
        tf.TensorSpec([None, size, size, 3], tf.float32))
    frozen = convert_variables_to_constants_v2(cf)
    gd = frozen.graph.as_graph_def()
    export_dir = tempfile.mkdtemp(prefix="tfnet_")
    pb = os.path.join(export_dir, "frozen.pb")
    with open(pb, "wb") as fh:
        fh.write(gd.SerializeToString())
    in_name = frozen.inputs[0].name
    out_name = frozen.outputs[0].name
    print(f"frozen graph -> {pb} ({len(gd.node)} nodes, "
          f"{in_name} -> {out_name})")

    # -- 2. TFNet inference: one XLA program, must agree with TF
    net = TFNet.from_frozen_graph(pb, inputs=[in_name],
                                  outputs=[out_name])
    preds = net.predict(x[:64], batch_size=args.batch_size)
    want = model(x[:64]).numpy()
    np.testing.assert_allclose(preds, want, atol=1e-4)
    acc = float((np.argmax(preds, -1) == y[:64]).mean())
    print(f"TFNet inference agrees with TF eager; accuracy={acc:.3f}")

    # -- 3. cut at the pool layer -> embeddings -> new NNClassifier
    # head (the notebook's transfer-learning part)
    f_pool = tf.function(lambda img: backbone(img, training=False))
    cf_pool = f_pool.get_concrete_function(
        tf.TensorSpec([None, size, size, 3], tf.float32))
    frozen_pool = convert_variables_to_constants_v2(cf_pool)
    pb_pool = os.path.join(export_dir, "frozen_pool.pb")
    with open(pb_pool, "wb") as fh:
        fh.write(frozen_pool.graph.as_graph_def().SerializeToString())
    emb_net = TFNet.from_frozen_graph(
        pb_pool, inputs=[frozen_pool.inputs[0].name],
        outputs=[frozen_pool.outputs[0].name])
    emb = emb_net.predict(x, batch_size=args.batch_size)
    print(f"pool embeddings: {emb.shape}")

    import pandas as pd

    from analytics_zoo_tpu.pipeline.api.keras import (
        Sequential as ZSequential, layers as L)
    head = ZSequential()
    head.add(L.Dense(2, activation="softmax",
                     input_shape=(emb.shape[1],)))
    df = pd.DataFrame({"features": [e for e in emb],
                       "label": y.astype(np.float64)})
    clf = (NNClassifier(head, "sparse_categorical_crossentropy",
                        SeqToTensor((emb.shape[1],)))
           .set_batch_size(args.batch_size)
           .set_max_epoch(args.head_epochs)
           .set_learning_rate(0.05))
    nn_model = clf.fit(df)
    out = nn_model.transform(df)
    head_acc = float((out["prediction"] == out["label"]).mean())
    print(f"transfer head accuracy on embeddings: {head_acc:.3f}")
    assert head_acc > 0.8
    return head_acc


if __name__ == "__main__":
    main()
