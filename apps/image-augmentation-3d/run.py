"""3D image-augmentation app (reference
`apps/image-augmentation-3d/image-augmentation-3d.ipynb`): the
notebook loads an MRI volume (meniscus_full.mat) and walks Crop3D →
Rotate3D(π/6) → Rotate3D(π/2) → AffineTransform3D, then composes them
with ChainedPreprocessing. This runs the identical sequence through
`feature.image3d` on a synthetic MRI-shaped volume (pass ``--volume``
with a .npy (D, H, W) file for real data) and writes mid-slice PNGs
of every stage for visual inspection.
"""

from __future__ import annotations

import argparse
import math
import os
import tempfile

import numpy as np


def synth_volume(rng, shape=(30, 200, 300)) -> np.ndarray:
    """Meniscus-scan-shaped volume: a bright ellipsoidal band with
    texture, so rotations/crops are visually meaningful."""
    d, h, w = shape
    zz, yy, xx = np.mgrid[0:d, 0:h, 0:w].astype(np.float32)
    band = np.exp(-(((zz - d / 2) / (d / 4)) ** 2 +
                    ((yy - h / 2) / (h / 3)) ** 2 +
                    ((xx - w / 2) / (w / 3)) ** 2))
    stripes = 0.3 * np.sin(2 * np.pi * yy / 20)
    return (band * (1.0 + stripes) +
            rng.rand(d, h, w).astype(np.float32) * 0.05)


def save_mid_slice(vol: np.ndarray, path: str) -> None:
    from PIL import Image
    sl = np.asarray(vol)[vol.shape[0] // 2]
    lo, hi = float(sl.min()), float(sl.max())
    Image.fromarray(((sl - lo) / (hi - lo + 1e-8) * 255)
                    .astype(np.uint8)).save(path)


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--volume", default=None,
                   help=".npy (D, H, W) volume; omit for synthetic")
    p.add_argument("--out-dir", default=None)
    args = p.parse_args(argv)

    from analytics_zoo_tpu import init_nncontext
    from analytics_zoo_tpu.feature.common import ChainedPreprocessing
    from analytics_zoo_tpu.feature.image3d import (
        AffineTransform3D, Crop3D, ImageFeature3D, Rotation3D)

    init_nncontext(seed=0)
    rng = np.random.RandomState(0)
    vol = (np.load(args.volume) if args.volume
           else synth_volume(rng))
    out_dir = args.out_dir or tempfile.mkdtemp(prefix="aug3d_")
    os.makedirs(out_dir, exist_ok=True)
    print(f"volume: {vol.shape}")

    # the notebook's exact sequence
    start_loc, patch = [13, 80, 125], [5, 40, 40]
    crop = Crop3D(start=start_loc, patch_size=patch)
    cropped = crop.apply(ImageFeature3D(vol))
    print(f"Crop3D{tuple(patch)}: {cropped.image.shape}")
    save_mid_slice(cropped.image, os.path.join(out_dir, "crop.png"))

    rotate_30 = Rotation3D([0.0, 0.0, math.pi / 6])
    r30 = rotate_30.apply(cropped)
    print(f"Rotate3D(pi/6): {r30.image.shape}")
    save_mid_slice(r30.image, os.path.join(out_dir, "rot30.png"))

    rotate_90 = Rotation3D([0.0, 0.0, math.pi / 2])
    r90 = rotate_90.apply(r30)
    print(f"Rotate3D(pi/2): {r90.image.shape}")
    save_mid_slice(r90.image, os.path.join(out_dir, "rot90.png"))

    affine_mat = rng.rand(3, 3)
    affine = AffineTransform3D(affine_mat)
    aff = affine.apply(r90)
    print(f"AffineTransform3D(random): {aff.image.shape}")
    save_mid_slice(aff.image, os.path.join(out_dir, "affine.png"))

    # the composed pipeline (notebook's ChainedPreprocessing cell)
    chain = ChainedPreprocessing([
        Crop3D(start=start_loc, patch_size=patch),
        Rotation3D([0.0, 0.0, math.pi / 6]),
        Rotation3D([0.0, 0.0, math.pi / 2]),
        AffineTransform3D(affine_mat),
    ])
    chained = chain.apply(ImageFeature3D(vol))
    assert chained.image.shape == tuple(patch)
    np.testing.assert_allclose(np.asarray(chained.image),
                               np.asarray(aff.image), atol=1e-5)
    print(f"chained pipeline reproduces the staged result; "
          f"4 slices in {out_dir}")
    return out_dir


if __name__ == "__main__":
    main()
