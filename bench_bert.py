"""Tertiary benchmark: BERT fine-tune training throughput
(samples/sec/chip).

BASELINE.json's config list names "TFPark TFOptimizer: distributed
BERT-base fine-tune on TPU pod" as the fifth recipe. This measures the
single-chip fine-tune step — the native BERT encoder
(`layers/transformer.py`, reference `BERT.scala:53-110`) + pooled
classifier head, bf16 activations, Adam — and prints ONE JSON line:

    {"metric": "bert_finetune_samples_per_sec_per_chip", "value": N,
     "unit": "samples/sec", "vs_baseline": null, "config": "..."}

`vs_baseline` is null (the reference publishes no BERT throughput).
`bench.py` embeds this record in `extra_metrics` budget-permitting, so
a live BENCH artifact carries all three BASELINE workloads. The
default config is BERT-base-shaped but truncated to 4 blocks so the
measurement + compile fit the bench budget window; the `config` field
says exactly what ran (scale honestly, never silently).

Timing follows bench.py: one jitted lax.scan chain of train steps,
one scalar host fetch, min-of-5 dispatch overhead subtracted.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def measure(batch: int = 32, steps: int = 10, seq_len: int = 128,
            hidden: int = 768, blocks: int = 4,
            metric: str = "bert_finetune_samples_per_sec_per_chip"
            ) -> dict:
    """Measure on the ALREADY-initialized backend; returns the metric
    record (callable in-process from bench.py)."""
    import jax
    import jax.numpy as jnp
    import optax

    from analytics_zoo_tpu import init_nncontext
    from analytics_zoo_tpu.pipeline.api.keras import layers as L

    init_nncontext(tpu_mesh={"data": 1}, devices=jax.devices()[:1],
                   log_level="WARNING")
    vocab, classes = 30522, 2   # BERT-base vocab; sentence-pair task
    bert = L.BERT(vocab=vocab, hidden_size=hidden, n_block=blocks,
                  n_head=hidden // 64, seq_len=seq_len,
                  intermediate_size=4 * hidden,
                  output_all_block=False, input_shape=[(seq_len,)] * 4)
    rngk = jax.random.PRNGKey(0)
    k1, k2 = jax.random.split(rngk)
    params = {"bert": bert.build(k1, [(seq_len,)] * 4)}
    params["head_w"] = jax.random.normal(
        k2, (hidden, classes), jnp.float32) * 0.02
    params["head_b"] = jnp.zeros((classes,), jnp.float32)

    tx = optax.adam(5e-5)
    opt_state = tx.init(params)

    rs = np.random.RandomState(0)
    tok = jnp.asarray(rs.randint(1, vocab, (batch, seq_len)), jnp.int32)
    seg = jnp.zeros((batch, seq_len), jnp.int32)
    pos = jnp.tile(jnp.arange(seq_len, dtype=jnp.int32), (batch, 1))
    msk = jnp.ones((batch, seq_len), jnp.bfloat16)
    y = jnp.asarray(rs.randint(0, classes, (batch,)), jnp.int32)

    def train_step(params, opt_state, rng):
        def compute_loss(p):
            # bf16 activations via bf16 embeddings (framework policy)
            bp = jax.tree_util.tree_map(
                lambda a: a.astype(jnp.bfloat16)
                if a.dtype == jnp.float32 else a, p["bert"])
            _, pooled = bert.call(bp, [tok, seg, pos, msk],
                                  training=True, rng=rng)
            logits = pooled.astype(jnp.float32) @ p["head_w"] \
                + p["head_b"]
            logp = jax.nn.log_softmax(logits)
            return -jnp.mean(
                jnp.take_along_axis(logp, y[:, None], axis=1))

        loss, grads = jax.value_and_grad(compute_loss)(params)
        updates, opt_state2 = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state2, loss

    def run(params, opt_state, rng):
        def body(carry, i):
            p, o = carry
            p, o, loss = train_step(p, o, jax.random.fold_in(rng, i))
            return (p, o), loss
        (p, o), losses_seq = jax.lax.scan(
            body, (params, opt_state), jnp.arange(steps))
        return p, o, losses_seq[-1]

    t0 = time.perf_counter()
    compiled = jax.jit(run).lower(params, opt_state, rngk).compile()
    t_compile = time.perf_counter() - t0

    from bench_common import time_chain
    dt, loss, rtt_bound = time_chain(
        compiled, (params, opt_state, rngk), with_quality=True)
    samples_per_sec = batch * steps / dt
    print(f"# [bert] batch={batch} T={seq_len} hidden={hidden} "
          f"blocks={blocks} steps={steps} "
          f"step_time={dt / steps * 1000:.1f}ms loss={loss:.3f} "
          f"compile={t_compile:.1f}s rtt_bound={rtt_bound}",
          file=sys.stderr, flush=True)
    from bench_common import flag_rtt_bound
    return flag_rtt_bound({
        "metric": metric,
        "value": round(samples_per_sec, 1),
        "unit": "samples/sec",
        "vs_baseline": None,
        "config": f"hidden={hidden} blocks={blocks} T={seq_len} "
                  f"batch={batch} bf16",
    }, rtt_bound)


def main():
    from bench_common import attach_metrics_snapshot
    rec = measure(
        batch=int(os.environ.get("ZOO_TPU_BENCH_BERT_BATCH", "32")),
        steps=int(os.environ.get("ZOO_TPU_BENCH_STEPS", "10")),
        hidden=int(os.environ.get("ZOO_TPU_BENCH_BERT_HIDDEN", "768")),
        blocks=int(os.environ.get("ZOO_TPU_BENCH_BERT_BLOCKS", "4")))
    print(json.dumps(attach_metrics_snapshot(rec)), flush=True)


if __name__ == "__main__":
    main()
