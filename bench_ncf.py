"""Secondary benchmark: NeuralCF training throughput (samples/sec/chip).

BASELINE.json names two workloads — "nnframes ResNet-50 images/sec/chip;
NCF recsys samples/sec". `bench.py` owns the first; this prints ONE JSON
line for the second:

    {"metric": "ncf_train_samples_per_sec_per_chip", "value": N,
     "unit": "samples/sec", "vs_baseline": null}

`vs_baseline` is null: the reference publishes no NCF throughput number
(BASELINE.md lists the workload without a target), so there is nothing
honest to normalise against. The measured number lives in PERF.md, and
`bench.py` embeds this metric in its own JSON line (`extra_metrics`) so
the driver's BENCH artifact carries both workloads.

Model/recipe: the reference NeuralCF ml-1m example
(`examples/recommendation/NeuralCFexample.scala`: 6040 users, 3706
items, 5 rating classes, userEmbed=itemEmbed=mfEmbed=20, MLP
40→20→10, Adam) — the same architecture `models/recommendation/
neuralcf.py` builds. Timing follows bench.py: one jitted lax.scan
chain of train steps, one scalar host fetch, min-of-5 dispatch
overhead subtracted (the axon tunnel's ~66 ms RTT would otherwise
dominate this sub-ms step).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

_t_start = time.perf_counter()


def measure(batch: int = 8192, steps: int = 20,
            metric: str = "ncf_train_samples_per_sec_per_chip") -> dict:
    """Measure NCF training throughput on the ALREADY-initialized
    backend; returns the metric record (callable in-process from
    bench.py after its own backend init)."""
    import jax
    import jax.numpy as jnp
    import optax

    from analytics_zoo_tpu import init_nncontext
    from analytics_zoo_tpu.models.recommendation import NeuralCF

    init_nncontext(tpu_mesh={"data": 1}, devices=jax.devices()[:1],
                   log_level="WARNING")
    # ml-1m scale + the reference example's dims
    ncf = NeuralCF(user_count=6040, item_count=3706, num_classes=5,
                   user_embed=20, item_embed=20,
                   hidden_layers=(40, 20, 10), mf_embed=20)
    model = ncf.build_model()
    params = model.init_params()
    tx = optax.adam(1e-3)
    opt_state = tx.init(params)

    def nll(y, logp):  # model ends in log_softmax (reference LogSoftMax)
        picked = jnp.take_along_axis(logp, y.astype(jnp.int32), axis=-1)
        return -jnp.mean(picked)

    def train_step(params, opt_state, x, y):
        def compute_loss(p):
            out, upd = model.apply(p, x, training=True)
            return nll(y, out), upd
        (loss, upd), grads = jax.value_and_grad(
            compute_loss, has_aux=True)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    rs = np.random.RandomState(0)
    users = rs.randint(0, 6040, size=batch)
    items = rs.randint(0, 3706, size=batch)
    x = jnp.asarray(np.stack([users, items], 1), jnp.int32)
    y = jnp.asarray(((users + items) % 5)[:, None], jnp.int32)

    def run(params, opt_state, x, y):
        def body(carry, _):
            p, o = carry
            p, o, loss = train_step(p, o, x, y)
            return (p, o), loss
        (p, o), losses_seq = jax.lax.scan(
            body, (params, opt_state), None, length=steps)
        return p, o, losses_seq[-1]

    t0 = time.perf_counter()
    compiled = jax.jit(run).lower(params, opt_state, x, y).compile()
    t_compile = time.perf_counter() - t0

    from bench_common import time_chain
    dt, loss, rtt_bound = time_chain(
        compiled, (params, opt_state, x, y), with_quality=True)
    samples_per_sec = batch * steps / dt
    print(f"# [ncf] batch={batch} steps={steps} "
          f"step_time={dt / steps * 1e6:.0f}us loss={loss:.3f} "
          f"compile={t_compile:.1f}s rtt_bound={rtt_bound}",
          file=sys.stderr, flush=True)
    from bench_common import flag_rtt_bound
    return flag_rtt_bound({
        "metric": metric,
        "value": round(samples_per_sec, 1),
        "unit": "samples/sec",
        "vs_baseline": None,
    }, rtt_bound)


def main():
    batch = int(os.environ.get("ZOO_TPU_BENCH_NCF_BATCH", "8192"))
    steps = int(os.environ.get("ZOO_TPU_BENCH_STEPS", "20"))

    import jax

    try:
        jax.config.update("jax_compilation_cache_dir",
                          os.environ.get("ZOO_TPU_COMPILE_CACHE",
                                         "/tmp/zoo_tpu_xla_cache"))
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", 2.0)
    except Exception:
        pass
    plat = os.environ.get("ZOO_TPU_BENCH_PLATFORM")
    if plat:
        jax.config.update("jax_platforms", plat)

    t0 = time.perf_counter()
    devices = jax.devices()
    t_init = time.perf_counter() - t0
    print(f"# backend={devices[0].platform} n_devices={len(devices)} "
          f"init={t_init:.1f}s", file=sys.stderr, flush=True)

    from bench_common import attach_metrics_snapshot
    rec = attach_metrics_snapshot(measure(batch=batch, steps=steps))
    print(json.dumps(rec), flush=True)
    print(f"# total={time.perf_counter() - _t_start:.1f}s",
          file=sys.stderr)


if __name__ == "__main__":
    main()
