"""Headline benchmark: ResNet-50 training throughput on the local chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The BASELINE.json target is the nnframes ResNet-50 ImageNet recipe at
>=45% MFU (v5e). vs_baseline here = achieved MFU / 0.45, with FLOPs taken
from XLA's own cost analysis of the compiled train step and peak chip
FLOPs from ZOO_TPU_PEAK_TFLOPS (default 197, TPU v5e bf16).

Round-2 hardening (VERDICT.md "What's weak" #1): round 1 timed out with
no JSON emitted (rc=124, parsed: null). Now:
  * a hard watchdog ALWAYS prints a JSON line and exits before
    ZOO_TPU_BENCH_BUDGET_S (default 480s) — a hanging backend init or a
    slow compile can no longer produce zero signal;
  * the train step is compiled exactly ONCE (one lax.scan chain; round 1
    compiled three program variants before printing anything);
  * platform/backend init time is measured and reported separately in
    the diagnostic stderr line, so a slow 'axon' init is visible.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

import numpy as np

_t_start = time.perf_counter()
_emit_lock = threading.Lock()
_emitted = False
# progressively-updated best-known result; the watchdog prints this
_result = {
    "metric": "resnet50_train_images_per_sec_per_chip",
    "value": 0.0,
    "unit": "images/sec",
    "vs_baseline": 0.0,
    "diag": "startup",
}


def _emit(final: bool = False) -> bool:
    """Print the (single) JSON line; idempotent across threads.
    Returns True iff this call did the printing."""
    global _emitted
    with _emit_lock:
        if _emitted:
            return False
        _emitted = True
        out = dict(_result)
        if final:
            out.pop("diag", None)
        print(json.dumps(out), flush=True)
        return True


def _watchdog(budget_s: float) -> None:
    deadline = _t_start + budget_s
    while True:
        time.sleep(min(5.0, max(deadline - time.perf_counter(), 0.01)))
        if _emitted:
            return
        if time.perf_counter() >= deadline:
            _result["diag"] = (
                f"watchdog: budget {budget_s:.0f}s exceeded at stage "
                f"'{_result.get('diag', '?')}'")
            if _emit():  # False ⇒ main already printed; let it finish
                sys.stdout.flush()
                os._exit(0)
            return


def _probe_main():
    """Fast backend health check (run as `--probe` in a subprocess
    with a hard deadline): a dead axon tunnel hangs `jax.devices()`
    indefinitely — round 3 burned its whole 440s budget there. The
    supervisor kills this child in tens of seconds instead and routes
    the budget to labeled non-chip signal."""
    if os.environ.get("ZOO_TPU_BENCH_SIMULATE_DEAD") == "1":
        time.sleep(3600)                      # test hook: dead tunnel
    import jax
    import jax.numpy as jnp
    plat = os.environ.get("ZOO_TPU_BENCH_PLATFORM")
    if plat:
        jax.config.update("jax_platforms", plat)
    devices = jax.devices()
    float(np.asarray(jax.jit(lambda a: a + 1.0)(jnp.zeros(()))))
    print(f"PROBE_OK {devices[0].platform} x{len(devices)}",
          flush=True)


def _fallback_metrics(extra: list) -> None:
    """Dead-backend path: spend the budget on clearly-labeled
    NON-CHIP signal instead of a bare 0.0 — interpret-mode kernel
    conformance plus the NCF workload on CPU."""
    import jax
    import jax.numpy as jnp

    _result["diag"] = _result.get("diag", "") + " [conformance A/B]"
    try:
        from analytics_zoo_tpu.ops import conv_bn
        rs = np.random.RandomState(0)
        x = jnp.asarray(rs.randn(256, 128), jnp.float32)
        w = jnp.asarray(rs.randn(128, 128), jnp.float32)
        y, s, q = conv_bn.matmul_bn(x, w, interpret=True)
        y_ref = x.astype(jnp.float32) @ w
        err = float(jnp.max(jnp.abs(y - y_ref)))
        err = max(err, float(jnp.max(jnp.abs(
            s - jnp.sum(y_ref, axis=0)))) / x.shape[0])
        extra.append({"metric": "conv_bn_conformance_max_abs_err",
                      "value": err, "unit": "abs_err (CPU interpret)",
                      "vs_baseline": None})
    except Exception as e:
        print(f"# [fallback conformance] FAILED: "
              f"{type(e).__name__}: {e}", file=sys.stderr, flush=True)
    try:
        from bench_ncf import measure as ncf_measure
        extra.append(ncf_measure(
            batch=int(os.environ.get("ZOO_TPU_BENCH_NCF_BATCH",
                                     "1024")),
            steps=int(os.environ.get("ZOO_TPU_BENCH_STEPS", "5")),
            metric="ncf_train_samples_per_sec_CPU_FALLBACK"))
    except Exception as e:
        print(f"# [fallback ncf] FAILED: {type(e).__name__}: {e}",
              file=sys.stderr, flush=True)


def main():
    # fire before the parent supervisor's kill (budget-15s) so the
    # stage diagnostic reaches the driver when the hang is in
    # GIL-releasing code; the supervisor covers GIL-holding hangs
    raw = float(os.environ.get("ZOO_TPU_BENCH_BUDGET_S", "480"))
    budget = max(raw - 40.0, 0.5 * raw)
    threading.Thread(target=_watchdog, args=(budget,),
                     daemon=True).start()

    batch = int(os.environ.get("ZOO_TPU_BENCH_BATCH", "128"))
    image = int(os.environ.get("ZOO_TPU_BENCH_IMAGE", "224"))
    steps = int(os.environ.get("ZOO_TPU_BENCH_STEPS", "20"))
    peak_tflops = float(os.environ.get("ZOO_TPU_PEAK_TFLOPS", "197"))

    _result["diag"] = "importing jax"
    import jax
    import jax.numpy as jnp
    import optax

    # persistent compile cache: repeat runs (driver reruns, perf
    # iteration) skip the ~25s ResNet-50 compile
    try:
        jax.config.update("jax_compilation_cache_dir",
                          os.environ.get("ZOO_TPU_COMPILE_CACHE",
                                         "/tmp/zoo_tpu_xla_cache"))
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", 2.0)
    except Exception:
        pass  # knob names vary across jax versions; cache is optional

    # Optional platform pin (e.g. ZOO_TPU_BENCH_PLATFORM=cpu for a local
    # smoke run): the JAX_PLATFORMS env var alone does not stop the axon
    # plugin from hanging device init; the config update does.
    plat = os.environ.get("ZOO_TPU_BENCH_PLATFORM")
    if plat:
        jax.config.update("jax_platforms", plat)

    if os.environ.get("ZOO_TPU_BENCH_FALLBACK") == "1":
        # supervisor's health probe found the backend dead: emit the
        # diag-bearing 0.0 headline fast, with labeled non-chip signal
        jax.config.update("jax_platforms", "cpu")
        _result["diag"] = os.environ.get(
            "ZOO_TPU_BENCH_FALLBACK_REASON",
            "backend dead; CPU fallback")
        extra: list = []
        _result["extra_metrics"] = extra
        _fallback_metrics(extra)
        _emit()          # non-final: the diag must reach the artifact
        return

    _result["diag"] = "backend init (jax.devices)"
    t0 = time.perf_counter()
    devices = jax.devices()
    t_init = time.perf_counter() - t0
    print(f"# backend={devices[0].platform} n_devices={len(devices)} "
          f"init={t_init:.1f}s", file=sys.stderr, flush=True)

    _result["diag"] = "building model"
    from analytics_zoo_tpu import init_nncontext
    from analytics_zoo_tpu.models.image.imageclassification import resnet50
    from analytics_zoo_tpu.ops import losses, optimizers
    from analytics_zoo_tpu.pipeline.estimator import Estimator

    init_nncontext(tpu_mesh={"data": 1}, devices=devices[:1],
                   log_level="WARNING")
    s2d = os.environ.get("ZOO_TPU_BENCH_S2D", "1") == "1"
    # ZOO_TPU_BENCH_FUSED: "auto" (default) measures the unfused XLA
    # graph, the Pallas fused-bottleneck variant AND the alternating
    # deferred-apply variant, reporting the fastest sane one;
    # "0"/"1"/"defer" pin a single variant.
    fused_mode = os.environ.get("ZOO_TPU_BENCH_FUSED", "auto")
    loss_fn = losses.softmax_cross_entropy
    tx = optimizers.SGD(lr=0.1, momentum=0.9).to_optax()

    def make_train_step(mdl):
        def train_step(params, opt_state, x, y):
            def compute_loss(p):
                out, upd = mdl.apply(p, x, training=True)
                return loss_fn(y, out), upd

            (loss, upd), grads = jax.value_and_grad(
                compute_loss, has_aux=True)(params)
            updates, opt_state2 = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            params = Estimator._merge_updates(params, upd)
            return params, opt_state2, loss
        return train_step

    rs = np.random.RandomState(0)
    # bf16 inputs: layers compute in input dtype, params stay f32
    x = jnp.asarray(rs.randn(batch, image, image, 3), jnp.bfloat16)
    y = jnp.asarray(rs.randint(0, 1000, size=(batch, 1)), jnp.int32)

    # analytic estimate: fwd ~4.09 GFLOPs/img @224, train ~3x fwd
    flops_analytic = 3 * 4.09e9 * batch * (image / 224.0) ** 2

    def _cost_flops(comp) -> float:
        try:
            cost = comp.cost_analysis()
            cost = cost[0] if isinstance(cost, (list, tuple)) else cost
            # XLA's HloCostAnalysis counts a while/scan body ONCE, not
            # per trip, so the chain's flops ~= one step's
            return float(cost.get("flops", 0.0))
        except Exception:
            return 0.0

    # constant dispatch/round-trip overhead estimate (min of 5 samples:
    # a single transient RPC spike must not inflate the reported MFU)
    tiny = jax.jit(lambda a: a + 1.0).lower(
        jnp.zeros((), jnp.float32)).compile()
    float(np.asarray(tiny(jnp.zeros((), jnp.float32))))  # warm
    overhead = float("inf")
    for _ in range(5):
        t0 = time.perf_counter()
        float(np.asarray(tiny(jnp.zeros((), jnp.float32))))
        overhead = min(overhead, time.perf_counter() - t0)

    # FLOPs accounting baseline: HloCostAnalysis cannot see inside
    # Pallas custom calls, so the fused program under-reports its
    # matmul FLOPs; every variant is accounted with the UNFUSED
    # program's visible count (cost_analysis on the LOWERED program —
    # no second backend compile).
    ref_flops_holder = {}
    # unfused 20-step loss: the numeric-sanity reference for the
    # fused/defer variants (same data, same step count; init RNGs
    # differ so the band is deliberately loose)
    ref_loss_holder = {}

    VARIANT_TAGS = {False: "unfused", True: "fused",
                    "defer": "defer"}

    def _host_init(model):
        """Host-CPU param + opt init (one device transfer later beats
        ~270 per-op tunnel round trips). ``init_params(device="host")``
        returns CPU-committed leaves, so the eager ``tx.init`` zeros
        follow them onto the CPU automatically."""
        params = model.init_params(device="host")
        return params, tx.init(params)

    def measure_variant(fused):
        tag = VARIANT_TAGS[fused]
        _result["diag"] = f"building {tag} model"
        model = resnet50(input_shape=(image, image, 3), classes=1000,
                         space_to_depth=s2d, fused=fused)
        # Param/optimizer init is ~270 tiny eager ops; on the remote
        # axon tunnel each one is a compile + RTT (round 3's "building
        # model" watchdog kill). Run them on host CPU, transfer once.
        t0 = time.perf_counter()
        params, opt_state = jax.device_put(
            _host_init(model), jax.devices()[0])
        jax.block_until_ready((params, opt_state))
        print(f"# [{tag}] host init+transfer="
              f"{time.perf_counter() - t0:.1f}s", file=sys.stderr,
              flush=True)
        train_step = make_train_step(model)

        # ONE compiled program: a lax.scan chain of `steps` train
        # steps — one dispatch + one scalar fetch over the remote
        # transport; the constant round-trip overhead is subtracted.
        def run(params, opt_state, x, y):
            def body(carry, _):
                p, o = carry
                p, o, loss = train_step(p, o, x, y)
                return (p, o), loss
            (p, o), losses_seq = jax.lax.scan(
                body, (params, opt_state), None, length=steps)
            return p, o, losses_seq[-1]

        _result["diag"] = f"compiling {tag} train step"
        t0 = time.perf_counter()
        lowered = jax.jit(run).lower(params, opt_state, x, y)
        if not fused:
            ref_flops_holder["flops"] = _cost_flops(lowered)
        elif "flops" not in ref_flops_holder:
            # fused-only mode: lower (don't compile) the unfused
            # program purely for the visible-FLOPs account
            ref_model = resnet50(input_shape=(image, image, 3),
                                 classes=1000, space_to_depth=s2d,
                                 fused=False)
            # host-side init: lowering only needs avals, and eager
            # init on the remote device is the RTT storm (see above)
            rp, ro = _host_init(ref_model)
            ref_flops_holder["flops"] = _cost_flops(
                jax.jit(make_train_step(ref_model)).lower(
                    rp, ro, x, y))
        compiled = lowered.compile()
        t_compile = time.perf_counter() - t0
        print(f"# [{tag}] compile={t_compile:.1f}s", file=sys.stderr,
              flush=True)

        flops_per_step = max(_cost_flops(compiled),
                             ref_flops_holder.get("flops", 0.0))
        if not (0.2 * flops_analytic < flops_per_step <
                5 * flops_analytic):
            # nan/zero, or a cost-model change (per-trip counting)
            flops_per_step = flops_analytic

        def timed():
            t0 = time.perf_counter()
            p, o, loss = compiled(params, opt_state, x, y)
            loss_val = float(np.asarray(loss))  # host fetch = sync
            return time.perf_counter() - t0, loss_val

        def derive(best_dt):
            dt = max(best_dt - overhead, 1e-9)
            images_per_sec = batch * steps / dt
            mfu = (flops_per_step * steps / dt) / (peak_tflops * 1e12)
            # model-FLOPs MFU: the honest number (analytic 3x-forward
            # FLOPs, not XLA's hardware-op count which includes remat
            # and counts some fusions generously) — VERDICT r3 weak #1
            mfu_model = (flops_analytic * steps / dt) / \
                (peak_tflops * 1e12)
            return dt, images_per_sec, mfu, mfu_model

        _result["diag"] = f"warmup run ({tag})"
        timed()  # warmup (execution path, allocator)
        profile_dir = os.environ.get("ZOO_TPU_BENCH_PROFILE_DIR")
        if profile_dir:  # jax.profiler trace of one measured chain
            jax.profiler.start_trace(os.path.join(profile_dir, tag))
            timed()
            jax.profiler.stop_trace()
            print(f"# [{tag}] profile trace -> {profile_dir}/{tag}",
                  file=sys.stderr, flush=True)
        _result["diag"] = f"timing ({tag})"
        best_dt, loss = None, float("nan")
        for _ in range(2):
            dt_i, loss = timed()
            # numeric sanity: a variant whose 20-step loss is not
            # finite (or wildly off the unfused reference's — garbage
            # computed fast) must not win the A/B on speed alone
            if not np.isfinite(loss):
                raise RuntimeError(
                    f"non-finite loss {loss} after {steps} steps")
            ref_loss = ref_loss_holder.get("loss")
            if ref_loss is not None and not (
                    0.5 * ref_loss < loss < 2.0 * ref_loss):
                raise RuntimeError(
                    f"loss {loss:.3f} diverges from the unfused "
                    f"reference's {ref_loss:.3f}")
            if not fused:
                ref_loss_holder["loss"] = loss
            best_dt = dt_i if best_dt is None else min(best_dt, dt_i)
            dt, images_per_sec, mfu, mfu_model = derive(best_dt)
            # record as soon as one measurement exists (and only if
            # better than a previous variant) so the watchdog always
            # has the best real number
            if images_per_sec > _result["value"]:
                _result.update(
                    value=round(images_per_sec, 2),
                    vs_baseline=round(mfu / 0.45, 4),
                    mfu_xla_flops=round(mfu, 6),
                    mfu_model_flops=round(mfu_model, 6),
                    vs_baseline_model_flops=round(mfu_model / 0.45, 6),
                    variant=tag,
                    diag=f"timed ({tag})")
        dt, images_per_sec, mfu, mfu_model = derive(best_dt)
        print(f"# [{tag}] batch={batch} image={image} steps={steps} "
              f"step_time={dt / steps * 1000:.1f}ms mfu={mfu:.3f} "
              f"mfu_model={mfu_model:.3f} "
              f"loss={loss:.3f} flops/step={flops_per_step:.3e} "
              f"overhead={overhead * 1000:.1f}ms "
              f"compile={t_compile:.1f}s", file=sys.stderr, flush=True)
        return images_per_sec

    # auto order matters: unfused first BANKS a headline number (the
    # watchdog emits best-so-far), then the Pallas variants try to
    # beat it — a budget blowout mid-Mosaic-compile costs nothing
    variants = {"0": [False], "1": [True],
                "defer": ["defer"]}.get(fused_mode,
                                        [False, True, "defer"])
    succeeded, last_err = 0, None
    for fused in variants:
        try:
            measure_variant(fused)
            succeeded += 1
        except Exception as e:
            # one variant failing must not cost the round's number
            print(f"# [{VARIANT_TAGS[fused]}] FAILED: "
                  f"{type(e).__name__}: {e}", file=sys.stderr,
                  flush=True)
            last_err = e
            if fused_mode in ("0", "1", "defer"):
                raise
    if not succeeded:
        # both variants failed: surface the error (diag JSON + rc 1)
        # instead of a silent value-0.0 "success"
        raise last_err
    if os.environ.get("ZOO_TPU_BENCH_NCF", "1") == "1":
        # second BASELINE.json workload rides the same artifact
        # (VERDICT r3 weak #4: the NCF number was orphaned in PERF.md)
        _result["diag"] = "ncf secondary"
        try:
            from bench_ncf import measure as ncf_measure
            _result.setdefault("extra_metrics", []).append(
                ncf_measure(
                    batch=int(os.environ.get("ZOO_TPU_BENCH_NCF_BATCH",
                                             "8192")),
                    steps=steps))
        except Exception as e:
            print(f"# [ncf] FAILED: {type(e).__name__}: {e}",
                  file=sys.stderr, flush=True)
    # third BASELINE workload (config #5, BERT fine-tune) — budget-
    # aware: "auto" runs it only when enough budget remains after the
    # headline + NCF; "1" forces, "0" skips
    bert_mode = os.environ.get("ZOO_TPU_BENCH_BERT", "auto")
    remaining = budget - (time.perf_counter() - _t_start)
    skip_why = None
    if bert_mode == "auto" and jax.default_backend() not in (
            "tpu", "axon"):
        bert_mode, skip_why = "0", "non-TPU backend (base-width " \
            "BERT is minutes on CPU; ZOO_TPU_BENCH_BERT=1 forces)"
    elif bert_mode == "auto" and remaining <= 150:
        bert_mode, skip_why = "0", \
            f"{remaining:.0f}s budget left (<150s)"
    if bert_mode in ("1", "auto"):
        _result["diag"] = "bert tertiary"
        try:
            from bench_bert import measure as bert_measure
            _result.setdefault("extra_metrics", []).append(
                bert_measure(
                    batch=int(os.environ.get(
                        "ZOO_TPU_BENCH_BERT_BATCH", "32")),
                    steps=min(steps, 10),
                    hidden=int(os.environ.get(
                        "ZOO_TPU_BENCH_BERT_HIDDEN", "768")),
                    blocks=int(os.environ.get(
                        "ZOO_TPU_BENCH_BERT_BLOCKS", "4"))))
        except Exception as e:
            print(f"# [bert] FAILED: {type(e).__name__}: {e}",
                  file=sys.stderr, flush=True)
    elif skip_why:
        print(f"# [bert] skipped: {skip_why}", file=sys.stderr,
              flush=True)
    _emit(final=True)
    print(f"# init={t_init:.1f}s "
          f"total={time.perf_counter() - _t_start:.1f}s",
          file=sys.stderr)


def _supervise(budget_s: float) -> None:
    """Run the measurement in a child process; the parent never imports
    jax, so a C-level hang holding the GIL in the child (the round-1
    axon-init failure mode) cannot starve this timeout. The parent
    relays the child's output and prints the fallback JSON itself if
    the child produces no JSON line in time.

    Before committing the budget, a `--probe` child must prove the
    backend alive within ZOO_TPU_BENCH_PROBE_S (default 90s — backend
    init is ~10s when healthy); a dead axon tunnel is detected in
    seconds instead of consuming the round's whole budget inside
    `jax.devices()` (the BENCH_r03 failure), and the budget goes to
    the labeled CPU fallback instead."""
    import subprocess

    deadline = _t_start + budget_s
    probe_s = float(os.environ.get("ZOO_TPU_BENCH_PROBE_S", "90"))
    env = dict(os.environ)
    try:
        p = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--probe"],
            timeout=min(probe_s,
                        max(deadline - time.perf_counter(), 1.0)),
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            text=True)
        probe_ok = p.returncode == 0 and "PROBE_OK" in (p.stdout or "")
        probe_msg = (p.stdout or "").strip() or f"rc={p.returncode}"
    except subprocess.TimeoutExpired:
        probe_ok, probe_msg = False, f"no response in {probe_s:.0f}s"
    if not probe_ok:
        reason = (f"backend probe failed ({probe_msg}) — dead "
                  "tunnel?; CPU fallback metrics in extra_metrics")
        print(f"# PROBE FAILED: {reason}", file=sys.stderr, flush=True)
        env["ZOO_TPU_BENCH_FALLBACK"] = "1"
        env["ZOO_TPU_BENCH_FALLBACK_REASON"] = reason
    else:
        print(f"# probe: {probe_msg} "
              f"[{time.perf_counter() - _t_start:.1f}s]",
              file=sys.stderr, flush=True)
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--child"],
        stdout=subprocess.PIPE, text=True, env=env)
    json_line = None
    try:
        out, _ = proc.communicate(
            timeout=max(deadline - time.perf_counter(), 1.0))
        for line in out.splitlines():
            if line.startswith("{"):
                json_line = line
            else:
                print(line)
    except subprocess.TimeoutExpired:
        proc.kill()
        out = proc.communicate()[0] or ""
        for line in out.splitlines():
            if line.startswith("{"):
                json_line = line
    if json_line is not None:
        print(json_line, flush=True)
    else:
        _result["diag"] = (
            f"supervisor: child produced no JSON within {budget_s:.0f}s "
            f"(rc={proc.returncode})")
        _emit()
    sys.exit(0 if json_line is not None else 1 if proc.returncode else 0)


if __name__ == "__main__":
    if "--probe" in sys.argv:
        _probe_main()
    elif "--child" in sys.argv:
        try:
            main()
        except Exception as e:  # emit signal even on crash
            _result["diag"] = f"error: {type(e).__name__}: {e}"
            _emit()
            raise
    else:
        raw = float(os.environ.get("ZOO_TPU_BENCH_BUDGET_S", "480"))
        # leave headroom under the driver's timeout, but never zero out
        # a small (smoke-run) budget
        _supervise(max(raw - 15.0, 0.6 * raw))
