"""Headline benchmark: ResNet-50 training throughput on the local chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The BASELINE.json target is the nnframes ResNet-50 ImageNet recipe at
>=45% MFU (v5e). vs_baseline here = achieved MFU / 0.45, with FLOPs taken
from XLA's own cost analysis of the compiled train step and peak chip
FLOPs from ZOO_TPU_PEAK_TFLOPS (default 197, TPU v5e bf16).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def main():
    import jax

    from analytics_zoo_tpu import init_nncontext
    from analytics_zoo_tpu.models.image.imageclassification import resnet50
    from analytics_zoo_tpu.ops import losses, optimizers
    import optax

    batch = int(os.environ.get("ZOO_TPU_BENCH_BATCH", "128"))
    image = int(os.environ.get("ZOO_TPU_BENCH_IMAGE", "224"))
    steps = int(os.environ.get("ZOO_TPU_BENCH_STEPS", "10"))
    peak_tflops = float(os.environ.get("ZOO_TPU_PEAK_TFLOPS", "197"))

    ctx = init_nncontext(tpu_mesh={"data": 1},
                         devices=jax.devices()[:1],
                         log_level="WARNING")
    model = resnet50(input_shape=(image, image, 3), classes=1000)
    params = model.init_params()
    loss_fn = losses.softmax_cross_entropy
    tx = optimizers.SGD(lr=0.1, momentum=0.9).to_optax()
    opt_state = tx.init(params)

    def train_step(params, opt_state, x, y):
        def compute_loss(p):
            out, upd = model.apply(p, x, training=True)
            return loss_fn(y, out), upd

        (loss, upd), grads = jax.value_and_grad(
            compute_loss, has_aux=True)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        from analytics_zoo_tpu.pipeline.estimator import Estimator
        params = Estimator._merge_updates(params, upd)
        return params, opt_state, loss

    rs = np.random.RandomState(0)
    # bf16 inputs: layers compute in input dtype, params stay f32
    x = jax.numpy.asarray(
        rs.randn(batch, image, image, 3), jax.numpy.bfloat16)
    y = jax.numpy.asarray(rs.randint(0, 1000, size=(batch, 1)),
                          jax.numpy.int32)

    # Remote-device transports make per-call host syncs expensive and
    # async dispatch unreliable for timing: chain K steps inside ONE jit
    # via lax.scan, force a scalar to host to sync, and difference two
    # chain lengths to cancel the constant round-trip/dispatch overhead.
    def chain(k):
        def run(params, opt_state, x, y):
            def body(carry, _):
                p, o = carry
                p, o, loss = train_step(p, o, x, y)
                return (p, o), loss
            (p, o), losses_seq = jax.lax.scan(
                body, (params, opt_state), None, length=k)
            return p, o, losses_seq[-1]
        return jax.jit(run)

    single = jax.jit(train_step)
    try:
        cost = single.lower(params, opt_state, x, y).compile() \
            .cost_analysis()
        cost = cost[0] if isinstance(cost, (list, tuple)) else cost
        flops_per_step = float(cost.get("flops", 0.0))
    except Exception:
        flops_per_step = 0.0
    if not flops_per_step or flops_per_step != flops_per_step:
        # analytic fallback: fwd ~4.09 GFLOPs/img @224, train ~3x fwd
        flops_per_step = 3 * 4.09e9 * batch * (image / 224.0) ** 2

    k_short, k_long = 2, 2 + steps
    run_short = chain(k_short)
    run_long = chain(k_long)

    def timed(fn):
        t0 = time.perf_counter()
        p, o, loss = fn(params, opt_state, x, y)
        loss_val = float(np.asarray(loss))  # host fetch = real sync
        return time.perf_counter() - t0, loss_val

    timed(run_short)  # warmup (compile)
    timed(run_long)
    t_short, _ = timed(run_short)
    t_long, loss = timed(run_long)
    dt = max(t_long - t_short, 1e-9)

    images_per_sec = batch * steps / dt
    steps_per_sec = steps / dt
    mfu = (flops_per_step * steps_per_sec) / (peak_tflops * 1e12)
    print(json.dumps({
        "metric": "resnet50_train_images_per_sec_per_chip",
        "value": round(images_per_sec, 2),
        "unit": "images/sec",
        "vs_baseline": round(mfu / 0.45, 4),
    }))
    print(f"# batch={batch} image={image} steps={steps} "
          f"step_time={dt / steps * 1000:.1f}ms mfu={mfu:.3f} "
          f"loss={float(loss):.3f} flops/step={flops_per_step:.3e}",
          file=sys.stderr)


if __name__ == "__main__":
    main()
