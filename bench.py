"""Headline benchmark: ResNet-50 training throughput on the local chip.

Prints JSON lines: {"metric", "value", "unit", "vs_baseline", ...}.
Every printed JSON line is a SELF-CONTAINED best-so-far artifact; the
last line is the most complete. The driver may parse any one of them
and still get real signal.

The BASELINE.json target is the nnframes ResNet-50 ImageNet recipe at
>=45% MFU (v5e). vs_baseline here = achieved MFU / 0.45, with FLOPs taken
from XLA's own cost analysis of the compiled train step and peak chip
FLOPs from ZOO_TPU_PEAK_TFLOPS (default 197, TPU v5e bf16).

Round-5 hardening (VERDICT r4 next-round #1 — twice-failed artifact):
  * ROOT CAUSE of the r4 465s-kill found and fixed: the driver env sets
    JAX_PLATFORMS=axon, and analytics_zoo_tpu's import-time env pin
    re-clobbered the fallback child's programmatic cpu pin back to
    axon; the first array op then initialized the axon backend and hung
    on the dead tunnel (the plugin's sitecustomize clobbers the env
    var's own selection with jax_platforms="axon,cpu" at interpreter
    startup, so env-only pins never work either). The package pin now
    respects programmatic pins (analytics_zoo_tpu/__init__.py).
  * The supervisor runs each fallback workload in its OWN subprocess
    with its OWN deadline (fast probe <=25s with probe_latency_s +
    failure kind banked in the artifact, then NCF / BERT /
    conformance / small-ResNet each stage-capped), merging records
    and re-emitting
    the full JSON line after EVERY stage: a kill at any point can no
    longer erase banked signal.
  * The live child's watchdog budget is handed down by the supervisor
    (ZOO_TPU_BENCH_CHILD_BUDGET_S) so it fires BEFORE the supervisor's
    kill — in r4 the probe's 90s was not subtracted and the child was
    killed 25s before its own watchdog would have emitted.
  * The live child emits a best-so-far line after every measured
    variant, so a tunnel death mid-A/B (r4's one live window) still
    delivers the already-banked unfused number even if a C-level hang
    starves the watchdog thread.
"""

from __future__ import annotations

import json
import os
import signal
import sys
import threading
import time

import numpy as np

_t_start = time.perf_counter()
_emit_lock = threading.Lock()
_emitted = False
# progressively-updated best-known result; the watchdog prints this
_result = {
    "metric": "resnet50_train_images_per_sec_per_chip",
    "value": 0.0,
    "unit": "images/sec",
    "vs_baseline": 0.0,
    "diag": "startup",
}


def _emit(final: bool = False) -> bool:
    """Print the final JSON line; idempotent across threads.
    Returns True iff this call did the printing."""
    global _emitted
    with _emit_lock:
        if _emitted:
            return False
        _emitted = True
        out = dict(_result)
        if final:
            out.pop("diag", None)
        print(json.dumps(out), flush=True)
        return True


def _emit_progress() -> None:
    """Print the current best-so-far snapshot WITHOUT consuming the
    final emission: each line is a valid, self-contained artifact, so
    a later kill cannot erase what is already on stdout."""
    with _emit_lock:
        if _emitted:
            return
        print(json.dumps(_result), flush=True)


def _watchdog(budget_s: float) -> None:
    deadline = _t_start + budget_s
    while True:
        time.sleep(min(5.0, max(deadline - time.perf_counter(), 0.01)))
        if _emitted:
            return
        if time.perf_counter() >= deadline:
            _result["diag"] = (
                f"watchdog: budget {budget_s:.0f}s exceeded at stage "
                f"'{_result.get('diag', '?')}'")
            if _emit():  # False ⇒ main already printed; let it finish
                sys.stdout.flush()
                os._exit(0)
            return


def _probe_main():
    """Fast backend health check (run as `--probe` in a subprocess
    with a hard deadline): a dead axon tunnel hangs `jax.devices()`
    indefinitely — round 3 burned its whole 440s budget there. The
    supervisor kills this child in tens of seconds instead and routes
    the budget to labeled non-chip signal."""
    if os.environ.get("ZOO_TPU_BENCH_SIMULATE_DEAD") == "1":
        time.sleep(3600)                      # test hook: dead tunnel
    import jax
    import jax.numpy as jnp
    plat = os.environ.get("ZOO_TPU_BENCH_PLATFORM")
    if plat:
        jax.config.update("jax_platforms", plat)
    devices = jax.devices()
    float(np.asarray(jax.jit(lambda a: a + 1.0)(jnp.zeros(()))))
    print(f"PROBE_OK {devices[0].platform} x{len(devices)}",
          flush=True)


# ---------------------------------------------------------------------------
# Sticky probe-failure cache: a dead tunnel stays dead for the rest of
# the bench round (and usually the whole session) — once one probe has
# burned its 25s confirming that, later invocations inside the TTL
# should not pay it again. The first failure is banked to a small temp
# file; while it is fresh the probe is SKIPPED and the round fails over
# to CPU stages instantly, with `probe_fast_path: true` in the artifact
# so dashboards can tell a measured dead probe from a remembered one.
# A probe that succeeds clears the cache (tunnel revived).
# ---------------------------------------------------------------------------

def _probe_cache_path() -> str:
    import tempfile
    return os.environ.get(
        "ZOO_TPU_BENCH_PROBE_CACHE",
        os.path.join(tempfile.gettempdir(),
                     f"zoo_tpu_probe_fail_{os.getuid()}.json"))


def _probe_cache_ttl_s() -> float:
    # 0 disables the fast path (every invocation probes live)
    return float(os.environ.get("ZOO_TPU_BENCH_PROBE_CACHE_S", "600"))


def _cached_probe_failure():
    """The banked failure ``{"kind": ..., "ts": ..., "msg": ...}``
    when one exists and is inside the TTL, else None."""
    ttl = _probe_cache_ttl_s()
    if ttl <= 0:
        return None
    try:
        with open(_probe_cache_path()) as f:
            rec = json.load(f)
        age = time.time() - float(rec["ts"])
        if 0 <= age < ttl:
            rec["age_s"] = round(age, 1)
            return rec
    except (OSError, ValueError, KeyError, TypeError):
        pass
    return None


def _bank_probe_failure(kind: str, msg: str) -> None:
    if _probe_cache_ttl_s() <= 0:
        return
    try:
        with open(_probe_cache_path(), "w") as f:
            json.dump({"kind": kind, "msg": msg, "ts": time.time()},
                      f)
    except OSError:
        pass  # uncacheable tmpdir — the next round just probes again


def _clear_probe_failure() -> None:
    try:
        os.unlink(_probe_cache_path())
    except OSError:
        pass


# ---------------------------------------------------------------------------
# CPU fallback stages: each runs in its own subprocess (own deadline,
# own interpreter) and prints ONE JSON record line. Each pins the CPU
# platform FIRST — both the config (authoritative over the axon
# plugin's sitecustomize startup clobber) and the env var (so
# analytics_zoo_tpu's import-time pin agrees instead of reverting it).
# ---------------------------------------------------------------------------

def _pin_cpu():
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    return jax


def _stage_ncf_main():
    _pin_cpu()
    from bench_ncf import measure
    rec = measure(
        batch=int(os.environ.get("ZOO_TPU_BENCH_NCF_BATCH", "1024")),
        steps=int(os.environ.get("ZOO_TPU_BENCH_STEPS", "5")),
        metric="ncf_train_samples_per_sec_CPU_FALLBACK")
    print(json.dumps(rec), flush=True)


def _stage_bert_main():
    _pin_cpu()
    from bench_bert import measure
    rec = measure(
        batch=int(os.environ.get("ZOO_TPU_BENCH_FB_BERT_BATCH", "8")),
        steps=3, seq_len=128,
        hidden=int(os.environ.get("ZOO_TPU_BENCH_FB_BERT_HIDDEN",
                                  "256")),
        blocks=2,
        metric="bert_finetune_samples_per_sec_CPU_FALLBACK")
    print(json.dumps(rec), flush=True)


def _stage_conformance_main():
    """Interpret-mode Pallas kernel conformance: non-chip evidence the
    fused path computes the right numbers."""
    _pin_cpu()
    import jax.numpy as jnp

    from analytics_zoo_tpu.ops import conv_bn
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(256, 128), jnp.float32)
    w = jnp.asarray(rs.randn(128, 128), jnp.float32)
    y, s, q = conv_bn.matmul_bn(x, w, interpret=True)
    y_ref = x.astype(jnp.float32) @ w
    err = float(jnp.max(jnp.abs(y - y_ref)))
    err = max(err, float(jnp.max(jnp.abs(
        s - jnp.sum(y_ref, axis=0)))) / x.shape[0])
    print(json.dumps({"metric": "conv_bn_conformance_max_abs_err",
                      "value": err, "unit": "abs_err (CPU interpret)",
                      "vs_baseline": None}), flush=True)


def _resnet_train_chain(model, tx, loss_fn, steps):
    """The ONE training-semantics definition every ResNet measurement
    uses (chip variants and CPU fallback alike — methodology must not
    diverge): returns ``(train_step, run)`` where ``run`` is a
    ``steps``-long ``lax.scan`` chain of ``train_step`` over a fixed
    batch (one dispatch + one scalar fetch per measurement)."""
    import jax
    import optax

    from analytics_zoo_tpu.pipeline.estimator import Estimator

    def train_step(params, opt_state, x, y):
        def compute_loss(p):
            out, upd = model.apply(p, x, training=True)
            return loss_fn(y, out), upd

        (loss, upd), grads = jax.value_and_grad(
            compute_loss, has_aux=True)(params)
        updates, opt_state2 = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        params = Estimator._merge_updates(params, upd)
        return params, opt_state2, loss

    def run(params, opt_state, x, y):
        def body(carry, _):
            p, o = carry
            p, o, loss = train_step(p, o, x, y)
            return (p, o), loss
        (p, o), losses_seq = jax.lax.scan(
            body, (params, opt_state), None, length=steps)
        return p, o, losses_seq[-1]

    return train_step, run


def _stage_resnet_cpu_main():
    """Small-config ResNet-50 train throughput on host CPU: keeps the
    headline metric non-zero (clearly labeled) when the chip is
    unreachable."""
    jax = _pin_cpu()
    import jax.numpy as jnp

    from analytics_zoo_tpu import init_nncontext
    from analytics_zoo_tpu.models.image.imageclassification import (
        resnet50)
    from analytics_zoo_tpu.ops import losses, optimizers

    batch = int(os.environ.get("ZOO_TPU_BENCH_FB_BATCH", "4"))
    image = int(os.environ.get("ZOO_TPU_BENCH_FB_IMAGE", "96"))
    steps = int(os.environ.get("ZOO_TPU_BENCH_FB_STEPS", "2"))

    init_nncontext(tpu_mesh={"data": 1}, devices=jax.devices()[:1],
                   log_level="WARNING")
    model = resnet50(input_shape=(image, image, 3), classes=1000,
                     space_to_depth=True, fused=False)
    params = model.init_params(jax.random.PRNGKey(0), device="host")
    tx = optimizers.SGD(lr=0.1, momentum=0.9).to_optax()
    opt_state = tx.init(params)

    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(batch, image, image, 3), jnp.bfloat16)
    y = jnp.asarray(rs.randint(0, 1000, size=(batch, 1)), jnp.int32)

    _, run = _resnet_train_chain(
        model, tx, losses.softmax_cross_entropy, steps)
    lowered = jax.jit(run).lower(params, opt_state, x, y)
    # same executed-vs-model account as the chip path (see
    # _measure_variant_inner): 2x because flops_analytic counts MACs
    flops_ratio = None
    try:
        from analytics_zoo_tpu.perf import flops as perf_flops
        flops_ratio = round(
            perf_flops.executed_flops(perf_flops.hlo_text(lowered)) /
            (2.0 * 3 * 4.09e9 * batch * (image / 224.0) ** 2), 4)
    except Exception as e:
        print(f"# flops audit failed: {e}", file=sys.stderr,
              flush=True)
    compiled = lowered.compile()
    from bench_common import time_chain
    dt, loss = time_chain(compiled, (params, opt_state, x, y), reps=2)
    print(json.dumps({
        "metric": "resnet50_train_images_per_sec_CPU_FALLBACK",
        "value": round(batch * steps / dt, 2), "unit": "images/sec",
        "vs_baseline": None,
        "config": f"batch={batch} image={image} steps={steps} bf16 "
                  f"host-CPU (chip unreachable)",
        "flops_ratio_executed_vs_model": flops_ratio,
        "loss": round(float(loss), 4)}), flush=True)


def main():
    # fire before the parent supervisor's kill so the stage diagnostic
    # reaches the driver when the hang is in GIL-releasing code; the
    # supervisor covers GIL-holding hangs
    child_b = os.environ.get("ZOO_TPU_BENCH_CHILD_BUDGET_S")
    if child_b:
        # the supervisor computed our true remaining time (its own
        # deadline minus probe time minus margin) — use it directly;
        # the supervisor waits child_budget+8s before killing, so any
        # clamp here must match its floor exactly or the watchdog
        # fires after the kill
        budget = max(float(child_b), 5.0)
    else:
        raw = float(os.environ.get("ZOO_TPU_BENCH_BUDGET_S", "480"))
        budget = max(raw - 40.0, 0.5 * raw)
    threading.Thread(target=_watchdog, args=(budget,),
                     daemon=True).start()

    batch = int(os.environ.get("ZOO_TPU_BENCH_BATCH", "128"))
    image = int(os.environ.get("ZOO_TPU_BENCH_IMAGE", "224"))
    steps = int(os.environ.get("ZOO_TPU_BENCH_STEPS", "20"))
    peak_tflops = float(os.environ.get("ZOO_TPU_PEAK_TFLOPS", "197"))

    _result["diag"] = "importing jax"
    import jax
    import jax.numpy as jnp

    # persistent compile cache: repeat runs (driver reruns, perf
    # iteration) skip the ~25s ResNet-50 compile
    try:
        jax.config.update("jax_compilation_cache_dir",
                          os.environ.get("ZOO_TPU_COMPILE_CACHE",
                                         "/tmp/zoo_tpu_xla_cache"))
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", 2.0)
    except Exception:
        pass  # knob names vary across jax versions; cache is optional

    # Optional platform pin (e.g. ZOO_TPU_BENCH_PLATFORM=cpu for a local
    # smoke run): the JAX_PLATFORMS env var alone does not stop the axon
    # plugin from hanging device init; the config update does.
    plat = os.environ.get("ZOO_TPU_BENCH_PLATFORM")
    if plat:
        os.environ["JAX_PLATFORMS"] = plat
        jax.config.update("jax_platforms", plat)

    _result["diag"] = "backend init (jax.devices)"
    t0 = time.perf_counter()
    devices = jax.devices()
    t_init = time.perf_counter() - t0
    print(f"# backend={devices[0].platform} n_devices={len(devices)} "
          f"init={t_init:.1f}s", file=sys.stderr, flush=True)

    _result["diag"] = "building model"
    from analytics_zoo_tpu import init_nncontext
    from analytics_zoo_tpu.models.image.imageclassification import resnet50
    from analytics_zoo_tpu.ops import losses, optimizers

    init_nncontext(tpu_mesh={"data": 1}, devices=devices[:1],
                   log_level="WARNING")
    s2d = os.environ.get("ZOO_TPU_BENCH_S2D", "1") == "1"
    # ZOO_TPU_BENCH_FUSED: "auto" (default) measures the unfused XLA
    # graph, the Pallas fused-bottleneck variant AND the chained
    # deferred-apply variant (every interior block tail + residual
    # epilogue riding its successor's kernel), reporting the fastest
    # sane one; "0"/"1"/"defer" pin a single variant.
    fused_mode = os.environ.get("ZOO_TPU_BENCH_FUSED", "auto")
    loss_fn = losses.softmax_cross_entropy
    tx = optimizers.SGD(lr=0.1, momentum=0.9).to_optax()

    rs = np.random.RandomState(0)
    # bf16 inputs: layers compute in input dtype, params stay f32
    x = jnp.asarray(rs.randn(batch, image, image, 3), jnp.bfloat16)
    y = jnp.asarray(rs.randint(0, 1000, size=(batch, 1)), jnp.int32)

    # analytic estimate: fwd ~4.09 GFLOPs/img @224, train ~3x fwd
    flops_analytic = 3 * 4.09e9 * batch * (image / 224.0) ** 2

    def _cost_flops(comp) -> float:
        try:
            cost = comp.cost_analysis()
            cost = cost[0] if isinstance(cost, (list, tuple)) else cost
            # XLA's HloCostAnalysis counts a while/scan body ONCE, not
            # per trip, so the chain's flops ~= one step's
            return float(cost.get("flops", 0.0))
        except Exception:
            return 0.0

    # constant dispatch/round-trip overhead estimate (min of 5 samples:
    # a single transient RPC spike must not inflate the reported MFU)
    tiny = jax.jit(lambda a: a + 1.0).lower(
        jnp.zeros((), jnp.float32)).compile()
    float(np.asarray(tiny(jnp.zeros((), jnp.float32))))  # warm
    overhead = float("inf")
    for _ in range(5):
        t0 = time.perf_counter()
        float(np.asarray(tiny(jnp.zeros((), jnp.float32))))
        overhead = min(overhead, time.perf_counter() - t0)

    # FLOPs accounting baseline: HloCostAnalysis cannot see inside
    # Pallas custom calls, so the fused program under-reports its
    # matmul FLOPs; every variant is accounted with the UNFUSED
    # program's visible count (cost_analysis on the LOWERED program —
    # no second backend compile).
    ref_flops_holder = {}
    # unfused 20-step loss: the numeric-sanity reference for the
    # fused/defer variants (all variants now init from the SAME
    # PRNGKey(0) and see identical data, so a >2x divergence after
    # `steps` steps is real numerical trouble, not init noise —
    # ADVICE r4 #3)
    ref_loss_holder = {}

    VARIANT_TAGS = {False: "unfused", True: "fused",
                    "defer": "defer", "phase": "phase"}

    def _host_init(model):
        """Host-CPU param + opt init (one device transfer later beats
        ~270 per-op tunnel round trips). ``init_params(device="host")``
        returns CPU-committed leaves, so the eager ``tx.init`` zeros
        follow them onto the CPU automatically. Fixed PRNGKey: every
        variant starts from identical weights."""
        params = model.init_params(jax.random.PRNGKey(0),
                                   device="host")
        return params, tx.init(params)

    def measure_variant(fused):
        tag = VARIANT_TAGS[fused]
        _result["diag"] = f"building {tag} model"
        if fused == "phase":
            # unfused XLA graph + phase-decomposed strided backward
            # (ops.conv_grad): the flag is read at trace time, so it
            # must wrap the lower() below; restored in the finally
            os.environ["ZOO_TPU_PHASE_BWD"] = "1"
        try:
            return _measure_variant_inner(fused, tag)
        finally:
            if fused == "phase":
                os.environ.pop("ZOO_TPU_PHASE_BWD", None)

    def _measure_variant_inner(fused, tag):
        model = resnet50(input_shape=(image, image, 3), classes=1000,
                         space_to_depth=s2d,
                         fused=False if fused == "phase" else fused)
        # Param/optimizer init is ~270 tiny eager ops; on the remote
        # axon tunnel each one is a compile + RTT (round 3's "building
        # model" watchdog kill). Run them on host CPU, transfer once.
        t0 = time.perf_counter()
        params, opt_state = jax.device_put(
            _host_init(model), jax.devices()[0])
        jax.block_until_ready((params, opt_state))
        print(f"# [{tag}] host init+transfer="
              f"{time.perf_counter() - t0:.1f}s", file=sys.stderr,
              flush=True)
        # ONE compiled program: a lax.scan chain of `steps` train
        # steps — one dispatch + one scalar fetch over the remote
        # transport; the constant round-trip overhead is subtracted.
        _, run = _resnet_train_chain(model, tx, loss_fn, steps)

        _result["diag"] = f"compiling {tag} train step"
        t0 = time.perf_counter()
        lowered = jax.jit(run).lower(params, opt_state, x, y)
        if fused in (False, "phase") and \
                "flops_ratio_executed_vs_model" not in _result:
            # executed-vs-model FLOPs ratio of the XLA graph actually
            # measured (perf.flops: dilation zeros count as executed;
            # HloCostAnalysis discounts them and cannot see the gap).
            # flops_analytic counts MACs (torchvision's 4.09e9/img);
            # executed_flops counts 2 FLOPs/MAC — hence the 2x.
            try:
                from analytics_zoo_tpu.perf import flops as perf_flops
                _result["flops_ratio_executed_vs_model"] = round(
                    perf_flops.executed_flops(
                        perf_flops.hlo_text(lowered)) /
                    (2.0 * flops_analytic), 4)
            except Exception as e:
                print(f"# [{tag}] flops audit failed: {e}",
                      file=sys.stderr, flush=True)
        if not fused:
            ref_flops_holder["flops"] = _cost_flops(lowered)
        elif "flops" not in ref_flops_holder:
            # fused-only mode: lower (don't compile) the unfused
            # program purely for the visible-FLOPs account
            ref_model = resnet50(input_shape=(image, image, 3),
                                 classes=1000, space_to_depth=s2d,
                                 fused=False)
            # host-side init: lowering only needs avals, and eager
            # init on the remote device is the RTT storm (see above)
            rp, ro = _host_init(ref_model)
            ref_step, _ = _resnet_train_chain(
                ref_model, tx, loss_fn, steps)
            ref_flops_holder["flops"] = _cost_flops(
                jax.jit(ref_step).lower(rp, ro, x, y))
        compiled = lowered.compile()
        t_compile = time.perf_counter() - t0
        print(f"# [{tag}] compile={t_compile:.1f}s", file=sys.stderr,
              flush=True)

        flops_per_step = max(_cost_flops(compiled),
                             ref_flops_holder.get("flops", 0.0))
        if not (0.2 * flops_analytic < flops_per_step <
                5 * flops_analytic):
            # nan/zero, or a cost-model change (per-trip counting)
            flops_per_step = flops_analytic

        def timed():
            t0 = time.perf_counter()
            p, o, loss = compiled(params, opt_state, x, y)
            loss_val = float(np.asarray(loss))  # host fetch = sync
            return time.perf_counter() - t0, loss_val

        def derive(best_dt):
            dt = max(best_dt - overhead, 1e-9)
            images_per_sec = batch * steps / dt
            mfu = (flops_per_step * steps / dt) / (peak_tflops * 1e12)
            # model-FLOPs MFU: the honest number (analytic 3x-forward
            # FLOPs, not XLA's hardware-op count which includes remat
            # and counts some fusions generously) — VERDICT r3 weak #1
            mfu_model = (flops_analytic * steps / dt) / \
                (peak_tflops * 1e12)
            return dt, images_per_sec, mfu, mfu_model

        _result["diag"] = f"warmup run ({tag})"
        timed()  # warmup (execution path, allocator)
        profile_dir = os.environ.get("ZOO_TPU_BENCH_PROFILE_DIR")
        if profile_dir:  # jax.profiler trace of one measured chain
            jax.profiler.start_trace(os.path.join(profile_dir, tag))
            timed()
            jax.profiler.stop_trace()
            print(f"# [{tag}] profile trace -> {profile_dir}/{tag}",
                  file=sys.stderr, flush=True)
        _result["diag"] = f"timing ({tag})"
        best_dt, loss = None, float("nan")
        for _ in range(2):
            dt_i, loss = timed()
            # numeric sanity: a variant whose 20-step loss is not
            # finite (or wildly off the unfused reference's — garbage
            # computed fast) must not win the A/B on speed alone
            if not np.isfinite(loss):
                raise RuntimeError(
                    f"non-finite loss {loss} after {steps} steps")
            ref_loss = ref_loss_holder.get("loss")
            if ref_loss is not None and not (
                    0.5 * ref_loss < loss < 2.0 * ref_loss):
                raise RuntimeError(
                    f"loss {loss:.3f} diverges from the unfused "
                    f"reference's {ref_loss:.3f}")
            if not fused:
                ref_loss_holder["loss"] = loss
            best_dt = dt_i if best_dt is None else min(best_dt, dt_i)
            dt, images_per_sec, mfu, mfu_model = derive(best_dt)
            # record as soon as one measurement exists (and only if
            # better than a previous variant) so the watchdog always
            # has the best real number
            if images_per_sec > _result["value"]:
                _result.update(
                    value=round(images_per_sec, 2),
                    vs_baseline=round(mfu / 0.45, 4),
                    mfu_xla_flops=round(mfu, 6),
                    mfu_model_flops=round(mfu_model, 6),
                    vs_baseline_model_flops=round(mfu_model / 0.45, 6),
                    variant=tag,
                    diag=f"timed ({tag})")
        dt, images_per_sec, mfu, mfu_model = derive(best_dt)
        print(f"# [{tag}] batch={batch} image={image} steps={steps} "
              f"step_time={dt / steps * 1000:.1f}ms mfu={mfu:.3f} "
              f"mfu_model={mfu_model:.3f} "
              f"loss={loss:.3f} flops/step={flops_per_step:.3e} "
              f"overhead={overhead * 1000:.1f}ms "
              f"compile={t_compile:.1f}s", file=sys.stderr, flush=True)
        return images_per_sec

    # auto order matters: unfused first BANKS a headline number (the
    # watchdog emits best-so-far), then phase (plain XLA, cheap to
    # compile) and the Pallas variants try to beat it — a budget
    # blowout mid-Mosaic-compile costs nothing
    variants = {"0": [False], "1": [True], "defer": ["defer"],
                "phase": ["phase"]}.get(
                    fused_mode, [False, "phase", True, "defer"])
    succeeded, last_err = 0, None
    for fused in variants:
        try:
            measure_variant(fused)
            succeeded += 1
            if len(variants) > 1:
                # bank the number on stdout NOW: a mid-A/B tunnel
                # death (r4's live window) must not erase it
                _emit_progress()
        except Exception as e:
            # one variant failing must not cost the round's number
            print(f"# [{VARIANT_TAGS[fused]}] FAILED: "
                  f"{type(e).__name__}: {e}", file=sys.stderr,
                  flush=True)
            last_err = e
            if fused_mode in ("0", "1", "defer", "phase"):
                raise
    if not succeeded:
        # both variants failed: surface the error (diag JSON + rc 1)
        # instead of a silent value-0.0 "success"
        raise last_err
    if os.environ.get("ZOO_TPU_BENCH_NCF", "1") == "1":
        # second BASELINE.json workload rides the same artifact
        # (VERDICT r3 weak #4: the NCF number was orphaned in PERF.md)
        _result["diag"] = "ncf secondary"
        try:
            from bench_ncf import measure as ncf_measure
            _result.setdefault("extra_metrics", []).append(
                ncf_measure(
                    batch=int(os.environ.get("ZOO_TPU_BENCH_NCF_BATCH",
                                             "8192")),
                    steps=steps))
            _emit_progress()
        except Exception as e:
            print(f"# [ncf] FAILED: {type(e).__name__}: {e}",
                  file=sys.stderr, flush=True)
    # third BASELINE workload (config #5, BERT fine-tune) — guaranteed
    # on a live chip (VERDICT r4 next-round #4): full config when the
    # budget allows, a reduced labeled config when it is tight, skip
    # only when the watchdog is imminent. On CPU backends the
    # supervisor's fallback stage owns the (labeled) BERT record.
    bert_mode = os.environ.get("ZOO_TPU_BENCH_BERT", "auto")
    remaining = budget - (time.perf_counter() - _t_start)
    skip_why = None
    bert_kw = dict(
        batch=int(os.environ.get("ZOO_TPU_BENCH_BERT_BATCH", "32")),
        steps=min(steps, 10),
        hidden=int(os.environ.get("ZOO_TPU_BENCH_BERT_HIDDEN", "768")),
        blocks=int(os.environ.get("ZOO_TPU_BENCH_BERT_BLOCKS", "4")))
    if bert_mode == "auto" and jax.default_backend() not in (
            "tpu", "axon"):
        bert_mode, skip_why = "0", "non-TPU backend (the supervisor's " \
            "CPU fallback stage owns the labeled BERT record; " \
            "ZOO_TPU_BENCH_BERT=1 forces)"
    elif bert_mode == "auto" and remaining <= 45:
        bert_mode, skip_why = "0", \
            f"{remaining:.0f}s budget left (<45s; watchdog imminent)"
    elif bert_mode == "auto" and remaining <= 150:
        # reduced config still banks a real chip number
        bert_kw.update(batch=8, steps=3, hidden=256, blocks=2)
        print(f"# [bert] reduced config ({remaining:.0f}s left)",
              file=sys.stderr, flush=True)
    if bert_mode in ("1", "auto"):
        _result["diag"] = "bert tertiary"
        try:
            from bench_bert import measure as bert_measure
            _result.setdefault("extra_metrics", []).append(
                bert_measure(**bert_kw))
        except Exception as e:
            print(f"# [bert] FAILED: {type(e).__name__}: {e}",
                  file=sys.stderr, flush=True)
    elif skip_why:
        print(f"# [bert] skipped: {skip_why}", file=sys.stderr,
              flush=True)
    _emit(final=True)
    print(f"# init={t_init:.1f}s "
          f"total={time.perf_counter() - _t_start:.1f}s",
          file=sys.stderr)


# ---------------------------------------------------------------------------
# Supervisor: never imports jax (a C-level hang in a child cannot
# starve it), stages every unit of work in its own subprocess with its
# own deadline, and re-prints the merged best-so-far JSON line after
# every stage.
# ---------------------------------------------------------------------------

_STAGE_FLAGS = {
    "ncf": ("--stage-ncf", 130.0),
    "bert": ("--stage-bert", 130.0),
    "conformance": ("--stage-conformance", 90.0),
    "resnet": ("--stage-resnet-cpu", 180.0),
}


def _last_json_line(text: str):
    for line in reversed((text or "").splitlines()):
        if line.startswith("{"):
            try:
                return json.loads(line)
            except ValueError:
                continue
    return None


def _child_banked_signal(rec) -> bool:
    """True iff a relayed chip-child JSON line carries real signal
    (a positive headline value or any extra metric). Null-safe on
    "value": a line in the fallback schema (``"value": null`` +
    ``cpu_fallback_value``) must not TypeError-crash the supervisor
    before its own CPU stages get to run."""
    if rec is None:
        return False
    return (rec.get("value") or 0) > 0 or bool(rec.get("extra_metrics"))


def _supervise(budget_s: float) -> None:
    """Probe the backend (<=ZOO_TPU_BENCH_PROBE_S, default a fast
    25s), then either run the
    full chip bench in a child (budget handed down so its watchdog
    fires before our kill), or spend the budget on stage-capped,
    individually-subprocessed CPU fallback workloads — re-emitting the
    merged JSON artifact after every stage."""
    import subprocess

    deadline = _t_start + budget_s
    merged = dict(_result)
    merged["extra_metrics"] = []
    state = {"printed_any": False}

    def emit_merged():
        state["printed_any"] = True
        try:  # refresh per emit: telemetry accrues across stages
            from bench_common import attach_metrics_snapshot
            attach_metrics_snapshot(merged)
        except Exception:
            pass  # the artifact must go out even if telemetry fails
        print(json.dumps(merged), flush=True)

    def on_term(signum, frame):
        # driver killed us: make sure SOMETHING is on stdout
        if not state["printed_any"]:
            merged["diag"] = (merged.get("diag", "") +
                              " [supervisor SIGTERM]").strip()
            emit_merged()
        sys.stdout.flush()
        os._exit(1)
    try:
        signal.signal(signal.SIGTERM, on_term)
    except ValueError:
        pass  # non-main thread (tests importing us)

    # fast bounded probe (ROADMAP item 5): rounds 3-5 burned up to 90s
    # per round waiting on dead axon tunnels before failing over. A
    # live tunnel answers in well under 25s (round 2 probed in ~10s),
    # so that now caps the worst case and the budget fails over to CPU
    # stages immediately; latency + failure kind are banked in the
    # artifact so dead rounds stay diagnosable from the JSON alone.
    probe_s = float(os.environ.get("ZOO_TPU_BENCH_PROBE_S", "25"))
    t_probe = time.perf_counter()
    probe_fail_kind = None
    cached = _cached_probe_failure()
    if cached is not None:
        # sticky fast path: a probe already died within the TTL — skip
        # straight to CPU fallback instead of re-burning up to 25s
        probe_ok = False
        probe_fail_kind = cached.get("kind", "cached")
        probe_msg = (f"cached failure ({cached.get('msg', '?')}, "
                     f"{cached.get('age_s', '?')}s ago)")
        merged["probe_fast_path"] = True
    else:
        try:
            p = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 "--probe"],
                timeout=min(probe_s,
                            max(deadline - time.perf_counter(), 1.0)),
                stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
                text=True)
            probe_ok = p.returncode == 0 and \
                "PROBE_OK" in (p.stdout or "")
            probe_msg = (p.stdout or "").strip() or \
                f"rc={p.returncode}"
            if not probe_ok:
                probe_fail_kind = ("probe_rc" if p.returncode != 0
                                   else "no_probe_ok")
        except subprocess.TimeoutExpired:
            probe_ok, probe_msg = (False,
                                   f"no response in {probe_s:.0f}s")
            probe_fail_kind = "timeout"
        if probe_ok:
            _clear_probe_failure()  # tunnel alive — forget old deaths
        else:
            _bank_probe_failure(probe_fail_kind, probe_msg)
    merged["probe_latency_s"] = round(
        time.perf_counter() - t_probe, 3)

    if probe_ok:
        print(f"# probe: {probe_msg} "
              f"[{time.perf_counter() - _t_start:.1f}s]",
              file=sys.stderr, flush=True)
        env = dict(os.environ)
        remaining = deadline - time.perf_counter()
        # child watchdog deadline < our kill deadline, ALWAYS: the
        # child must get to emit its best-so-far line first. The wait
        # below is child_budget+8 (not min'd with the real deadline —
        # in the pathological sub-10s case that overruns by a few
        # seconds, well inside _supervise's 15s driver margin), and
        # the child's own floor matches ours.
        child_budget = max(remaining - 12.0, 5.0)
        env["ZOO_TPU_BENCH_CHILD_BUDGET_S"] = str(child_budget)
        proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--child"],
            stdout=subprocess.PIPE, text=True, env=env)
        last_json = [None]

        def relay():
            for line in proc.stdout:
                line = line.rstrip("\n")
                if line.startswith("{"):
                    last_json[0] = line
                    state["printed_any"] = True
                    print(line, flush=True)  # incremental: bank it NOW
                else:
                    print(line)
        t = threading.Thread(target=relay, daemon=True)
        t.start()
        try:
            proc.wait(timeout=child_budget + 8.0)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait()
        t.join(timeout=10.0)
        try:
            child_rec = (json.loads(last_json[0])
                         if last_json[0] is not None else None)
        except ValueError:  # truncated mid-line by the kill
            child_rec = None
        if _child_banked_signal(child_rec):
            sys.exit(0)  # real signal banked by the chip child
        # child died silently OR emitted only a zero-signal error
        # line — fall through to CPU stages with whatever remains
        merged["diag"] = (
            f"chip child banked no signal "
            f"(rc={proc.returncode}, "
            f"child_diag={child_rec.get('diag') if child_rec else None!r});"
            f" CPU fallback metrics in extra_metrics")
    else:
        merged["probe_failure"] = probe_fail_kind
        merged["diag"] = (
            f"backend probe failed ({probe_msg}; "
            f"kind={probe_fail_kind}, "
            f"{merged['probe_latency_s']:.1f}s) — dead tunnel?; "
            "CPU fallback metrics in extra_metrics")
        print(f"# PROBE FAILED: {probe_msg} "
              f"(kind={probe_fail_kind}, "
              f"{merged['probe_latency_s']:.1f}s)",
              file=sys.stderr, flush=True)
    # chip unreachable from here on: the headline is explicitly null
    # so no consumer mistakes a host-CPU img/s for chip perf — the
    # CPU number rides in cpu_fallback_value instead (VERDICT #8)
    merged["value"] = None
    merged["vs_baseline"] = None

    # --- CPU fallback: one subprocess per workload, each with its own
    # deadline; merged artifact re-emitted after every stage ---------
    stage_names = os.environ.get(
        "ZOO_TPU_BENCH_FB_STAGES", "ncf,bert,conformance,resnet")
    for name in [s.strip() for s in stage_names.split(",") if s.strip()]:
        if name not in _STAGE_FLAGS:
            merged.setdefault("stage_errors", []).append(
                f"{name}: unknown stage (valid: "
                f"{','.join(_STAGE_FLAGS)})")
            continue
        flag, cap = _STAGE_FLAGS[name]
        remaining = deadline - time.perf_counter()
        if remaining < 25.0:
            merged.setdefault("stage_errors", []).append(
                f"{name}: skipped ({remaining:.0f}s left)")
            continue
        t_stage = min(cap, remaining - 5.0)
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"  # stages never touch the tunnel
        try:
            p = subprocess.run(
                [sys.executable, os.path.abspath(__file__), flag],
                timeout=t_stage, stdout=subprocess.PIPE, text=True,
                env=env)
            rec = _last_json_line(p.stdout)
            err = None if rec else f"{name}: no JSON (rc={p.returncode})"
        except subprocess.TimeoutExpired as te:
            # salvage: the stage may have printed its record and then
            # hung in teardown — a banked line must never be erased
            rec = _last_json_line(
                te.stdout.decode() if isinstance(te.stdout, bytes)
                else (te.stdout or ""))
            err = None if rec else f"{name}: no result in {t_stage:.0f}s"
        if rec is not None:
            merged["extra_metrics"].append(rec)
            if name == "resnet":
                # the headline stays null (chip unreachable); the
                # host-CPU measurement is banked under its own
                # unambiguous key
                merged["cpu_fallback_value"] = rec["value"]
                merged["fallback"] = rec.get("config", "cpu")
        else:
            merged.setdefault("stage_errors", []).append(err)
        emit_merged()
    if not state["printed_any"]:
        emit_merged()
    # rc contract: 0 only when real signal was banked — a dead run
    # whose every stage failed must not look like success to
    # `bench.py && publish`-style automation
    sys.exit(0 if merged["extra_metrics"] else 1)


if __name__ == "__main__":
    if "--probe" in sys.argv:
        _probe_main()
    elif "--stage-ncf" in sys.argv:
        _stage_ncf_main()
    elif "--stage-bert" in sys.argv:
        _stage_bert_main()
    elif "--stage-conformance" in sys.argv:
        _stage_conformance_main()
    elif "--stage-resnet-cpu" in sys.argv:
        _stage_resnet_cpu_main()
    elif "--child" in sys.argv:
        try:
            main()
        except Exception as e:  # emit signal even on crash
            _result["diag"] = f"error: {type(e).__name__}: {e}"
            _emit()
            raise
    else:
        raw = float(os.environ.get("ZOO_TPU_BENCH_BUDGET_S", "480"))
        # leave headroom under the driver's timeout, but never zero out
        # a small (smoke-run) budget
        _supervise(max(raw - 15.0, 0.6 * raw))
